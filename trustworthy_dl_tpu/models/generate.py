"""Autoregressive generation for the GPT-2 family (beyond-reference).

The reference trains GPT-2 but offers no way to sample from it; a complete
framework does.  TPU-native decode loop:

* **KV cache as a pytree of static-shape arrays** ``[L, B, H, S, Dh]`` —
  no dynamic shapes anywhere, so the whole generate call jits once per
  (prompt_len, max_new_tokens) pair and runs as a single XLA program.
* **Prefill** runs the stacked-block scan over the full prompt (MXU-sized
  matmuls), writing the cache; **decode** steps a ``lax.scan`` over new
  positions, each step attending to the cache via one [B,H,1,S] product.
* Sampling: greedy, temperature, top-k and top-p.  Pure top-k selects
  its k candidates hierarchically (``_exact_topk``: segment-wise
  ``lax.top_k`` then re-select — exact, ~10× cheaper than full-vocab
  top-k on TPU) and samples among them, so no full-vocab mask or
  categorical ever runs; composed top-k+top-p falls back to the
  threshold-mask path (the nucleus filter needs full-vocab order
  anyway).

Numerics are pinned to the training forward: tests assert prefill+decode
logits equal ``gpt2.forward``'s at every position (same params, same
layernorm/attention code via models/layers.py primitives).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from trustworthy_dl_tpu.models import gpt2
from trustworthy_dl_tpu.models import layers as L

Params = Dict[str, Any]


class KVCache(NamedTuple):
    k: jax.Array       # [L, B, H, S, Dh]
    v: jax.Array       # [L, B, H, S, Dh]
    # i32[] — number of valid positions, shared by every row (batch
    # generate), OR i32[B] — per-row valid lengths (the serving engine's
    # slotted cache, where each slot decodes at its own position).  The
    # rank is static under jit, so the two spellings trace to different
    # programs but share all the code below.
    length: jax.Array
    # int8 KV tier (quant/int8.py): when k/v store int8, these hold the
    # per-(head, position) f32 scales [L, B, H, S]; None selects the
    # full-precision path.  The presence branch is on pytree STRUCTURE,
    # resolved at trace time — each engine still compiles exactly one
    # decode program, and None adds zero leaves to the batch-generate
    # pytree (its program is bit-identical to the pre-quant one).
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None


def init_cache(cfg: gpt2.GPT2Config, batch: int, max_len: int,
               kv_dtype: Optional[Any] = None) -> KVCache:
    """``kv_dtype=None`` keeps the model compute dtype; ``jnp.int8``
    selects the quantized cache (int8 values + f32 per-(head, position)
    scales, initialised to 0 so untouched rows dequantise to exact
    zeros, same as the dense zeros of the plain cache)."""
    kv_dtype = cfg.dtype if kv_dtype is None else kv_dtype
    shape = (cfg.n_layer, batch, cfg.n_head, max_len,
             cfg.n_embd // cfg.n_head)
    if kv_dtype == jnp.int8:
        scales = jnp.zeros(shape[:-1], jnp.float32)
        return KVCache(
            k=jnp.zeros(shape, jnp.int8), v=jnp.zeros(shape, jnp.int8),
            length=jnp.zeros((), jnp.int32),
            k_scale=scales, v_scale=scales,
        )
    return KVCache(
        k=jnp.zeros(shape, kv_dtype),
        v=jnp.zeros(shape, kv_dtype),
        length=jnp.zeros((), jnp.int32),
    )


def _split_heads(a: jax.Array, n_head: int) -> jax.Array:
    b, t, d = a.shape
    return a.reshape(b, t, n_head, d // n_head).transpose(0, 2, 1, 3)


def _write_cache_rows(layer_kv: jax.Array, new: jax.Array,
                      start: jax.Array) -> jax.Array:
    """Write [B, H, T, ...] new rows into the [B, H, S, ...] cache at
    ``start`` — scalar (all rows aligned) or i32[B] (per-row offsets;
    the vmap'd dynamic_update_slice lowers to a static-shape scatter)."""
    new = new.astype(layer_kv.dtype)
    trail = (0,) * (layer_kv.ndim - 3)
    if jnp.ndim(start) == 0:
        return jax.lax.dynamic_update_slice(
            layer_kv, new, (0, 0, start) + trail
        )
    row_update = jax.vmap(
        lambda cache_row, new_row, off: jax.lax.dynamic_update_slice(
            cache_row, new_row, (0, off) + trail
        )
    )
    return row_update(layer_kv, new, start)


def _attn_qkv(block: Params, x: jax.Array,
              cfg: gpt2.GPT2Config) -> Tuple[jax.Array, jax.Array,
                                             jax.Array]:
    """The pre-attention scaffolding EVERY cached-decode block shares
    (gathered-view path and kernel path alike — one spelling, so a
    numerics fix cannot diverge them): ln_1 + fused qkv projection +
    head split.  [B, T, D] -> q, k, v [B, H, T, Dh]."""
    from trustworthy_dl_tpu.quant import int8 as q8

    dtype = cfg.dtype
    y = L.layernorm(block["ln_1"], x).astype(dtype)
    qkv = q8.qdense(block["attn"]["qkv"], y, dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    return tuple(_split_heads(a, cfg.n_head) for a in (q, k, v))


def _attn_mlp_tail(block: Params, x: jax.Array, out: jax.Array,
                   cfg: gpt2.GPT2Config,
                   adapter: Optional[tuple] = None,
                   adapter_pool: Optional[tuple] = None,
                   adapter_impl: str = "jnp") -> jax.Array:
    """The post-attention scaffolding every cached-decode block shares:
    merge heads, attention projection + residual, ln_2 + MLP +
    residual.  ``out`` [B, H, T, Dh] is the attention output.

    ``adapter`` (serve/adapters.py) is the per-row gathered adapter
    slice ``(a [B, 2, D, r], b [B, 2, r, D], a_scale, b_scale)`` —
    scales None except on the int8 tier.  Site 0 rides the attention
    output projection's input, site 1 the MLP's ln_2 input; a row
    pointing at the reserved zero page contributes an exactly-zero
    delta.  ``None`` (every non-serving caller, and every serve program
    with ``adapter_rank == 0``) keeps this function bit-for-bit the
    pre-adapter tail — structural absence, not a traced branch.

    ``adapter_pool`` is the UNGATHERED pool form ``(a_l [P+1, 2, D, r],
    b_l [P+1, 2, r, D], a_scale_l, b_scale_l, apages [B])`` for the
    in-grid kernel path (``adapter_impl`` "pallas"/"interpret"): the
    per-slot page row joins the kernel's scalar-prefetch operands and
    the A/B tiles stream HBM→VMEM inside ``ops.adapter_delta`` — no
    gathered page copy exists.  Exactly one of ``adapter`` /
    ``adapter_pool`` may be given."""
    from trustworthy_dl_tpu.ops.fused_dequant_matmul import lowrank_delta
    from trustworthy_dl_tpu.quant import int8 as q8

    dtype = cfg.dtype
    b, t, d = x.shape
    out = out.transpose(0, 2, 1, 3).reshape(b, t, d)

    def delta(site_x: jax.Array, site: int) -> Optional[jax.Array]:
        if adapter_pool is not None:
            from trustworthy_dl_tpu.ops import paged_attention as pattn

            a_l, b_l, as_l, bs_l, apages = adapter_pool
            return pattn.adapter_delta(
                site_x, a_l[:, site], b_l[:, site], apages,
                a_scale=None if as_l is None else as_l[:, site],
                b_scale=None if bs_l is None else bs_l[:, site],
                interpret=(adapter_impl == "interpret"),
            )
        if adapter is not None:
            a_s, b_s, a_sc, b_sc = adapter
            return lowrank_delta(
                site_x, a_s[:, site], b_s[:, site],
                None if a_sc is None else a_sc[:, site],
                None if b_sc is None else b_sc[:, site],
            )
        return None

    x = x + q8.qdense(block["attn"]["proj"], out, dtype).astype(x.dtype)
    d0 = delta(out, 0)
    if d0 is not None:
        x = x + d0.astype(x.dtype)
    y = L.layernorm(block["ln_2"], x).astype(dtype)
    ln2 = y
    y = q8.qdense(block["mlp"]["fc"], y, dtype)
    y = jax.nn.gelu(y)
    mlp = q8.qdense(block["mlp"]["proj"], y, dtype).astype(x.dtype)
    d1 = delta(ln2, 1)
    if d1 is not None:
        mlp = mlp + d1.astype(x.dtype)
    return x + mlp


def _block_with_cache(block: Params, x: jax.Array, layer_k: jax.Array,
                      layer_v: jax.Array, start: jax.Array,
                      cfg: gpt2.GPT2Config,
                      layer_k_scale: Optional[jax.Array] = None,
                      layer_v_scale: Optional[jax.Array] = None,
                      adapter: Optional[tuple] = None,
                      ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                 Optional[jax.Array], Optional[jax.Array]]:
    """One transformer block over [B, T, D] new positions, attending to
    cached K/V [B, H, S, Dh] plus itself (causal).  ``start`` is the write
    offset — positions [start, start+T) land in the cache.  Scalar
    ``start`` writes every row at the same offset (batch generate);
    ``start`` i32[B] writes each row at its own offset (the serving
    engine's slotted cache).

    int8 KV tier: when ``layer_k_scale``/``layer_v_scale`` [B, H, S] are
    given, the cache stores int8 and new K/V rows are quantized at the
    write site (symmetric per-(head, position), quant/int8.py).  The
    reads never materialise a dequantized cache copy: a cached key's
    scale is constant along the contracted Dh axis, so it multiplies the
    score AFTER the int8 dot product, and a cached value's scale folds
    into the attention probabilities before the PV contraction — exact
    algebra, only the int8 rounding differs from the dense path.

    Returns (activations, layer_k, layer_v, layer_k_scale,
    layer_v_scale); scales pass through as None on the dense path."""
    from trustworthy_dl_tpu.quant import int8 as q8

    dtype = cfg.dtype
    b, t, d = x.shape
    h = cfg.n_head
    s = layer_k.shape[-2]
    quantized = layer_k_scale is not None

    q, k, v = _attn_qkv(block, x, cfg)                 # [B, H, T, Dh]

    if quantized:
        k_q, k_s = q8.quantize_kv(k)                   # int8, f32 [B,H,T]
        v_q, v_s = q8.quantize_kv(v)
        layer_k = _write_cache_rows(layer_k, k_q, start)
        layer_v = _write_cache_rows(layer_v, v_q, start)
        layer_k_scale = _write_cache_rows(layer_k_scale, k_s, start)
        layer_v_scale = _write_cache_rows(layer_v_scale, v_s, start)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q,
                            layer_k.astype(dtype))
        scores = scores * layer_k_scale[:, :, None, :] / math.sqrt(d // h)
    else:
        layer_k = _write_cache_rows(layer_k, k, start)
        layer_v = _write_cache_rows(layer_v, v, start)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, layer_k) \
            / math.sqrt(d // h)
    # Causal vs cache: query at absolute position start+i may see cache
    # slots [0, start+i].
    if jnp.ndim(start) == 0:
        q_pos = start + jnp.arange(t)[:, None]         # [T, 1]
        k_pos = jnp.arange(s)[None, :]                 # [1, S]
        mask = k_pos <= q_pos                          # [T, S]
        mask = mask[None, None]                        # [1, 1, T, S]
    else:
        q_pos = start[:, None, None] + jnp.arange(t)[None, :, None]
        k_pos = jnp.arange(s)[None, None, :]
        mask = (k_pos <= q_pos)[:, None]               # [B, 1, T, S]
    scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dtype)
    if quantized:
        pv = (probs * layer_v_scale[:, :, None, :]).astype(dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", pv, layer_v.astype(dtype))
    else:
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, layer_v)
    x = _attn_mlp_tail(block, x, out, cfg, adapter=adapter)
    return x, layer_k, layer_v, layer_k_scale, layer_v_scale


def _decode_view(params: Params, cfg: gpt2.GPT2Config) -> Params:
    """Pre-cast the bandwidth-dominant weights to the compute dtype ONCE.

    ``dense()``/``project_logits()`` cast their f32 master weights to
    ``cfg.dtype`` at every use; inside the decode scan that cast re-reads
    the f32 copy from HBM every token.  b=1 decode is pure
    weight-bandwidth, so hoisting the cast halves the per-token HBM
    traffic (f32 → bf16 reads).  Numerics are bit-identical: it is the
    same cast, done once — ``dense``'s ``astype`` becomes a no-op on the
    pre-cast leaves.  Embedding lookups and layernorms keep their f32
    params (their numerics are defined in f32)."""
    if cfg.dtype == jnp.float32:
        return params

    def cast_dense(d):
        return {"w": d["w"].astype(cfg.dtype),
                "b": d["b"].astype(cfg.dtype)}

    blocks = params["blocks"]
    out = dict(params)
    out["blocks"] = {
        "ln_1": blocks["ln_1"],
        "ln_2": blocks["ln_2"],
        "attn": {"qkv": cast_dense(blocks["attn"]["qkv"]),
                 "proj": cast_dense(blocks["attn"]["proj"])},
        "mlp": {"fc": cast_dense(blocks["mlp"]["fc"]),
                "proj": cast_dense(blocks["mlp"]["proj"])},
    }
    # Pre-cast tied head for the per-token [B,D]x[D,V] projection — the
    # single largest weight read of a decode step.  params["wte"] itself
    # stays f32 for the embedding lookup.
    out["wte_head"] = params["wte"].astype(cfg.dtype)
    return out


def _apply_with_cache(params: Params, tokens: jax.Array, cache: KVCache,
                      cfg: gpt2.GPT2Config,
                      last_pos: Optional[jax.Array] = None
                      ) -> Tuple[jax.Array, KVCache]:
    """Run all blocks over ``tokens`` [B, T] starting at cache.length;
    returns (logits of the LAST position [B, V], updated cache).

    ``cache.length`` may be scalar (all rows aligned — batch generate) or
    i32[B] (per-row offsets — the serving engine's slotted decode); see
    _block_with_cache.  ``last_pos`` (traced i32[], optional) overrides
    WHICH position's logits are returned: the serving prefill pads prompts
    to a bucket length, so the logits it needs live at real_len-1, not at
    the (padded) last position.  None keeps the static [-1] slice — the
    batch-generate program is unchanged."""
    start = cache.length
    t = tokens.shape[-1]
    if jnp.ndim(start) == 0:
        pos = start + jnp.arange(t)                        # [T]
    else:
        pos = start[:, None] + jnp.arange(t)[None, :]      # [B, T]
    x = (params["wte"][tokens] + params["wpe"][pos]).astype(jnp.float32)

    def scan_fn(carry, layer):
        x = carry
        block, lk, lv, lks, lvs = layer
        x, lk, lv, lks, lvs = _block_with_cache(block, x, lk, lv, start,
                                                cfg, lks, lvs)
        return x, (lk, lv, lks, lvs)

    # Rolled layer scan: unrolling was measured SLOWER on v5e decode
    # (1.39 vs 1.24 ms/token b=1) — the rolled body's weight streams
    # pipeline fine, and the smaller program wins.  The int8 scale
    # planes (None on the dense path — zero leaves, same program) ride
    # the same scan.
    x, (new_k, new_v, new_ks, new_vs) = jax.lax.scan(
        scan_fn, x,
        (params["blocks"], cache.k, cache.v, cache.k_scale, cache.v_scale),
    )
    logits = _final_logits(params, x, cfg, last_pos)
    return logits, KVCache(k=new_k, v=new_v, length=start + t,
                           k_scale=new_ks, v_scale=new_vs)


def _final_logits(params: Params, x: jax.Array, cfg: gpt2.GPT2Config,
                  last_pos: Optional[jax.Array]) -> jax.Array:
    """Project ONE position's activations to logits [B, V] — the shared
    tail of the dense and paged cache paths.  ``last_pos=None`` keeps the
    static [-1] slice (batch generate); a traced value selects the real
    last prompt position under bucket/chunk padding."""
    if last_pos is None:
        x_last = x[:, -1:, :]
    else:
        x_last = jax.lax.dynamic_slice_in_dim(x, last_pos, 1, axis=1)
    wte_head = params.get("wte_head")
    if wte_head is None:
        return gpt2.unembed(params, x_last, cfg)[:, 0, :]  # [B, V]
    normed = L.layernorm(params["ln_f"], x_last)
    return (normed.astype(cfg.dtype) @ wte_head.T).astype(jnp.float32)[:, 0, :]


def _all_logits(params: Params, x: jax.Array,
                cfg: gpt2.GPT2Config) -> jax.Array:
    """Project EVERY fed position to logits [B, T, V] — the speculative
    verify pass needs the target model's choice at each draft position,
    not just the last one.  Per-position math is identical to
    :func:`_final_logits` (same layernorm + head matmul, row-wise), so
    position i of a T-wide projection is bit-identical to a 1-wide
    projection of the same activations."""
    wte_head = params.get("wte_head")
    if wte_head is None:
        return gpt2.unembed(params, x, cfg)
    normed = L.layernorm(params["ln_f"], x)
    return (normed.astype(cfg.dtype) @ wte_head.T).astype(jnp.float32)


def fused_verify_logits(params: Params, x: jax.Array,
                        cfg: gpt2.GPT2Config, *, interpret: bool
                        ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Kernel twin of :func:`_all_logits` + the trust epilogue for the
    speculative-verify tail: pre-``ln_f`` activations ``x`` [R, T, D]
    -> (logits [R, T, V] f32, entropy [R·T], margin [R·T]) in ONE
    streaming pass over the vocab (``ops.fused_verify_tail``) — the
    [R, T, V] materialise-then-re-read of the jnp tail collapses into
    per-tile reductions while each head tile is still in VMEM.

    The head operand is exactly the one ``_all_logits`` contracts with:
    ``wte_head`` when the decode view split one out, else the tied
    ``wte`` cast to the compute dtype (``gpt2.project_logits``' own
    cast); the layernorm + dtype rounding discipline matches
    position-for-position, so the verify sampler sees bit-identical
    logits and the scheduler's trust stats keep the pinned epilogue
    algebra."""
    from trustworthy_dl_tpu.ops import paged_attention as pattn

    r, t, d = x.shape
    wte_head = params.get("wte_head")
    if wte_head is None:
        wte_head = params["wte"].astype(cfg.dtype)
    normed = L.layernorm(params["ln_f"], x).astype(cfg.dtype)
    logits, ent, mar = pattn.fused_verify_tail(
        normed.reshape(r * t, d), wte_head, interpret=interpret)
    return logits.reshape(r, t, -1), ent, mar


# ---------------------------------------------------------------------------
# Paged-KV read/write path (serve/kv_slots.PagedKV pools).
#
# The paged pool stores K/V in fixed-size token blocks [NB, H, BLOCK, Dh]
# per layer; a slot's logical cache is reassembled by gathering its block
# table (i32 per-slot physical ids — traced VALUES, so block churn never
# recompiles).  The attention core is the untouched _block_with_cache:
# the gathered view is numerically the same [R, H, S, Dh] cache the
# stripe engine holds resident (valid positions carry identical values;
# garbage positions are masked to exactly-zero probabilities), so paged
# decode is bit-identical to stripe decode by construction.  After the
# core runs, the rows it wrote into the view are extracted and scattered
# back into the pool at (physical block, offset); positions outside the
# slot's table land in the reserved trash block 0.
# ---------------------------------------------------------------------------


def _paged_gather(layer_pool: jax.Array, table: jax.Array) -> jax.Array:
    """[NB, H, BLOCK, Dh] (or scale [NB, H, BLOCK]) pool slice + block
    table [R, NBPS] -> contiguous per-row view [R, H, NBPS*BLOCK(, Dh)]."""
    g = layer_pool[table]                       # [R, NBPS, H, BLOCK(, Dh)]
    if g.ndim == 5:
        g = g.transpose(0, 2, 1, 3, 4)          # [R, H, NBPS, BLOCK, Dh]
        return g.reshape(g.shape[0], g.shape[1], -1, g.shape[-1])
    g = g.transpose(0, 2, 1, 3)                 # [R, H, NBPS, BLOCK]
    return g.reshape(g.shape[0], g.shape[1], -1)


def _pool_write_coords(table_read: jax.Array, start: jax.Array, r: int,
                       t: int, bsz: int, nbps: int
                       ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(positions [R, T], physical block [R·T], in-block offset [R·T])
    for the T positions each row writes this call — positions past the
    slot's real table land in the reserved trash block 0.  ONE spelling
    shared by the gather path (which extracts the written rows from its
    view at ``pos``) and the kernel path (which scatters the fresh K/V
    directly), so for the SAME block input the two paths write identical
    values to identical pool coordinates (across a multi-layer scan,
    deeper layers inherit the attention paths' f32-rounding epsilon
    through their activations)."""
    if jnp.ndim(start) == 0:
        pos = jnp.broadcast_to((start + jnp.arange(t))[None, :], (r, t))
    else:
        pos = start[:, None] + jnp.arange(t)[None, :]      # [R, T]
    lb = pos // bsz
    valid = lb < nbps
    phys = jnp.take_along_axis(table_read, jnp.minimum(lb, nbps - 1),
                               axis=1)
    phys = jnp.where(valid, phys, 0).reshape(-1)           # 0 = trash
    offs = (pos % bsz).reshape(-1)
    return pos, phys, offs


def _paged_block(block: Params, x: jax.Array, pool_k_l: jax.Array,
                 pool_v_l: jax.Array, table: jax.Array, start: jax.Array,
                 cfg: gpt2.GPT2Config,
                 pool_ks_l: Optional[jax.Array] = None,
                 pool_vs_l: Optional[jax.Array] = None,
                 attn_impl: str = "jnp",
                 adapter_l: Optional[tuple] = None,
                 adapter_impl: str = "jnp",
                 ) -> Tuple[jax.Array, jax.Array, jax.Array,
                            Optional[jax.Array], Optional[jax.Array]]:
    """One transformer block over [R, T, D] new positions against a PAGED
    layer pool.  ``attn_impl`` (trace-time static — the scheduler bakes
    its resolved path into each compiled program) selects the attention
    read:

    * ``"jnp"`` (default, the reference semantics): gather each row's
      view through ``table``, run the dense ``_block_with_cache`` core on
      it (one numerics source for generate, stripe serve and paged
      serve), then scatter the newly written rows back into the pool.
    * ``"pallas"`` / ``"interpret"``: scatter the fresh K/V into the pool
      FIRST (same quantize-at-write values, same ``_pool_write_coords``
      scatter), then run the ragged ``ops.paged_attention`` kernel
      straight over the pool: no [R, H, S, Dh] view is ever
      materialised, int8 tiles dequantise in-register, rows stop
      streaming at their true length.  Write-then-attend equals the jnp
      path's write-into-view because writes only ever land in blocks the
      row owns exclusively (kv_slots' COW discipline) — no row can
      observe another row's same-tick write on either path.

    ``start`` follows the dense contract: scalar (chunked prefill, R=1)
    or i32[R] (fused decode, T=1).

    ``adapter_l`` is one layer's slice of the paged adapter pool plus
    the per-slot page table: ``(a_l [P+1, 2, D, r], b_l [P+1, 2, r, D],
    a_scale_l, b_scale_l, apages [R])``.  On the jnp paths the page
    gather happens HERE, inside the layer scan — exactly one layer's
    gathered pages are ever live, mirroring the KV view discipline —
    and feeds ``_attn_mlp_tail``.  When ``adapter_impl`` (trace-time
    static, resolved per-program by ``ops.resolve_attn_impls``) is
    "pallas"/"interpret" AND the attention read is on a kernel path,
    the gather disappears entirely: the pool form is handed down and
    ``ops.adapter_delta`` streams exactly the pages it needs HBM→VMEM
    inside its own grid, per-slot page row as scalar prefetch."""
    adapter_s: Optional[tuple] = None
    if attn_impl != "jnp":
        adapter_pool = None
        if adapter_l is not None and adapter_impl != "jnp":
            adapter_pool = adapter_l
        elif adapter_l is not None:
            a_l, b_l, as_l, bs_l, apages = adapter_l
            adapter_s = (a_l[apages], b_l[apages],
                         None if as_l is None else as_l[apages],
                         None if bs_l is None else bs_l[apages])
        return _paged_block_kernel(block, x, pool_k_l, pool_v_l, table,
                                   start, cfg, pool_ks_l, pool_vs_l,
                                   interpret=(attn_impl == "interpret"),
                                   adapter=adapter_s,
                                   adapter_pool=adapter_pool,
                                   adapter_impl=adapter_impl)
    if adapter_l is not None:
        a_l, b_l, as_l, bs_l, apages = adapter_l
        adapter_s = (a_l[apages], b_l[apages],
                     None if as_l is None else as_l[apages],
                     None if bs_l is None else bs_l[apages])
    r, t, _ = x.shape
    nbps = table.shape[1]
    bsz = pool_k_l.shape[2]
    if t > 1:
        # A prefill chunk may extend past the logical view (its start is
        # only block-aligned, not chunk-aligned, after a prefix hit) —
        # pad the table with trash columns so the in-view write never
        # clamps onto real positions.  Width is static; the extra
        # columns are masked (k_pos > q_pos) so numerics are unchanged.
        pad = jnp.zeros((r, t // bsz + 1), table.dtype)
        table_read = jnp.concatenate([table, pad], axis=1)
    else:
        table_read = table
    view_k = _paged_gather(pool_k_l, table_read)
    view_v = _paged_gather(pool_v_l, table_read)
    view_ks = (_paged_gather(pool_ks_l, table_read)
               if pool_ks_l is not None else None)
    view_vs = (_paged_gather(pool_vs_l, table_read)
               if pool_vs_l is not None else None)
    x, view_k, view_v, view_ks, view_vs = _block_with_cache(
        block, x, view_k, view_v, start, cfg, view_ks, view_vs,
        adapter=adapter_s
    )
    # Positions this call wrote into the view -> (physical block, offset).
    pos, phys, offs = _pool_write_coords(table_read, start, r, t, bsz,
                                         nbps)
    idx = pos[:, None, :, None]                            # [R, 1, T, 1]

    def rows_of(view):                                     # [R, H, S(,Dh)]
        if view.ndim == 4:
            got = jnp.take_along_axis(view, idx, axis=2)   # [R, H, T, Dh]
            return got.transpose(0, 2, 1, 3).reshape(
                r * t, got.shape[1], got.shape[-1])
        got = jnp.take_along_axis(view, idx[..., 0], axis=2)  # [R, H, T]
        return got.transpose(0, 2, 1).reshape(r * t, got.shape[1])

    pool_k_l = pool_k_l.at[phys, :, offs].set(rows_of(view_k))
    pool_v_l = pool_v_l.at[phys, :, offs].set(rows_of(view_v))
    if pool_ks_l is not None:
        pool_ks_l = pool_ks_l.at[phys, :, offs].set(rows_of(view_ks))
        pool_vs_l = pool_vs_l.at[phys, :, offs].set(rows_of(view_vs))
    return x, pool_k_l, pool_v_l, pool_ks_l, pool_vs_l


def _paged_block_kernel(block: Params, x: jax.Array, pool_k_l: jax.Array,
                        pool_v_l: jax.Array, table: jax.Array,
                        start: jax.Array, cfg: gpt2.GPT2Config,
                        pool_ks_l: Optional[jax.Array],
                        pool_vs_l: Optional[jax.Array],
                        interpret: bool,
                        adapter: Optional[tuple] = None,
                        adapter_pool: Optional[tuple] = None,
                        adapter_impl: str = "jnp",
                        ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                   Optional[jax.Array],
                                   Optional[jax.Array]]:
    """The kernel-path twin of the gather branch in :func:`_paged_block`:
    write-then-attend.  The fresh K/V (quantized at the write on the int8
    tier — the exact values the gather path writes) scatter into the pool
    first; a ``ops.paged_attention`` program then reads positions
    [0, start+T) straight from the pool with the causal window masked
    in absolute positions, which is precisely what the gathered view
    exposes to ``_block_with_cache``.  T selects the program (static —
    each serve program compiles one shape): the one-query-tile decode
    kernel up to ``QROWS`` rows (decode T=1, speculative verify T=k+1),
    the query-tiled chunked-prefill flash kernel above it (per-tile
    causal block bounds skip KV tiles whole query tiles cannot see)."""
    from trustworthy_dl_tpu.ops import paged_attention as pattn
    from trustworthy_dl_tpu.quant import int8 as q8

    r, t, _ = x.shape
    h = cfg.n_head
    nbps = table.shape[1]
    bsz = pool_k_l.shape[2]
    quantized = pool_ks_l is not None

    # Shared pre/post-attention scaffolding (_attn_qkv/_attn_mlp_tail):
    # only the attention READ differs from _block_with_cache.
    q, k, v = _attn_qkv(block, x, cfg)                     # [R, H, T, Dh]

    _, phys, offs = _pool_write_coords(table, start, r, t, bsz, nbps)

    def rows_of(a):                       # [R, H, T(, Dh)] -> [R·T, H(, Dh)]
        if a.ndim == 4:
            return a.transpose(0, 2, 1, 3).reshape(r * t, h, a.shape[-1])
        return a.transpose(0, 2, 1).reshape(r * t, h)

    if quantized:
        k_w, k_s = q8.quantize_kv(k)                       # int8, f32 [R,H,T]
        v_w, v_s = q8.quantize_kv(v)
        pool_ks_l = pool_ks_l.at[phys, :, offs].set(rows_of(k_s))
        pool_vs_l = pool_vs_l.at[phys, :, offs].set(rows_of(v_s))
    else:
        k_w = k.astype(pool_k_l.dtype)
        v_w = v.astype(pool_v_l.dtype)
    pool_k_l = pool_k_l.at[phys, :, offs].set(rows_of(k_w))
    pool_v_l = pool_v_l.at[phys, :, offs].set(rows_of(v_w))

    attend = (pattn.paged_prefill_attention if t > pattn.QROWS
              else pattn.paged_attention)
    out = attend(
        q, pool_k_l, pool_v_l, table, start,
        k_scale=pool_ks_l, v_scale=pool_vs_l, interpret=interpret,
    ).astype(cfg.dtype)                                    # [R, H, T, Dh]
    x = _attn_mlp_tail(block, x, out, cfg, adapter=adapter,
                       adapter_pool=adapter_pool, adapter_impl=adapter_impl)
    return x, pool_k_l, pool_v_l, pool_ks_l, pool_vs_l


def _apply_with_cache_paged(params: Params, tokens: jax.Array,
                            pool_k: jax.Array, pool_v: jax.Array,
                            pool_ks: Optional[jax.Array],
                            pool_vs: Optional[jax.Array],
                            table: jax.Array, start: jax.Array,
                            cfg: gpt2.GPT2Config,
                            last_pos: Optional[jax.Array] = None,
                            all_logits: bool = False,
                            attn_impl: str = "jnp",
                            adapter: Optional[tuple] = None,
                            adapter_impl: str = "jnp",
                            hidden: bool = False,
                            ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                       Optional[jax.Array],
                                       Optional[jax.Array]]:
    """Paged twin of :func:`_apply_with_cache`: run all blocks over
    ``tokens`` [R, T] against the block pool, gathering each layer's view
    inside the layer scan (only ONE layer's view is ever live) and
    scattering its writes back.  Returns (logits [R, V], updated pool
    arrays) — pool updates are functional, the scheduler threads them.
    ``all_logits`` (trace-time bool) returns [R, T, V] logits at every
    fed position instead — the speculative-verify program's tail, where
    the target's token choice is needed at each draft position.
    ``hidden`` (trace-time bool) skips the projection entirely and
    returns the pre-``ln_f`` activations [R, T, D] — the fused-verify
    caller hands them to :func:`fused_verify_logits`, which streams the
    vocab ONCE for logits AND trust stats instead of materialising
    [R, T, V] and re-reading it.
    ``attn_impl`` (trace-time static, see :func:`_paged_block`) swaps the
    gathered-view attention for the ragged ``ops.paged_attention``
    kernel, and ``adapter_impl`` likewise swaps the per-layer adapter
    page gather for the in-grid ``ops.adapter_delta`` stream;
    tables/starts/pages stay traced values every way, so the
    compile-once pin holds on all paths.

    ``adapter`` is the paged adapter-pool pytree ``(a [L, P+1, 2, D,
    r], b, a_scale, b_scale, apages [R])`` (serve/adapters.py): the
    pool sides join the layer scan's xs (leading L axis, like the KV
    pools) and the per-slot page table is closed over — both traced
    values, so adapter churn and tenant-mix changes never recompile.
    ``None`` (adapter_rank == 0) contributes zero pytree leaves: the
    compiled program is structurally identical to the pre-adapter
    one."""
    t = tokens.shape[-1]
    if jnp.ndim(start) == 0:
        pos = start + jnp.arange(t)                        # [T]
    else:
        pos = start[:, None] + jnp.arange(t)[None, :]      # [R, T]
    x = (params["wte"][tokens] + params["wpe"][pos]).astype(jnp.float32)

    if adapter is not None:
        ad_a, ad_b, ad_as, ad_bs, apages = adapter
    else:
        ad_a = ad_b = ad_as = ad_bs = apages = None

    def scan_fn(carry, layer):
        x = carry
        block, pk, pv, pks, pvs, a_l, b_l, as_l, bs_l = layer
        adapter_l = (None if a_l is None
                     else (a_l, b_l, as_l, bs_l, apages))
        x, pk, pv, pks, pvs = _paged_block(block, x, pk, pv, table, start,
                                           cfg, pks, pvs,
                                           attn_impl=attn_impl,
                                           adapter_l=adapter_l,
                                           adapter_impl=adapter_impl)
        return x, (pk, pv, pks, pvs)

    x, (new_k, new_v, new_ks, new_vs) = jax.lax.scan(
        scan_fn, x, (params["blocks"], pool_k, pool_v, pool_ks, pool_vs,
                     ad_a, ad_b, ad_as, ad_bs),
    )
    if hidden:
        return x, new_k, new_v, new_ks, new_vs
    if all_logits:
        return _all_logits(params, x, cfg), new_k, new_v, new_ks, new_vs
    return _final_logits(params, x, cfg, last_pos), new_k, new_v, \
        new_ks, new_vs


def _exact_topk(logits: jax.Array, k: int, rows: int = 32
                ) -> Tuple[jax.Array, jax.Array]:
    """[B, V] -> (values [B, k], indices [B, k]) — exact top-k,
    hierarchically.

    ``lax.top_k`` straight over a 50k-wide vocab row costs ~0.47 ms/token
    on v5e — as much as the entire 12-layer decode body.  Splitting the
    vocab into ``rows`` segments, taking top-k per segment (parallel,
    log-factor on a 32× smaller extent) and re-selecting over the
    rows·k candidates is EXACT — every global top-k element is within its
    own segment's top-k.  -inf padding never enters the top k real values
    since k ≤ segment width."""
    b, v = logits.shape
    seg = -(-v // rows)          # ceil
    if k > seg:                  # degenerate: segments smaller than k
        return jax.lax.top_k(logits, k)
    pad = rows * seg - v
    padded = jnp.pad(logits, ((0, 0), (0, pad)),
                     constant_values=-jnp.inf)
    seg_vals, seg_idx = jax.lax.top_k(
        padded.reshape(b, rows, seg), k
    )                                                       # [B, R, k]
    global_idx = seg_idx + (jnp.arange(rows) * seg)[None, :, None]
    vals, sel = jax.lax.top_k(seg_vals.reshape(b, rows * k), k)  # [B, k]
    idx = jnp.take_along_axis(global_idx.reshape(b, rows * k), sel,
                              axis=-1)
    return vals, idx


def _sample(logits: jax.Array, rng: jax.Array, temperature: jax.Array,
            greedy: bool, top_k: int, top_p: jax.Array,
            use_top_p: bool) -> jax.Array:
    """[B, V] -> [B] next tokens.  ``greedy``, ``top_k`` and ``use_top_p``
    are static (top_k changes lax.top_k output shapes; the nucleus filter
    costs a full-vocab sort per token, so it is compiled out entirely when
    not requested); ``temperature`` and ``top_p`` are traced so sampling
    sweeps reuse one compiled program."""
    if greedy:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k > 0 and not use_top_p:
        # Pure top-k fast path: select the k candidates hierarchically
        # (exact) and sample AMONG them — the categorical runs over
        # [B, k] instead of the full vocab.  Identical distribution: the
        # kept set is the exact top-k and softmax is shift-invariant, so
        # restricting to the candidate values IS the filtered softmax.
        vals, idx = _exact_topk(logits, top_k)
        choice = jax.random.categorical(rng, vals, axis=-1)   # [B]
        return jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0]
    if top_k > 0:
        kth = _exact_topk(logits, top_k)[0][:, -1:]      # [B, 1], exact
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if use_top_p:
        # Nucleus: keep the smallest prefix of the sorted distribution
        # whose mass exceeds top_p.  One sort, no scatter — the keep-mask
        # is mapped back by threshold comparison.
        probs = jax.nn.softmax(logits, axis=-1)
        sorted_probs = jnp.sort(probs, axis=-1)[:, ::-1]
        cum = jnp.cumsum(sorted_probs, axis=-1)
        # Threshold = probability of the last kept token: smallest sorted
        # index where cumulative mass reaches top_p.
        keep_sorted = cum - sorted_probs < top_p
        threshold = jnp.min(
            jnp.where(keep_sorted, sorted_probs, jnp.inf), axis=-1,
            keepdims=True,
        )
        logits = jnp.where(probs >= threshold, logits, -jnp.inf)
    return jax.random.categorical(rng, logits, axis=-1)


@partial(jax.jit, static_argnums=(5, 6, 7, 8, 9))
def _generate_jit(params: Params, prompt: jax.Array, rng: jax.Array,
                  temperature: jax.Array, top_p: jax.Array,
                  cfg: gpt2.GPT2Config,
                  max_new_tokens: int, greedy: bool, top_k: int,
                  use_top_p: bool) -> jax.Array:
    b, t_prompt = prompt.shape
    params = _decode_view(params, cfg)
    cache = init_cache(cfg, b, t_prompt + max_new_tokens)
    logits, cache = _apply_with_cache(params, prompt, cache, cfg)
    first = _sample(logits, rng, temperature, greedy, top_k, top_p,
                    use_top_p)

    def body(carry, step_rng):
        tok, cache = carry
        logits, cache = _apply_with_cache(
            params, tok[:, None], cache, cfg
        )
        nxt = _sample(logits, step_rng, temperature, greedy, top_k, top_p,
                      use_top_p)
        return (nxt, cache), nxt

    if max_new_tokens == 1:
        return jnp.concatenate([prompt, first[:, None]], axis=1)
    step_rngs = jax.random.split(jax.random.fold_in(rng, 1),
                                 max_new_tokens - 1)
    (_, _), rest = jax.lax.scan(body, (first, cache), step_rngs)
    out = jnp.concatenate(
        [prompt, first[:, None], rest.T], axis=1
    )
    return out


def generate(params: Params, cfg: gpt2.GPT2Config, prompt: jax.Array,
             max_new_tokens: int, temperature: float = 0.0, top_k: int = 0,
             top_p: float = 1.0, rng: Optional[jax.Array] = None
             ) -> jax.Array:
    """Sample ``max_new_tokens`` continuations of ``prompt`` [B, T].

    Returns [B, T + max_new_tokens].  ``temperature=0`` decodes greedily;
    ``top_k>0`` restricts sampling to the k most likely tokens;
    ``top_p<1`` restricts to the nucleus holding that probability mass
    (filters compose: top-k first, then top-p).  The whole call is one
    jitted XLA program (static-shape KV cache), compiled once per
    (shape, greedy, top_k) — temperature and top_p are traced, so
    sampling sweeps do not recompile.

    ``rng=None`` defaults to ``PRNGKey(0)``: sampling is DETERMINISTIC
    across identical calls by design (reproducibility-first, like every
    other seed in this framework) — pass a fresh key per call for variety.

    Decode always runs the fused XLA attention over the cache; numerics
    are pinned token-for-token against an XLA-attention training forward
    (the default ``attn_impl='auto'`` resolves to that path for contexts
    below AUTO_FLASH_MIN_T; tests/test_generate.py).  A forward that ran
    the Pallas flash kernel instead — explicit ``attn_impl='flash'``, or
    auto at T ≥ AUTO_FLASH_MIN_T on TPU — agrees to kernel-vs-XLA
    epsilon, where near-tie logits can flip under greedy decode."""
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    total = prompt.shape[-1] + max_new_tokens
    if total > cfg.n_positions:
        raise ValueError(
            f"prompt+new = {total} exceeds n_positions={cfg.n_positions}"
        )
    if not 0 <= top_k <= cfg.vocab_size:
        raise ValueError(
            f"top_k={top_k} out of range [0, vocab_size={cfg.vocab_size}]"
        )
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p={top_p} out of range (0, 1]")
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    return _generate_jit(params, prompt, rng,
                         jnp.asarray(max(temperature, 1e-6), jnp.float32),
                         jnp.asarray(top_p, jnp.float32),
                         cfg, int(max_new_tokens),
                         float(temperature) <= 0.0, int(top_k),
                         float(top_p) < 1.0)

"""Console entry point ``trustworthy-dl-train`` (setup_py.py:62-64 implies
``trustworthy_dl.cli:main``; the module itself is absent from the reference
snapshot — interface reconstructed from the README usage example,
README.md:50-78, and the YAML schema at README.md:111-132).

Unlike the reference, ``--config`` actually loads the file, and flag
overrides win over file values (experiment_runner.py:605,613-623 parsed the
flag and ignored it)."""

from __future__ import annotations

import argparse
import logging
from typing import List, Optional

logging.basicConfig(
    level=logging.INFO,
    format="%(asctime)s %(name)s %(levelname)s %(message)s",
)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="trustworthy-dl-train",
        description="Trust-gated distributed training on TPU meshes",
    )
    parser.add_argument("--config", type=str,
                        help="YAML/JSON config (README.md:111-132 schema)")
    parser.add_argument("--model", type=str, default=None,
                        help="gpt2[-medium|-large|-xl], resnet32/50/101, "
                             "vgg11/13/16")
    parser.add_argument("--dataset", type=str, default=None)
    parser.add_argument("--nodes", type=int, default=None)
    parser.add_argument("--epochs", type=int, default=None)
    parser.add_argument("--batch-size", type=int, default=None)
    parser.add_argument("--learning-rate", type=float, default=None)
    parser.add_argument("--parallelism", type=str, default=None,
                        choices=["data", "model", "tensor", "sequence",
                                 "expert", "hybrid"])
    parser.add_argument("--checkpoint-dir", type=str, default=None)
    parser.add_argument("--resume", action="store_true",
                        help="restore the latest checkpoint before training")
    parser.add_argument("--no-detection", action="store_true",
                        help="disable the in-step attack detector")
    parser.add_argument("--steps-per-epoch", type=int, default=50,
                        help="synthetic-data epoch length")
    parser.add_argument("--async-host-depth", type=int, default=None,
                        help="steps kept in flight by the async host "
                             "pipeline (engine/async_host.py): dispatch "
                             "runs up to this many steps ahead of the "
                             "host bookkeeping, which drains lagged "
                             "through one packed device->host copy per "
                             "step; 0 = fully synchronous (config "
                             "default: 2).  Deterministic chaos drills "
                             "asserting exact retry counts need 0")
    parser.add_argument("--compile-cache", action="store_true",
                        help="enable JAX's persistent compilation cache "
                             "under the run dir (<obs-dir or "
                             "checkpoint-dir>/jax_cache) so repeat runs "
                             "skip recompiles of identical SPMD programs")
    # Self-healing supervisor (engine/supervisor.py) + chaos drills.
    parser.add_argument("--supervise", action="store_true",
                        help="wrap training in the self-healing supervisor: "
                             "non-finite step guard, bounded retries, "
                             "verified-checkpoint rollback, SIGTERM "
                             "save-on-signal + capped auto-resume")
    parser.add_argument("--max-retries", type=int, default=2,
                        help="per-step retry budget before a step counts "
                             "as bad (supervisor)")
    parser.add_argument("--rollback-after", type=int, default=3,
                        help="consecutive bad steps before rolling back to "
                             "the last verified checkpoint (supervisor)")
    parser.add_argument("--max-restarts", type=int, default=3,
                        help="auto-resume budget after preemptions "
                             "(supervisor)")
    parser.add_argument("--chaos-seed", type=int, default=None,
                        help="run under a seeded chaos fault plan "
                             "(implies --supervise): non-finite state, "
                             "stalls, lost batches, preemptions, "
                             "checkpoint corruption — chaos/plan.py")
    parser.add_argument("--chaos-rate", type=float, default=0.02,
                        help="per-step probability of each drill fault "
                             "kind under --chaos-seed")
    # Unified telemetry (trustworthy_dl_tpu/obs/).
    parser.add_argument("--obs-dir", type=str, default=None,
                        help="write run telemetry here: trace.jsonl "
                             "(structured events with step correlation "
                             "ids), metrics_snapshot.json + metrics.prom "
                             "(registry export), obs_report.json "
                             "(per-phase step-time breakdown + MFU), and "
                             "flight-recorder dumps")
    parser.add_argument("--metrics-snapshot-every", type=int, default=0,
                        help="re-write the metrics snapshot every N steps "
                             "(0 = only at run end); needs --obs-dir")
    parser.add_argument("--trace-max-bytes", type=int, default=0,
                        help="rotate trace.jsonl once it exceeds this "
                             "many bytes (trace.1.jsonl, trace.2.jsonl, "
                             "...; 0 = no rotation; env "
                             "TDDL_TRACE_MAX_BYTES is the default)")
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    from trustworthy_dl_tpu.core.config import TrainingConfig, load_config
    from trustworthy_dl_tpu.data import get_dataloader
    from trustworthy_dl_tpu.engine.trainer import DistributedTrainer

    args = build_parser().parse_args(argv)
    overrides = {
        k: v for k, v in {
            "model_name": args.model,
            "dataset_name": args.dataset,
            "num_nodes": args.nodes,
            "num_epochs": args.epochs,
            "batch_size": args.batch_size,
            "learning_rate": args.learning_rate,
            "parallelism": args.parallelism,
            "checkpoint_dir": args.checkpoint_dir,
            "async_host_depth": args.async_host_depth,
        }.items() if v is not None
    }
    if args.no_detection:
        overrides["attack_detection_enabled"] = False
    if args.config:
        config = load_config(args.config, **overrides)
    else:
        config = TrainingConfig(**overrides)
    if args.compile_cache:
        import dataclasses
        import os

        run_dir = args.obs_dir or config.checkpoint_dir
        config = dataclasses.replace(
            config,
            compilation_cache_dir=os.path.join(run_dir, "jax_cache"),
        )

    trainer = DistributedTrainer(config)
    trainer.initialize()
    obs_session = None
    if args.obs_dir:
        from trustworthy_dl_tpu.obs import ObsSession

        obs_session = ObsSession(
            args.obs_dir,
            metrics_snapshot_every=args.metrics_snapshot_every,
            trace_max_bytes=args.trace_max_bytes,
        )
        # Active plane: per-step spans (train.step → per-phase children)
        # and the EWMA anomaly watcher on step-time/loss/grad-norm; no
        # serving SLO rules on a training run, but the step_time_s
        # percentile sketch still lands in slo_status.json.
        obs_session.enable_spans()
        obs_session.install_watchers(slo_rules=())
        # Forensics: the supervisor's guard-trip/rollback/preemption
        # dumps each get a paired incident with the causal ladder.
        obs_session.enable_forensics()
        # Performance tier: every XLA compile metered + the train-step
        # compile-once contract enforced at runtime, live-HBM watermark
        # gauges, and the perf fingerprint appended at finalize.
        obs_session.enable_compile_watch()
        obs_session.enable_hbm()
        trainer.attach_obs(obs_session)
    if args.resume:
        trainer.load_checkpoint()

    num_examples = config.batch_size * args.steps_per_epoch
    train_dl = get_dataloader(config.dataset_name, split="train",
                              batch_size=config.batch_size,
                              num_examples=num_examples)
    val_dl = get_dataloader(config.dataset_name, split="validation",
                            batch_size=config.batch_size,
                            num_examples=max(num_examples // 10,
                                             config.batch_size))
    if args.supervise or args.chaos_seed is not None:
        from trustworthy_dl_tpu.chaos import FaultInjector, FaultKind, \
            FaultPlan
        from trustworthy_dl_tpu.engine.supervisor import TrainingSupervisor

        injector = None
        max_restarts = args.max_restarts
        if args.chaos_seed is not None:
            horizon = args.steps_per_epoch * config.num_epochs
            rate = args.chaos_rate
            plan = FaultPlan.generate(args.chaos_seed, horizon, {
                FaultKind.GRAD_NAN: rate,
                FaultKind.DATA_LOSS: rate,
                FaultKind.STALL: rate,
                FaultKind.PREEMPT: rate / 4,
                FaultKind.CKPT_CRASH: rate / 4,
                FaultKind.CKPT_CORRUPT: rate / 4,
            }, severity=0.05)
            injector = FaultInjector(plan)
            # Every planned preemption costs one restart; keep the budget
            # above the plan so the drill exercises resume, not give-up.
            max_restarts = max(max_restarts,
                               plan.count(FaultKind.PREEMPT) + 1)
            print(f"chaos drill: seed {args.chaos_seed}, "
                  f"{len(plan.events)} fault(s) over {horizon} steps")
        supervisor = TrainingSupervisor(
            trainer, max_retries=args.max_retries,
            rollback_after=args.rollback_after, max_restarts=max_restarts,
            chaos=injector, handle_signals=True, obs=obs_session,
        )
        result = supervisor.run(train_dl, val_dl)
        print(f"supervisor report: {result['supervisor']}")
    else:
        result = trainer.train(train_dl, val_dl)
    stats = result["stats"]
    print(f"Training completed: {stats['global_step']} steps, "
          f"final state {stats['training_state']}")
    trainer.save_checkpoint()
    if obs_session is not None:
        obs_session.hbm.sweep(emit=True)
        obs_session.finalize()
        print(f"obs artifacts in {args.obs_dir}: trace.jsonl, "
              "metrics_snapshot.json, metrics.prom, obs_report.json")
        _print_perf_verdict(obs_session)
    trainer.cleanup()
    return 0


def _print_perf_verdict(obs_session) -> None:
    """One-line sentinel summary at the end of an instrumented run."""
    verdict = obs_session.perf_verdict
    if verdict is None:
        return
    if verdict["regressed"]:
        bad = [f"{c['metric']} {c.get('delta_pct', 0):+.1f}%"
               for c in verdict["checks"] if c.get("regressed")]
        print(f"perf sentinel: REGRESSION vs {verdict['baseline_n']} "
              f"baseline run(s): {', '.join(bad)}")
    else:
        print(f"perf sentinel: within the noise band "
              f"({verdict['baseline_n']} baseline run(s), ledger "
              f"{obs_session.perf_ledger_path})")


def build_generate_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="trustworthy-dl-generate",
        description="Sample from a trained GPT-2 checkpoint with the "
                    "KV-cache decoder (beyond-reference; the reference "
                    "trains GPT-2 but cannot sample from it)",
    )
    parser.add_argument("--model", type=str, default="gpt2")
    parser.add_argument("--checkpoint-dir", type=str, default="checkpoints",
                        help="restore the latest checkpoint from here "
                             "(falls back to fresh init with a warning)")
    parser.add_argument("--prompt", type=str, default="1,2,3,4",
                        help="comma-separated token ids")
    parser.add_argument("--prompt-text", type=str, default=None,
                        help="raw text prompt; needs --tokenizer-dir "
                             "(output is decoded back to text)")
    parser.add_argument("--tokenizer-dir", type=str, default=None,
                        help="vocab.json + merges.txt directory "
                             "(trustworthy-dl-prepare-data writes one)")
    parser.add_argument("--max-new-tokens", type=int, default=32)
    parser.add_argument("--temperature", type=float, default=0.8)
    parser.add_argument("--top-k", type=int, default=40)
    parser.add_argument("--top-p", type=float, default=1.0)
    parser.add_argument("--seed", type=int, default=0)
    return parser


def generate_main(argv: Optional[List[str]] = None,
                  model_overrides: Optional[dict] = None) -> int:
    """Console entry point ``trustworthy-dl-generate``.

    ``model_overrides`` is an internal hook (tests shrink the model with
    it); the CLI surface restores whatever the checkpoint was trained as.
    """
    import jax
    import jax.numpy as jnp

    from trustworthy_dl_tpu.core.config import TrainingConfig
    from trustworthy_dl_tpu.engine.checkpoint import CheckpointManager
    from trustworthy_dl_tpu.engine.trainer import DistributedTrainer
    from trustworthy_dl_tpu.models.generate import generate

    args = build_generate_parser().parse_args(argv)
    if not args.model.startswith("gpt") or args.model.endswith("-moe"):
        print("generation supports the dense GPT-2 family")
        return 2
    # Pipeline-trained checkpoints store stage-stacked [S, L/S, ...] block
    # params — a different tree than the decoder's; refuse clearly rather
    # than let Orbax fail with a structure mismatch.  The topology sidecar
    # records the training parallelism for exactly this check.
    probe = CheckpointManager(args.checkpoint_dir)
    # verified=False: this probe only reads the topology sidecar to
    # refuse pipeline checkpoints — no reason to checksum the whole
    # payload here (load_checkpoint verifies on the actual restore).
    latest = probe.latest_step(verified=False)
    if latest is not None:
        meta = probe.load_metadata(latest) or {}
        if meta.get("parallelism") == "model":
            print("checkpoint was trained with pipeline (stage) "
                  "parallelism; generation needs a data-parallel "
                  "checkpoint (params stage-stacked)")
            return 2
    # Validate the prompt BEFORE the expensive init/restore: the int parse
    # needs nothing, the vocab bound only needs the (cheap) model config.
    tokenizer = None
    if args.prompt_text is not None:
        if not args.tokenizer_dir:
            print("--prompt-text requires --tokenizer-dir")
            return 2
        from trustworthy_dl_tpu.data.tokenizer import BPETokenizer

        try:
            tokenizer = BPETokenizer.load(args.tokenizer_dir)
        except (OSError, ValueError) as exc:
            print(f"could not load tokenizer from {args.tokenizer_dir!r}: "
                  f"{exc}")
            return 2
        tokens = tokenizer.encode(args.prompt_text)
    else:
        try:
            tokens = [int(t) for t in args.prompt.split(",") if t.strip()]
        except ValueError:
            print(f"--prompt must be comma-separated token ids, got "
                  f"{args.prompt!r}")
            return 2
    config = TrainingConfig(model_name=args.model, num_nodes=1, batch_size=1,
                            checkpoint_dir=args.checkpoint_dir)
    trainer = DistributedTrainer(config, model_overrides=model_overrides)
    vocab = trainer.model.config.vocab_size
    if not tokens or any(not 0 <= t < vocab for t in tokens):
        if tokenizer is not None:
            print(f"--prompt-text encoded to {len(tokens)} token id(s); "
                  f"the model accepts ids in [0, {vocab}) — the tokenizer "
                  f"(vocab {tokenizer.vocab_size}) and model vocabularies "
                  "must be compatible and the prompt non-empty")
        else:
            print(f"--prompt needs at least one token id in [0, {vocab})")
        return 2
    trainer.initialize()
    try:
        trainer.load_checkpoint()
        print(f"restored step {int(trainer.state.step)} "
              f"from {args.checkpoint_dir}")
    except FileNotFoundError:
        print(f"no checkpoint under {args.checkpoint_dir!r}; "
              "sampling from random init")

    prompt = jnp.asarray([tokens], jnp.int32)
    out = generate(
        trainer.state.params, trainer.model.config, prompt,
        max_new_tokens=args.max_new_tokens, temperature=args.temperature,
        top_k=args.top_k, top_p=args.top_p,
        rng=jax.random.PRNGKey(args.seed),
    )
    new_ids = out[0, len(tokens):].tolist()
    if tokenizer is not None:
        print("prompt:    ", args.prompt_text)
        print("generated: ", tokenizer.decode(new_ids))
    else:
        print("prompt:    ", tokens)
        print("generated: ", new_ids)
    trainer.cleanup()
    return 0


def build_serve_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="trustworthy-dl-serve",
        description="Serve a GPT-2 checkpoint with the continuous-batching "
                    "engine (slotted KV cache, iteration-level scheduling, "
                    "trust-aware output monitoring).  Drives a synthetic "
                    "heterogeneous workload and prints serving metrics — "
                    "the smoke-deployment mode; hook ServingEngine.submit "
                    "into a real frontend for production traffic.",
    )
    parser.add_argument("--model", type=str, default="gpt2")
    parser.add_argument("--checkpoint-dir", type=str, default="checkpoints",
                        help="restore the latest checkpoint from here "
                             "(falls back to fresh init with a warning)")
    parser.add_argument("--max-slots", type=int, default=8,
                        help="concurrent sequences resident in the KV pool")
    parser.add_argument("--max-seq", type=int, default=256,
                        help="KV slot depth (prompt + generated tokens)")
    parser.add_argument("--queue-limit", type=int, default=64,
                        help="admission-queue bound (backpressure beyond)")
    parser.add_argument("--num-requests", type=int, default=32,
                        help="synthetic workload size")
    parser.add_argument("--max-new-tokens", type=int, default=32)
    parser.add_argument("--prompt-len", type=int, default=16,
                        help="mean synthetic prompt length (lengths vary "
                             "around it — heterogeneity is the point)")
    parser.add_argument("--temperature", type=float, default=0.8)
    parser.add_argument("--deadline-ms", type=float, default=None,
                        help="per-request wall-clock deadline")
    parser.add_argument("--no-monitor", action="store_true",
                        help="disable the trust-aware output monitor")
    parser.add_argument("--legacy-stripe", action="store_true",
                        help="use the legacy per-request stripe KV pool "
                             "instead of the paged block pool (escape "
                             "hatch; paged is the default — occupancy "
                             "bounded by tokens in flight, not request "
                             "count; README §Serving)")
    parser.add_argument("--block-size", type=int, default=16,
                        help="paged-pool token positions per KV block "
                             "(--max-seq must be a multiple)")
    parser.add_argument("--num-blocks", type=int, default=None,
                        help="usable paged-pool blocks; default sizes "
                             "the pool to --max-slots full stripes")
    parser.add_argument("--no-prefix-cache", action="store_true",
                        help="disable the radix prefix cache (requests "
                             "sharing a prompt prefix otherwise reuse "
                             "already-filled blocks copy-on-write)")
    parser.add_argument("--prefill-chunk", type=int, default=None,
                        help="prompt positions fed per chunked-prefill "
                             "tick (multiple of --block-size; default "
                             "auto) — bounds how long one admission can "
                             "stall the fused decode step")
    parser.add_argument("--spec-k", type=int, default=0,
                        help="speculative decoding draft depth: per "
                             "decode tick draft this many tokens per "
                             "active slot with the int8 weight tier "
                             "(built automatically as the draft model) "
                             "and verify them in ONE batched "
                             "model-dtype forward — streams stay "
                             "bit-identical to spec off except where a "
                             "greedy near-tie (top-1 margin under the "
                             "int8 parity tolerance) lets a draft flip "
                             "through, counted in spec_near_tie_flips; "
                             "rejected draft KV rolls back by COW "
                             "refcount decrement. "
                             "0 disables (default).  Requires the paged "
                             "pool and weight-dtype 'model'; README "
                             "§Serving/'Speculative decoding'")
    parser.add_argument("--no-spec-decode", action="store_true",
                        help="force speculative decoding OFF even when "
                             "--spec-k is set (A/B escape hatch; fleet "
                             "replica restarts inherit whichever the "
                             "config resolved to)")
    parser.add_argument("--kv-dtype", type=str, default="model",
                        choices=["model", "bfloat16", "float32", "int8"],
                        help="KV slot-pool storage dtype; int8 stores "
                             "per-(head, position)-scaled int8 — about "
                             "half the KV bytes per slot, so ~2x the "
                             "slots at fixed HBM (parity-gated with "
                             "automatic fallback to the model dtype; "
                             "README §Serving/Quantization)")
    parser.add_argument("--weight-dtype", type=str, default="model",
                        choices=["model", "int8"],
                        help="decode-matmul weight tier; int8 halves "
                             "the weight bytes streamed per decode "
                             "token (embedding/lm-head stay high "
                             "precision)")
    parser.add_argument("--adapter-rank", type=int, default=0,
                        help="per-tenant low-rank adapter tier: rank of "
                             "the paged adapter deltas gathered into "
                             "the decode/prefill matmuls by a traced "
                             "per-slot page table.  0 disables "
                             "(default) — the serve programs keep their "
                             "adapter-free signatures, streams "
                             "bit-identical to today's.  >0 requires "
                             "the paged pool and is incompatible with "
                             "--spec-k; README §Serving/Adapters")
    parser.add_argument("--adapter-pool-pages", type=int, default=None,
                        help="usable pages in the adapter HBM pool "
                             "(page 0 is the pinned all-zero page; "
                             "unset sizes the pool from the HBM "
                             "headroom gate).  More distinct adapters "
                             "than pages churn by LRU eviction of cold "
                             "pages — never by recompiling")
    parser.add_argument("--adapter-dtype", type=str, default="model",
                        choices=["model", "int8"],
                        help="adapter pool storage tier; int8 stores "
                             "per-page-scaled deltas dequantized in "
                             "register inside the gathered matmul "
                             "(~1/4 the pool bytes at f32 model dtype)")
    parser.add_argument("--compile-cache", action="store_true",
                        help="enable JAX's persistent compilation cache "
                             "under the run dir (<obs-dir or "
                             "checkpoint-dir>/jax_cache) so repeat "
                             "serves skip recompiles of the prefill/"
                             "decode programs (parity with "
                             "trustworthy-dl-train)")
    parser.add_argument("--obs-dir", type=str, default=None,
                        help="write serving telemetry here: trace.jsonl "
                             "(request lifecycle events + spans "
                             "correlated by request id), "
                             "attribution.jsonl (per-request ledger: "
                             "slot/blocks/weight-tier/verdict), "
                             "slo_status.json, trace_events.json "
                             "(Chrome/Perfetto timeline) + metrics "
                             "snapshot/Prometheus export")
    parser.add_argument("--slo-ttft-ms", type=float, default=2000.0,
                        help="TTFT SLO target per request (needs "
                             "--obs-dir); breaches emit slo_breach "
                             "events, burn the tddl_slo_burn_rate gauge "
                             "and shed lowest-priority admissions")
    parser.add_argument("--slo-itl-ms", type=float, default=250.0,
                        help="inter-token-latency SLO target (needs "
                             "--obs-dir)")
    parser.add_argument("--fleet-replicas", type=int, default=1,
                        help="serve through a ServingFleet of N engine "
                             "replicas (replica lifecycle supervision, "
                             "trust-aware routing, request fail-over "
                             "with bounded retries, drain/quarantine; "
                             "README §Fleet).  1 = single engine "
                             "(default)")
    parser.add_argument("--pool-roles", type=str, default=None,
                        metavar="ROLE[,ROLE...]",
                        help="fleet only: disaggregate the replicas "
                             "into prefill/decode specialist pools — "
                             "one comma-separated role per replica "
                             "('prefill' or 'decode', at least one of "
                             "each; e.g. 'prefill,decode,decode').  New "
                             "requests prefill on a prefill specialist "
                             "and hand off to a decode specialist at "
                             "their first decode token as a LIVE KV "
                             "block-table migration; the autoscaler "
                             "(when on) scales each pool independently")
    parser.add_argument("--no-live-migration", action="store_true",
                        help="fleet only: disable live KV block-table "
                             "migration everywhere (drains run out, "
                             "failures replay from the prompt — the "
                             "pre-migration arcs; escape hatch and "
                             "bench A/B toggle)")
    parser.add_argument("--hedge-deadline-ms", type=float, default=None,
                        help="fleet only: launch a hedged duplicate on "
                             "a second replica when a request's "
                             "remaining deadline drops below this "
                             "(first completed attempt wins; the loser "
                             "is cancelled and recorded hedge_lost)")
    parser.add_argument("--vote-k", type=int, default=0,
                        help="fleet only: cross-replica verdict voting "
                             "— replay a SUSPECTED replica's completed "
                             "requests on this many other replicas and "
                             "majority-vote the streams token-for-token "
                             "(README §Fleet/'Adversarial scenarios'); "
                             "0 disables (default), >= 2 needed for "
                             "outvote quarantines")
    parser.add_argument("--vote-outvote-limit", type=int, default=2,
                        help="fleet only: outvoted verdicts before the "
                             "suspected replica enters the drain -> "
                             "quarantine ladder")
    parser.add_argument("--autoscale-min", type=int, default=None,
                        help="fleet only: autoscaler floor — enables "
                             "the closed-loop control plane (with "
                             "--autoscale-max): replica count breathes "
                             "between min and max from queue depth, "
                             "occupancy, ITL-p99 and SLO burn with "
                             "hysteresis; scale-up builds replicas "
                             "through the HBM headroom gate, "
                             "scale-down drains (in-flight runs out, "
                             "never killed).  --fleet-replicas is the "
                             "starting count and must sit inside "
                             "[min, max] (default min: --fleet-"
                             "replicas)")
    parser.add_argument("--autoscale-max", type=int, default=None,
                        help="fleet only: autoscaler ceiling (enables "
                             "autoscaling when > --fleet-replicas or "
                             "with --autoscale-min)")
    parser.add_argument("--tenant-quota", type=int, default=None,
                        help="fleet only: per-tenant token-bucket "
                             "capacity (a submission costs prompt + "
                             "max_new tokens against its tenant's "
                             "bucket; over-budget submissions are "
                             "throttled loudly — tenant_throttle "
                             "events + tddl_fleet_tenant_throttled_"
                             "total{tenant=} — so a flooding tenant "
                             "backpressures itself, not the fleet)")
    parser.add_argument("--tenant-quota-refill", type=float,
                        default=None,
                        help="fleet only: bucket refill in tokens per "
                             "fleet tick (default: capacity / 64)")
    parser.add_argument("--slo-class", action="append", default=None,
                        metavar="NAME:PRIO:TTFT_MS:ITL_MS:WEIGHT",
                        help="fleet only, repeatable: define an SLO "
                             "class (priority orders shedding — "
                             "higher sheds last; weight scales the "
                             "deficit-round-robin share; TTFT_MS/"
                             "ITL_MS are per-class targets, '-' = "
                             "untracked).  Workload tenant priorities "
                             "map onto the class ladder.  The single "
                             "value 'default' installs the built-in "
                             "batch/standard/premium ladder")
    parser.add_argument("--trace-max-bytes", type=int, default=0,
                        help="rotate trace.jsonl once it exceeds this "
                             "many bytes (trace.1.jsonl, ...; 0 = no "
                             "rotation; env TDDL_TRACE_MAX_BYTES is the "
                             "default)")
    parser.add_argument("--seed", type=int, default=0)
    return parser


def serve_main(argv: Optional[List[str]] = None,
               model_overrides: Optional[dict] = None) -> int:
    """Console entry point ``trustworthy-dl-serve``.

    Same checkpoint handling as ``trustworthy-dl-generate`` (dense GPT-2
    family; pipeline-stacked checkpoints refused with a clear message);
    ``model_overrides`` is the tests' shrink hook."""
    import jax
    import numpy as np

    from trustworthy_dl_tpu.core.config import ServeConfig, TrainingConfig
    from trustworthy_dl_tpu.engine.checkpoint import CheckpointManager
    from trustworthy_dl_tpu.engine.trainer import DistributedTrainer
    from trustworthy_dl_tpu.serve import ServeRequest, ServingEngine

    args = build_serve_parser().parse_args(argv)
    if not args.model.startswith("gpt") or args.model.endswith("-moe"):
        print("serving supports the dense GPT-2 family")
        return 2
    spec_k = 0 if args.no_spec_decode else args.spec_k
    if spec_k > args.max_new_tokens:
        # A draft deeper than the longest possible stream can never be
        # accepted past the budget — loud operator error, not silence.
        print(f"--spec-k {spec_k} exceeds --max-new-tokens "
              f"{args.max_new_tokens}: every draft past the request "
              "budget is discarded; lower --spec-k")
        return 2
    # Construction-time validation of the serving knobs (loud, before any
    # model init) — the dtype strings fail here, never at trace time.
    serve_config = ServeConfig(
        max_slots=args.max_slots, max_seq=args.max_seq,
        queue_limit=args.queue_limit,
        kv_dtype=args.kv_dtype, weight_dtype=args.weight_dtype,
        paged=not args.legacy_stripe, block_size=args.block_size,
        num_blocks=args.num_blocks,
        prefix_cache=not args.no_prefix_cache,
        prefill_chunk=args.prefill_chunk,
        spec_k=spec_k,
        adapter_rank=args.adapter_rank,
        adapter_pool_pages=args.adapter_pool_pages,
        adapter_dtype=args.adapter_dtype,
    )
    if args.compile_cache:
        import os

        from trustworthy_dl_tpu.utils.compile_cache import (
            enable_persistent_cache,
        )

        run_dir = args.obs_dir or args.checkpoint_dir
        enable_persistent_cache(os.path.join(run_dir, "jax_cache"))
    probe = CheckpointManager(args.checkpoint_dir)
    # verified=False: this probe only reads the topology sidecar to
    # refuse pipeline checkpoints — no reason to checksum the whole
    # payload here (load_checkpoint verifies on the actual restore).
    latest = probe.latest_step(verified=False)
    if latest is not None:
        meta = probe.load_metadata(latest) or {}
        if meta.get("parallelism") == "model":
            print("checkpoint was trained with pipeline (stage) "
                  "parallelism; serving needs a data-parallel checkpoint "
                  "(params stage-stacked)")
            return 2
    config = TrainingConfig(model_name=args.model, num_nodes=1, batch_size=1,
                            checkpoint_dir=args.checkpoint_dir)
    trainer = DistributedTrainer(config, model_overrides=model_overrides)
    cfg = trainer.model.config
    if args.max_seq > cfg.n_positions:
        print(f"--max-seq {args.max_seq} exceeds the model's "
              f"n_positions={cfg.n_positions}")
        return 2
    if args.prompt_len + args.max_new_tokens > args.max_seq:
        print(f"--prompt-len + --max-new-tokens = "
              f"{args.prompt_len + args.max_new_tokens} exceeds "
              f"--max-seq {args.max_seq}")
        return 2
    trainer.initialize()
    try:
        trainer.load_checkpoint()
        print(f"restored step {int(trainer.state.step)} "
              f"from {args.checkpoint_dir}")
    except FileNotFoundError:
        print(f"no checkpoint under {args.checkpoint_dir!r}; "
              "serving from random init")

    obs_session = None
    extra = {}
    if args.obs_dir:
        from trustworthy_dl_tpu.obs import ObsSession

        obs_session = ObsSession(args.obs_dir,
                                 trace_max_bytes=args.trace_max_bytes)
        obs_session.enable_spans()
        obs_session.open_ledger()
        # Forensics: every flight-dump-grade episode gets a paired
        # incident_NNN_<reason>.json (causal timeline + blast radius)
        # and a durable VERDICTS.jsonl trust-history row — what the
        # 'trustworthy-dl-obs incident' subcommands render offline.
        obs_session.enable_forensics()
        # Performance tier: compile watcher (the decode loop's
        # compile-once pin enforced live), HBM watermark gauges + the
        # pool headroom gate, cost ledger + perf fingerprint at exit.
        obs_session.enable_compile_watch()
        obs_session.enable_hbm()
    control_knobs = (args.autoscale_min is not None
                     or args.autoscale_max is not None
                     or args.tenant_quota is not None
                     or bool(args.slo_class))
    if args.fleet_replicas > 1 or control_knobs:
        # Fleet mode builds PER-REPLICA watchers from the SLO flags (a
        # breach is a replica-local signal) — the session-level watcher
        # pair stays uninstalled rather than sitting attached-but-unfed.
        # ANY control-plane knob routes here too (quotas, classes and
        # autoscaling live in the fleet's tick loop — a 1-replica fleet
        # enforces them fine, silently ignoring them would not).
        return _serve_fleet(args, trainer, cfg, serve_config, obs_session)
    if obs_session is not None:
        from trustworthy_dl_tpu.obs.slo import default_serve_rules

        obs_session.install_watchers(slo_rules=default_serve_rules(
            ttft_target_s=args.slo_ttft_ms / 1e3,
            itl_target_s=args.slo_itl_ms / 1e3,
        ))
        extra = dict(spans=obs_session.spans, ledger=obs_session.ledger,
                     slo=obs_session.slo, anomaly=obs_session.anomaly,
                     compilewatch=obs_session.compilewatch,
                     hbm=obs_session.hbm)
    tenant_names: list = []
    adapter_map = None
    if serve_config.adapter_rank > 0:
        # The smoke loop's synthetic traffic needs tenants for the
        # adapter tier to resolve: a Zipf-skewed tenant->adapter map
        # over more adapters than pool pages, so the run exercises
        # residency churn (LRU eviction, never recompiles).
        from trustworthy_dl_tpu.serve.workload import (
            make_tenant_population, zipf_adapter_assignments)

        tenant_names = [t.name for t in make_tenant_population(8)]
        n_adapters = (args.adapter_pool_pages or 4) + 1
        adapter_map = zipf_adapter_assignments(tenant_names, n_adapters,
                                               seed=args.seed)
    engine = ServingEngine.from_config(
        trainer.state.params, cfg, serve_config,
        enable_monitor=not args.no_monitor,
        rng=jax.random.PRNGKey(args.seed),
        trace=obs_session.trace if obs_session else None,
        registry=obs_session.registry if obs_session else None,
        adapter_map=adapter_map,
        **extra,
    )
    if engine.kv_fallback_reason:
        print(f"kv_dtype={args.kv_dtype} fell back to the model dtype "
              f"({engine.kv_fallback_reason})")
    rng = np.random.default_rng(args.seed)
    deadline = args.deadline_ms / 1e3 if args.deadline_ms else None
    submitted = 0
    for i in range(args.num_requests):
        plen = int(np.clip(rng.integers(max(args.prompt_len // 2, 1),
                                        args.prompt_len * 2 + 1),
                           1, args.max_seq - args.max_new_tokens))
        prompt = rng.integers(0, cfg.vocab_size, plen).tolist()
        new = int(rng.integers(1, args.max_new_tokens + 1))
        tenant = tenant_names[i % len(tenant_names)] \
            if tenant_names else None
        rid = engine.submit(ServeRequest(
            prompt=prompt, max_new_tokens=new,
            temperature=args.temperature, deadline_s=deadline,
            tenant=tenant,
        ))
        if rid is None:
            engine.run_until_idle()  # drain, then retry the arrival
            rid = engine.submit(ServeRequest(
                prompt=prompt, max_new_tokens=new,
                temperature=args.temperature, deadline_s=deadline,
                tenant=tenant,
            ))
        if rid is not None:
            submitted += 1
    engine.run_until_idle()
    summary = engine.metrics_summary()
    print(f"served {submitted} request(s) on {args.max_slots} slot(s)")
    for key in ("requests_completed", "requests_deadline_exceeded",
                "requests_flagged", "tokens_emitted", "tokens_per_s",
                "itl_p50_ms", "itl_p99_ms", "ttft_p50_ms",
                "peak_tokens_in_flight", "blocks_in_use",
                "prefix_hits", "prefix_hit_rate",
                "spec_k", "spec_proposed", "spec_accepted",
                "accepted_rate", "spec_near_tie_flips"):
        if key in summary:
            value = summary[key]
            shown = f"{value:.3f}" if isinstance(value, float) else value
            print(f"  {key}: {shown}")
    if summary.get("quarantined_slots"):
        print(f"  quarantined slots: {summary['quarantined_slots']}")
    adapters = summary.get("adapters")
    if adapters:
        print(f"  adapters: rank={adapters['rank']} "
              f"dtype={adapters['dtype']} pages={adapters['pages']} "
              f"resident={adapters['resident']} "
              f"hit_rate={adapters['hit_rate']:.3f} "
              f"evictions={adapters['evictions']} "
              f"uploads={adapters['uploads']}")
    if obs_session is not None:
        ok, problems = engine.verify_attribution()
        print(f"attribution: {engine.ledger.total} record(s), "
              f"block-lifecycle reconciliation "
              f"{'OK' if ok else 'FAILED'}")
        for p in problems[:5]:
            print(f"  !! {p}")
        if obs_session.slo.active:
            print(f"SLO breaches active: {obs_session.slo.active}")
        # Performance tier artifacts: per-program cost ledger into
        # obs_report.json, a final HBM sweep, and the compile-watch
        # verdict (zero storms = the compile-once pin held live).
        engine.analyze_programs(obs_session.cost_ledger)
        obs_session.hbm.sweep(emit=True)
        compiles = obs_session.compiles.summary()
        print(f"compiles: {compiles['total']} "
              f"({compiles['seconds']:.2f}s), decode storms: "
              f"{obs_session.compilewatch.storm_total}")
        obs_session.finalize()
        print(f"obs artifacts in {args.obs_dir}")
        _print_perf_verdict(obs_session)
    trainer.cleanup()
    return 0


def _parse_slo_classes(specs):
    """``--slo-class NAME:PRIO:TTFT_MS:ITL_MS:WEIGHT`` (repeatable;
    '-' leaves a latency target untracked; the single spec 'default'
    installs the built-in ladder).  Raises ValueError with the exact
    offending spec — an operator typo must fail before any model
    work."""
    if not specs:
        return None
    from trustworthy_dl_tpu.serve import DEFAULT_SLO_CLASSES, SLOClass

    if len(specs) == 1 and specs[0].strip().lower() == "default":
        return DEFAULT_SLO_CLASSES
    classes = []
    for spec in specs:
        parts = spec.split(":")
        if len(parts) != 5:
            raise ValueError(
                f"--slo-class {spec!r}: expected "
                "NAME:PRIO:TTFT_MS:ITL_MS:WEIGHT (use '-' for an "
                "untracked target), or the single value 'default'")
        name, prio, ttft, itl, weight = (p.strip() for p in parts)
        try:
            classes.append(SLOClass(
                name=name, priority=int(prio),
                ttft_target_s=(None if ttft in ("-", "")
                               else float(ttft) / 1e3),
                itl_target_s=(None if itl in ("-", "")
                              else float(itl) / 1e3),
                weight=float(weight),
            ))
        except ValueError as exc:
            raise ValueError(f"--slo-class {spec!r}: {exc}")
    return tuple(classes)


def _parse_autoscale(args):
    """--autoscale-min/--autoscale-max -> AutoscalerConfig (None when
    neither is given).  --fleet-replicas is the STARTING count and must
    sit inside the bounds."""
    if args.autoscale_min is None and args.autoscale_max is None:
        return None
    from trustworthy_dl_tpu.serve import AutoscalerConfig

    lo = (args.autoscale_min if args.autoscale_min is not None
          else args.fleet_replicas)
    hi = (args.autoscale_max if args.autoscale_max is not None
          else max(args.fleet_replicas, lo))
    if not lo <= args.fleet_replicas <= hi:
        raise ValueError(
            f"--fleet-replicas {args.fleet_replicas} must start inside "
            f"the autoscale bounds [{lo}, {hi}]")
    return AutoscalerConfig(
        min_replicas=lo, max_replicas=hi,
        scale_up_queue_per_replica=float(args.max_slots),
        scale_down_queue_per_replica=max(args.max_slots / 8.0, 0.5),
        itl_p99_target_s=(args.slo_itl_ms / 1e3
                          if args.obs_dir else None),
    )


def _serve_fleet(args, trainer, cfg, serve_config, obs_session) -> int:
    """The ``--fleet-replicas N`` serve path: a ServingFleet over the
    seeded workload generator (bursty arrivals, heavy-tailed lengths,
    tenant priority skew) — the smoke-deployment mirror of the
    single-engine loop."""
    import jax

    from trustworthy_dl_tpu.serve import (
        FleetConfig,
        ServeRequest,
        ServingFleet,
        WorkloadConfig,
        generate_workload,
    )
    from trustworthy_dl_tpu.serve.workload import replay_workload

    slo_rules = None
    if obs_session is not None:
        from trustworthy_dl_tpu.obs.slo import default_serve_rules

        # The SLO flags become PER-REPLICA watcher rules: each replica
        # sheds its own breached admissions and feeds its own
        # degraded-signal, instead of one fleet-wide watcher conflating
        # every replica's latency stream.
        slo_rules = default_serve_rules(
            ttft_target_s=args.slo_ttft_ms / 1e3,
            itl_target_s=args.slo_itl_ms / 1e3,
        )
    # Control plane knobs (serve/control.py), all opt-in.
    try:
        slo_classes = _parse_slo_classes(args.slo_class)
        autoscale = _parse_autoscale(args)
        tenant_quota = None
        if args.tenant_quota is not None:
            from trustworthy_dl_tpu.serve import TenantQuotaConfig

            refill = (args.tenant_quota_refill
                      if args.tenant_quota_refill is not None
                      else args.tenant_quota / 64.0)
            tenant_quota = TenantQuotaConfig(
                capacity_tokens=args.tenant_quota,
                refill_per_tick=refill)
        pool_roles = None
        if args.pool_roles:
            pool_roles = tuple(
                r.strip() for r in args.pool_roles.split(","))
    except ValueError as exc:
        print(f"control plane: {exc}")
        return 2
    adapter_map = None
    if serve_config.adapter_rank > 0:
        # Same adapter resolution as the single-engine path, over the
        # workload generator's own tenant population: Zipf-skewed onto
        # one more adapter than the pool holds, so the smoke run churns
        # residency (and a crashed replica's rebuilt pool re-creates
        # the same deterministic weights).
        from trustworthy_dl_tpu.serve.workload import (
            DEFAULT_TENANTS, zipf_adapter_assignments)

        n_adapters = (args.adapter_pool_pages or 4) + 1
        adapter_map = zipf_adapter_assignments(
            [t.name for t in DEFAULT_TENANTS], n_adapters,
            seed=args.seed)
    # One source of truth for the serving knobs: the SAME validated
    # ServeConfig the single-engine path uses, via from_config.
    fleet = ServingFleet.from_config(
        trainer.state.params, cfg, serve_config,
        fleet_config=FleetConfig(
            num_replicas=args.fleet_replicas,
            hedge_deadline_s=(args.hedge_deadline_ms / 1e3
                              if args.hedge_deadline_ms else None),
            vote_k=args.vote_k,
            vote_outvote_limit=args.vote_outvote_limit,
            slo_classes=slo_classes,
            tenant_quota=tenant_quota,
            autoscale=autoscale,
            pool_roles=pool_roles,
            live_migration=not args.no_live_migration,
        ),
        rng=jax.random.PRNGKey(args.seed),
        trace=obs_session.trace if obs_session else None,
        registry=obs_session.registry if obs_session else None,
        spans=obs_session.spans if obs_session else None,
        ledger=obs_session.ledger if obs_session else None,
        forensics=obs_session.forensics if obs_session else None,
        slo_rules=slo_rules,
        enable_monitor=not args.no_monitor,
        # Performance tier rides every replica build (and rebuild): the
        # decode loops share one compile watcher scope, and each
        # replica's pool allocation consults the HBM headroom gate.
        compilewatch=obs_session.compilewatch if obs_session else None,
        hbm=obs_session.hbm if obs_session else None,
        adapter_map=adapter_map,
    )
    workload = generate_workload(
        WorkloadConfig(seed=args.seed, num_requests=args.num_requests,
                       prompt_median=args.prompt_len,
                       output_median=max(args.max_new_tokens // 2, 1),
                       max_output=args.max_new_tokens),
        cfg.vocab_size, args.max_seq,
    )
    deadline = args.deadline_ms / 1e3 if args.deadline_ms else None
    submitted = replay_workload(fleet, workload, lambda item: ServeRequest(
        prompt=list(item.prompt), max_new_tokens=item.max_new_tokens,
        temperature=args.temperature, priority=item.priority,
        deadline_s=(deadline if deadline is not None
                    else item.deadline_s),
        tenant=item.tenant,
    ))
    if fleet.autoscaler is not None:
        # Give a trailing scale-down room to land: the replay exits at
        # drain, the controller breathes a beat later.
        for _ in range(64):
            fleet.step()
    summary = fleet.metrics_summary()
    print(f"fleet served {submitted} request(s) on "
          f"{args.fleet_replicas} replica(s) x {args.max_slots} slot(s)")
    for key in ("statuses", "completed_tokens", "replica_states", "ticks",
                "fleet_failovers", "fleet_migrations", "fleet_preempts",
                "fleet_hedges", "fleet_drains",
                "fleet_quarantines", "fleet_restarts",
                "fleet_suspicions", "fleet_votes", "fleet_outvotes",
                "fleet_tenant_floods", "fleet_throttles",
                "fleet_scale_ups", "fleet_scale_downs",
                "replicas_in_service", "replica_trace",
                "per_class", "class_queue_depth",
                "replica_suspicion", "replica_slo_active"):
        if key in summary:
            print(f"  {key}: {summary[key]}")
    if obs_session is not None:
        ok, problems = fleet.verify_attribution()
        print(f"attribution: {fleet.ledger.total} record(s), "
              f"fleet block-lifecycle reconciliation "
              f"{'OK' if ok else 'FAILED'}")
        for p in problems[:5]:
            print(f"  !! {p}")
        if fleet.replicas:
            fleet.replicas[0].engine.analyze_programs(
                obs_session.cost_ledger)
        obs_session.hbm.sweep(emit=True)
        print(f"compiles: {obs_session.compiles.summary()['total']}, "
              f"decode storms: {obs_session.compilewatch.storm_total}")
        obs_session.finalize()
        print(f"obs artifacts in {args.obs_dir}")
        _print_perf_verdict(obs_session)
    trainer.cleanup()
    return 0


def build_prepare_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="trustworthy-dl-prepare-data",
        description="Tokenize a raw .txt corpus into the loader's .bin "
                    "token memmap (byte-level BPE, trained on the corpus "
                    "or loaded from GPT-2-format vocab.json/merges.txt)",
    )
    parser.add_argument("txt", type=str, help="input UTF-8 text file")
    parser.add_argument("--out", type=str, default=None,
                        help="output .bin path (default: alongside input)")
    parser.add_argument("--vocab-size", type=int, default=8192)
    parser.add_argument("--tokenizer-dir", type=str, default=None,
                        help="directory holding (or to receive) "
                             "vocab.json + merges.txt")
    parser.add_argument("--val-fraction", type=float, default=0.0,
                        help="also write a *_val.bin holdout split")
    return parser


def prepare_main(argv: Optional[List[str]] = None) -> int:
    """Console entry point ``trustworthy-dl-prepare-data`` — the offline
    .txt → .bin pipeline (experiment_runner.py:100-110 parity: the
    'openwebtext' tier works from raw text with no external tooling)."""
    import os

    from trustworthy_dl_tpu.data.tokenizer import prepare_data

    args = build_prepare_parser().parse_args(argv)
    if not os.path.exists(args.txt):
        print(f"no such file: {args.txt}")
        return 2
    info = prepare_data(args.txt, out_path=args.out,
                        vocab_size=args.vocab_size,
                        tokenizer_dir=args.tokenizer_dir,
                        val_fraction=args.val_fraction)
    print(f"wrote {info['num_tokens']} tokens (vocab {info['vocab_size']}) "
          f"to {info['out_path']}"
          + (f" + val split {info['val_path']}" if info["val_path"] else ""))
    print(f"tokenizer files in {info['tokenizer_dir']}")
    return 0


def build_obs_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="trustworthy-dl-obs",
        description="Render an obs directory: tail/filter trace.jsonl by "
                    "request/step id (rotated trace.N.jsonl segments are "
                    "walked in order), convert spans to a Chrome/Perfetto "
                    "timeline, pretty-print obs_report.json and the "
                    "SLO/anomaly status.  With no action flags, prints a "
                    "summary of everything the directory holds.  The "
                    "'diff' subcommand (trustworthy-dl-obs diff A B) "
                    "renders two obs_report/perf-ledger artifacts side "
                    "by side with deltas; the 'incident' subcommand "
                    "(trustworthy-dl-obs incident list|show|blast) "
                    "renders assembled incident forensics.",
    )
    parser.add_argument("obs_dir", type=str,
                        help="directory a run wrote with --obs-dir")
    parser.add_argument("--tail", type=int, default=None, metavar="N",
                        help="print the last N trace events (after any "
                             "filters)")
    parser.add_argument("--request-id", type=int, default=None,
                        help="only events correlated to this request id")
    parser.add_argument("--step", type=int, default=None,
                        help="only events correlated to this step id")
    parser.add_argument("--type", type=str, default=None,
                        help="only events of this type (e.g. span, "
                             "anomaly, serve_retire)")
    parser.add_argument("--chrome", type=str, default=None, metavar="OUT",
                        help="convert the trace's span events to a Chrome/"
                             "Perfetto trace_events JSON at OUT (load in "
                             "chrome://tracing or ui.perfetto.dev)")
    parser.add_argument("--report", action="store_true",
                        help="pretty-print obs_report.json (step-time "
                             "breakdown + MFU)")
    parser.add_argument("--slo", action="store_true",
                        help="print SLO burn rates / anomaly status "
                             "(slo_status.json + snapshot gauges)")
    return parser


def obs_main(argv: Optional[List[str]] = None) -> int:
    """Console entry point ``trustworthy-dl-obs`` — the reader side of
    the obs directory (host-only; imports no jax)."""
    import json
    import os
    import sys as _sys

    from trustworthy_dl_tpu.obs.events import read_jsonl_rotated
    from trustworthy_dl_tpu.obs.spans import chrome_trace_from_events

    if argv is None:
        argv = _sys.argv[1:]
    if argv and argv[0] == "diff":
        return _obs_diff(argv[1:])
    if argv and argv[0] == "incident":
        return _obs_incident(argv[1:])
    args = build_obs_parser().parse_args(argv)
    if not os.path.isdir(args.obs_dir):
        print(f"no such obs directory: {args.obs_dir}")
        return 2
    trace_path = os.path.join(args.obs_dir, "trace.jsonl")
    # Rotated segments (trace.1.jsonl, ...) are walked oldest-first so a
    # size-capped long run reads exactly like an uncapped one.
    events = read_jsonl_rotated(trace_path)

    filtered = events
    if args.request_id is not None:
        filtered = [e for e in filtered
                    if e.get("request_id") == args.request_id]
    if args.step is not None:
        filtered = [e for e in filtered if e.get("step") == args.step]
    if args.type is not None:
        filtered = [e for e in filtered if e.get("type") == args.type]

    acted = False
    if args.tail is not None or args.request_id is not None \
            or args.step is not None or args.type is not None:
        acted = True
        for e in filtered[-(args.tail or 20):]:
            print(json.dumps(e))
    if args.chrome is not None:
        acted = True
        payload = chrome_trace_from_events(events, args.chrome)
        print(f"wrote {len(payload['traceEvents'])} span event(s) to "
              f"{args.chrome}")
    if args.report:
        acted = True
        path = os.path.join(args.obs_dir, "obs_report.json")
        if os.path.exists(path):
            with open(path) as f:
                print(json.dumps(json.load(f), indent=2))
        else:
            print(f"no obs_report.json under {args.obs_dir}")
    if args.slo:
        acted = True
        _print_slo_status(args.obs_dir)
    if not acted:
        _print_obs_summary(args.obs_dir, events)
    return 0


def _obs_diff(argv: List[str]) -> int:
    """``trustworthy-dl-obs diff A B`` — two obs artifact sets side by
    side (obs dirs, obs_report.json files, or PERF_LEDGER.jsonl files;
    host-only, imports no jax)."""
    import argparse as _argparse

    from trustworthy_dl_tpu.obs.sentinel import (
        load_perf_artifact,
        render_diff,
    )

    parser = _argparse.ArgumentParser(
        prog="trustworthy-dl-obs diff",
        description="Pretty-print two obs_report/perf-ledger artifacts "
                    "side by side: step time, phase fractions, MFU "
                    "(nominal + analyzed), per-program FLOPs/temp "
                    "bytes, compile counts, HBM watermark — with "
                    "relative deltas.",
    )
    parser.add_argument("a", type=str, help="first artifact (obs dir, "
                                            "obs_report.json, or "
                                            "PERF_LEDGER.jsonl)")
    parser.add_argument("b", type=str, help="second artifact")
    args = parser.parse_args(argv)
    try:
        view_a = load_perf_artifact(args.a)
        view_b = load_perf_artifact(args.b)
    except FileNotFoundError as exc:
        print(f"diff: {exc}")
        return 2
    print(render_diff(view_a, view_b))
    return 0


def _obs_incident(argv: List[str]) -> int:
    """``trustworthy-dl-obs incident list|show|blast`` — render the
    forensic incident artifacts a run assembled next to its flight
    dumps (obs/forensics.py; host-only, imports no jax)."""
    import argparse as _argparse

    from trustworthy_dl_tpu.obs.forensics import (
        find_incident,
        load_incidents,
        render_blast,
        render_incident,
    )

    parser = _argparse.ArgumentParser(
        prog="trustworthy-dl-obs incident",
        description="Offline incident forensics: 'list' the assembled "
                    "incident_NNN_<reason>.json reports in a directory, "
                    "'show' one causal timeline (trigger event -> "
                    "contributing signals -> actions taken, each with "
                    "trace seq ids), or 'blast' one blast radius (every "
                    "request that decoded off the suspect's KV blocks "
                    "or adapter page, with per-journal block sets).",
    )
    parser.add_argument("action", choices=("list", "show", "blast"))
    parser.add_argument("ident", nargs="?", default=None,
                        help="incident id, bare index, or reason "
                             "substring (show/blast)")
    parser.add_argument("--dir", dest="directory", default=".",
                        help="directory holding the incident artifacts "
                             "(an obs dir or a checkpoint dir; "
                             "default: cwd)")
    args = parser.parse_args(argv)
    if args.action == "list":
        incidents = load_incidents(args.directory)
        if not incidents:
            print(f"no incident artifacts under {args.directory}")
            return 0
        for inc in incidents:
            radius = inc.get("blast_radius") or {}
            print(f"{inc.get('incident_id'):<40} "
                  f"tick={str(inc.get('tick')):<6} "
                  f"suspects={inc.get('suspect_replicas')} "
                  f"actions={len(inc.get('actions') or [])} "
                  f"blast={len(radius.get('requests') or [])}")
        return 0
    if args.ident is None:
        print(f"incident {args.action}: an incident id (or index, or "
              f"reason substring) is required")
        return 2
    inc = find_incident(args.directory, args.ident)
    if inc is None:
        print(f"no incident matching {args.ident!r} under "
              f"{args.directory}")
        return 2
    print(render_incident(inc) if args.action == "show"
          else render_blast(inc))
    return 0


def _print_slo_status(obs_dir: str) -> None:
    import json
    import os

    path = os.path.join(obs_dir, "slo_status.json")
    if os.path.exists(path):
        with open(path) as f:
            status = json.load(f)
        for rule in status.get("slo", {}).get("rules", ()):
            flag = " BREACHED" if rule["active"] else ""
            print(f"  slo {rule['name']:<12} ({rule['signal']} <= "
                  f"{rule['target']:g}): burn {rule['burn_rate']:.2f}"
                  f"{flag}")
        anomaly = status.get("anomaly", {})
        if anomaly:
            print(f"  anomaly events: {anomaly.get('event_total', 0)}, "
                  f"active: {anomaly.get('active', [])}")
        return
    # Fall back to the burn-rate gauges in the metrics snapshot (a run
    # that died before finalize still snapshotted on cadence).
    snap_path = os.path.join(obs_dir, "metrics_snapshot.json")
    if not os.path.exists(snap_path):
        print(f"  no slo_status.json or metrics_snapshot.json under "
              f"{obs_dir}")
        return
    with open(snap_path) as f:
        snap = json.load(f)
    for name in ("tddl_slo_burn_rate", "tddl_anomaly_active"):
        metric = snap.get("metrics", {}).get(name)
        if not metric:
            continue
        for row in metric.get("series", ()):
            labels = ",".join(f"{k}={v}" for k, v in row["labels"].items())
            print(f"  {name}{{{labels}}} = {row['value']}")


def _print_obs_summary(obs_dir: str, events: list) -> None:
    import json
    import os

    print(f"obs dir: {obs_dir}")
    counts: dict = {}
    for e in events:
        counts[e.get("type", "?")] = counts.get(e.get("type", "?"), 0) + 1
    if counts:
        print(f"trace.jsonl: {len(events)} event(s)")
        for etype, n in sorted(counts.items()):
            print(f"  {etype}: {n}")
    report_path = os.path.join(obs_dir, "obs_report.json")
    if os.path.exists(report_path):
        with open(report_path) as f:
            report = json.load(f)
        line = f"obs_report.json: {report.get('num_steps', 0)} step(s)"
        mfu = report.get("mfu", {})
        if isinstance(mfu, dict) and mfu.get("mfu") is not None:
            line += f", MFU {mfu['mfu']:.1%} ({mfu['peak_flops_source']})"
        print(line)
    ledger_path = os.path.join(obs_dir, "attribution.jsonl")
    if os.path.exists(ledger_path):
        from trustworthy_dl_tpu.obs.attribution import read_ledger

        _, records = read_ledger(ledger_path)
        flagged = sum(1 for r in records if r.get("flagged"))
        print(f"attribution.jsonl: {len(records)} record(s), "
              f"{flagged} flagged")
    _print_slo_status(obs_dir)
    dumps = sorted(p for p in os.listdir(obs_dir)
                   if p.startswith("flight_") and p.endswith(".json"))
    if dumps:
        print(f"flight dumps: {', '.join(dumps)}")
    incidents = sorted(p for p in os.listdir(obs_dir)
                       if p.startswith("incident_")
                       and p.endswith(".json"))
    if incidents:
        print(f"incidents: {', '.join(incidents)} "
              f"(render with 'trustworthy-dl-obs incident "
              f"list --dir {obs_dir}')")
    verdicts_path = os.path.join(obs_dir, "VERDICTS.jsonl")
    if os.path.exists(verdicts_path):
        from trustworthy_dl_tpu.obs.verdicts import VerdictStore

        rows = VerdictStore(verdicts_path).read()
        kinds: dict = {}
        for row in rows:
            key = f"{row.get('kind')}:{row.get('outcome')}"
            kinds[key] = kinds.get(key, 0) + 1
        print(f"VERDICTS.jsonl: {len(rows)} row(s)"
              + (" — " + ", ".join(f"{k}={n}" for k, n in
                                   sorted(kinds.items()))
                 if kinds else ""))


if __name__ == "__main__":
    raise SystemExit(main())

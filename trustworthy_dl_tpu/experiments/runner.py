"""ExperimentRunner — the L5 experiment layer, driving the REAL trainer.

The reference's runner never calls its own trainer: ``_training_step``
fabricates a loss curve (experiment_runner.py:201-216), system metrics are
random draws (:262-268), and the trust-evolution plot is simulated
(:407-425).  Here every artifact derives from recorded state: per-step
losses and trust trajectories come from the trainer's MetricsCollector,
detection events from ``trainer.attack_history``, and — because the fault
injection is ground-truth-controlled — the report can state real detection
precision/recall and time-to-detection, numbers the reference could only
simulate.

Artifact contract (parity with experiment_runner.py:325-359,521-591):
``results/<name>/`` gets experiment_results.json, training_metrics.csv,
four PNGs (training_loss, trust_evolution, attack_impact, system_metrics),
experiment_report.md, and intermediate_epoch_N.json every 5 epochs.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np

from trustworthy_dl_tpu.attacks.adversarial import AdversarialAttacker, \
    null_plan
from trustworthy_dl_tpu.core.config import (
    AttackConfig,
    ExperimentConfig,
    TrainingConfig,
)
from trustworthy_dl_tpu.data import get_dataloader
from trustworthy_dl_tpu.utils.io import atomic_write_json, \
    atomic_write_text
from trustworthy_dl_tpu.engine.trainer import DistributedTrainer

logger = logging.getLogger(__name__)


def _jsonable(obj: Any) -> Any:
    """Best-effort conversion for json.dump(default=...)."""
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    return str(obj)


class ExperimentRunner:
    """Orchestrates a full experiment: real training under controlled fault
    injection, metric recording, artifact generation."""

    def __init__(self, config: ExperimentConfig,
                 model_overrides: Optional[Dict[str, Any]] = None,
                 data_overrides: Optional[Dict[str, Any]] = None):
        self.config = config
        self.output_dir = Path(config.output_dir) / config.experiment_name
        self.output_dir.mkdir(parents=True, exist_ok=True)
        self.training_config = config.to_training_config()
        self.model_overrides = dict(model_overrides or {})
        self.data_overrides = dict(data_overrides or {})

        self.trainer: Optional[DistributedTrainer] = None
        self.attacker: Optional[AdversarialAttacker] = None
        self.train_loader = None
        self.val_loader = None
        self.epoch_records: List[Dict[str, Any]] = []
        self._step_records_cache: Optional[List[Dict[str, Any]]] = None
        self.obs: Optional[Any] = None
        logger.info("ExperimentRunner initialized: %s", config.experiment_name)

    # ------------------------------------------------------------------
    # Setup / run
    # ------------------------------------------------------------------

    def setup_experiment(self) -> None:
        self.trainer = DistributedTrainer(
            self.training_config, model_overrides=self.model_overrides
        )
        # Unified telemetry: every experiment run carries a trace, a
        # metrics snapshot and the step-time/MFU report under
        # <output_dir>/obs, and experiment_results.json embeds the report.
        from trustworthy_dl_tpu.obs import ObsSession

        self.obs = ObsSession(str(self.output_dir / "obs"))
        self.trainer.attach_obs(self.obs)
        if self.config.attack_enabled:
            attack_config = AttackConfig(
                attack_types=list(self.config.attack_types),
                target_nodes=[
                    n for n in self.config.target_nodes
                    if n < self.config.num_nodes
                ],
                intensity=self.config.attack_intensity,
                start_step=self.config.attack_start_epoch
                * self.config.steps_per_epoch,
            )
            self.attacker = AdversarialAttacker(attack_config)

        # steps_per_epoch governs the epoch length (it also anchors the
        # attack start step above), unless the caller pins num_examples.
        loader_kwargs = dict(self.data_overrides)
        train_kwargs = dict(loader_kwargs)
        train_kwargs.setdefault(
            "num_examples",
            self.config.batch_size * self.config.steps_per_epoch,
        )
        val_kwargs = dict(loader_kwargs)
        val_kwargs.setdefault(
            "num_examples",
            max(self.config.batch_size,
                train_kwargs["num_examples"] // 10),
        )
        self.train_loader = get_dataloader(
            self.config.dataset_name, split="train",
            batch_size=self.config.batch_size, **train_kwargs,
        )
        self.val_loader = get_dataloader(
            self.config.dataset_name, split="validation",
            batch_size=self.config.batch_size, **val_kwargs,
        )
        self.trainer.initialize()
        logger.info("Experiment setup completed")

    def run_experiment(self) -> Dict[str, Any]:
        logger.info("Starting experiment: %s", self.config.experiment_name)
        start_time = time.time()
        try:
            if self.trainer is None:
                self.setup_experiment()
            self._run_training_with_monitoring()
            final_results = self._collect_final_results()
            final_results["experiment_time_s"] = time.time() - start_time
            self._save_results(final_results)
            self._generate_visualizations()
            self._generate_experiment_report(final_results)
            logger.info("Experiment completed in %.2f seconds",
                        final_results["experiment_time_s"])
            return final_results
        except Exception:
            logger.exception("Experiment failed")
            raise
        finally:
            self._cleanup()

    def _run_training_with_monitoring(self) -> None:
        for epoch in range(self.config.num_epochs):
            epoch_start = time.time()
            if (self.config.attack_enabled and self.attacker
                    and epoch >= self.config.attack_start_epoch
                    and (self.config.attack_end_epoch is None
                         or epoch < self.config.attack_end_epoch)
                    and not self.attacker.is_active()):
                self.attacker.activate_attacks()
                # plan_for: targets are ORIGINAL identities; a
                # pre-activation eviction means coordinate != identity.
                # target_ids carries identities that are currently
                # off-mesh so a readmission during the attack window
                # re-attacks them.
                self.trainer.set_attack_plan(
                    self.attacker.plan_for(self.trainer.node_map),
                    target_ids=self.attacker.config.target_nodes,
                )
            if (self.attacker and self.attacker.is_active()
                    and self.config.attack_end_epoch is not None
                    and epoch >= self.config.attack_end_epoch):
                # Transient attack over: the recovery/readmission story
                # (probation + elastic readmission) plays out from here.
                self.attacker.deactivate_attacks()
                self.trainer.set_attack_plan(
                    null_plan(self.trainer.config.num_nodes)
                )
            epoch_loss = self.trainer.train_epoch(self.train_loader, epoch)
            val_loss = (self.trainer.validate(self.val_loader)
                        if self.val_loader is not None else None)
            self.epoch_records.append(
                self._epoch_snapshot(epoch, epoch_loss, val_loss,
                                     time.time() - epoch_start)
            )
            logger.info("Epoch %d/%d - loss %.4f - %.2fs", epoch + 1,
                        self.config.num_epochs, epoch_loss,
                        time.time() - epoch_start)
            if (epoch + 1) % 5 == 0:
                path = self.output_dir / f"intermediate_epoch_{epoch}.json"
                atomic_write_json(path, self.epoch_records,
                                  default=_jsonable)

    def _epoch_snapshot(self, epoch: int, train_loss: float,
                        val_loss: Optional[float], epoch_time: float
                        ) -> Dict[str, Any]:
        """Real per-epoch state — every value observed, none simulated."""
        tm = self.trainer.trust_manager
        n = self.config.num_nodes
        snapshot = {
            "epoch": epoch,
            "timestamp": time.time(),
            "training_loss": train_loss,
            "epoch_time_s": epoch_time,
            "trust_scores": {i: tm.get_trust_score(i) for i in range(n)},
            "node_statuses": {
                i: tm.get_node_status(i).name.lower() for i in range(n)
            },
            "system_trust": tm.calculate_system_trust(),
            "attacks_detected_so_far": len(self.trainer.attack_history),
            "reassignments_so_far": len(self.trainer.reassignment_history),
            # Elastic topology timeline: live coordinate count and the
            # identities they carry (evictions shrink it, readmissions
            # grow it back).
            "live_nodes": self.trainer.config.num_nodes,
            "node_map": list(self.trainer.node_map),
            "readmissions_so_far": self._count_records("readmitted_nodes"),
            "system_metrics": self._system_metrics(),
        }
        if val_loss is not None:
            snapshot["validation_loss"] = val_loss
        if self.attacker is not None:
            snapshot["attack_metrics"] = self.attacker.get_attack_statistics()
        return snapshot

    def _count_records(self, key: str) -> int:
        """Reassignment-history records of one kind (eviction records
        carry 'evicted_nodes', readmissions 'readmitted_nodes')."""
        return sum(
            1 for r in self.trainer.reassignment_history if key in r
        )

    def _system_metrics(self) -> Dict[str, Any]:
        """Measured system metrics (the reference simulated these,
        experiment_runner.py:262-274)."""
        out: Dict[str, Any] = {}
        stats = self.trainer.metrics_collector.step_time_stats()
        if stats:
            out["step_time"] = stats
            per_step = stats["mean_s"]
            if per_step > 0:
                out["samples_per_sec"] = self.config.batch_size / per_step
        try:
            import jax

            mem = jax.local_devices()[0].memory_stats()
            if mem:
                out["device_memory_bytes_in_use"] = int(
                    mem.get("bytes_in_use", 0)
                )
                limit = int(mem.get("bytes_limit", 0))
                if limit:
                    out["device_memory_utilization"] = (
                        out["device_memory_bytes_in_use"] / limit
                    )
        except Exception:  # memory_stats unsupported on some backends
            pass
        return out

    # ------------------------------------------------------------------
    # Results
    # ------------------------------------------------------------------

    def _detection_quality(self) -> Dict[str, Any]:
        """Ground-truth detection quality — possible because the fault
        injection is ours: configured targets vs detected nodes."""
        detected = {rec["node_id"] for rec in self.trainer.attack_history}
        if not self.config.attack_enabled:
            return {
                "attack_enabled": False,
                "false_positive_nodes": sorted(detected),
                "false_positive_rate": len(detected)
                / max(self.config.num_nodes, 1),
            }
        targets = {n for n in self.config.target_nodes
                   if n < self.config.num_nodes}
        tp = detected & targets
        fp = detected - targets
        start_step = (self.config.attack_start_epoch
                      * self.config.steps_per_epoch)
        detection_steps = {
            rec["node_id"]: rec["step"] - start_step
            for rec in reversed(self.trainer.attack_history)
            if rec["node_id"] in tp
        }
        return {
            "attack_enabled": True,
            "target_nodes": sorted(targets),
            "detected_nodes": sorted(detected),
            "true_positives": sorted(tp),
            "false_positives": sorted(fp),
            "missed": sorted(targets - detected),
            "precision": len(tp) / len(detected) if detected else None,
            "recall": len(tp) / len(targets) if targets else None,
            "steps_to_detection": detection_steps,
        }

    def _collect_final_results(self) -> Dict[str, Any]:
        trust_stats = self.trainer.trust_manager.get_trust_statistics()
        attack_stats = (self.attacker.get_final_statistics()
                        if self.attacker else {})
        losses = [r["training_loss"] for r in self.epoch_records]
        summary = {
            "total_epochs": len(self.epoch_records),
            "total_steps": self.trainer.global_step,
            "average_loss": float(np.mean(losses)) if losses else None,
            "final_loss": losses[-1] if losses else None,
            "loss_reduction": (
                (losses[0] - losses[-1]) / losses[0]
                if len(losses) > 1 and losses[0] else None
            ),
            "final_system_trust":
                self.trainer.trust_manager.calculate_system_trust(),
            "compromised_nodes": sorted(
                self.trainer.trust_manager.get_compromised_nodes()
            ),
            "total_attacks_detected": len(self.trainer.attack_history),
            "total_reassignments": len(self.trainer.reassignment_history),
            "total_evictions": self._count_records("evicted_nodes"),
            "total_readmissions": self._count_records("readmitted_nodes"),
            "final_live_nodes": self.trainer.config.num_nodes,
            "recovered_nodes": sorted({
                nid for r in self.trainer.reassignment_history
                if "readmitted_nodes" in r for nid in r["readmitted_nodes"]
                if nid in self.trainer.node_map
            }),
            "detection_quality": self._detection_quality(),
        }
        from trustworthy_dl_tpu.obs.meta import run_metadata

        return {
            "experiment_config": dataclasses.asdict(self.config),
            "training_config": dataclasses.asdict(self.training_config),
            "run_metadata": run_metadata(),
            "epoch_records": self.epoch_records,
            "attack_history": self.trainer.attack_history,
            "reassignment_history": self.trainer.reassignment_history,
            "final_trust_statistics": trust_stats,
            "final_attack_statistics": attack_stats,
            "training_stats": self.trainer.get_training_stats(),
            "experiment_summary": summary,
            # Step-time breakdown + MFU for THIS run (obs/report.py);
            # the standalone copy lands at <output_dir>/obs/.
            "obs_report": (self.obs.step_timer.report()
                           if self.obs is not None else None),
        }

    def _step_records(self) -> List[Dict[str, Any]]:
        """Per-step records (loss + per-node trust), computed once.
        Plain dicts — the runner must work on a base install (pandas is an
        optional extra)."""
        if getattr(self, "_step_records_cache", None) is None:
            records = []
            for m in self.trainer.metrics_collector.batch_metrics:
                row = {"step": m.get("step"), "epoch": m.get("epoch"),
                       "loss": m.get("loss"), "timestamp": m.get("timestamp")}
                for node, score in (m.get("trust_scores") or {}).items():
                    row[f"trust_node_{node}"] = score
                records.append(row)
            self._step_records_cache = records
        return self._step_records_cache

    def _save_results(self, results: Dict[str, Any]) -> None:
        import csv

        atomic_write_json(self.output_dir / "experiment_results.json",
                          results, default=_jsonable)
        records = self._step_records()
        if records:
            fields = list(records[0].keys())
            for r in records[1:]:
                for k in r:
                    if k not in fields:
                        fields.append(k)
            import io as _io

            buf = _io.StringIO(newline="")
            writer = csv.DictWriter(buf, fieldnames=fields)
            writer.writeheader()
            writer.writerows(records)
            atomic_write_text(
                self.output_dir / "training_metrics.csv", buf.getvalue())
        logger.info("Results saved to %s", self.output_dir)

    # ------------------------------------------------------------------
    # Visualizations — all from recorded data
    # ------------------------------------------------------------------

    def _generate_visualizations(self) -> None:
        try:
            import matplotlib

            matplotlib.use("Agg")
        except ImportError:
            logger.warning("matplotlib unavailable; skipping plots")
            return
        self._plot_training_loss()
        self._plot_trust_evolution()
        self._plot_attack_impact()
        self._plot_system_metrics()
        if self.trainer.reassignment_history:
            # Elastic runs only: the topology actually changed (the
            # history catches even an evict+readmit that reverts within
            # one epoch, which per-epoch snapshots would miss).
            self._plot_topology_timeline()
        logger.info("Visualizations saved to %s", self.output_dir)

    def _plot_topology_timeline(self) -> None:
        """Live-coordinate count per epoch with eviction/readmission
        markers — the elastic lifecycle at a glance (recovery
        experiments)."""
        import matplotlib.pyplot as plt

        epochs = [r["epoch"] for r in self.epoch_records]
        live = [r["live_nodes"] for r in self.epoch_records]
        fig, ax = plt.subplots(figsize=(10, 5))
        ax.step(epochs, live, where="post", linewidth=2)
        ax.set_ylim(0, self.config.num_nodes + 1)
        ax.set_xlabel("epoch")
        ax.set_ylabel("live mesh coordinates")
        ax.set_title("Elastic Topology Timeline")
        steps_per = max(self.config.steps_per_epoch, 1)
        for rec in self.trainer.reassignment_history:
            x = rec.get("step", 0) / steps_per
            if "evicted_nodes" in rec:
                ax.axvline(x, color="tab:red", linestyle="--", alpha=0.7)
                ax.annotate(f"evict {rec['evicted_nodes']}", (x, 0.5),
                            rotation=90, fontsize=8, color="tab:red")
            elif "readmitted_nodes" in rec:
                ax.axvline(x, color="tab:green", linestyle="--", alpha=0.7)
                ax.annotate(f"readmit {rec['readmitted_nodes']}", (x, 0.5),
                            rotation=90, fontsize=8, color="tab:green")
        fig.tight_layout()
        fig.savefig(self.output_dir / "topology_timeline.png", dpi=120)
        plt.close(fig)

    def _plot_training_loss(self) -> None:
        import matplotlib.pyplot as plt

        records = self._step_records()
        if not records:
            return
        steps = np.array([r["step"] for r in records])
        losses = np.array([r["loss"] for r in records], dtype=float)
        plt.figure(figsize=(12, 6))
        plt.plot(steps, losses, alpha=0.6, label="per-step loss")
        if len(losses) > 10:
            window = min(20, max(len(losses) // 5, 2))
            kernel = np.ones(window) / window
            ma = np.convolve(losses, kernel, mode="valid")
            plt.plot(steps[window - 1:], ma, linewidth=2,
                     label=f"moving average ({window})")
        self._mark_attack_start(plt)
        plt.xlabel("Step")
        plt.ylabel("Loss")
        plt.title("Training Loss (recorded)")
        plt.legend()
        plt.grid(True, alpha=0.3)
        plt.savefig(self.output_dir / "training_loss.png", dpi=150,
                    bbox_inches="tight")
        plt.close()

    def _plot_trust_evolution(self) -> None:
        import matplotlib.pyplot as plt

        records = self._step_records()
        if not records:
            return
        trust_cols = sorted(
            {k for r in records for k in r if k.startswith("trust_node_")},
            key=lambda c: int(c.rsplit("_", 1)[1]),
        )
        if not trust_cols:
            return
        steps = np.array([r["step"] for r in records])
        plt.figure(figsize=(12, 8))
        targets = set(self.config.target_nodes) if (
            self.config.attack_enabled) else set()
        for col in trust_cols:
            node = int(col.rsplit("_", 1)[1])
            style = "--" if node in targets else "-"
            series = np.array([r.get(col, np.nan) for r in records],
                              dtype=float)
            plt.plot(steps, series, style, linewidth=2,
                     label=f"node {node}" + (" (target)" if node in targets
                                             else ""))
        self._mark_attack_start(plt)
        plt.axhline(self.config.trust_threshold, color="grey", alpha=0.5,
                    label="trust threshold")
        plt.xlabel("Step")
        plt.ylabel("Trust score")
        plt.title("Trust Score Evolution by Node (recorded)")
        plt.legend(ncol=2, fontsize=8)
        plt.grid(True, alpha=0.3)
        plt.ylim(0, 1.05)
        plt.savefig(self.output_dir / "trust_evolution.png", dpi=150,
                    bbox_inches="tight")
        plt.close()

    def _mark_attack_start(self, plt) -> None:
        if self.config.attack_enabled:
            start = (self.config.attack_start_epoch
                     * self.config.steps_per_epoch)
            plt.axvline(start, color="red", alpha=0.4, linestyle=":",
                        label="attack start")

    def _plot_attack_impact(self) -> None:
        """2×2: detections over time, per-node detections, system trust,
        attack timeline — real events, not the reference's synthetic ramps
        (experiment_runner.py:427-451)."""
        import matplotlib.pyplot as plt

        fig, axes = plt.subplots(2, 2, figsize=(15, 10))
        steps = [r["step"] for r in self.trainer.attack_history]
        max_step = max(self.trainer.global_step, 1)

        grid = np.arange(0, max_step + 1)
        cumulative = np.searchsorted(np.sort(steps), grid, side="right")
        axes[0, 0].plot(grid, cumulative, linewidth=2)
        axes[0, 0].set_title("Cumulative Detections")
        axes[0, 0].set_ylabel("incidents")

        nodes = [r["node_id"] for r in self.trainer.attack_history]
        counts = np.bincount(nodes, minlength=self.config.num_nodes) if nodes \
            else np.zeros(self.config.num_nodes)
        axes[0, 1].bar(range(self.config.num_nodes), counts)
        axes[0, 1].set_title("Detections per Node")
        axes[0, 1].set_xlabel("node")
        axes[0, 1].set_ylabel("incidents")

        epochs = [r["epoch"] for r in self.epoch_records]
        axes[1, 0].plot(epochs,
                        [r["system_trust"] for r in self.epoch_records],
                        linewidth=2)
        axes[1, 0].set_title("System Trust")
        axes[1, 0].set_xlabel("epoch")
        axes[1, 0].set_ylim(0, 1.05)

        active = [
            1 if (self.config.attack_enabled
                  and e >= self.config.attack_start_epoch) else 0
            for e in epochs
        ]
        axes[1, 1].fill_between(epochs, active, alpha=0.3, color="red",
                                label="attack period")
        axes[1, 1].set_title("Attack Timeline")
        axes[1, 1].set_xlabel("epoch")
        axes[1, 1].legend()

        for ax in axes.flat:
            ax.grid(True, alpha=0.3)
        plt.tight_layout()
        plt.savefig(self.output_dir / "attack_impact.png", dpi=150,
                    bbox_inches="tight")
        plt.close()

    def _plot_system_metrics(self) -> None:
        """Measured step time / throughput / memory (reference simulated
        all three, experiment_runner.py:488-519)."""
        import matplotlib.pyplot as plt

        epochs = [r["epoch"] for r in self.epoch_records]
        fig, axes = plt.subplots(1, 3, figsize=(18, 5))

        axes[0].plot(epochs, [r["epoch_time_s"] for r in self.epoch_records],
                     linewidth=2)
        axes[0].set_title("Epoch Wall Time")
        axes[0].set_ylabel("seconds")

        sps = [r["system_metrics"].get("samples_per_sec")
               for r in self.epoch_records]
        if any(v is not None for v in sps):
            axes[1].plot(epochs, sps, linewidth=2)
        axes[1].set_title("Throughput")
        axes[1].set_ylabel("samples/sec")

        mem = [r["system_metrics"].get("device_memory_utilization")
               for r in self.epoch_records]
        if any(v is not None for v in mem):
            axes[2].plot(epochs, mem, linewidth=2)
            axes[2].set_ylabel("fraction of HBM")
            axes[2].set_title("Device Memory Utilization")
        else:
            st = self.trainer.metrics_collector._step_times
            if st:
                axes[2].hist(st, bins=30)
                axes[2].set_title("Step Time Histogram")
                axes[2].set_xlabel("seconds")

        for ax in axes:
            ax.grid(True, alpha=0.3)
            ax.set_xlabel("epoch")
        plt.tight_layout()
        plt.savefig(self.output_dir / "system_metrics.png", dpi=150,
                    bbox_inches="tight")
        plt.close()

    # ------------------------------------------------------------------
    # Report
    # ------------------------------------------------------------------

    def _generate_experiment_report(self, results: Dict[str, Any]) -> None:
        summary = results.get("experiment_summary", {})
        quality = summary.get("detection_quality", {})
        reliability = {
            i: self.trainer.trust_manager.predict_node_reliability(i)
            for i in range(self.config.num_nodes)
        }

        def fmt(v, spec=".4f"):
            return format(v, spec) if isinstance(v, (int, float)) else "n/a"

        lines = [
            f"# Experiment Report: {self.config.experiment_name}",
            "",
            "## Configuration",
            f"- model: {self.config.model_name}"
            f" / dataset: {self.config.dataset_name}",
            f"- nodes: {self.config.num_nodes}"
            f" ({self.config.parallelism} parallelism)",
            f"- epochs: {self.config.num_epochs},"
            f" batch size: {self.config.batch_size},"
            f" lr: {self.config.learning_rate}",
            f"- attacks: {self.config.attack_enabled}"
            + (f" ({', '.join(self.config.attack_types)} on nodes"
               f" {self.config.target_nodes}, intensity"
               f" {self.config.attack_intensity}, from epoch"
               f" {self.config.attack_start_epoch})"
               if self.config.attack_enabled else ""),
            f"- trust threshold: {self.config.trust_threshold}",
            "",
            "## Training",
            f"- steps: {summary.get('total_steps')}",
            f"- average loss: {fmt(summary.get('average_loss'))}",
            f"- final loss: {fmt(summary.get('final_loss'))}",
            f"- loss reduction: {fmt(summary.get('loss_reduction'), '.2%')}",
            "",
            "## Security (all measured against ground-truth injection)",
            f"- final system trust: "
            f"{fmt(summary.get('final_system_trust'), '.3f')}",
            f"- compromised nodes: {summary.get('compromised_nodes')}",
            f"- incidents recorded: {summary.get('total_attacks_detected')},"
            f" reassignments: {summary.get('total_reassignments')}",
        ]
        if quality.get("attack_enabled"):
            lines += [
                f"- detection precision: {fmt(quality.get('precision'), '.2f')}"
                f" / recall: {fmt(quality.get('recall'), '.2f')}",
                f"- steps to detection: {quality.get('steps_to_detection')}",
                f"- false positives: {quality.get('false_positives')}",
            ]
        else:
            lines += [
                "- clean run false-positive rate: "
                f"{fmt(quality.get('false_positive_rate'), '.3f')}",
            ]
        lines += [
            "",
            "## Node reliability forecast (trend extrapolation)",
        ]
        for node, pred in reliability.items():
            lines.append(f"- node {node}: {fmt(pred, '.3f')}")
        lines += [
            "",
            "## Artifacts",
            "- `experiment_results.json`, `training_metrics.csv`",
            "- `training_loss.png`, `trust_evolution.png`,"
            " `attack_impact.png`, `system_metrics.png`",
            "",
            f"*Generated {time.strftime('%Y-%m-%d %H:%M:%S')}*",
        ]
        atomic_write_text(self.output_dir / "experiment_report.md",
                          "\n".join(lines) + "\n")
        logger.info("Experiment report generated")

    def _cleanup(self) -> None:
        if self.obs is not None:
            self.obs.finalize()  # snapshot + obs_report.json + close trace
        if self.trainer is not None:
            self.trainer.cleanup()
        if self.attacker is not None:
            self.attacker.cleanup()
        logger.info("Experiment cleanup completed")


# ---------------------------------------------------------------------------
# BASELINE.md benchmark-matrix presets
# ---------------------------------------------------------------------------

PRESETS: Dict[str, Dict[str, Any]] = {
    # 1. ResNet-32 / CIFAR-10 clean
    "resnet32_cifar10_clean": dict(
        model_name="resnet32", dataset_name="cifar10", num_nodes=8,
        attack_enabled=False, parallelism="data",
    ),
    # 2. VGG-16 / CIFAR-10 gradient poisoning + detector
    "vgg16_cifar10_poisoning": dict(
        model_name="vgg16", dataset_name="cifar10", num_nodes=8,
        attack_enabled=True,
        attack_types=["gradient_poisoning", "data_poisoning"],
        target_nodes=[1, 3], parallelism="data",
    ),
    # 3. GPT-2-small / OpenWebText 8-way model parallel, clean
    "gpt2_small_pipeline_clean": dict(
        model_name="gpt2", dataset_name="openwebtext", num_nodes=8,
        attack_enabled=False, parallelism="model",
    ),
    # 4. GPT-2-medium, 2/8 compromised, reassignment
    "gpt2_medium_reassignment": dict(
        model_name="gpt2-medium", dataset_name="openwebtext", num_nodes=8,
        attack_enabled=True, attack_types=["gradient_poisoning"],
        target_nodes=[1, 3], parallelism="data",
    ),
    # 5. ResNet-101 Byzantine multi-node (trust-threshold sweep via
    #    run_threshold_sweep)
    "resnet101_byzantine": dict(
        model_name="resnet101", dataset_name="cifar10", num_nodes=8,
        attack_enabled=True, attack_types=["byzantine"],
        target_nodes=[1, 3], parallelism="data",
    ),
    # 6. (beyond-reference) Transient attack -> eviction -> recovery /
    #    readmission: the full elastic lifecycle as a measured experiment.
    "gpt2_transient_recovery": dict(
        model_name="gpt2", dataset_name="openwebtext", num_nodes=8,
        attack_enabled=True, attack_types=["gradient_poisoning"],
        target_nodes=[5], attack_start_epoch=1, attack_end_epoch=3,
        parallelism="data", elastic_resharding=True,
        readmit_after_steps=60, num_epochs=6,
    ),
}


def preset_config(name: str, **overrides: Any) -> ExperimentConfig:
    if name not in PRESETS:
        raise ValueError(
            f"unknown preset {name!r}; available: {sorted(PRESETS)}"
        )
    kwargs = dict(PRESETS[name])
    kwargs.update(overrides)
    kwargs.setdefault(
        "experiment_name", f"{name}_{time.strftime('%Y%m%d_%H%M%S')}"
    )
    return ExperimentConfig(**kwargs)


def run_threshold_sweep(base: ExperimentConfig,
                        thresholds: List[float],
                        **runner_kwargs: Any) -> Dict[str, Any]:
    """BASELINE config 5: repeat an experiment across trust thresholds and
    aggregate detection quality per threshold."""
    from trustworthy_dl_tpu.obs.meta import run_metadata

    sweep: Dict[str, Any] = {"thresholds": {}, "base": base.experiment_name,
                             "run_metadata": run_metadata()}
    for threshold in thresholds:
        config = dataclasses.replace(
            base,
            experiment_name=f"{base.experiment_name}_t{threshold:g}",
            trust_threshold=threshold,
        )
        results = ExperimentRunner(config, **runner_kwargs).run_experiment()
        sweep["thresholds"][f"{threshold:g}"] = {
            "summary": results["experiment_summary"],
            # The threshold's direct lever is the status machine
            # (trust_manager.py:162-181): per-threshold status counts are
            # what a sweep consumer compares first.
            "trust_statistics": results["final_trust_statistics"],
        }
    out_dir = Path(base.output_dir) / f"{base.experiment_name}_sweep"
    out_dir.mkdir(parents=True, exist_ok=True)
    atomic_write_json(out_dir / "sweep_results.json", sweep,
                      default=_jsonable)
    return sweep


# ---------------------------------------------------------------------------
# Console entry point: trustworthy-dl-experiment (setup_py.py:62-65)
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="Run trustworthy distributed DL experiments"
    )
    parser.add_argument("--config", type=str,
                        help="experiment config file (YAML/JSON)")
    parser.add_argument("--preset", type=str, choices=sorted(PRESETS),
                        help="BASELINE.md benchmark preset")
    parser.add_argument("--name", type=str, help="experiment name")
    parser.add_argument("--model", type=str, default=None)
    parser.add_argument("--dataset", type=str, default=None)
    parser.add_argument("--nodes", type=int, default=None)
    parser.add_argument("--epochs", type=int, default=None)
    parser.add_argument("--batch-size", type=int, default=None)
    parser.add_argument("--attack", action="store_true",
                        help="enable fault injection")
    parser.add_argument("--parallelism", type=str, default=None)
    parser.add_argument("--steps-per-epoch", type=int, default=None)
    parser.add_argument("--output-dir", type=str, default=None)
    parser.add_argument("--sweep-thresholds", type=str, default=None,
                        help="comma-separated trust thresholds (preset 5)")
    parser.add_argument("--envelope", action="store_true",
                        help="measure the detection envelope (attack type "
                        "x intensity matrix) instead of a single "
                        "experiment")
    parser.add_argument("--serve-envelope", action="store_true",
                        help="measure the SERVE-side detection envelope "
                        "(adaptive attacker strength x monitor threshold "
                        "x vote K against a ServingFleet) instead of a "
                        "single experiment")
    args = parser.parse_args(argv)

    if args.serve_envelope:
        from trustworthy_dl_tpu.experiments.serve_envelope import (
            run_serve_envelope,
        )

        kwargs: Dict[str, Any] = {}
        if args.output_dir:
            kwargs["output_dir"] = args.output_dir
        results = run_serve_envelope(**kwargs)
        caught = sum(1 for c in results["cells"]
                     if c["detected_by"] != "none")
        print(f"Serve envelope: {len(results['cells'])} cells "
              f"({caught} detected) in {results['wall_time_s']:.1f}s")
        return 0

    if args.envelope:
        from trustworthy_dl_tpu.experiments.envelope import (
            run_detection_envelope,
        )

        # Refuse flags the sweep would silently ignore: a user passing
        # --model/--steps must not publish numbers believing they
        # measured that configuration.
        unsupported = {
            "--config": args.config, "--preset": args.preset,
            "--name": args.name, "--model": args.model,
            "--dataset": args.dataset, "--epochs": args.epochs,
            "--batch-size": args.batch_size,
            "--parallelism": args.parallelism,
            "--steps-per-epoch": args.steps_per_epoch,
            "--attack": args.attack or None,
            "--sweep-thresholds": args.sweep_thresholds,
        }
        rejected = [flag for flag, value in unsupported.items()
                    if value is not None]
        if rejected:
            parser.error(
                f"--envelope does not take {', '.join(rejected)}; it "
                "sweeps its own fixed matrix (use "
                "run_detection_envelope(...) for custom shapes)"
            )
        kwargs: Dict[str, Any] = {}
        if args.output_dir:
            kwargs["output_dir"] = args.output_dir
        if args.nodes:
            kwargs["num_nodes"] = args.nodes
        results = run_detection_envelope(**kwargs)
        print(f"Detection envelope: {len(results['cells'])} cells in "
              f"{results['wall_time_s']:.1f}s")
        return 0

    overrides = {
        k: v for k, v in {
            "model_name": args.model,
            "dataset_name": args.dataset,
            "num_nodes": args.nodes,
            "num_epochs": args.epochs,
            "batch_size": args.batch_size,
            "parallelism": args.parallelism,
            "steps_per_epoch": args.steps_per_epoch,
            "output_dir": args.output_dir,
            "experiment_name": args.name,
        }.items() if v is not None
    }
    if args.attack:
        overrides["attack_enabled"] = True

    if args.config:
        from trustworthy_dl_tpu.core.config import load_experiment_config

        overrides.setdefault(
            "experiment_name",
            f"experiment_{time.strftime('%Y%m%d_%H%M%S')}",
        )
        config = load_experiment_config(args.config, **overrides)
    elif args.preset:
        config = preset_config(args.preset, **overrides)
    else:
        overrides.setdefault("model_name", "gpt2")
        overrides.setdefault("dataset_name", "openwebtext")
        name = overrides.pop(
            "experiment_name",
            "{}_{}_nodes{}_{}".format(
                overrides["model_name"], overrides["dataset_name"],
                overrides.get("num_nodes", 4),
                time.strftime("%Y%m%d_%H%M%S"),
            ),
        )
        config = ExperimentConfig(experiment_name=name, **overrides)

    if args.sweep_thresholds:
        thresholds = [float(t) for t in args.sweep_thresholds.split(",")]
        run_threshold_sweep(config, thresholds)
        print(f"Sweep completed: {config.experiment_name} over {thresholds}")
        return 0

    runner = ExperimentRunner(config)
    runner.run_experiment()
    print(f"Experiment completed: {config.experiment_name}")
    print(f"Results saved to: {runner.output_dir}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

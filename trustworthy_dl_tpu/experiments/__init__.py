"""Experiment layer (L5) — real-trainer-driven runner + BASELINE presets."""

from trustworthy_dl_tpu.experiments.envelope import (
    render_table,
    run_detection_envelope,
)
from trustworthy_dl_tpu.experiments.runner import (
    PRESETS,
    ExperimentRunner,
    main,
    preset_config,
    run_threshold_sweep,
)

__all__ = [
    "ExperimentRunner",
    "PRESETS",
    "main",
    "preset_config",
    "render_table",
    "run_detection_envelope",
    "run_threshold_sweep",
]

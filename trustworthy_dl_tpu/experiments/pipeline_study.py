"""Pipeline schedule study: GPipe step time & memory vs microbatch count.

VERDICT r4 weak #4: at the dryrun's S=8, M=2 the pipe was 78 % bubble and
nothing reduced it.  This study measures the schedule-level lever — M —
on the full trusted pipeline train step (detection, canary, trust gating
included) at fixed global batch, and backs the auto default
(``TrainingConfig.num_microbatches = 0`` →
``parallel.pipeline.choose_num_microbatches``).

Why not 1F1B?  The forward/backward here are the AD transpose of one
``lax.scan`` ppermute ring (parallel/pipeline.py): all M forwards run,
then all M backwards — a time bubble of (S-1)/(M+S-1), which is the SAME
as non-interleaved 1F1B's.  1F1B's real advantage is peak activation
memory (S in-flight microbatches instead of M); under XLA that benefit
is already available compositionally via ``remat`` (activation bytes per
microbatch drop by ~L/S) and, in data modes, grad accumulation.  The
measured ``temp_bytes`` column quantifies what 1F1B would save; the
step-time column shows large-M GPipe captures the throughput win without
hand-scheduling the backward (which would mean a custom VJP around the
ring, bypassing AD — high risk for the detection battery that rides it).

Outputs (under ``<output_dir>/``): ``pipeline_schedule_study.json`` and
``pipeline_schedule_study.md``.
"""

from __future__ import annotations

import json
import logging
import time
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

from trustworthy_dl_tpu.utils.io import atomic_write_json, \
    atomic_write_text

logger = logging.getLogger(__name__)

TINY = dict(n_embd=64, n_head=4, vocab_size=256, n_positions=64,
            seq_len=32)


def _measure_cell(num_stages: int, num_microbatches: int, batch: int,
                  steps: int, model_overrides: Dict[str, Any]
                  ) -> Dict[str, Any]:
    import jax

    from trustworthy_dl_tpu.core.config import TrainingConfig
    from trustworthy_dl_tpu.data import get_dataloader
    from trustworthy_dl_tpu.engine import DistributedTrainer
    from trustworthy_dl_tpu.parallel.pipeline import bubble_fraction

    config = TrainingConfig(
        model_name="gpt2", dataset_name="openwebtext", batch_size=batch,
        num_nodes=num_stages, optimizer="adamw", learning_rate=1e-3,
        checkpoint_interval=10 ** 9, detector_warmup=10 ** 6,
        parallelism="model", num_microbatches=num_microbatches,
    )
    overrides = dict(TINY, n_layer=num_stages, **model_overrides)
    trainer = DistributedTrainer(config, model_overrides=overrides)
    dl = get_dataloader("openwebtext", batch_size=batch,
                        seq_len=overrides["seq_len"],
                        vocab_size=overrides["vocab_size"],
                        num_examples=batch)
    trainer.initialize()
    [first] = list(dl)
    nb = trainer._node_batch(first)

    # Compiled-memory introspection (XLA buffer assignment): temp bytes
    # is the activation/workspace footprint the schedule controls.
    lowered = trainer._train_step.lower(trainer.state, nb,
                                        trainer.attack_plan)
    compiled = lowered.compile()
    try:
        mem = compiled.memory_analysis()
        temp_bytes = int(getattr(mem, "temp_size_in_bytes", 0))
    except Exception:  # backend without memory_analysis
        temp_bytes = 0

    state = trainer.state
    plan = trainer.attack_plan
    state, metrics = compiled(state, nb, plan)  # warmup (already compiled)
    jax.block_until_ready(metrics.loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = compiled(state, nb, plan)
    jax.block_until_ready(metrics.loss)
    dt = (time.perf_counter() - t0) / steps
    assert np.isfinite(float(metrics.loss))
    return {
        "num_stages": num_stages,
        "num_microbatches": num_microbatches,
        "batch": batch,
        "step_time_s": dt,
        "bubble_fraction": bubble_fraction(num_stages, num_microbatches),
        "temp_bytes": temp_bytes,
    }


def run_pipeline_study(
    output_dir: str = "experiments/pipeline_schedule_study",
    stage_counts: Iterable[int] = (4, 8),
    microbatches: Iterable[int] = (2, 4, 8, 16, 32),
    batch: int = 64,
    steps: int = 5,
    model_overrides: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    t0 = time.time()
    out = Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    cells: List[Dict[str, Any]] = []
    for s in stage_counts:
        for m in microbatches:
            if batch % m:
                continue
            logger.info("study: S=%d M=%d", s, m)
            cells.append(_measure_cell(s, m, batch,
                                       steps, model_overrides or {}))
    from trustworthy_dl_tpu.obs.meta import run_metadata

    results = {
        "config": {"batch": batch, "steps": steps,
                   "stage_counts": list(stage_counts),
                   "microbatches": list(microbatches),
                   "model": dict(TINY)},
        # Platform/jax-version stamp (VERDICT weak #5): schedule timings
        # are meaningless without the hardware that produced them.
        "run_metadata": run_metadata(),
        "cells": cells,
        "wall_time_s": time.time() - t0,
    }
    atomic_write_json(out / "pipeline_schedule_study.json", results)
    atomic_write_text(out / "pipeline_schedule_study.md",
                      render_study(results))
    return results


def render_study(results: Dict[str, Any]) -> str:
    lines = ["| S | M | bubble | step time | vs M=2 | temp MiB |",
             "|---|---|---|---|---|---|"]
    base: Dict[int, float] = {}
    for c in results["cells"]:
        if c["num_microbatches"] == 2:
            base[c["num_stages"]] = c["step_time_s"]
        rel = base.get(c["num_stages"])
        speed = (f"{rel / c['step_time_s']:.2f}x" if rel else "—")
        lines.append(
            f"| {c['num_stages']} | {c['num_microbatches']} "
            f"| {c['bubble_fraction']:.0%} | {c['step_time_s'] * 1e3:.0f} ms "
            f"| {speed} | {c['temp_bytes'] / 2**20:.0f} |"
        )
    return "\n".join(lines) + "\n"


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    print(json.dumps(run_pipeline_study()["cells"], indent=2))

"""Serve-side detection envelope: attacker strength × monitor threshold
× vote K, measured against a real ``ServingFleet``.

The training side has ``experiments/envelope.py`` — a measured (attack
type × intensity) matrix replacing the reference's simulated curves.
Serving had nothing: the PR 8 flag-rate ladder was only ever exercised
at full poison strength, so the paper's detectability-boundary figure
did not exist for the serving half of the system.  This study produces
it: every cell runs IDENTICAL seeded traffic through a fleet with one
adaptively-poisoned replica at a FIXED corruption strength (the
``chaos.adversary`` machinery with its controller pinned — the sweep
measures the boundary; the controller is what walks along it) and
records which tier caught it:

* ``ladder`` — the monitor flag rate crossed ``flag_rate_quarantine``
  (the PR 8 defence);
* ``vote``   — the flag rate stayed sub-threshold but cross-replica
  verdict voting outvoted the corrupted streams
  (``FleetConfig.vote_k``);
* ``none``   — undetected: the corruption was too weak to flag at this
  monitor threshold AND voting was off (or never triggered — with zero
  flags there is no suspicion and nothing to audit: the measured floor
  of the defence, the serving mirror of the training envelope's 50 %
  collusion blind spot).

Outputs (same run-metadata-stamped artifact shape as the training
envelope, under ``<output_dir>/``):
  - ``serve_envelope.json`` — the full matrix + per-cell counters
  - ``serve_envelope.md``   — README-ready table (one block per vote K)
  - ``serve_envelope.png``  — detection heatmap, one panel per vote K
"""

from __future__ import annotations

import json
import logging
import time
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

from trustworthy_dl_tpu.utils.io import atomic_write_json, \
    atomic_write_text

logger = logging.getLogger(__name__)

STRENGTHS = (0.15, 0.45, 0.9)
THRESHOLDS = (12.0, 24.0)
VOTE_KS = (0, 2)

#: Tiny default geometry (vocab 131 continues the process-global
#: jit-cache isolation sequence 97/101/103/107/113/127 the serve test
#: files document — this study's decode programs never collide with
#: theirs when run in one process).
TINY_GPT = dict(n_layer=2, n_embd=32, n_head=4, vocab_size=131,
                n_positions=64)


class _RecordingTrace:
    """Host-only trace sink: keeps the typed events the cell classifier
    reads (replica transitions, suspicion, votes)."""

    def __init__(self) -> None:
        self.events: List[Dict[str, Any]] = []

    def emit(self, type: Any, **data: Any) -> None:
        self.events.append({"type": getattr(type, "value", str(type)),
                            **data})


def _run_cell(params: Any, cfg: Any, *, seed: int, strength: float,
              threshold: float, vote_k: int, num_replicas: int,
              num_requests: int, max_slots: int, max_seq: int,
              fleet_overrides: Optional[Dict[str, Any]],
              adversary_overrides: Optional[Dict[str, Any]]
              ) -> Dict[str, Any]:
    """One measured cell: fresh fleet, one adaptively-poisoned replica
    at FIXED ``strength``, monitor at ``threshold``, voting at
    ``vote_k`` — identical seeded traffic across every cell."""
    import jax

    from trustworthy_dl_tpu.chaos import (
        AdaptivePoisonAttacker,
        AdversaryConfig,
        FaultEvent,
        FaultInjector,
        FaultKind,
        FaultPlan,
        MarginSignatureMonitor,
    )
    from trustworthy_dl_tpu.serve import (
        FleetConfig,
        ServeRequest,
        ServingFleet,
    )

    target = num_replicas - 1
    adv_kwargs: Dict[str, Any] = dict(
        target=target, seed=seed,
        # FIXED strength: the controller is pinned (min == max ==
        # initial) so the cell measures the boundary at this strength;
        # per-request signal jitter makes flag probability vary
        # smoothly with strength instead of all-or-nothing.
        initial_strength=strength, min_strength=strength,
        max_strength=strength, step_up=0.0, backoff=1.0,
        signal_jitter=0.5, vocab_size=cfg.vocab_size,
    )
    adv_kwargs.update(adversary_overrides or {})
    adversary = AdaptivePoisonAttacker(AdversaryConfig(**adv_kwargs))
    plan = FaultPlan.scripted([FaultEvent(
        step=1, kind=FaultKind.REPLICA_ADAPTIVE_POISON, target=target,
    )], seed=seed)
    injector = FaultInjector(plan, adversary=adversary)
    trace = _RecordingTrace()
    fleet_kwargs: Dict[str, Any] = dict(
        num_replicas=num_replicas,
        # flag_min_count 4: the ladder needs SUSTAINED evidence (4 flags
        # in the window at >= the rate), so the short-window early
        # rates of a mid-strength attacker don't trip it before the
        # sub-threshold regime — the regime this study exists to
        # measure — can even appear.  Suspicion still opens at 2 flags.
        flag_window=16, flag_min_count=4, flag_rate_quarantine=0.5,
        suspicion_threshold=0.08, suspicion_min_flags=2,
        vote_k=vote_k, vote_outvote_limit=2,
        max_retries=6,
        # Pinned past the run: the envelope measures first-detection,
        # not the quarantine-probe churn of an unhealed replica.
        quarantine_cooloff_ticks=10 ** 6,
    )
    fleet_kwargs.update(fleet_overrides or {})
    fleet = ServingFleet(
        params, cfg,
        fleet_config=FleetConfig(**fleet_kwargs),
        chaos=injector, trace=trace,
        rng=jax.random.PRNGKey(seed + 1),
        max_slots=max_slots, max_seq=max_seq,
        queue_limit=num_requests,
        monitor=MarginSignatureMonitor(threshold),
    )
    rng = np.random.default_rng(seed)
    t0 = time.time()
    for _ in range(num_requests):
        plen = int(rng.integers(3, max(max_seq // 4, 4)))
        new = int(rng.integers(4, max(max_seq // 4, 5)))
        prompt = rng.integers(0, cfg.vocab_size, plen).tolist()
        fleet.submit(ServeRequest(prompt=prompt, max_new_tokens=new))
    results = fleet.run_until_idle(max_ticks=20_000)

    quarantine_reasons = [
        (e.get("replica"), e.get("reason"))
        for e in trace.events
        if e["type"] == "replica_transition"
        and e.get("to_state") == "quarantined"
    ]
    target_reasons = {r for rep, r in quarantine_reasons if rep == target}
    # "ladder" groups the two FLAG-driven tiers (window-rate trip and
    # per-slot quarantine exhaustion); "vote" is the disagreement tier.
    if target_reasons & {"monitor_flag_rate", "slot_quarantine_exhausted"}:
        detected_by = "ladder"
    elif "verdict_outvoted" in target_reasons:
        detected_by = "vote"
    else:
        detected_by = "none"
    corrupted_served = sum(
        1 for r in results.values()
        if r.status == "completed" and r.replica == target
    )
    return {
        "strength": strength,
        "threshold": threshold,
        "vote_k": vote_k,
        "detected_by": detected_by,
        "clean_replica_quarantines": sum(
            1 for rep, _ in quarantine_reasons if rep != target),
        "corrupted_served": corrupted_served,
        "completed": sum(1 for r in results.values()
                         if r.status == "completed"),
        "requests": num_requests,
        "target_flag_rate": round(fleet.replicas[target].flag_rate, 4),
        "target_suspicion": round(fleet.replicas[target].suspicion, 4),
        "suspicions": fleet.counters["suspicions"],
        "votes": fleet.counters["votes"],
        "outvotes": fleet.counters["outvotes"],
        "drains": fleet.counters["drains"],
        "quarantines": fleet.counters["quarantines"],
        "ticks": fleet.tick,
        "wall_time_s": round(time.time() - t0, 2),
    }


def run_serve_envelope(
    output_dir: str = "experiments/serve_envelope",
    strengths: Iterable[float] = STRENGTHS,
    thresholds: Iterable[float] = THRESHOLDS,
    vote_ks: Iterable[int] = VOTE_KS,
    num_replicas: int = 3,
    num_requests: int = 24,
    # 4 slots per replica: per-slot quarantine exhaustion then needs 4
    # flags, so the vote tier gets room to win the race in the
    # sub-threshold regime (suspicion opens at 2).
    max_slots: int = 4,
    max_seq: int = 48,
    seed: int = 0,
    model_overrides: Optional[Dict[str, Any]] = None,
    fleet_overrides: Optional[Dict[str, Any]] = None,
    adversary_overrides: Optional[Dict[str, Any]] = None,
    make_figure: bool = True,
) -> Dict[str, Any]:
    """Measure the serve-side detection envelope and write JSON +
    figure + table.  Defaults fit a CPU dev machine (tiny GPT-2, one
    compile per program shared across every cell via the process jit
    cache); pass ``model_overrides`` for real shapes on TPU."""
    import jax
    import jax.numpy as jnp

    from trustworthy_dl_tpu.models import gpt2

    t0 = time.time()
    # Materialise once: the grid is iterated per vote_k pass AND again
    # for the config stamp — a generator argument would silently
    # exhaust after the first pass and drop most of the matrix.
    strengths = [float(s) for s in strengths]
    thresholds = [float(t) for t in thresholds]
    vote_ks = [int(k) for k in vote_ks]
    out = Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    overrides = dict(TINY_GPT, **(model_overrides or {}))
    cfg = gpt2.GPT2Config(dtype=jnp.float32, **overrides)
    params = gpt2.init_params(jax.random.PRNGKey(seed), cfg)

    cells: List[Dict[str, Any]] = []
    for vote_k in vote_ks:
        for strength in strengths:
            for threshold in thresholds:
                logger.info("serve envelope: strength %.2f, threshold "
                            "%.1f, K=%d", strength, threshold, vote_k)
                cells.append(_run_cell(
                    params, cfg, seed=seed, strength=float(strength),
                    threshold=float(threshold), vote_k=int(vote_k),
                    num_replicas=num_replicas,
                    num_requests=num_requests, max_slots=max_slots,
                    max_seq=max_seq, fleet_overrides=fleet_overrides,
                    adversary_overrides=adversary_overrides,
                ))

    from trustworthy_dl_tpu.obs.meta import run_metadata

    results = {
        "config": {
            "strengths": [float(s) for s in strengths],
            "thresholds": [float(t) for t in thresholds],
            "vote_ks": [int(k) for k in vote_ks],
            "num_replicas": num_replicas,
            "num_requests": num_requests,
            "max_slots": max_slots, "max_seq": max_seq,
            "seed": seed, "model_overrides": overrides,
        },
        # Platform/jax-version stamp: an envelope measured on a CPU dev
        # mesh must never be mistaken for TPU data (same contract as
        # the training envelope).
        "run_metadata": run_metadata(),
        "cells": cells,
        "wall_time_s": round(time.time() - t0, 2),
    }
    atomic_write_json(out / "serve_envelope.json", results)
    atomic_write_text(out / "serve_envelope.md", render_table(results))
    if make_figure:
        try:
            _figure(results, out / "serve_envelope.png")
        except Exception:  # matplotlib backend quirks must not kill data
            logger.exception("serve envelope figure failed")
    logger.info("serve envelope: %d cells in %.1fs -> %s", len(cells),
                results["wall_time_s"], out)
    return results


def render_table(results: Dict[str, Any]) -> str:
    """README-ready markdown: one block per vote K; rows = strength,
    columns = monitor threshold, cell = which tier caught it (plus the
    corrupted streams that reached users before it did)."""
    config = results["config"]
    by_key = {(c["vote_k"], c["strength"], c["threshold"]): c
              for c in results["cells"]}
    marks = {"ladder": "LADDER", "vote": "VOTE", "none": "—"}
    lines: List[str] = []
    for vote_k in config["vote_ks"]:
        lines.append(f"**vote K = {vote_k}**"
                     + (" (voting off)" if vote_k == 0 else ""))
        lines.append("")
        lines.append("| strength \\ threshold | "
                     + " | ".join(f"{t:g}" for t in config["thresholds"])
                     + " |")
        lines.append("|---" * (len(config["thresholds"]) + 1) + "|")
        for s in config["strengths"]:
            row = [f"{s:g}"]
            for t in config["thresholds"]:
                c = by_key.get((vote_k, s, t))
                if c is None:
                    row.append("—")
                    continue
                row.append(f"{marks[c['detected_by']]} "
                           f"({c['corrupted_served']} corrupted served)")
            lines.append("| " + " | ".join(row) + " |")
        lines.append("")
    clean = sum(c["clean_replica_quarantines"] for c in results["cells"])
    lines.append(f"Clean-replica quarantines across all cells: {clean} "
                 "(a lone faulty voter can never outvote a clean "
                 "replica — majority needs two agreeing dissenters).")
    return "\n".join(lines) + "\n"


def _figure(results: Dict[str, Any], path: Path) -> None:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    config = results["config"]
    strengths = config["strengths"]
    thresholds = config["thresholds"]
    vote_ks = config["vote_ks"]
    by_key = {(c["vote_k"], c["strength"], c["threshold"]): c
              for c in results["cells"]}
    level = {"none": 0.0, "vote": 0.5, "ladder": 1.0}

    fig, axes = plt.subplots(1, len(vote_ks),
                             figsize=(4.2 * len(vote_ks), 3.6),
                             squeeze=False)
    for ax, vote_k in zip(axes[0], vote_ks):
        grid = np.full((len(strengths), len(thresholds)), np.nan)
        for r, s in enumerate(strengths):
            for c, t in enumerate(thresholds):
                cell = by_key.get((vote_k, s, t))
                if cell is not None:
                    grid[r, c] = level[cell["detected_by"]]
        im = ax.imshow(grid, cmap="viridis", vmin=0.0, vmax=1.0,
                       aspect="auto")
        ax.set_xticks(range(len(thresholds)),
                      [f"{t:g}" for t in thresholds])
        ax.set_yticks(range(len(strengths)),
                      [f"{s:g}" for s in strengths])
        ax.set_xlabel("monitor threshold")
        ax.set_ylabel("attacker strength")
        ax.set_title(f"vote K = {vote_k}")
        for r, s in enumerate(strengths):
            for c, t in enumerate(thresholds):
                cell = by_key.get((vote_k, s, t))
                if cell is None:
                    continue
                ax.text(c, r, cell["detected_by"], ha="center",
                        va="center", fontsize=9,
                        color="white" if grid[r, c] < 0.6 else "black")
    fig.suptitle("Serve-side detection envelope (which tier caught the "
                 "adaptive poison)")
    fig.colorbar(im, ax=axes[0].tolist(), label="0 = none, 0.5 = vote, "
                 "1 = ladder")
    fig.savefig(path, dpi=120, bbox_inches="tight")
    plt.close(fig)

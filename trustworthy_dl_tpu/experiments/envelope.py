"""Measured detection envelope: attack type × intensity sweep.

The reference SIMULATED its detection curves — a hard-coded 0.3→0.9
detection-rate ramp and a 0.2→0.05 false-positive decay
(experiment_runner.py:427-451) — and narrated qualitative "Expected
Results" (README.md:134-156).  This module replaces them with *measured*
values: every cell of the (attack type × intensity) matrix is a real
trusted-training run on the mesh with deterministic fault injection, and
the reported detection rate / latency / false-positive rate / attribution
accuracy come from ground truth (the injection plan knows who was
attacked when).

Cells share ONE trainer — ``DistributedTrainer.reset_for_run`` gives each
cell fresh device state and host bookkeeping on the same jitted step, so
the XLA compile is paid once for the whole sweep.

Outputs (under ``<output_dir>/``):
  - ``detection_envelope.json`` — the full matrix + clean-run floor
  - ``detection_envelope.png``  — detection-rate heatmap annotated with
    median latency (one figure)
  - ``detection_envelope.md``   — the README-ready table
"""

from __future__ import annotations

import json
import logging
import time
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from trustworthy_dl_tpu.attacks import AdversarialAttacker, AttackConfig
from trustworthy_dl_tpu.utils.io import atomic_write_json, \
    atomic_write_text
from trustworthy_dl_tpu.attacks.adversarial import ATTACK_KINDS
from trustworthy_dl_tpu.core.config import TrainingConfig
from trustworthy_dl_tpu.data import get_dataloader
from trustworthy_dl_tpu.engine import DistributedTrainer

logger = logging.getLogger(__name__)

INTENSITIES = (0.1, 0.25, 0.5, 1.0)

# The attribution LADDER (tests/test_attribution.py): acceptable labels for
# the FIRST incident of each injected family.  A byzantine gradient
# replacement legitimately presents as gradient corruption on its first
# confirmed step (the signature separating them needs more evidence), so
# family-level accuracy is the headline and strict accuracy is reported
# alongside.
ATTRIBUTION_FAMILIES = {
    "gradient_poisoning": {"gradient_poisoning"},
    "byzantine": {"gradient_poisoning", "byzantine"},
    "data_poisoning": {"data_poisoning", "adversarial_input",
                       "gradient_poisoning"},
    "backdoor": {"backdoor", "data_poisoning", "adversarial_input",
                 "gradient_poisoning"},
}

TINY_GPT = dict(n_layer=2, n_embd=32, n_head=4, vocab_size=128,
                n_positions=32, seq_len=16)


def _run_cell(trainer: DistributedTrainer, dl_kwargs: Dict[str, Any], *,
              seed: int, attack_type: Optional[str], intensity: float,
              targets: Sequence[int], warmup_steps: int,
              attack_steps: int) -> Dict[str, Any]:
    """One measured cell: reset, run warmup+attack steps, read ground
    truth out of the trainer's incident records.

    The dataloader is built FRESH per cell: a shared loader's internal
    epoch counter would advance across cells, making each cell's data
    permutation depend on its position in the sweep — every cell must be
    reproducible standalone."""
    dl = get_dataloader(**dl_kwargs)
    trainer.reset_for_run(seed=seed)
    n = trainer.config.num_nodes
    if attack_type is not None:
        attacker = AdversarialAttacker(AttackConfig(
            attack_types=[attack_type], target_nodes=list(targets),
            intensity=intensity, start_step=warmup_steps,
        ))
        attacker.activate_attacks()
        trainer.set_attack_plan(attacker.plan(n))
    total = warmup_steps + attack_steps
    steps_per_epoch = max(len(dl), 1)
    for epoch in range((total + steps_per_epoch - 1) // steps_per_epoch):
        trainer.train_epoch(dl, epoch)
        if trainer.global_step >= total:
            break

    records = trainer.attack_history
    target_set = set(targets) if attack_type is not None else set()
    detected: Dict[int, Dict[str, Any]] = {}
    false_positives: List[Dict[str, Any]] = []
    pre_attack_target_incidents: List[Dict[str, Any]] = []
    for rec in records:
        slim = {"node_id": rec["node_id"], "step": rec["step"],
                "attack_type": rec["attack_type"]}
        if rec["node_id"] in target_set:
            if rec["step"] > warmup_steps:
                detected.setdefault(rec["node_id"], rec)  # first incident
            else:
                # A target flagged BEFORE its attack started is a false
                # alarm, but it belongs to a different population than
                # the clean nodes the fp_rate denominator counts — keep
                # it out of fp_rate and report it separately.
                pre_attack_target_incidents.append(slim)
        else:
            false_positives.append(slim)
    # global_step was already incremented when the incident is recorded,
    # so rec["step"] == warmup+1 means "caught on the first attacked
    # step" -> latency 1.
    latencies = sorted(rec["step"] - warmup_steps
                       for rec in detected.values())
    family = ATTRIBUTION_FAMILIES.get(attack_type or "", {attack_type})
    attributed = [rec for rec in detected.values()
                  if rec["attack_type"] in family]
    strict = [rec for rec in detected.values()
              if rec["attack_type"] == attack_type]
    losses = [m["loss"] for m in trainer.metrics_collector.batch_metrics]
    cell = {
        "attack_type": attack_type,
        "intensity": intensity if attack_type is not None else 0.0,
        "targets": sorted(target_set),
        "steps": trainer.global_step,
        "warmup_steps": warmup_steps,
        "detection_rate": (len(detected) / len(target_set)
                           if target_set else None),
        "detected_nodes": sorted(detected),
        "median_latency_steps": (float(np.median(latencies))
                                 if latencies else None),
        "latencies": latencies,
        "false_positive_incidents": false_positives,
        "pre_attack_target_incidents": pre_attack_target_incidents,
        # Node-steps a clean node could have been falsely flagged in
        # (numerator and denominator both count NON-TARGET nodes only).
        "fp_rate": len(false_positives)
        / max((n - len(target_set)) * trainer.global_step, 1),
        "attribution_accuracy": (len(attributed) / len(detected)
                                 if detected else None),
        "strict_attribution_accuracy": (len(strict) / len(detected)
                                        if detected else None),
        "attributed_types": sorted({rec["attack_type"]
                                    for rec in detected.values()}),
        "finite": bool(np.all(np.isfinite(losses))) if losses else False,
    }
    return cell


def run_detection_envelope(
    output_dir: str = "experiments/detection_envelope",
    attack_types: Iterable[str] = ATTACK_KINDS,
    intensities: Iterable[float] = INTENSITIES,
    num_nodes: int = 8,
    targets: Optional[Tuple[int, ...]] = None,
    warmup_steps: int = 8,
    # Long enough for the slow family: data poisoning is caught by loss
    # DETACHMENT (the honest fleet learns away from the stuck shard),
    # which needs tens of steps at this scale — the contrast between its
    # latency and gradient poisoning's ~2 steps is part of the envelope's
    # deliverable, so the horizon must not truncate it.
    attack_steps: int = 40,
    seed: int = 0,
    model_overrides: Optional[Dict[str, Any]] = None,
    make_figure: bool = True,
) -> Dict[str, Any]:
    """Measure the full detection envelope and write JSON + figure + table.

    Defaults fit an 8-device CPU mesh (tiny GPT-2, data parallelism) so
    the sweep runs anywhere the test suite runs; on TPU the same code
    measures the real model shapes via ``model_overrides``.
    """
    t0 = time.time()
    if targets is None:
        # 2 of n attacked (1 of n on tiny fleets), spread across the mesh
        # — (1, 5) at the default n=8.
        targets = (1, num_nodes // 2 + 1) if num_nodes >= 4 else (1,)
    if any(not 0 <= t < num_nodes for t in targets):
        raise ValueError(
            f"targets {targets} out of range for num_nodes={num_nodes}; "
            "a silently-dropped target would skew every published rate"
        )
    out = Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    overrides = dict(TINY_GPT, **(model_overrides or {}))

    config = TrainingConfig(
        model_name="gpt2", dataset_name="openwebtext",
        batch_size=2 * num_nodes, num_nodes=num_nodes, optimizer="adamw",
        learning_rate=3e-3, checkpoint_interval=10_000,
        detector_warmup=4, parallelism="data",
        # Keep topology static: detection keeps firing (and keeps being
        # measurable) instead of evicting the node after the first hit.
        elastic_resharding=False,
    )
    trainer = DistributedTrainer(config, model_overrides=overrides)
    total = warmup_steps + attack_steps
    dl_kwargs = dict(
        dataset_name="openwebtext", batch_size=config.batch_size,
        seq_len=overrides.get("seq_len", 16),
        vocab_size=overrides.get("vocab_size", 128),
        num_examples=config.batch_size * total,
    )

    # Clean floor first: FP rate with no attack at all.
    logger.info("envelope: clean floor run")
    clean = _run_cell(trainer, dl_kwargs, seed=seed, attack_type=None,
                      intensity=0.0, targets=(), warmup_steps=warmup_steps,
                      attack_steps=attack_steps)

    cells: List[Dict[str, Any]] = []
    for attack_type in attack_types:
        for intensity in intensities:
            logger.info("envelope: %s @ %.2f", attack_type, intensity)
            cells.append(_run_cell(
                trainer, dl_kwargs, seed=seed, attack_type=attack_type,
                intensity=float(intensity), targets=targets,
                warmup_steps=warmup_steps, attack_steps=attack_steps,
            ))

    from trustworthy_dl_tpu.obs.meta import run_metadata

    results = {
        "config": {
            "num_nodes": num_nodes, "targets": list(targets),
            "warmup_steps": warmup_steps, "attack_steps": attack_steps,
            "seed": seed, "model_overrides": overrides,
            "attack_types": list(attack_types),
            "intensities": [float(i) for i in intensities],
        },
        # Platform/jax-version stamp (VERDICT weak #5): an envelope
        # measured on a CPU dev mesh must never be mistaken for TPU data.
        "run_metadata": run_metadata(),
        "clean": clean,
        "cells": cells,
        "wall_time_s": time.time() - t0,
    }
    atomic_write_json(out / "detection_envelope.json", results)
    table = render_table(results)
    atomic_write_text(out / "detection_envelope.md", table)
    if make_figure:
        try:
            _figure(results, out / "detection_envelope.png")
        except Exception:  # matplotlib backend quirks must not kill data
            logger.exception("envelope figure failed")
    logger.info("envelope: %d cells in %.1fs -> %s",
                len(cells) + 1, results["wall_time_s"], out)
    return results


def render_table(results: Dict[str, Any]) -> str:
    """README-ready markdown: one row per attack type, one column per
    intensity, each cell 'rate / latency'."""
    intensities = results["config"]["intensities"]
    types = results["config"]["attack_types"]
    by_key = {(c["attack_type"], c["intensity"]): c
              for c in results["cells"]}
    lines = [
        "| attack \\ intensity | "
        + " | ".join(f"{i:g}" for i in intensities) + " |",
        "|---" * (len(intensities) + 1) + "|",
    ]
    for t in types:
        row = [t.replace("_", " ")]
        for i in intensities:
            c = by_key.get((t, float(i)))
            if c is None:
                row.append("—")
                continue
            rate = c["detection_rate"]
            lat = c["median_latency_steps"]
            row.append(f"{rate:.0%}" + (f" / {lat:.0f} st" if lat else ""))
        lines.append("| " + " | ".join(row) + " |")
    clean = results["clean"]
    lines.append("")
    lines.append(
        f"Clean-run false-positive rate: "
        f"{clean['fp_rate']:.4f} per node-step "
        f"({len(clean['false_positive_incidents'])} incidents over "
        f"{clean['steps']} steps × {results['config']['num_nodes']} nodes)."
    )
    return "\n".join(lines) + "\n"


def _figure(results: Dict[str, Any], path: Path) -> None:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    intensities = results["config"]["intensities"]
    types = results["config"]["attack_types"]
    by_key = {(c["attack_type"], c["intensity"]): c
              for c in results["cells"]}
    grid = np.full((len(types), len(intensities)), np.nan)
    for r, t in enumerate(types):
        for c, i in enumerate(intensities):
            cell = by_key.get((t, float(i)))
            if cell and cell["detection_rate"] is not None:
                grid[r, c] = cell["detection_rate"]

    fig, ax = plt.subplots(figsize=(7, 4.2))
    im = ax.imshow(grid, cmap="viridis", vmin=0.0, vmax=1.0,
                   aspect="auto")
    ax.set_xticks(range(len(intensities)),
                  [f"{i:g}" for i in intensities])
    ax.set_yticks(range(len(types)),
                  [t.replace("_", " ") for t in types])
    ax.set_xlabel("attack intensity")
    ax.set_title("Measured detection rate (annotation: median "
                 "steps-to-detect)")
    for r in range(len(types)):
        for c in range(len(intensities)):
            cell = by_key.get((types[r], float(intensities[c])))
            if cell is None or cell["detection_rate"] is None:
                continue
            lat = cell["median_latency_steps"]
            txt = f"{cell['detection_rate']:.0%}"
            if lat is not None:
                txt += f"\n{lat:.0f} st"
            ax.text(c, r, txt, ha="center", va="center",
                    color="white" if grid[r, c] < 0.6 else "black",
                    fontsize=9)
    fig.colorbar(im, ax=ax, label="detection rate")
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)

"""Perf regression sentinel: a rolling fingerprint ledger + noise-band
comparison, so "did this run get slower than the last one?" has a
machine answer instead of a human rereading BENCH_r*.json.

Every finishing run appends one compact **fingerprint** — tokens/s,
step time, phase fractions, compile counts/seconds, HBM watermark —
to ``PERF_LEDGER.jsonl`` (``ObsSession.finalize`` for instrumented
runs, ``bench.py`` for bench rounds, each under its own ``key`` so a
cpu debug round never bands against a TPU round).  The sentinel
compares a fresh fingerprint against the ledger's recent entries for
the same key: a metric outside ``max(nsigma·std, rel_floor·mean)`` of
the baseline mean in its BAD direction is a regression — typed
``perf_regression`` events, ``tddl_perf_regressions_total{metric=}``,
and (for bench, behind ``TDDL_BENCH_SENTINEL=1``) a non-zero exit the
CI can gate on.

Entirely host-side and jax-free: the ``trustworthy-dl-obs diff A B``
subcommand renders two artifact sets (obs_report.json / ledger
fingerprints) side by side offline.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from trustworthy_dl_tpu.obs.events import EventType

FINGERPRINT_SCHEMA = "tddl-perf-v1"

#: metric -> direction ("higher" = bigger is better).  Only metrics a
#: fingerprint actually carries are checked.
SENTINEL_METRICS: Dict[str, str] = {
    "tokens_per_s": "higher",
    "step_time_s": "lower",
    "compile_total": "lower",
    "compile_seconds": "lower",
    "hbm_watermark_bytes": "lower",
    # Speculative-decode draft quality: the fraction of drafted tokens
    # the model-dtype verify accepted.  A draft-quality regression
    # (quantization drift, a draft/verify numerics split) pages exactly
    # like a throughput regression — tokens/s would eventually show it,
    # but accepted_rate names the cause.
    "accepted_rate": "higher",
    # Decode-phase share of the serve wall (engine.decode_tick_s /
    # elapsed).  A silent fall-back from the paged-attention kernel to
    # the jnp gather path (gate flipped, geometry stopped tiling,
    # backend change) inflates exactly this number — it pages like a
    # perf regression even while tokens/s noise hides it, and the
    # tddl_serve_attn_kernel{path=} gauge names the culprit.
    "decode_tick_fraction": "lower",
    # Prefill-chunk and speculative-verify shares of the serve wall —
    # the same silent-downgrade story as decode_tick_fraction, one per
    # new kernel program: the chunked-prefill flash program falling
    # back to the gathered-view jnp path inflates the prefill share,
    # the fused verify tail falling back to materialise-then-reduce
    # inflates the verify share.  The per-program
    # tddl_serve_attn_kernel{path=,program=} gauge names the culprit.
    "prefill_chunk_fraction": "lower",
    "spec_verify_fraction": "lower",
    # Adapter-pool locality (pool hits / lookups) and the equal-HBM
    # personalisation cost (adapter-arm tokens/s over base-arm tokens/s
    # at the SAME budget, TDDL_BENCH_ADAPTERS rounds).  A colder pool
    # (eviction thrash after a Zipf-shape shift) or a pricier gathered
    # low-rank path both band — and name their cause — before the
    # headline tokens/s notices.
    "adapter_hit_rate": "higher",
    "adapter_tokens_ratio": "higher",
    # Live-migration success under capacity loss (migrations over
    # migrations + replay failovers in the TDDL_BENCH_MIGRATE drain
    # arm).  A structural regression — pool-geometry drift breaking
    # ``can_migrate``, a claim path that starts refusing — silently
    # degrades every capacity loss back to prompt replay; the fraction
    # bands (and names the cause) before goodput noise shows it.
    "migration_fraction": "higher",
}


def fingerprint(source: str, *, metric: Optional[str] = None,
                tokens_per_s: Optional[float] = None,
                step_time_s: Optional[float] = None,
                phase_fractions: Optional[Dict[str, float]] = None,
                compile_total: Optional[int] = None,
                compile_seconds: Optional[float] = None,
                hbm_watermark_bytes: Optional[int] = None,
                accepted_rate: Optional[float] = None,
                decode_tick_fraction: Optional[float] = None,
                prefill_chunk_fraction: Optional[float] = None,
                spec_verify_fraction: Optional[float] = None,
                adapter_hit_rate: Optional[float] = None,
                adapter_tokens_ratio: Optional[float] = None,
                migration_fraction: Optional[float] = None,
                run_metadata: Optional[Dict[str, Any]] = None,
                extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One compact perf fingerprint.  ``key`` scopes comparability:
    same producer, same headline metric, same platform/device kind."""
    meta = run_metadata or {}
    key = ":".join([
        str(source), str(metric or "-"),
        str(meta.get("platform", "?")), str(meta.get("device_kind", "?")),
    ])
    fp: Dict[str, Any] = {
        "schema": FINGERPRINT_SCHEMA,
        # tddl-lint: disable=tick-determinism — ledger wall stamp for
        # humans reading PERF_LEDGER.jsonl; never a comparison input
        # (the sentinel bands on metric values keyed by ``key``).
        "t": time.time(),
        "source": source,
        "key": key,
    }
    if metric is not None:
        fp["metric"] = metric
    for name, value in (("tokens_per_s", tokens_per_s),
                        ("step_time_s", step_time_s),
                        ("compile_total", compile_total),
                        ("compile_seconds", compile_seconds),
                        ("hbm_watermark_bytes", hbm_watermark_bytes),
                        ("accepted_rate", accepted_rate),
                        ("decode_tick_fraction", decode_tick_fraction),
                        ("prefill_chunk_fraction", prefill_chunk_fraction),
                        ("spec_verify_fraction", spec_verify_fraction),
                        ("adapter_hit_rate", adapter_hit_rate),
                        ("adapter_tokens_ratio", adapter_tokens_ratio),
                        ("migration_fraction", migration_fraction)):
        if value is not None:
            fp[name] = float(value)
    if phase_fractions:
        fp["phase_fractions"] = {k: round(float(v), 4)
                                 for k, v in phase_fractions.items()}
    if meta:
        fp["run_metadata"] = {
            k: meta[k] for k in ("platform", "device_kind", "num_devices",
                                 "jax_version", "framework_version")
            if k in meta
        }
    if extra:
        fp.update(extra)
    return fp


class PerfLedger:
    """Rolling JSONL of fingerprints.  ``keep`` bounds the FILE: an
    append that pushes past it rewrites the tail — the ledger is a
    trajectory window, not an archive."""

    def __init__(self, path: str, keep: int = 512):
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.path = str(path)
        self.keep = keep

    def read(self) -> List[Dict[str, Any]]:
        entries: List[Dict[str, Any]] = []
        try:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        entries.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # a torn line loses one row, not the file
        except OSError:
            pass
        return entries

    def append(self, fp: Dict[str, Any]) -> Dict[str, Any]:
        entries = self.read()
        entries.append(fp)
        if len(entries) > self.keep:
            entries = entries[-self.keep:]
        directory = os.path.dirname(self.path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for entry in entries:
                f.write(json.dumps(entry) + "\n")
        os.replace(tmp, self.path)
        return fp

    def baseline(self, key: str, limit: int = 20
                 ) -> List[Dict[str, Any]]:
        """The most recent ``limit`` prior entries for ``key`` (newest
        last).  Entries already marked regressed are EXCLUDED — a
        confirmed-bad round must not drag the band down to itself."""
        rows = [e for e in self.read()
                if e.get("key") == key and not e.get("regressed")]
        return rows[-limit:]

    def last(self, key: Optional[str] = None) -> Optional[Dict[str, Any]]:
        rows = self.read()
        if key is not None:
            rows = [e for e in rows if e.get("key") == key]
        return rows[-1] if rows else None


class PerfSentinel:
    """Noise-band comparison of one fingerprint against the ledger."""

    def __init__(self, ledger: PerfLedger, *, min_baseline: int = 3,
                 nsigma: float = 3.0, rel_floor: float = 0.05,
                 trace: Any = None, registry: Any = None):
        self.ledger = ledger
        self.min_baseline = min_baseline
        self.nsigma = nsigma
        self.rel_floor = rel_floor
        self.trace = trace
        self._regression_metric = None
        if registry is not None:
            self._regression_metric = registry.counter(
                "tddl_perf_regressions_total",
                "Fingerprint metrics outside the ledger noise band",
                labels=("metric",),
            )

    def check(self, fp: Dict[str, Any]) -> Dict[str, Any]:
        """Verdict: per-metric baseline mean / band / regressed flags.
        Fewer than ``min_baseline`` comparable prior rows → everything
        passes (no band to be outside of) and ``baseline_n`` says so."""
        baseline = self.ledger.baseline(fp.get("key", ""))
        checks: List[Dict[str, Any]] = []
        regressed = False
        for name, direction in SENTINEL_METRICS.items():
            value = fp.get(name)
            if value is None:
                continue
            values = [float(e[name]) for e in baseline if name in e]
            if len(values) < self.min_baseline:
                checks.append({"metric": name, "value": float(value),
                               "baseline_n": len(values),
                               "regressed": False})
                continue
            mean = sum(values) / len(values)
            var = sum((v - mean) ** 2 for v in values) / len(values)
            band = max(self.nsigma * math.sqrt(var),
                       self.rel_floor * abs(mean))
            if direction == "higher":
                bad = float(value) < mean - band
            else:
                bad = float(value) > mean + band
            delta_pct = ((float(value) - mean) / mean * 100.0
                         if mean else 0.0)
            checks.append({
                "metric": name, "value": float(value),
                "baseline_mean": mean, "band": band,
                "baseline_n": len(values), "direction": direction,
                "delta_pct": round(delta_pct, 2), "regressed": bad,
            })
            if bad:
                regressed = True
                if self._regression_metric is not None:
                    self._regression_metric.inc(metric=name)
                if self.trace is not None:
                    self.trace.emit(EventType.PERF_REGRESSION, metric=name,
                                    value=float(value), baseline=mean,
                                    band=band, key=fp.get("key"),
                                    delta_pct=round(delta_pct, 2))
        return {
            "key": fp.get("key"),
            "baseline_n": len(baseline),
            "regressed": regressed,
            "checks": checks,
        }


# ---------------------------------------------------------------------------
# Offline diff (the `trustworthy-dl-obs diff A B` subcommand body)
# ---------------------------------------------------------------------------


def load_perf_artifact(path: str) -> Dict[str, Any]:
    """One comparable perf view from an artifact path: an obs dir
    (obs_report.json + PERF_LEDGER.jsonl), an obs_report.json, or a
    perf-ledger JSONL (last fingerprint)."""
    out: Dict[str, Any] = {"path": path}
    report_path = ledger_path = None
    if os.path.isdir(path):
        report_path = os.path.join(path, "obs_report.json")
        ledger_path = os.path.join(path, "PERF_LEDGER.jsonl")
    elif path.endswith(".jsonl"):
        ledger_path = path
    else:
        report_path = path
    if report_path and os.path.exists(report_path):
        with open(report_path) as f:
            out["report"] = json.load(f)
    if ledger_path and os.path.exists(ledger_path):
        fp = PerfLedger(ledger_path).last()
        if fp is not None:
            out["fingerprint"] = fp
    if "report" not in out and "fingerprint" not in out:
        raise FileNotFoundError(
            f"{path!r} holds neither an obs_report.json nor a perf "
            "ledger fingerprint"
        )
    return out


def _flatten_perf(view: Dict[str, Any]) -> "List[Tuple[str, Any]]":
    """Comparable (label, value) rows from one artifact view."""
    rows: List[Tuple[str, Any]] = []
    report = view.get("report") or {}
    fp = view.get("fingerprint") or {}

    def add(label: str, value: Any) -> None:
        if value is not None:
            rows.append((label, value))

    step = report.get("step_time_s") or {}
    add("step_time_mean_s", step.get("mean") or fp.get("step_time_s"))
    add("step_time_p95_s", step.get("p95"))
    mfu = report.get("mfu") or {}
    if isinstance(mfu, dict):
        add("tokens_per_s_per_chip", mfu.get("tokens_per_s_per_chip"))
        add("mfu_nominal", mfu.get("mfu"))
    analyzed = report.get("mfu_analyzed") or {}
    if isinstance(analyzed, dict):
        add("mfu_analyzed", analyzed.get("mfu"))
    for phase, stats in sorted((report.get("phases") or {}).items()):
        add(f"phase_{phase}_fraction", stats.get("fraction"))
    for name, cost in sorted((report.get("cost_ledger") or {}).items()):
        add(f"flops[{name}]", cost.get("flops"))
        add(f"temp_bytes[{name}]", cost.get("temp_bytes"))
    compile_block = report.get("compile") or {}
    add("compile_total",
        compile_block.get("total", fp.get("compile_total")))
    add("compile_seconds",
        compile_block.get("seconds", fp.get("compile_seconds")))
    hbm = report.get("hbm") or {}
    add("hbm_watermark_bytes",
        hbm.get("watermark_bytes", fp.get("hbm_watermark_bytes")))
    add("tokens_per_s", fp.get("tokens_per_s"))
    add("accepted_rate", fp.get("accepted_rate"))
    add("decode_tick_fraction", fp.get("decode_tick_fraction"))
    return rows


def render_diff(a: Dict[str, Any], b: Dict[str, Any]) -> str:
    """Two artifact views side by side with relative deltas — the obs
    CLI prints this verbatim."""
    rows_a = dict(_flatten_perf(a))
    rows_b = dict(_flatten_perf(b))
    labels = list(rows_a) + [k for k in rows_b if k not in rows_a]
    name_a = a.get("path", "A")
    name_b = b.get("path", "B")
    width = max([len(label) for label in labels] + [6])
    lines = [f"A: {name_a}", f"B: {name_b}",
             f"{'':{width}}  {'A':>14}  {'B':>14}  {'delta':>9}",
             "-" * (width + 43)]

    def fmt(v: Any) -> str:
        if v is None:
            return "-"
        if isinstance(v, float):
            if v and (abs(v) >= 1e5 or abs(v) < 1e-3):
                return f"{v:.3e}"
            return f"{v:.4f}"
        return str(v)

    for label in labels:
        va, vb = rows_a.get(label), rows_b.get(label)
        delta = "-"
        if isinstance(va, (int, float)) and isinstance(vb, (int, float)) \
                and va:
            delta = f"{(vb - va) / abs(va) * 100.0:+.1f}%"
        lines.append(f"{label:{width}}  {fmt(va):>14}  {fmt(vb):>14}  "
                     f"{delta:>9}")
    return "\n".join(lines)

"""Incident forensics: one structured post-mortem per flight-dump-grade
episode — causal timeline, blast radius, reconciled counters.

The obs plane records every signal (typed trace events, per-request
attribution records, allocator journals, flight dumps, the perf
ledger) but correlating them after a quarantine or rollback used to be
a manual JSONL join.  The :class:`IncidentAssembler` performs that join
AT the episode and emits ``incident_NNN_<reason>.json`` next to the
flight dump:

* **causal chain** — trigger event → contributing signals → actions
  taken, each entry carrying its trace ``seq`` id so the timeline is
  replayable against the raw segments (``read_jsonl_rotated``);
* **blast radius** — every request that decoded off the suspect's KV
  blocks (via each attempt's ``journal`` key and the attribution
  ledger's per-block publisher records) or a quarantined tenant's
  adapter page, INCLUDING cross-replica reach via ``migrated_from``
  provenance — no over- or under-attribution, by the same ledger
  ``verify_attribution`` reconciles;
* **counters** — the fleet/supervisor counter snapshot at assembly,
  which drills reconcile exactly against ``predict_fleet()``.

Incident ``reason`` strings come from the registered vocabulary in
``analysis/contracts.py`` (``ARTIFACT_REASONS``) — a typo'd reason
would silently orphan an incident from its trigger, so the
``artifact-reason-vocab`` lint rule pins every literal call site.

Host-only by contract (HOST_ONLY_MODULES): incidents are assembled and
rendered on machines whose accelerator backend may be the thing that
broke.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence

from trustworthy_dl_tpu.obs.events import read_jsonl_rotated

INCIDENT_SCHEMA_VERSION = 1

#: Event types that count as CONTRIBUTING SIGNALS in a causal chain —
#: evidence that accumulated before the trigger.
SIGNAL_EVENTS = frozenset({
    "fleet_suspicion", "verdict_vote", "anomaly", "slo_breach",
    "compile_storm", "chaos_fault", "guard_trip", "hbm_pressure",
    "detection_verdict", "fleet_alert",
})

#: Event types that count as ACTIONS TAKEN — what the control plane did
#: about it.
ACTION_EVENTS = frozenset({
    "replica_transition", "kv_migration", "fleet_failover",
    "adapter_quarantine", "serve_quarantine", "fleet_scale",
    "supervisor_retry", "supervisor_rollback", "supervisor_restart",
    "ckpt_restore", "elastic_evict", "elastic_readmit", "flight_dump",
})

_INCIDENT_RE = re.compile(r"incident_(\d+)_(.+)\.json$")


def _placement_touches(att: Dict[str, Any]) -> bool:
    """True when an attempt/placement actually held physical state —
    an unplaced attempt (layout None, no blocks, slot -1) never touched
    the pool and must not inflate a blast radius."""
    if att.get("block_ids"):
        return True
    return att.get("layout") == "stripe" and att.get("slot", -1) >= 0


def blast_radius(records: Iterable[Dict[str, Any]],
                 suspect_journals: Sequence[str] = (),
                 adapter: Optional[str] = None,
                 tenant: Optional[str] = None) -> Dict[str, Any]:
    """Compute which requests a suspect touched, from ledger records.

    A request is in the radius iff (a) any of its attempts ran on a
    suspect allocator generation (``journal`` ∈ ``suspect_journals``)
    while holding blocks or a stripe slot, (b) any attempt's
    ``migrated_from`` provenance names a suspect journal (the stream
    STARTED on the suspect and was live-migrated off — cross-replica
    reach), or (c) it decoded through a quarantined ``adapter``'s page
    or belongs to a quarantined ``tenant``.  Pure and host-only so
    tests can pin exact sets against hand-built ledgers.
    """
    suspects = set(suspect_journals)
    via: Dict[Any, List[Dict[str, Any]]] = {}
    suspect_blocks: Dict[str, set] = {}

    def touch(journal: str, blocks: Iterable[int]) -> None:
        suspect_blocks.setdefault(journal, set()).update(blocks or ())

    for rec in records:
        rid = rec.get("request_id")
        if rec.get("admitted") is False:
            # Hedge losers / vote replays carry no canonical placement;
            # the canonical record's ``attempts`` list already owns
            # every placement this request ever held.
            continue
        attempts = rec.get("attempts") or [rec]
        hows: List[Dict[str, Any]] = []
        for att in attempts:
            journal = att.get("journal")
            if journal is None and att.get("replica") is not None:
                journal = f"{att.get('replica')}:{att.get('gen', 0)}"
            if journal in suspects and _placement_touches(att):
                blocks = sorted(att.get("block_ids") or [])
                hows.append({"journal": journal, "blocks": blocks})
                touch(journal, blocks)
            src = att.get("migrated_from")
            if src and src.get("journal") in suspects:
                blocks = sorted(src.get("block_ids") or [])
                hows.append({"journal": src["journal"], "blocks": blocks,
                             "migrated_from": src.get("replica")})
                touch(src["journal"], blocks)
        if adapter is not None and rec.get("adapter") == adapter:
            hows.append({"adapter": adapter,
                         "adapter_page": rec.get("adapter_page")})
        if tenant is not None and rec.get("tenant") == tenant:
            hows.append({"tenant": tenant})
        if hows:
            via.setdefault(rid, []).extend(hows)
    return {
        "requests": sorted(via),
        "via": {str(rid): via[rid] for rid in sorted(via)},
        "suspect_blocks": {j: sorted(b)
                           for j, b in sorted(suspect_blocks.items())},
    }


class IncidentAssembler:
    """Joins the run's artifacts into one incident JSON per episode.

    ``directory=None`` is the in-memory mode (bench arms): incidents
    are assembled and counted but no file is written.  Trace events
    resolve from, in order: an explicit ``events=`` list passed to
    :meth:`assemble`, a ``trace`` object exposing ``.events`` (the
    test RecordingTrace) or ``.jsonl_path`` (a TraceBus), or
    ``trace_path`` via :func:`read_jsonl_rotated` — sealed rotation
    segments included.
    """

    def __init__(self, directory: Optional[str] = None, *,
                 trace: Any = None, trace_path: Optional[str] = None,
                 ledger: Any = None, journals: Any = None,
                 perf_ledger: Any = None, verdicts: Any = None,
                 registry: Any = None,
                 run_meta: Optional[Dict[str, Any]] = None):
        self.directory = str(directory) if directory else None
        if self.directory:
            os.makedirs(self.directory, exist_ok=True)
        self.trace = trace
        self.trace_path = trace_path
        self.ledger = ledger
        self.journals = journals
        self.perf_ledger = perf_ledger
        self.verdicts = verdicts
        if run_meta is None:
            from trustworthy_dl_tpu.obs.meta import run_metadata

            # host_only: this module is in HOST_ONLY_MODULES — an
            # offline post-mortem must never initialise the backend.
            # The paired flight dump carries the device-probed stamp;
            # a live session passes its own ``run_meta`` to match.
            run_meta = run_metadata(host_only=True)
        self._run_meta = run_meta
        self._lock = threading.Lock()
        self._index = 0
        #: (incident_id, reason) in assembly order — the bench's counts
        #: source when no directory is attached.
        self.incidents: List[Dict[str, str]] = []
        self._incident_counter = None
        if registry is not None:
            self._incident_counter = registry.counter(
                "tddl_incidents_total",
                "Forensic incident reports assembled, by reason",
                labels=("reason",),
            )

    # -- sources ------------------------------------------------------------

    def _events(self) -> List[Dict[str, Any]]:
        events: List[Dict[str, Any]] = []
        if self.trace is not None and hasattr(self.trace, "events"):
            events = [dict(e) for e in self.trace.events]
        else:
            path = self.trace_path
            if path is None and self.trace is not None:
                path = getattr(self.trace, "jsonl_path", None)
            if path and os.path.exists(path):
                events = read_jsonl_rotated(path)
        for i, event in enumerate(events):
            event.setdefault("seq", i + 1)
        return events

    def _records(self) -> List[Dict[str, Any]]:
        if self.ledger is None:
            return []
        if hasattr(self.ledger, "records"):
            return self.ledger.records()
        return list(self.ledger)

    def counts_by_reason(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for inc in self.incidents:
            out[inc["reason"]] = out.get(inc["reason"], 0) + 1
        return dict(sorted(out.items()))

    # -- assembly -----------------------------------------------------------

    def _mentions(self, event: Dict[str, Any],
                  suspects: Optional[Sequence[int]],
                  adapter: Optional[str]) -> bool:
        """Does this event reference one of the suspects?  With no
        suspects named (training-plane episodes) every signal/action
        event is in scope — the trigger's step window bounds it."""
        if suspects is None and adapter is None:
            return True
        if suspects is not None:
            for key in ("replica", "from_replica", "to_replica",
                        "primary"):
                if event.get(key) in suspects:
                    return True
        if adapter is not None and event.get("adapter") == adapter:
            return True
        return False

    def assemble(self, reason: str, *,
                 step: Optional[int] = None,
                 tick: Optional[int] = None,
                 suspects: Optional[Sequence[int]] = None,
                 suspect_journals: Sequence[str] = (),
                 adapter: Optional[str] = None,
                 tenant: Optional[str] = None,
                 trigger_type: Optional[str] = None,
                 flight_path: Optional[str] = None,
                 directory: Optional[str] = None,
                 counters: Optional[Dict[str, int]] = None,
                 refusals: Optional[List[Dict[str, Any]]] = None,
                 events: Optional[List[Dict[str, Any]]] = None,
                 records: Optional[List[Dict[str, Any]]] = None,
                 extra: Optional[Dict[str, Any]] = None
                 ) -> Optional[str]:
        """Assemble and (when a directory is known) write one incident.

        Returns the written path, or ``None`` in in-memory mode.  The
        incident index pairs with the flight dump when ``flight_path``
        is given (``flight_007_x.json`` → ``incident_007_x.json``);
        otherwise it increments a private counter.
        """
        if events is None:
            events = self._events()
        else:
            events = [dict(e) for e in events]
            for i, event in enumerate(events):
                event.setdefault("seq", i + 1)
        if records is None:
            records = self._records()

        trigger: Optional[Dict[str, Any]] = None
        want = trigger_type or reason
        for event in events:
            if event.get("type") == want \
                    and self._mentions(event, suspects, adapter):
                trigger = event  # LAST matching event wins (the episode)
        if trigger is None:
            trigger = {"type": want, "seq": None, "synthetic": True}
        trigger_seq = trigger.get("seq")

        contributing = [
            e for e in events
            if e.get("type") in SIGNAL_EVENTS
            and self._mentions(e, suspects, adapter)
            and (trigger_seq is None or e.get("seq", 0) <= trigger_seq)
        ]
        actions = [
            e for e in events
            if e.get("type") in ACTION_EVENTS
            and self._mentions(e, suspects, adapter)
        ]

        radius = blast_radius(records, suspect_journals=suspect_journals,
                              adapter=adapter, tenant=tenant)

        perf_tail = None
        if self.perf_ledger is not None:
            try:
                perf_tail = self.perf_ledger.last()
            except (OSError, AttributeError):
                perf_tail = None

        with self._lock:
            index = None
            if flight_path:
                m = re.search(r"flight_(\d+)_", os.path.basename(
                    flight_path))
                if m:
                    index = int(m.group(1))
            if index is None:
                index = self._index
            self._index = max(self._index + 1, index + 1)
            incident_id = f"incident_{index:03d}_{reason}"
            self.incidents.append({"incident_id": incident_id,
                                   "reason": reason})

        incident: Dict[str, Any] = {
            "schema_version": INCIDENT_SCHEMA_VERSION,
            "incident_id": incident_id,
            "reason": reason,
            "step": step, "tick": tick,
            "suspect_replicas": list(suspects) if suspects else [],
            "suspect_journals": list(suspect_journals),
            "adapter": adapter, "tenant": tenant,
            "flight_dump": flight_path,
            "trigger": trigger,
            "contributing": contributing,
            "actions": actions,
            "blast_radius": radius,
            "counters": dict(counters or {}),
            "refused_destinations": list(refusals or []),
            "perf_tail": perf_tail,
            "t": time.time(),
            "run_metadata": self._run_meta,
        }
        if extra:
            incident["extra"] = dict(extra)

        directory = directory or self.directory
        path = None
        if directory:
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(directory, incident_id + ".json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(incident, f, indent=2, default=str)
            os.replace(tmp, path)
        if self._incident_counter is not None:
            self._incident_counter.inc(reason=reason)
        if self.verdicts is not None:
            self.verdicts.append(
                "incident", "recorded", reason=reason,
                replica=suspects[0] if suspects else None,
                adapter=adapter, tenant=tenant,
                incident_id=incident_id, tick=tick, step=step)
        if self.trace is not None and hasattr(self.trace, "emit"):
            from trustworthy_dl_tpu.obs.events import EventType

            self.trace.emit(EventType.INCIDENT, incident_id=incident_id,
                            reason=reason, path=path, step=step)
        return path


# -- offline readers (the obs CLI renders from these) ------------------------


def load_incidents(directory: str) -> List[Dict[str, Any]]:
    """All ``incident_NNN_<reason>.json`` files under ``directory``,
    sorted by index; unreadable files are skipped (torn-artifact
    tolerance, same stance as the ledgers)."""
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        m = _INCIDENT_RE.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                out.append(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
    out.sort(key=lambda inc: inc.get("incident_id", ""))
    return out


def find_incident(directory: str, ident: str) -> Optional[Dict[str, Any]]:
    """Look an incident up by full id, bare index ("7"), or reason
    substring (first match wins)."""
    incidents = load_incidents(directory)
    for inc in incidents:
        if inc.get("incident_id") == ident:
            return inc
    if ident.isdigit():
        idx = int(ident)
        for inc in incidents:
            m = _INCIDENT_RE.match(inc.get("incident_id", "") + ".json")
            if m and int(m.group(1)) == idx:
                return inc
    for inc in incidents:
        if ident in inc.get("incident_id", ""):
            return inc
    return None


def _event_line(event: Dict[str, Any]) -> str:
    seq = event.get("seq")
    etype = event.get("type", "?")
    keys = ("replica", "from_replica", "to_replica", "from_state",
            "to_state", "reason", "outcome", "request_id", "adapter",
            "kind", "score", "signal", "metric", "step", "tick")
    detail = " ".join(f"{k}={event[k]}" for k in keys
                      if event.get(k) is not None)
    return f"  [seq {seq if seq is not None else '—'}] {etype} {detail}"


def render_incident(incident: Dict[str, Any]) -> str:
    """Human-readable causal timeline for ``incident show``."""
    lines = [
        f"{incident.get('incident_id')}  reason={incident.get('reason')}"
        f"  tick={incident.get('tick')}  step={incident.get('step')}",
        f"suspects: replicas={incident.get('suspect_replicas')} "
        f"journals={incident.get('suspect_journals')} "
        f"adapter={incident.get('adapter')}",
    ]
    if incident.get("flight_dump"):
        lines.append(f"flight dump: {incident['flight_dump']}")
    lines.append("trigger:")
    lines.append(_event_line(incident.get("trigger") or {}))
    lines.append(f"contributing signals "
                 f"({len(incident.get('contributing') or [])}):")
    lines.extend(_event_line(e)
                 for e in incident.get("contributing") or [])
    lines.append(f"actions taken ({len(incident.get('actions') or [])}):")
    lines.extend(_event_line(e) for e in incident.get("actions") or [])
    if incident.get("refused_destinations"):
        lines.append("refused destinations:")
        lines.extend(f"  replica {r.get('replica')}: {r.get('reason')}"
                     for r in incident["refused_destinations"])
    counters = incident.get("counters") or {}
    hot = {k: v for k, v in counters.items() if v}
    if hot:
        lines.append("counters at assembly: " + ", ".join(
            f"{k}={v}" for k, v in sorted(hot.items())))
    radius = incident.get("blast_radius") or {}
    lines.append(f"blast radius: {len(radius.get('requests') or [])} "
                 f"request(s) {radius.get('requests')}")
    return "\n".join(lines)


def render_blast(incident: Dict[str, Any]) -> str:
    """Per-request blast-radius detail for ``incident blast``."""
    radius = incident.get("blast_radius") or {}
    lines = [f"{incident.get('incident_id')}  blast radius "
             f"({len(radius.get('requests') or [])} requests)"]
    via = radius.get("via") or {}
    for rid in radius.get("requests") or []:
        lines.append(f"request {rid}:")
        for how in via.get(str(rid), []):
            if "journal" in how:
                src = (f" (migrated from replica "
                       f"{how['migrated_from']})"
                       if "migrated_from" in how else "")
                lines.append(f"  journal {how['journal']} blocks "
                             f"{how.get('blocks')}{src}")
            elif "adapter" in how:
                lines.append(f"  adapter {how['adapter']} page "
                             f"{how.get('adapter_page')}")
            elif "tenant" in how:
                lines.append(f"  tenant {how['tenant']}")
    blocks = radius.get("suspect_blocks") or {}
    if blocks:
        lines.append("suspect blocks by journal:")
        lines.extend(f"  {j}: {b}" for j, b in blocks.items())
    return "\n".join(lines)

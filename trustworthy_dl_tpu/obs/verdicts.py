"""VerdictStore — durable cross-plane trust history (ROADMAP item 5a's
data interface).

The fleet's trust machinery produces VERDICTS — suspicion episodes
opening and closing, cross-replica vote outcomes, replica and adapter
quarantines, readmissions, assembled incidents — but until this module
they lived only in the trace stream of the run that produced them.  The
VerdictStore is the durable, queryable aggregation both planes read:
one JSONL file (keep-trim, torn-line tolerant, ``run_metadata``-stamped
— the :class:`~trustworthy_dl_tpu.obs.sentinel.PerfLedger` pattern)
whose entries accumulate ACROSS runs, so a replica family that
misbehaved while serving can start its next training round with a
prior instead of a clean slate.

Entry shape (one JSON object per line)::

    {"kind": "vote", "outcome": "outvoted", "replica": 2,
     "tenant": null, "adapter": null, "reason": "verdict_outvoted",
     "request_id": 7, "incident_id": null, "tick": 9, "step": null,
     "t": 1722700000.1, "run_metadata": {...}}

``kind`` ∈ {"suspicion", "vote", "quarantine", "adapter_quarantine",
"incident"}; ``outcome`` is the small label vocabulary the
``tddl_verdicts_total{outcome=}`` counter pages on ("opened",
"confirmed", "outvoted", "inconclusive", "quarantined", "readmitted",
"recorded").

Host-only by contract (``analysis/contracts.py`` HOST_ONLY_MODULES):
the training plane consumes priors on machines whose serving backend
may be the broken thing.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

#: The closed outcome vocabulary — the label set of
#: ``tddl_verdicts_total{outcome=}`` (bounded cardinality by contract).
VERDICT_OUTCOMES = (
    "opened", "closed", "confirmed", "outvoted", "inconclusive",
    "quarantined", "readmitted", "recorded",
)


class VerdictStore:
    """Rolling JSONL of trust verdicts.  ``keep`` bounds the FILE: an
    append past it rewrites the tail — a trajectory window of recent
    trust history, not an archive (the trace segments are the
    archive)."""

    def __init__(self, path: str, keep: int = 512, *,
                 run_meta: Optional[Dict[str, Any]] = None,
                 registry: Any = None, trace: Any = None):
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.path = str(path)
        self.keep = keep
        self.trace = trace
        if run_meta is None:
            from trustworthy_dl_tpu.obs.meta import run_metadata

            # host_only: the store is in HOST_ONLY_MODULES — appending
            # a verdict must never initialise the backend (the training
            # plane reads priors on machines whose serving backend may
            # be the broken thing).
            run_meta = run_metadata(host_only=True)
        self._run_meta = run_meta
        self._verdict_counter = None
        if registry is not None:
            self._verdict_counter = registry.counter(
                "tddl_verdicts_total",
                "Durable trust verdicts appended to the VerdictStore",
                labels=("outcome",),
            )

    def read(self) -> List[Dict[str, Any]]:
        entries: List[Dict[str, Any]] = []
        try:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        entries.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # a torn line loses one row, not the file
        except OSError:
            pass
        return entries

    def append(self, kind: str, outcome: str, *,
               replica: Optional[int] = None,
               tenant: Optional[str] = None,
               adapter: Optional[str] = None,
               reason: Optional[str] = None,
               request_id: Optional[int] = None,
               incident_id: Optional[str] = None,
               tick: Optional[int] = None,
               step: Optional[int] = None,
               extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        if outcome not in VERDICT_OUTCOMES:
            raise ValueError(f"unknown verdict outcome {outcome!r} "
                             f"(vocabulary: {VERDICT_OUTCOMES})")
        entry: Dict[str, Any] = {
            "kind": kind, "outcome": outcome, "replica": replica,
            "tenant": tenant, "adapter": adapter, "reason": reason,
            "request_id": request_id, "incident_id": incident_id,
            "tick": tick, "step": step, "t": time.time(),
            "run_metadata": self._run_meta,
        }
        if extra:
            entry.update(extra)
        entries = self.read()
        entries.append(entry)
        if len(entries) > self.keep:
            entries = entries[-self.keep:]
        directory = os.path.dirname(self.path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for row in entries:
                f.write(json.dumps(row) + "\n")
        os.replace(tmp, self.path)
        if self._verdict_counter is not None:
            self._verdict_counter.inc(outcome=outcome)
        if self.trace is not None:
            from trustworthy_dl_tpu.obs.events import EventType

            self.trace.emit(EventType.VERDICT, kind=kind, outcome=outcome,
                            replica=replica, adapter=adapter,
                            reason=reason)
        return entry

    # -- the item-5a read interface -----------------------------------------

    def history(self, *, replica: Optional[int] = None,
                tenant: Optional[str] = None,
                adapter: Optional[str] = None) -> List[Dict[str, Any]]:
        """Entries for one subject, oldest first (filters AND)."""
        rows = self.read()
        if replica is not None:
            rows = [r for r in rows if r.get("replica") == replica]
        if tenant is not None:
            rows = [r for r in rows if r.get("tenant") == tenant]
        if adapter is not None:
            rows = [r for r in rows if r.get("adapter") == adapter]
        return rows

    def priors(self) -> Dict[str, Any]:
        """Aggregate the window into per-subject trust priors — the
        exact shape the training-side trust manager folds into its
        initial scores: per replica/tenant/adapter, counts by
        (kind, outcome) plus the incident ids on record."""
        out: Dict[str, Any] = {"replicas": {}, "tenants": {},
                               "adapters": {}}

        def bucket(table: Dict[str, Any], key: Any) -> Dict[str, Any]:
            key = str(key)
            if key not in table:
                table[key] = {"counts": {}, "incidents": []}
            return table[key]

        for row in self.read():
            subjects = []
            if row.get("replica") is not None:
                subjects.append(bucket(out["replicas"], row["replica"]))
            if row.get("tenant") is not None:
                subjects.append(bucket(out["tenants"], row["tenant"]))
            if row.get("adapter") is not None:
                subjects.append(bucket(out["adapters"], row["adapter"]))
            label = f"{row.get('kind')}:{row.get('outcome')}"
            for subject in subjects:
                subject["counts"][label] = \
                    subject["counts"].get(label, 0) + 1
                iid = row.get("incident_id")
                if iid and iid not in subject["incidents"]:
                    subject["incidents"].append(iid)
        return out

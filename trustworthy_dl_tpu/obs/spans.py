"""Hierarchical spans: timed, nestable units of work emitted through the
trace bus and exportable as a Chrome/Perfetto ``trace_events`` timeline.

Events (obs/events.py) answer *what happened*; spans answer *where the
time went*.  A span is one record — id, optional parent id, kind, name,
start/end monotonic timestamps, free-form attrs — correlated on the same
``step``/``request_id`` keys as every other trace row, so a reader can
join a request's ``serve.decode`` span against its ``serve_retire``
event, or a training step's ``train.compute`` span against its
``train_step`` row.

Design constraints (the serving hot loop runs through this):

* **Emit-on-close only.**  A span becomes one ``span`` trace event when
  it ENDS (start time and duration both known), so tracking N open spans
  costs N small dicts and the trace stays one-line-per-span.  There is
  no span-start event to pair up or leak.
* **Bounded memory.**  Open spans live in a dict keyed by id; closed
  spans are retained in a ring (``keep``) solely for in-process Chrome
  export — the durable record is the trace JSONL, which the CLI can
  convert without any retained state (:func:`chrome_trace_from_events`).
* **Host-only.**  Nothing here touches jax; ``time.perf_counter`` laps
  on the host step/iteration loop, exactly like obs/report.py.

Chrome export: ``chrome://tracing`` / https://ui.perfetto.dev consume
the JSON object format ``{"traceEvents": [{"ph": "X", ...}]}``; complete
("X") events need only name/cat/ts/dur/pid/tid, with attrs as ``args``.
The track (``tid``) is the request id for serving spans, so concurrent
requests render as parallel lanes; training spans all share one lane
(sequential steps read as a timeline, not a per-step ladder — the step
id rides in ``args``).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Sequence

from trustworthy_dl_tpu.utils.io import atomic_write_json


@dataclasses.dataclass
class Span:
    """One closed (or still-open, ``end is None``) unit of work."""

    span_id: int
    name: str
    kind: str
    start: float                      # time.perf_counter() domain
    end: Optional[float] = None
    parent_id: Optional[int] = None
    step: Optional[int] = None
    request_id: Optional[int] = None
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def duration_s(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start


class SpanTracker:
    """Start/end span bookkeeping + emission through a TraceBus.

    ``trace`` is any object with the TraceBus ``emit`` signature (or
    None — spans are then only retained for :meth:`export_chrome`).
    Thread-safe: the serving engine and an async drain may both close
    spans.
    """

    def __init__(self, trace: Any = None, keep: int = 8192):
        self.trace = trace
        self._lock = threading.Lock()
        self._open: Dict[int, Span] = {}
        self._closed: collections.deque = collections.deque(maxlen=keep)
        self._next_id = 0
        self._dropped = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self, name: str, kind: str = "span", *,
              parent_id: Optional[int] = None, step: Optional[int] = None,
              request_id: Optional[int] = None, t: Optional[float] = None,
              **attrs: Any) -> int:
        """Open a span; returns its id (pass as ``parent_id`` to nest)."""
        with self._lock:
            self._next_id += 1
            sid = self._next_id
            self._open[sid] = Span(
                span_id=sid, name=name, kind=kind,
                start=time.perf_counter() if t is None else t,
                parent_id=parent_id, step=step, request_id=request_id,
                attrs=dict(attrs),
            )
        return sid

    def end(self, span_id: int, t: Optional[float] = None,
            **attrs: Any) -> Optional[Span]:
        """Close a span and emit it.  Unknown/already-closed ids are a
        no-op returning None (a retire path may race a shed path; the
        second close must not corrupt the record)."""
        with self._lock:
            span = self._open.pop(span_id, None)
            if span is None:
                self._dropped += 1
                return None
            span.end = time.perf_counter() if t is None else t
            span.attrs.update(attrs)
            self._closed.append(span)
        self._emit(span)
        return span

    def add(self, name: str, start: float, end: float, kind: str = "span",
            *, parent_id: Optional[int] = None, step: Optional[int] = None,
            request_id: Optional[int] = None, **attrs: Any) -> Span:
        """Record an already-measured span in one call (the trainer's
        per-phase laps are synthesized this way at ``finish_step``)."""
        with self._lock:
            self._next_id += 1
            span = Span(span_id=self._next_id, name=name, kind=kind,
                        start=start, end=end, parent_id=parent_id,
                        step=step, request_id=request_id, attrs=dict(attrs))
            self._closed.append(span)
        self._emit(span)
        return span

    @contextmanager
    def span(self, name: str, kind: str = "span",
             **kwargs: Any) -> Iterator[int]:
        sid = self.start(name, kind, **kwargs)
        try:
            yield sid
        finally:
            self.end(sid)

    def _emit(self, span: Span) -> None:
        if self.trace is None:
            return
        from trustworthy_dl_tpu.obs.events import EventType

        self.trace.emit(
            EventType.SPAN, step=span.step, request_id=span.request_id,
            name=span.name, kind=span.kind, span_id=span.span_id,
            parent_id=span.parent_id, duration_s=span.duration_s,
            start_mono=span.start, **span.attrs,
        )

    # -- introspection -----------------------------------------------------

    @property
    def open_count(self) -> int:
        with self._lock:
            return len(self._open)

    def closed_spans(self) -> List[Span]:
        with self._lock:
            return list(self._closed)

    # -- Chrome/Perfetto export -------------------------------------------

    def export_chrome(self, path: Optional[str] = None) -> Dict[str, Any]:
        """Closed spans as a ``{"traceEvents": [...]}`` object (written
        to ``path`` when given) — load in chrome://tracing / Perfetto."""
        events = [_chrome_event(
            s.name, s.kind, s.start, s.duration_s or 0.0,
            step=s.step, request_id=s.request_id, span_id=s.span_id,
            parent_id=s.parent_id, attrs=s.attrs,
        ) for s in self.closed_spans()]
        payload = {"traceEvents": events, "displayTimeUnit": "ms"}
        if path is not None:
            atomic_write_json(path, payload, indent=None)
        return payload


def _chrome_event(name: str, kind: str, start: float, duration: float, *,
                  step: Optional[int], request_id: Optional[int],
                  span_id: Any, parent_id: Any,
                  attrs: Dict[str, Any]) -> Dict[str, Any]:
    # Track layout: serving spans lane per request, training spans lane
    # per kind (all steps on one lane reads as a timeline, not a ladder).
    if request_id is not None:
        pid, tid = 1, int(request_id)
    else:
        pid, tid = 0, 0
    args = {k: v for k, v in attrs.items() if v is not None}
    if step is not None:
        args["step"] = step
    if parent_id is not None:
        args["parent_id"] = parent_id
    return {
        "name": name, "cat": kind, "ph": "X",
        "ts": start * 1e6, "dur": max(duration, 0.0) * 1e6,
        "pid": pid, "tid": tid, "id": span_id, "args": args,
    }


def chrome_trace_from_events(events: Sequence[Dict[str, Any]],
                             path: Optional[str] = None) -> Dict[str, Any]:
    """Convert ``span`` rows of a trace JSONL (obs/events.py) into the
    Chrome trace_events object — the CLI's offline exporter, needing no
    in-process SpanTracker state."""
    meta_keys = {"seq", "t", "t_mono", "type", "name", "kind", "span_id",
                 "parent_id", "duration_s", "start_mono", "step",
                 "request_id"}
    out = []
    for e in events:
        if e.get("type") != "span" or e.get("duration_s") is None:
            continue
        out.append(_chrome_event(
            e.get("name", "?"), e.get("kind", "span"),
            float(e.get("start_mono", 0.0)), float(e["duration_s"]),
            step=e.get("step"), request_id=e.get("request_id"),
            span_id=e.get("span_id"), parent_id=e.get("parent_id"),
            attrs={k: v for k, v in e.items() if k not in meta_keys},
        ))
    payload = {"traceEvents": out, "displayTimeUnit": "ms"}
    if path is not None:
        atomic_write_json(path, payload, indent=None)
    return payload

"""Live-HBM accounting + per-program cost ledger.

Answers the two runtime questions the passive obs plane could not:
*how much accelerator memory is live right now* (and at peak), and
*what does each compiled program actually cost* — analyzed FLOPs and
bytes from XLA itself instead of the 6·params·tokens estimate and a
marketing peak table.

* :func:`live_buffer_bytes` — one ``jax.live_arrays()`` sweep grouped
  by device.  :class:`HbmMonitor` turns sweeps into
  ``tddl_hbm_live_bytes{device=}`` gauges, a monotone
  ``tddl_hbm_watermark_bytes{device=}`` watermark, typed ``hbm_sweep``
  events, and a **headroom gate**: the serve engine (and each fleet
  replica build/restart) calls :meth:`HbmMonitor.admit` before
  allocating a paged KV pool — low headroom shrinks/denies the growth
  instead of discovering the OOM at ``device_put`` time
  (``hbm_pressure`` event + ``tddl_hbm_pressure_total``).
* :func:`analyze_program` / :class:`CostLedger` — the
  ``lowered.cost_analysis()`` / ``compiled.memory_analysis()`` pattern
  proven in ``experiments/pipeline_study.py``, generalized: per-program
  FLOPs + bytes accessed from lowering (cheap — no backend compile),
  temp/argument/output allocation from the compiled executable when
  ``memory=True`` (one extra AOT compile; default gated on
  ``TDDL_OBS_MEMORY_ANALYSIS=1`` so attaching obs never doubles a big
  model's compile time silently).  The ledger lands in
  ``obs_report.json`` and feeds the **analyzed-FLOPs MFU** that
  replaces the nominal-peak-table guess (obs/report.py).

jax is imported lazily inside the functions — the obs CLI imports this
package with no jax present.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional

from trustworthy_dl_tpu.obs.events import EventType

logger = logging.getLogger(__name__)


def live_buffer_bytes() -> Dict[str, int]:
    """Bytes of live (undeleted, undonated) jax arrays per device.
    Committed single-device arrays count fully on their device; sharded
    arrays split their bytes evenly across their device set (addressable
    shard sizes are not exposed uniformly on 0.4.x)."""
    import jax

    out: Dict[str, int] = {}
    for arr in jax.live_arrays():
        try:
            devices = list(arr.devices())
            nbytes = int(arr.nbytes)
        except Exception:  # deleted/donated mid-sweep
            continue
        if not devices:
            continue
        share = nbytes // len(devices)
        for dev in devices:
            key = str(dev)
            out[key] = out.get(key, 0) + share
    return out


def device_budget_bytes() -> Optional[int]:
    """Per-device HBM budget: ``TDDL_HBM_BUDGET_BYTES`` env wins, else
    the backend's own ``memory_stats()['bytes_limit']`` (TPU/GPU), else
    None (unknown — CPU backends report no limit)."""
    env = os.environ.get("TDDL_HBM_BUDGET_BYTES")
    if env:
        return int(float(env))
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return None


class HbmMonitor:
    """Watermark gauges + the pool-growth headroom gate."""

    def __init__(self, registry: Any = None, trace: Any = None,
                 budget_bytes: Optional[int] = None,
                 reserve_fraction: float = 0.0):
        # ``reserve_fraction``: slack kept free even when admitting (a
        # pool sized to the last byte leaves nothing for activations).
        self.trace = trace
        self.budget_bytes = (int(budget_bytes) if budget_bytes is not None
                             else device_budget_bytes())
        self.reserve_fraction = float(reserve_fraction)
        self.watermark: Dict[str, int] = {}
        self.last_sweep: Dict[str, int] = {}
        #: Headroom measured by the LAST admit()/headroom_bytes() call —
        #: a denied caller sizes its shrunk allocation from THIS value,
        #: so the deny decision and the re-size use one sweep (a second
        #: sweep could report different headroom than the gate enforced).
        self.last_headroom: Optional[int] = None
        self.pressure_denials = 0
        self._live_gauge = None
        self._mark_gauge = None
        self._pressure_metric = None
        if registry is not None:
            self._live_gauge = registry.gauge(
                "tddl_hbm_live_bytes",
                "Live jax array bytes, by device (last sweep)",
                labels=("device",),
            )
            self._mark_gauge = registry.gauge(
                "tddl_hbm_watermark_bytes",
                "Peak live jax array bytes ever swept, by device",
                labels=("device",),
            )
            self._pressure_metric = registry.counter(
                "tddl_hbm_pressure_total",
                "Pool growths denied/shrunk by the headroom gate",
            )

    # -- sweeps ------------------------------------------------------------

    def sweep(self, step: Optional[int] = None,
              emit: bool = False) -> Dict[str, Any]:
        """One live-buffer sweep: update gauges + watermark; optionally
        emit a typed ``hbm_sweep`` event (sweeps can be frequent — the
        event is for cadence points, the gauges for dashboards)."""
        per_device = live_buffer_bytes()
        self.last_sweep = per_device
        for device, nbytes in per_device.items():
            peak = max(self.watermark.get(device, 0), nbytes)
            self.watermark[device] = peak
            if self._live_gauge is not None:
                self._live_gauge.set(float(nbytes), device=device)
                self._mark_gauge.set(float(peak), device=device)
        summary = {
            "per_device": per_device,
            "total_bytes": sum(per_device.values()),
            "watermark_bytes": self.watermark_bytes,
        }
        if emit and self.trace is not None:
            self.trace.emit(EventType.HBM_SWEEP, step=step,
                            live_bytes=summary["total_bytes"],
                            watermark_bytes=summary["watermark_bytes"],
                            devices=len(per_device))
        return summary

    @property
    def watermark_bytes(self) -> int:
        """Peak single-device live bytes (the OOM-relevant number)."""
        return max(self.watermark.values()) if self.watermark else 0

    def headroom_bytes(self) -> Optional[int]:
        """Budget minus the busiest device's CURRENT live bytes (after a
        fresh sweep), minus the reserve.  None when no budget is known."""
        if self.budget_bytes is None:
            self.last_headroom = None
            return None
        self.sweep()
        used = max(self.last_sweep.values()) if self.last_sweep else 0
        reserve = int(self.budget_bytes * self.reserve_fraction)
        self.last_headroom = self.budget_bytes - used - reserve
        return self.last_headroom

    # -- the growth gate ---------------------------------------------------

    def admit(self, requested_bytes: int, what: str = "",
              step: Optional[int] = None) -> bool:
        """May ``requested_bytes`` of new device allocation proceed?
        Unknown budget → always True (the gate never blocks dev boxes);
        a denial emits ``hbm_pressure`` so the refusal is attributable."""
        headroom = self.headroom_bytes()
        if headroom is None or requested_bytes <= headroom:
            return True
        self.pressure_denials += 1
        logger.warning(
            "HBM pressure: %s wants %d bytes but headroom is %d "
            "(budget %d, reserve %.0f%%) — growth denied",
            what or "allocation", requested_bytes, headroom,
            self.budget_bytes, self.reserve_fraction * 100,
        )
        if self._pressure_metric is not None:
            self._pressure_metric.inc()
        if self.trace is not None:
            self.trace.emit(EventType.HBM_PRESSURE, step=step,
                            requested_bytes=int(requested_bytes),
                            headroom_bytes=int(headroom),
                            what=what or None)
        return False


# ---------------------------------------------------------------------------
# Per-program cost ledger
# ---------------------------------------------------------------------------


def _normalize_cost(cost: Any) -> Dict[str, float]:
    """jax's cost_analysis returns a dict (Lowered) or a 1-list of dicts
    (Compiled) depending on path/version — normalize to one dict."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost or {})


def memory_analysis_enabled() -> bool:
    return os.environ.get("TDDL_OBS_MEMORY_ANALYSIS") == "1"


def analyze_program(fn: Any, *args: Any, memory: Optional[bool] = None,
                    **kwargs: Any) -> Dict[str, Any]:
    """Cost block for one jitted callable at concrete ``args``:
    ``flops`` / ``bytes_accessed`` from ``lower().cost_analysis()``
    (no backend compile), plus compiled ``memory_analysis`` fields
    (temp/argument/output/code bytes) when ``memory`` is on."""
    if memory is None:
        memory = memory_analysis_enabled()
    lowered = fn.lower(*args, **kwargs)
    cost = _normalize_cost(lowered.cost_analysis())
    out: Dict[str, Any] = {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        "cost_source": "lowered.cost_analysis",
    }
    if memory:
        compiled = lowered.compile()
        ccost = _normalize_cost(compiled.cost_analysis())
        if ccost.get("flops"):
            out["flops"] = float(ccost["flops"])
            out["bytes_accessed"] = float(ccost.get("bytes accessed", 0.0))
            out["cost_source"] = "compiled.cost_analysis"
        try:
            mem = compiled.memory_analysis()
            out["temp_bytes"] = int(
                getattr(mem, "temp_size_in_bytes", 0))
            out["argument_bytes"] = int(
                getattr(mem, "argument_size_in_bytes", 0))
            out["output_bytes"] = int(
                getattr(mem, "output_size_in_bytes", 0))
            out["generated_code_bytes"] = int(
                getattr(mem, "generated_code_size_in_bytes", 0))
        except Exception:  # backend without memory_analysis
            pass
    return out


class CostLedger:
    """Named compiled programs → analyzed cost blocks, stamped into
    ``obs_report.json`` (StepTimeReporter reads ``programs``)."""

    def __init__(self) -> None:
        self.programs: Dict[str, Dict[str, Any]] = {}

    def note(self, name: str, cost: Dict[str, Any]) -> None:
        self.programs[str(name)] = dict(cost)

    def analyze(self, name: str, fn: Any, *args: Any,
                memory: Optional[bool] = None, **kwargs: Any) -> None:
        """Analyze-and-note; failures degrade to an ``error`` entry — a
        cost stamp must never be the reason a run dies."""
        try:
            self.note(name, analyze_program(fn, *args, memory=memory,
                                            **kwargs))
        except Exception as exc:
            logger.debug("cost analysis of %r failed", name, exc_info=True)
            self.programs[str(name)] = {
                "error": f"{type(exc).__name__}: {str(exc)[:120]}"
            }

    def flops(self, name: str) -> Optional[float]:
        entry = self.programs.get(name)
        if entry and entry.get("flops"):
            return float(entry["flops"])
        return None

    def to_dict(self) -> Dict[str, Any]:
        return {name: dict(entry)
                for name, entry in sorted(self.programs.items())}

    def __bool__(self) -> bool:
        return bool(self.programs)

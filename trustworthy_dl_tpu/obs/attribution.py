"""Per-request attribution ledger: which replica, slot, physical KV
blocks, weight tier and monitor verdict produced each served stream.

The trust claim on the serving side is only auditable if a finished
token stream can be traced back to the physical state that produced it.
At stream completion the engine appends ONE durable record per request:

.. code-block:: json

    {"request_id": 7, "status": "completed", "admitted": true,
     "slot": 2, "layout": "paged",
     "block_ids": [3, 9, 14], "prefix_block_ids": [3],
     "prefix_publishers": {"3": 1},
     "kv_dtype": "int8", "weight_dtype": "model",
     "kv_fallback_reason": null,
     "flagged": false, "monitor_z": 0.41,
     "tokens": 12, "token_hash": "a3f0c2...", "t": 1722700000.1}

appended as JSONL beside the trace (``attribution.jsonl``; the first
line is a header carrying the replica's ``run_metadata`` once, not per
record), mirrored as a compact ``attribution`` trace-bus event, and
retained in a bounded in-memory ring for :func:`verify_attribution` —
which cross-checks the recorded block ids against the
``BlockAllocator``'s lifecycle journal (every claimed block was really
allocated; references never went negative; an unreferenced block is on
the free list or quarantined, never limbo).

``token_hash`` is a sha256 over the emitted int32 token ids — cheap
evidence two replicas (or a replay) produced the same stream without
shipping the stream itself.
"""

from __future__ import annotations

import collections
import hashlib
import json
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np


def token_hash(tokens: Sequence[int]) -> str:
    """sha256 hex digest of the token-id stream (int32 little-endian)."""
    arr = np.asarray(list(tokens), np.int32)
    return hashlib.sha256(arr.tobytes()).hexdigest()


class AttributionLedger:
    """Durable JSONL sink + bounded in-memory ring of per-request
    attribution records.

    ``path=None`` is the in-memory mode (tests, in-process fleets); the
    ring (``keep``) bounds host memory under the million-user framing —
    the FILE is the durable record, the ring is the working set
    ``verify_attribution`` and the monitor drills read."""

    def __init__(self, path: Optional[str] = None, keep: int = 4096,
                 run_meta: Optional[Dict[str, Any]] = None):
        self.path = str(path) if path else None
        self._ring: collections.deque = collections.deque(maxlen=keep)
        self._lock = threading.Lock()
        self._file: Any = None
        self._closed = False
        self.total = 0
        if self.path is not None:
            if run_meta is None:
                from trustworthy_dl_tpu.obs.meta import run_metadata

                run_meta = run_metadata()
            self._file = open(self.path, "a", buffering=1)
            self._file.write(json.dumps(
                {"header": True, "run_metadata": run_meta}
            ) + "\n")

    def append(self, record: Dict[str, Any]) -> Dict[str, Any]:
        record = dict(record)
        record.setdefault("t", time.time())
        with self._lock:
            self.total += 1
            self._ring.append(record)
            if self._file is not None and not self._closed:
                self._file.write(json.dumps(record) + "\n")
        return record

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._file is not None:
                self._file.close()
                self._file = None


def read_ledger(path: str) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Load a ledger file back as ``(header, records)``."""
    header: Dict[str, Any] = {}
    records: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if row.get("header"):
                header = row
            else:
                records.append(row)
    return header, records


def verify_attribution(records: Iterable[Dict[str, Any]],
                       allocator: Any) -> Tuple[bool, List[str]]:
    """Cross-check ledger records against a ``BlockAllocator``'s
    lifecycle journal.  Returns ``(ok, problems)``.

    Checks per admitted paged record:

    * block ids are unique, in ``[1, num_blocks]`` and never the trash
      block (a record claiming block 0 would mean a request attended to
      the garbage sink);
    * every claimed block has at least one ``alloc`` journal entry (it
      physically existed in the pool's handed-out set);
    * per block, lifetime releases never exceed lifetime
      ``alloc + incref`` references (no double free slipped through);
    * a block no longer referenced is on the free list or quarantined —
      never in limbo (the allocator's own invariant, asserted from the
      outside).

    Stripe-layout records only carry a slot id (no block pool); they
    verify as ``slot >= 0``.
    """
    problems: List[str] = []
    allocs: Dict[int, int] = {}
    refs: Dict[int, int] = {}
    releases: Dict[int, int] = {}
    lifetime = getattr(allocator, "lifetime", None)
    if lifetime is not None:
        # Exact cumulative per-block counts (bounded by pool size, never
        # by run length) — the ring journal would false-positive "never
        # allocated" once a long-pinned block's entry rotated out.
        for block, counts in lifetime.items():
            allocs[block] = counts.get("alloc", 0)
            refs[block] = counts.get("alloc", 0) + counts.get("incref", 0)
            releases[block] = counts.get("release", 0)
    else:
        for entry in getattr(allocator, "journal", ()):
            op, block = entry[0], entry[1]
            if op == "alloc":
                allocs[block] = allocs.get(block, 0) + 1
                refs[block] = refs.get(block, 0) + 1
            elif op == "incref":
                refs[block] = refs.get(block, 0) + 1
            elif op == "release":
                releases[block] = releases.get(block, 0) + 1
            # "unquarantine" re-enters the free pool without dropping a
            # reference — it does not change the accounting.

    for rec in records:
        rid = rec.get("request_id")
        if not rec.get("admitted", True):
            continue  # never touched a slot or block
        if rec.get("layout") == "stripe":
            if rec.get("slot", -1) < 0:
                problems.append(f"request {rid}: stripe record without a "
                                "slot id")
            continue
        blocks = rec.get("block_ids") or []
        if len(set(blocks)) != len(blocks):
            problems.append(f"request {rid}: duplicate block ids {blocks}")
        prefix = set(rec.get("prefix_block_ids") or [])
        if not prefix <= set(blocks):
            problems.append(f"request {rid}: prefix blocks {sorted(prefix)} "
                            f"not a subset of its table {blocks}")
        num_blocks = getattr(allocator, "num_blocks", None)
        for b in blocks:
            if b == 0:
                problems.append(f"request {rid}: claims the trash block")
                continue
            if num_blocks is not None and not 1 <= b <= num_blocks:
                problems.append(f"request {rid}: block {b} outside the "
                                f"pool [1, {num_blocks}]")
                continue
            if allocs.get(b, 0) < 1:
                problems.append(f"request {rid}: block {b} was never "
                                "allocated per the journal")
            if releases.get(b, 0) > refs.get(b, 0):
                problems.append(f"request {rid}: block {b} released "
                                f"{releases[b]}x with only "
                                f"{refs.get(b, 0)} references")

    # Allocator-side invariant: an unreferenced block must be free or
    # quarantined (never limbo).  Only checkable for real allocators.
    free = getattr(allocator, "_free", None)
    ref_now = getattr(allocator, "_ref", None)
    quarantined = getattr(allocator, "quarantined", set())
    num_blocks = getattr(allocator, "num_blocks", None)
    if free is not None and ref_now is not None and num_blocks is not None:
        for b in range(1, num_blocks + 1):
            if b not in ref_now and b not in free and b not in quarantined:
                problems.append(f"block {b} is unreferenced but neither "
                                "free nor quarantined")
    return not problems, problems

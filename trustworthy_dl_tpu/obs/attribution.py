"""Per-request attribution ledger: which replica, slot, physical KV
blocks, weight tier and monitor verdict produced each served stream.

The trust claim on the serving side is only auditable if a finished
token stream can be traced back to the physical state that produced it.
At stream completion the engine appends ONE durable record per request:

.. code-block:: json

    {"request_id": 7, "status": "completed", "admitted": true,
     "slot": 2, "layout": "paged",
     "block_ids": [3, 9, 14], "prefix_block_ids": [3],
     "prefix_publishers": {"3": 1},
     "kv_dtype": "int8", "weight_dtype": "model",
     "kv_fallback_reason": null,
     "flagged": false, "monitor_z": 0.41,
     "tokens": 12, "token_hash": "a3f0c2...", "t": 1722700000.1}

appended as JSONL beside the trace (``attribution.jsonl``; the first
line is a header carrying the replica's ``run_metadata`` once, not per
record), mirrored as a compact ``attribution`` trace-bus event, and
retained in a bounded in-memory ring for :func:`verify_attribution` —
which cross-checks the recorded block ids against the
``BlockAllocator``'s lifecycle journal (every claimed block was really
allocated; references never went negative; an unreferenced block is on
the free list or quarantined, never limbo).

``token_hash`` is a sha256 over the emitted int32 token ids — cheap
evidence two replicas (or a replay) produced the same stream without
shipping the stream itself.
"""

from __future__ import annotations

import collections
import hashlib
import json
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np


def token_hash(tokens: Sequence[int]) -> str:
    """sha256 hex digest of the token-id stream (int32 little-endian)."""
    arr = np.asarray(list(tokens), np.int32)
    return hashlib.sha256(arr.tobytes()).hexdigest()


class AttributionLedger:
    """Durable JSONL sink + bounded in-memory ring of per-request
    attribution records.

    ``path=None`` is the in-memory mode (tests, in-process fleets); the
    ring (``keep``) bounds host memory under the million-user framing —
    the FILE is the durable record, the ring is the working set
    ``verify_attribution`` and the monitor drills read."""

    def __init__(self, path: Optional[str] = None, keep: int = 4096,
                 run_meta: Optional[Dict[str, Any]] = None):
        self.path = str(path) if path else None
        self._ring: collections.deque = collections.deque(maxlen=keep)
        self._lock = threading.Lock()
        self._file: Any = None
        self._closed = False
        self.total = 0
        if self.path is not None:
            if run_meta is None:
                from trustworthy_dl_tpu.obs.meta import run_metadata

                run_meta = run_metadata()
            self._file = open(self.path, "a", buffering=1)
            self._file.write(json.dumps(
                {"header": True, "run_metadata": run_meta}
            ) + "\n")

    def append(self, record: Dict[str, Any]) -> Dict[str, Any]:
        record = dict(record)
        record.setdefault("t", time.time())
        with self._lock:
            self.total += 1
            self._ring.append(record)
            if self._file is not None and not self._closed:
                self._file.write(json.dumps(record) + "\n")
        return record

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._file is not None:
                self._file.close()
                self._file = None


def read_ledger(path: str) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Load a ledger file back as ``(header, records)``."""
    header: Dict[str, Any] = {}
    records: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if row.get("header"):
                header = row
            else:
                records.append(row)
    return header, records


def _journal_digest(allocator: Any) -> Tuple[Dict[int, int], Dict[int, int],
                                             Dict[int, int]]:
    """(allocs, refs, releases) per block from the allocator's lifecycle
    evidence — exact ``lifetime`` counters when present (bounded by pool
    size, never run length: the ring journal alone would false-positive
    "never allocated" once a long-pinned block's entry rotated out),
    the ring journal replay otherwise."""
    allocs: Dict[int, int] = {}
    refs: Dict[int, int] = {}
    releases: Dict[int, int] = {}
    lifetime = getattr(allocator, "lifetime", None)
    if lifetime is not None:
        for block, counts in lifetime.items():
            allocs[block] = counts.get("alloc", 0)
            refs[block] = counts.get("alloc", 0) + counts.get("incref", 0)
            releases[block] = counts.get("release", 0)
    else:
        for entry in getattr(allocator, "journal", ()):
            op, block = entry[0], entry[1]
            if op == "alloc":
                allocs[block] = allocs.get(block, 0) + 1
                refs[block] = refs.get(block, 0) + 1
            elif op == "incref":
                refs[block] = refs.get(block, 0) + 1
            elif op == "release":
                releases[block] = releases.get(block, 0) + 1
            # "unquarantine" re-enters the free pool without dropping a
            # reference — it does not change the accounting.
    return allocs, refs, releases


def _check_placement(rid: Any, placement: Dict[str, Any], allocator: Any,
                     digest: Tuple[Dict[int, int], Dict[int, int],
                                   Dict[int, int]],
                     problems: List[str], where: str = "") -> None:
    """The per-placement block checks (shared by single-engine records
    and each fleet attempt): unique non-trash in-pool block ids, every
    claimed block really allocated per the journal, releases never
    exceeding references, prefix ⊆ table.  Stripe placements only carry
    a slot id (no block pool); they verify as ``slot >= 0``."""
    if placement.get("layout") == "stripe":
        if placement.get("slot", -1) < 0:
            problems.append(f"request {rid}{where}: stripe record "
                            "without a slot id")
        return
    allocs, refs, releases = digest
    blocks = placement.get("block_ids") or []
    if len(set(blocks)) != len(blocks):
        problems.append(f"request {rid}{where}: duplicate block ids "
                        f"{blocks}")
    prefix = set(placement.get("prefix_block_ids") or [])
    if not prefix <= set(blocks):
        problems.append(f"request {rid}{where}: prefix blocks "
                        f"{sorted(prefix)} not a subset of its table "
                        f"{blocks}")
    num_blocks = getattr(allocator, "num_blocks", None)
    for b in blocks:
        if b == 0:
            problems.append(f"request {rid}{where}: claims the trash "
                            "block")
            continue
        if num_blocks is not None and not 1 <= b <= num_blocks:
            problems.append(f"request {rid}{where}: block {b} outside "
                            f"the pool [1, {num_blocks}]")
            continue
        if allocs.get(b, 0) < 1:
            problems.append(f"request {rid}{where}: block {b} was never "
                            "allocated per the journal")
        if releases.get(b, 0) > refs.get(b, 0):
            problems.append(f"request {rid}{where}: block {b} released "
                            f"{releases[b]}x with only "
                            f"{refs.get(b, 0)} references")


def verify_attribution(records: Iterable[Dict[str, Any]],
                       allocator: Any) -> Tuple[bool, List[str]]:
    """Cross-check ledger records against ``BlockAllocator`` lifecycle
    journals.  Returns ``(ok, problems)``.

    ``allocator`` is either one allocator (single engine) or a mapping
    of journal key → allocator (a fleet: one lifecycle journal per
    replica *generation* — a restarted replica's fresh pool must not be
    asked to vouch for blocks its predecessor handed out).  A fleet
    record carries the canonical stream once plus an ``attempts`` list;
    each attempt names its journal (``journal`` key, falling back to
    ``replica``), so ONE record's blocks can span two replicas'
    allocators and still reconcile.

    Checks per admitted record (or per attempt): block ids unique, in
    ``[1, num_blocks]`` and never the trash block; every claimed block
    has an ``alloc`` journal entry; per block, lifetime releases never
    exceed lifetime ``alloc + incref`` references; prefix blocks are a
    subset of the table.  Across records: at most ONE admitted record
    per request id (a double retire means two replicas both claimed the
    canonical stream — the dedup-at-retire invariant failed).  Per
    allocator: an unreferenced block is free or quarantined, never
    limbo.
    """
    import collections.abc as _abc

    problems: List[str] = []
    fleet = isinstance(allocator, _abc.Mapping) and not hasattr(
        allocator, "journal")
    digests: Dict[int, tuple] = {}

    def _resolve(key: Any, rid: Any, where: str):
        alloc = allocator.get(key) if fleet else allocator
        if alloc is None:
            problems.append(f"request {rid}{where}: no lifecycle journal "
                            f"for allocator key {key!r}")
            return None, None
        digest = digests.get(id(alloc))
        if digest is None:
            digest = _journal_digest(alloc)
            digests[id(alloc)] = digest
        return alloc, digest

    admitted_count: Dict[Any, int] = {}
    for rec in records:
        rid = rec.get("request_id")
        if not rec.get("admitted", True):
            continue  # never touched a slot or block (or lost a hedge)
        admitted_count[rid] = admitted_count.get(rid, 0) + 1
        attempts = rec.get("attempts")
        if attempts:
            for att in attempts:
                key = att.get("journal", att.get("replica"))
                where = f" attempt on replica {att.get('replica')}"
                alloc, digest = _resolve(key, rid, where)
                if alloc is None:
                    continue
                _check_placement(rid, att, alloc, digest, problems, where)
                src = att.get("migrated_from")
                if fleet and src and src.get("journal") is not None:
                    # A live-migrated attempt carries its SOURCE block
                    # table: those blocks were alloc'd on the source
                    # journal and released when the migration committed
                    # (or impounded, which the releases<=refs bound also
                    # admits) — reconcile them there, so a block the
                    # source never journalled, or released twice, still
                    # surfaces even though the attempt retired elsewhere.
                    swhere = (f" migration source on replica "
                              f"{src.get('replica')}")
                    salloc, sdigest = _resolve(src["journal"], rid, swhere)
                    if salloc is not None:
                        _check_placement(
                            rid,
                            {"layout": "paged",
                             "block_ids": src.get("block_ids") or []},
                            salloc, sdigest, problems, swhere)
        else:
            key = rec.get("journal", rec.get("replica"))
            alloc, digest = _resolve(key, rid, "")
            if alloc is None:
                continue
            _check_placement(rid, rec, alloc, digest, problems)
    for rid, n in admitted_count.items():
        if n > 1:
            problems.append(f"request {rid}: double retire — {n} admitted "
                            "records claim its canonical stream")

    # Allocator-side invariant: an unreferenced block must be free or
    # quarantined (never limbo).  Only checkable for real allocators.
    for alloc in (allocator.values() if fleet else (allocator,)):
        free = getattr(alloc, "_free", None)
        ref_now = getattr(alloc, "_ref", None)
        quarantined = getattr(alloc, "quarantined", set())
        num_blocks = getattr(alloc, "num_blocks", None)
        if free is None or ref_now is None or num_blocks is None:
            continue
        for b in range(1, num_blocks + 1):
            if b not in ref_now and b not in free and b not in quarantined:
                problems.append(f"block {b} is unreferenced but neither "
                                "free nor quarantined")
    return not problems, problems

"""Structured trace bus: typed JSONL events with monotonic timestamps
and step/request correlation ids.

Every event is one flat JSON object::

    {"seq": 17, "t": 1722700000.123, "t_mono": 8.201,
     "type": "train_step", "step": 12, "loss": 2.31, ...}

``seq`` is a per-bus monotone counter (total order even when wall clocks
collide), ``t`` wall-clock epoch seconds (cross-process alignment),
``t_mono`` ``time.monotonic()`` (intra-process durations immune to NTP
steps).  Training-side events correlate on ``step`` (the trainer's
global step), serving-side events on ``request_id`` — a reader joins
``train_step`` ↔ ``detection_verdict`` ↔ ``ckpt_save`` rows on the step
id, and ``serve_submit`` ↔ ``serve_retire`` on the request id.

Event types and their required fields are declared in
:data:`EVENT_SCHEMAS`; ``TraceBus.emit`` validates against it so a
malformed emission fails at the producer (loudly, in tests) instead of
corrupting the post-mortem record a recovery depends on.  Extra fields
are always allowed — schemas are a floor, not a ceiling.
"""

from __future__ import annotations

import enum
import json
import os
import re
import threading
import time
from typing import Any, Dict, IO, List, Optional, Tuple


class EventType(str, enum.Enum):
    """Everything the framework can say about itself.  README
    §Observability carries the same catalog as a table."""

    # Run lifecycle
    RUN_START = "run_start"
    RUN_END = "run_end"
    METRICS_SNAPSHOT = "metrics_snapshot"
    # Training
    TRAIN_STEP = "train_step"
    TRUST_TRANSITION = "trust_transition"
    DETECTION_VERDICT = "detection_verdict"
    FLEET_ALERT = "fleet_alert"
    ELASTIC_EVICT = "elastic_evict"
    ELASTIC_READMIT = "elastic_readmit"
    # Checkpointing
    CKPT_SAVE = "ckpt_save"
    CKPT_COMMIT = "ckpt_commit"
    CKPT_RESTORE = "ckpt_restore"
    # Supervisor recovery ladder
    GUARD_TRIP = "guard_trip"
    SUPERVISOR_RETRY = "supervisor_retry"
    SUPERVISOR_ROLLBACK = "supervisor_rollback"
    SUPERVISOR_RESTART = "supervisor_restart"
    PREEMPTION = "preemption"
    FLIGHT_DUMP = "flight_dump"
    # Chaos
    CHAOS_FAULT = "chaos_fault"
    # Serving request lifecycle
    SERVE_SUBMIT = "serve_submit"
    SERVE_ADMIT = "serve_admit"
    SERVE_RETIRE = "serve_retire"
    SERVE_QUARANTINE = "serve_quarantine"
    # Active observability plane (obs/spans.py, slo.py, anomaly.py,
    # attribution.py)
    SPAN = "span"
    SLO_BREACH = "slo_breach"
    ANOMALY = "anomaly"
    ATTRIBUTION = "attribution"
    # Serving fleet (serve/fleet.py): replica lifecycle + request
    # fail-over.  ``request_id`` on fleet events is the FLEET id; the
    # ENGINE lifecycle events (serve_submit/admit/retire/...) keep
    # replica-LOCAL ids but carry a ``replica`` field whenever the
    # engine runs inside a fleet, so a shared trace stays joinable.
    REPLICA_TRANSITION = "replica_transition"
    FLEET_FAILOVER = "fleet_failover"
    FLEET_HEDGE = "fleet_hedge"
    # Adversarial serving tier: the suspicion episode below the
    # quarantine threshold (sustained sub-threshold flag rate, anomaly
    # episode, or attribution irregularity) and each cross-replica
    # verdict vote's resolution.
    FLEET_SUSPICION = "fleet_suspicion"
    VERDICT_VOTE = "verdict_vote"
    # Fleet control plane (serve/control.py wired into serve/fleet.py):
    # each autoscaler action (replica count change, either direction)
    # and each per-tenant token-bucket throttle (a submission the
    # flooding tenant's own bucket refused).
    FLEET_SCALE = "fleet_scale"
    TENANT_THROTTLE = "tenant_throttle"
    # Adapter tier (serve/adapters.py): every residency change of the
    # paged adapter pool (a tenant's adapter uploaded into a pool page,
    # evicting a cold tenant when the pool is full) and every fleet-wide
    # adapter quarantine (the per-ADAPTER flag-rate window tripping —
    # the trust verdict that blames the model delta, not the replica).
    ADAPTER_SWAP = "adapter_swap"
    ADAPTER_QUARANTINE = "adapter_quarantine"
    # Live migration tier (serve/migrate.py wired into serve/fleet.py):
    # every live KV block-table hand-off of an in-flight request between
    # replicas (drain, heartbeat, scale-in, preemption, disaggregation)
    # and every pool-role rebalance sweep that moved decode-ready work
    # off a prefill-specialist replica.
    KV_MIGRATION = "kv_migration"
    POOL_REBALANCE = "pool_rebalance"
    # Performance tier (obs/compilewatch.py, hbm.py, sentinel.py):
    # every XLA compilation, compile-once contract violations, live-HBM
    # sweeps/pressure denials, and perf-ledger regressions.
    COMPILE = "compile"
    COMPILE_STORM = "compile_storm"
    HBM_SWEEP = "hbm_sweep"
    HBM_PRESSURE = "hbm_pressure"
    PERF_REGRESSION = "perf_regression"
    # Trace-bus housekeeping: the first event of a fresh segment after a
    # size-based rotation names the segment the bus just sealed.
    TRACE_ROTATE = "trace_rotate"
    # Forensics tier (obs/forensics.py, obs/verdicts.py): an ``incident``
    # announces the assembled post-mortem artifact for a flight-dump-
    # grade episode; a ``verdict`` announces each durable trust-history
    # row appended to the VerdictStore.
    INCIDENT = "incident"
    VERDICT = "verdict"


#: type -> {"requires": base correlation keys, "fields": required extras}.
EVENT_SCHEMAS: Dict[EventType, Dict[str, tuple]] = {
    EventType.RUN_START: {"requires": (), "fields": ()},
    EventType.RUN_END: {"requires": (), "fields": ()},
    EventType.METRICS_SNAPSHOT: {"requires": (), "fields": ("path",)},
    EventType.TRAIN_STEP: {"requires": ("step",),
                           "fields": ("loss", "grad_norm")},
    EventType.TRUST_TRANSITION: {
        "requires": ("step",),
        "fields": ("node", "from_status", "to_status"),
    },
    EventType.DETECTION_VERDICT: {
        "requires": ("step",), "fields": ("node", "attack_type"),
    },
    EventType.FLEET_ALERT: {"requires": ("step",), "fields": ()},
    EventType.ELASTIC_EVICT: {"requires": ("step",), "fields": ("nodes",)},
    EventType.ELASTIC_READMIT: {"requires": ("step",),
                                "fields": ("nodes",)},
    EventType.CKPT_SAVE: {"requires": ("step",), "fields": ("path",)},
    EventType.CKPT_COMMIT: {"requires": ("step",),
                            "fields": ("committed",)},
    EventType.CKPT_RESTORE: {"requires": ("step",), "fields": ()},
    EventType.GUARD_TRIP: {
        "requires": ("step",),
        "fields": ("loss", "grad_norm", "finite_nodes"),
    },
    EventType.SUPERVISOR_RETRY: {"requires": ("step",),
                                 "fields": ("attempt",)},
    EventType.SUPERVISOR_ROLLBACK: {
        "requires": ("step",), "fields": ("restored_step",),
    },
    EventType.SUPERVISOR_RESTART: {"requires": ("step",),
                                   "fields": ("restart",)},
    EventType.PREEMPTION: {"requires": ("step",), "fields": ()},
    EventType.FLIGHT_DUMP: {"requires": (), "fields": ("path", "reason")},
    EventType.CHAOS_FAULT: {"requires": ("step",), "fields": ("kind",)},
    EventType.SERVE_SUBMIT: {"requires": ("request_id",),
                             "fields": ("prompt_len", "max_new_tokens")},
    EventType.SERVE_ADMIT: {"requires": ("request_id",),
                            "fields": ("slot",)},
    EventType.SERVE_RETIRE: {"requires": ("request_id",),
                             "fields": ("status", "tokens")},
    EventType.SERVE_QUARANTINE: {"requires": ("request_id",),
                                 "fields": ("slot",)},
    # Spans correlate on whichever key their workload carries (a train
    # span has a step, a serve span a request id) — neither is required.
    EventType.SPAN: {"requires": (),
                     "fields": ("name", "kind", "span_id", "duration_s")},
    EventType.SLO_BREACH: {"requires": (),
                           "fields": ("slo", "signal", "burn_rate")},
    EventType.ANOMALY: {"requires": (), "fields": ("signal", "zscore")},
    EventType.ATTRIBUTION: {"requires": ("request_id",),
                            "fields": ("slot", "n_blocks", "token_hash")},
    # Fleet lifecycle is replica-keyed, not request-keyed: a transition
    # (healthy → degraded → draining → quarantined → restarting) names
    # the replica, the states, and the signal that drove it.
    EventType.REPLICA_TRANSITION: {
        "requires": (),
        "fields": ("replica", "from_state", "to_state", "reason"),
    },
    EventType.FLEET_FAILOVER: {
        "requires": ("request_id",),
        "fields": ("from_replica", "to_replica", "attempt"),
    },
    EventType.FLEET_HEDGE: {"requires": ("request_id",),
                            "fields": ("replica",)},
    # Suspicion is replica-keyed (an episode, not a request); a verdict
    # vote correlates on the FLEET request id it replayed and names the
    # suspected replica, the outcome (confirmed/outvoted/inconclusive)
    # and the ballot split.
    EventType.FLEET_SUSPICION: {
        "requires": (),
        "fields": ("replica", "score", "reason"),
    },
    EventType.VERDICT_VOTE: {
        "requires": ("request_id",),
        "fields": ("replica", "outcome", "agree", "dissent"),
    },
    # Control plane: a scale event names the direction, both replica
    # counts and the signal that drove it; a throttle names the tenant,
    # the token cost the bucket refused and the bucket's level.
    EventType.FLEET_SCALE: {
        "requires": (),
        "fields": ("direction", "from_replicas", "to_replicas",
                   "reason"),
    },
    EventType.TENANT_THROTTLE: {
        "requires": (),
        "fields": ("tenant", "tokens", "bucket_level"),
    },
    # Adapter pool residency: a swap names the adapter that moved in,
    # the pool page it landed on, and the evicted adapter (None for a
    # cold-start fill of a free page).  A quarantine names the adapter
    # and the flag-rate evidence that tripped the per-adapter window.
    EventType.ADAPTER_SWAP: {
        "requires": (),
        "fields": ("adapter", "page", "evicted"),
    },
    EventType.ADAPTER_QUARANTINE: {
        "requires": (),
        "fields": ("adapter", "reason"),
    },
    # Live migration: a kv_migration correlates on the FLEET request id
    # and names both replicas, the number of physical blocks copied and
    # the reason ("trust_drain"/"heartbeat"/"scale_down"/"preempt"/
    # "disagg"); a pool_rebalance is role-keyed (a sweep, not a request)
    # and counts what the sweep moved off the prefill pool.
    EventType.KV_MIGRATION: {
        "requires": ("request_id",),
        "fields": ("from_replica", "to_replica", "blocks", "reason"),
    },
    EventType.POOL_REBALANCE: {
        "requires": (),
        "fields": ("role", "replicas", "moved"),
    },
    # Performance tier.  ``compile`` rows are per-XLA-compilation (key =
    # the jax.monitoring stage, seconds = backend compile wall time);
    # ``compile_storm`` marks a post-warmup recompile inside a guarded
    # hot loop (scope = which loop).  HBM rows carry byte counts; a
    # ``perf_regression`` names the fingerprint metric that left the
    # ledger's noise band.
    EventType.COMPILE: {"requires": (), "fields": ("key", "seconds")},
    EventType.COMPILE_STORM: {"requires": (),
                              "fields": ("scope", "compiles")},
    EventType.HBM_SWEEP: {"requires": (),
                          "fields": ("live_bytes", "watermark_bytes")},
    EventType.HBM_PRESSURE: {
        "requires": (),
        "fields": ("requested_bytes", "headroom_bytes"),
    },
    EventType.PERF_REGRESSION: {
        "requires": (), "fields": ("metric", "value", "baseline"),
    },
    EventType.TRACE_ROTATE: {"requires": (),
                             "fields": ("path", "segment")},
    # Forensics: an incident names the artifact it wrote (path is None
    # in the in-memory bench mode) and the registered reason that
    # triggered assembly; a verdict names the (kind, outcome) pair the
    # VerdictStore recorded — same label the tddl_verdicts_total
    # counter pages on.
    EventType.INCIDENT: {"requires": (),
                         "fields": ("incident_id", "reason", "path")},
    EventType.VERDICT: {"requires": (), "fields": ("kind", "outcome")},
}


#: Floor for ``TraceBus.max_bytes``: a rotation cap must comfortably
#: hold the trace_rotate announcement line plus real events, or the
#: fresh segment would immediately re-trip the cap.
MIN_ROTATE_BYTES = 4096


def validate_event(event: Dict[str, Any]) -> None:
    """Raise ValueError when ``event`` violates its type's schema."""
    try:
        etype = EventType(event.get("type"))
    except ValueError:
        raise ValueError(f"unknown event type {event.get('type')!r}")
    schema = EVENT_SCHEMAS[etype]
    for key in schema["requires"]:
        if event.get(key) is None:
            raise ValueError(
                f"{etype.value} event requires correlation id {key!r}"
            )
    missing = [f for f in schema["fields"] if f not in event]
    if missing:
        raise ValueError(
            f"{etype.value} event missing required field(s) {missing}"
        )


class TraceBus:
    """Emits validated events to (any of) a JSONL file, a flight
    recorder, and the metrics registry's event counter.

    With no sinks configured the bus still validates and counts —
    instrumented code never branches on whether tracing is on; it only
    guards on ``bus is not None`` for the cost of building the dict.
    """

    def __init__(self, jsonl_path: Optional[str] = None,
                 recorder: Any = None, registry: Any = None,
                 validate: bool = True, max_bytes: int = 0):
        # ``max_bytes`` > 0 enables size-based rotation: when the live
        # file crosses the cap it is sealed as ``trace.<n>.jsonl`` (n
        # monotonically increasing) and a fresh ``trace.jsonl`` opens
        # whose FIRST event is a typed ``trace_rotate`` row naming the
        # sealed segment — long serve/fleet runs stay disk-bounded per
        # segment and the reader side (:func:`read_jsonl_rotated`, the
        # obs CLI, the offline Chrome export) walks segments in order.
        self.jsonl_path = str(jsonl_path) if jsonl_path else None
        self.max_bytes = int(max_bytes)
        if 0 < self.max_bytes < MIN_ROTATE_BYTES:
            # A cap smaller than a handful of event lines would make the
            # rotation ANNOUNCEMENT itself trip the cap — emit → rotate
            # → emit recursion producing thousands of one-line segments.
            self.max_bytes = MIN_ROTATE_BYTES
        self.rotations = 0
        self.recorder = recorder
        self.validate = validate
        self._file: Optional[IO[str]] = None
        self._closed = False
        self._lock = threading.Lock()
        self._seq = 0
        self._counter = None
        if registry is not None:
            self._counter = registry.counter(
                "tddl_obs_events_total", "Trace events emitted, by type",
                labels=("type",),
            )

    def emit(self, type: Any, *, step: Optional[int] = None,
             request_id: Optional[int] = None, **data: Any
             ) -> Dict[str, Any]:
        etype = type.value if isinstance(type, EventType) else str(type)
        event: Dict[str, Any] = {
            "seq": 0,  # patched under the lock below
            "t": time.time(),
            "t_mono": time.monotonic(),
            "type": etype,
        }
        if step is not None:
            event["step"] = int(step)
        if request_id is not None:
            event["request_id"] = int(request_id)
        event.update(data)
        if self.validate:
            validate_event(event)
        rotated: Optional[tuple] = None
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            # After close() the file never reopens: a straggler event
            # (e.g. an async checkpoint COMMIT joining during cleanup)
            # still reaches the recorder/counter but must not land in
            # the file after its final run_end line — nor leak a handle.
            if self.jsonl_path is not None and not self._closed:
                if self._file is None:
                    self._file = open(self.jsonl_path, "a", buffering=1)
                self._file.write(json.dumps(event) + "\n")
                if self.max_bytes > 0 \
                        and self._file.tell() >= self.max_bytes:
                    rotated = self._rotate_locked()
        if self.recorder is not None:
            self.recorder.record(event)
        if self._counter is not None:
            self._counter.inc(type=etype)
        if rotated is not None:
            # Outside the lock: the rotation announcement is a normal
            # typed event and lands as the FIRST line of the fresh
            # segment (the fresh file cannot itself trip the cap here).
            path, segment, size = rotated
            self.emit(EventType.TRACE_ROTATE, path=path, segment=segment,
                      bytes=size)
        return event

    def _rotate_locked(self) -> "tuple[str, int, int]":
        """Seal the live file as the next ``<stem>.<n>.jsonl`` segment
        (caller holds the lock).  Returns (sealed path, segment, bytes)."""
        size = self._file.tell()
        self._file.close()
        self._file = None
        existing = [n for _, n in rotated_segments(self.jsonl_path)]
        segment = (max(existing) + 1) if existing else 1
        sealed = _segment_path(self.jsonl_path, segment)
        os.replace(self.jsonl_path, sealed)
        self.rotations += 1
        return sealed, segment, size

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._file is not None:
                self._file.close()
                self._file = None


def read_jsonl(path: str) -> list:
    """Load a trace file back into event dicts (reader-side helper)."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events


def _segment_path(path: str, segment: int) -> str:
    stem, ext = os.path.splitext(path)
    return f"{stem}.{segment}{ext}"


def rotated_segments(path: str) -> "List[Tuple[str, int]]":
    """(path, segment) of the sealed rotation segments belonging to
    ``path`` (``trace.jsonl`` → ``trace.1.jsonl``, ``trace.2.jsonl``,
    ...), ordered oldest first."""
    stem, ext = os.path.splitext(os.path.basename(path))
    directory = os.path.dirname(path) or "."
    pattern = re.compile(
        rf"^{re.escape(stem)}\.(\d+){re.escape(ext)}$"
    )
    out: List[Tuple[str, int]] = []
    if os.path.isdir(directory):
        for name in os.listdir(directory):
            m = pattern.match(name)
            if m:
                out.append((os.path.join(directory, name), int(m.group(1))))
    out.sort(key=lambda item: item[1])
    return out


def read_jsonl_rotated(path: str) -> list:
    """Load a trace INCLUDING its sealed rotation segments, oldest
    events first — the reader every offline consumer (obs CLI, Chrome
    export) should use; a never-rotated trace reads identically to
    :func:`read_jsonl`."""
    events: list = []
    for segment_path, _ in rotated_segments(path):
        events.extend(read_jsonl(segment_path))
    if os.path.exists(path):
        events.extend(read_jsonl(path))
    return events

"""Streaming SLO evaluation: bounded-memory percentile estimators +
declarative target/window/burn-rate rules over the live signal streams.

Two pieces:

* :class:`P2Quantile` / :class:`StreamingPercentiles` — the P² algorithm
  (Jain & Chlamtac, CACM '85): one quantile tracked with FIVE markers,
  O(1) per observation, no sample list.  Under the million-user framing
  the engine cannot keep every ITL in a python list to ``np.percentile``
  at summary time; these estimators replace that (engine
  ``metrics_summary`` reads them) and feed the SLO rules.
* :class:`SLORule` / :class:`SLOWatcher` — a rule is
  ``observation > target`` counted over a sliding window of the last
  ``window`` observations; ``burn rate`` is the violating fraction
  divided by the error ``budget`` (the SRE burn-rate convention: 1.0 =
  exactly consuming budget, >1 = burning it).  Every observation
  re-evaluates its signal's rules: the burn rate lands in the
  ``tddl_slo_burn_rate{slo=}`` gauge, and a threshold crossing emits a
  typed ``slo_breach`` trace event, bumps
  ``tddl_slo_breaches_total{slo=}``, fires the registered callbacks
  (the serving engine sheds lowest-priority admissions off this hook),
  and — once per breach episode — triggers a flight-recorder dump with
  reason ``slo_breach``.

Everything is host work under one lock; nothing touches jax.
"""

from __future__ import annotations

import bisect
import dataclasses
import math
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple


class P2Quantile:
    """One streaming quantile via the P² algorithm — five markers, O(1)
    memory and per-observation work.  Exact below five observations
    (sorted insert), marker interpolation beyond."""

    def __init__(self, q: float):
        if not 0.0 < q < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {q}")
        self.q = q
        self.count = 0
        self._x: List[float] = []          # warmup buffer (first 5)
        self._h: Optional[List[float]] = None   # marker heights
        self._n: Optional[List[float]] = None   # marker positions
        self._np: Optional[List[float]] = None  # desired positions

    def observe(self, x: float) -> None:
        x = float(x)
        if not math.isfinite(x):
            return  # a NaN latency is an anomaly, not a percentile input
        self.count += 1
        if self._h is None:
            bisect.insort(self._x, x)
            if len(self._x) == 5:
                q = self.q
                self._h = list(self._x)
                self._n = [0.0, 1.0, 2.0, 3.0, 4.0]
                self._np = [0.0, 2 * q, 4 * q, 2 + 2 * q, 4.0]
            return
        h, n, np_ = self._h, self._n, self._np
        if x < h[0]:
            h[0] = x
            k = 0
        elif x >= h[4]:
            h[4] = x
            k = 3
        else:
            k = next(i for i in range(4) if h[i] <= x < h[i + 1])
        for i in range(k + 1, 5):
            n[i] += 1.0
        dn = (0.0, self.q / 2, self.q, (1 + self.q) / 2, 1.0)
        for i in range(5):
            np_[i] += dn[i]
        for i in (1, 2, 3):
            d = np_[i] - n[i]
            if ((d >= 1.0 and n[i + 1] - n[i] > 1.0)
                    or (d <= -1.0 and n[i - 1] - n[i] < -1.0)):
                d = 1.0 if d > 0 else -1.0
                hp = self._parabolic(i, d)
                if not h[i - 1] < hp < h[i + 1]:
                    hp = self._linear(i, d)
                h[i] = hp
                n[i] += d

    def _parabolic(self, i: int, d: float) -> float:
        h, n = self._h, self._n
        return h[i] + d / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + d) * (h[i + 1] - h[i]) / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - d) * (h[i] - h[i - 1]) / (n[i] - n[i - 1])
        )

    def _linear(self, i: int, d: float) -> float:
        h, n = self._h, self._n
        j = i + int(d)
        return h[i] + d * (h[j] - h[i]) / (n[j] - n[i])

    @property
    def value(self) -> Optional[float]:
        if self._h is not None:
            return self._h[2]
        if not self._x:
            return None
        idx = int(round(self.q * (len(self._x) - 1)))
        return self._x[max(0, min(idx, len(self._x) - 1))]


#: Quantiles every signal tracks by default (the serving SLO surface).
DEFAULT_QUANTILES = (0.5, 0.9, 0.99)


class StreamingPercentiles:
    """A signal's bounded-memory distribution sketch: one P² marker set
    per tracked quantile plus count/mean/min/max."""

    def __init__(self, quantiles: Sequence[float] = DEFAULT_QUANTILES):
        self._q = {q: P2Quantile(q) for q in quantiles}
        self.count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, x: float) -> None:
        x = float(x)
        if not math.isfinite(x):
            return
        self.count += 1
        self._sum += x
        self._min = min(self._min, x)
        self._max = max(self._max, x)
        for est in self._q.values():
            est.observe(x)

    def quantile(self, q: float) -> Optional[float]:
        est = self._q.get(q)
        return est.value if est is not None else None

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"count": self.count}
        if self.count:
            out["mean"] = self._sum / self.count
            out["min"] = self._min
            out["max"] = self._max
            for q, est in sorted(self._q.items()):
                out[f"p{round(q * 100)}"] = est.value
        return out


@dataclasses.dataclass(frozen=True)
class SLORule:
    """``observation > target`` counted over the last ``window``
    observations of ``signal``; burning when the violating fraction
    exceeds ``budget * burn_threshold``.  ``min_count`` is the warmup —
    a rule never breaches on the first unlucky sample."""

    name: str
    signal: str          # e.g. "ttft_s", "itl_s", "step_time_s"
    target: float        # per-observation upper bound (seconds, ratio..)
    budget: float = 0.01         # allowed violating fraction
    window: int = 256            # sliding-window length (observations)
    min_count: int = 32          # observations before breach can fire
    burn_threshold: float = 1.0  # breach at burn_rate >= this

    def __post_init__(self) -> None:
        if not 0.0 < self.budget <= 1.0:
            raise ValueError(f"budget must be in (0, 1], got {self.budget}")
        if self.window < 1 or self.min_count < 1:
            raise ValueError("window and min_count must be >= 1")
        if self.min_count > self.window:
            raise ValueError("min_count cannot exceed window")
        if self.burn_threshold <= 0.0:
            raise ValueError("burn_threshold must be > 0")


def default_serve_rules(ttft_target_s: float = 2.0,
                        itl_target_s: float = 0.25) -> Tuple[SLORule, ...]:
    """The serving defaults the CLI/bench install: generous enough that
    a healthy engine never trips them, tight enough that a degrading
    engine (slow-but-completing requests) does.  TTFT/ITL are observed
    at retirement, so a FULLY wedged loop emits no observations — that
    failure mode is the anomaly watcher's / supervisor's territory, not
    a latency SLO's."""
    return (
        SLORule("ttft", signal="ttft_s", target=ttft_target_s,
                budget=0.05, window=128, min_count=16),
        SLORule("itl", signal="itl_s", target=itl_target_s,
                budget=0.01, window=512, min_count=64),
    )


class _RuleState:
    __slots__ = ("rule", "window", "violations", "burn", "active")

    def __init__(self, rule: SLORule):
        self.rule = rule
        self.window: deque = deque(maxlen=rule.window)
        self.violations = 0
        self.burn = 0.0
        self.active = False


class SLOWatcher:
    """Evaluates :class:`SLORule`\\ s on every observation and keeps the
    per-signal percentile sketches.

    ``dump`` is a callable ``(reason, step=None, extra=None) -> path``
    (``ObsSession.dump_flight``); it fires once per breach *episode*
    (the transition into any-rule-breached), not per breached
    observation — post-mortems stay bounded.
    """

    def __init__(self, rules: Sequence[SLORule] = (), *,
                 registry: Any = None, trace: Any = None,
                 dump: Optional[Callable[..., Any]] = None,
                 quantiles: Sequence[float] = DEFAULT_QUANTILES):
        self._lock = threading.Lock()
        self._quantiles = tuple(quantiles)
        self._signals: Dict[str, StreamingPercentiles] = {}
        self._by_signal: Dict[str, List[_RuleState]] = {}
        self._states: Dict[str, _RuleState] = {}
        self.trace = trace
        self.dump = dump
        self._callbacks: List[Callable[[str, Dict[str, Any]], None]] = []
        self.breach_total = 0
        self._burn_gauge = None
        self._breach_counter = None
        if registry is not None:
            self._burn_gauge = registry.gauge(
                "tddl_slo_burn_rate",
                "Error-budget burn rate per SLO rule (1.0 = consuming "
                "budget exactly; breach at the rule's threshold)",
                labels=("slo",),
            )
            self._breach_counter = registry.counter(
                "tddl_slo_breaches_total", "SLO breach onsets, by rule",
                labels=("slo",),
            )
        for rule in rules:
            self.add_rule(rule)

    def add_rule(self, rule: SLORule) -> None:
        with self._lock:
            if rule.name in self._states:
                raise ValueError(f"duplicate SLO rule {rule.name!r}")
            state = _RuleState(rule)
            self._states[rule.name] = state
            self._by_signal.setdefault(rule.signal, []).append(state)
        if self._burn_gauge is not None:
            self._burn_gauge.set(0.0, slo=rule.name)

    def on_breach(self, callback: Callable[[str, Dict[str, Any]], None]
                  ) -> None:
        """Register ``callback(rule_name, info)`` fired at breach onset
        — the host-side hook the engine's admission shedding uses."""
        self._callbacks.append(callback)

    # -- observation -------------------------------------------------------

    def observe(self, signal: str, value: float,
                step: Optional[int] = None) -> None:
        onsets: List[Tuple[str, Dict[str, Any]]] = []
        episode_start = False
        with self._lock:
            est = self._signals.get(signal)
            if est is None:
                est = StreamingPercentiles(self._quantiles)
                self._signals[signal] = est
            est.observe(value)
            for st in self._by_signal.get(signal, ()):
                rule = st.rule
                bad = 1 if (not math.isfinite(float(value))
                            or float(value) > rule.target) else 0
                if len(st.window) == st.window.maxlen:
                    st.violations -= st.window[0]
                st.window.append(bad)
                st.violations += bad
                st.burn = (st.violations / len(st.window)) / rule.budget
                warm = len(st.window) >= rule.min_count
                breached = warm and st.burn >= rule.burn_threshold
                if breached and not st.active:
                    was_any = any(s.active for s in self._states.values())
                    st.active = True
                    self.breach_total += 1
                    episode_start = episode_start or not was_any
                    onsets.append((rule.name, {
                        "signal": signal, "burn_rate": st.burn,
                        "target": rule.target, "value": float(value),
                        "step": step,
                    }))
                elif not breached and st.active:
                    st.active = False
            if self._burn_gauge is not None:
                for st in self._by_signal.get(signal, ()):
                    self._burn_gauge.set(st.burn, slo=st.rule.name)
        for name, info in onsets:
            if self._breach_counter is not None:
                self._breach_counter.inc(slo=name)
            if self.trace is not None:
                from trustworthy_dl_tpu.obs.events import EventType

                self.trace.emit(EventType.SLO_BREACH, step=step,
                                slo=name, signal=info["signal"],
                                burn_rate=info["burn_rate"],
                                target=info["target"])
            for cb in self._callbacks:
                cb(name, info)
        if onsets and episode_start and self.dump is not None:
            self.dump("slo_breach", step=step,
                      extra={"slo_rules": [n for n, _ in onsets]})

    # -- reads -------------------------------------------------------------

    @property
    def active(self) -> List[str]:
        with self._lock:
            return sorted(n for n, st in self._states.items() if st.active)

    @property
    def breached(self) -> bool:
        """True while ANY rule is in breach — the shed hook's condition."""
        with self._lock:
            return any(st.active for st in self._states.values())

    def burn_rate(self, name: str) -> float:
        with self._lock:
            return self._states[name].burn

    def percentiles(self, signal: str) -> Dict[str, Any]:
        with self._lock:
            est = self._signals.get(signal)
            return est.summary() if est is not None else {"count": 0}

    def quantile(self, signal: str, q: float) -> Optional[float]:
        with self._lock:
            est = self._signals.get(signal)
            return est.quantile(q) if est is not None else None

    def status(self) -> Dict[str, Any]:
        """JSON-serialisable rollup: per-rule burn + per-signal sketch
        (what the CLI prints and the bench stamps)."""
        with self._lock:
            return {
                "rules": [{
                    "name": st.rule.name, "signal": st.rule.signal,
                    "target": st.rule.target, "budget": st.rule.budget,
                    "window": st.rule.window,
                    "burn_rate": st.burn, "active": st.active,
                } for st in self._states.values()],
                "active": sorted(n for n, st in self._states.items()
                                 if st.active),
                "breach_total": self.breach_total,
                "signals": {s: est.summary()
                            for s, est in sorted(self._signals.items())},
            }

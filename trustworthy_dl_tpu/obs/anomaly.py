"""EWMA/z-score anomaly detection over host telemetry streams.

The training trust stack z-scores *nodes against the fleet*; this module
z-scores *the run against its own recent past* — step time, loss,
grad-norm, inter-token latency.  Each signal keeps an exponentially
weighted mean/variance (O(1) memory) and scores every new observation
BEFORE absorbing it; an anomalous observation is never absorbed
(score-then-absorb-only-clean — the same hardening the detection
baseline and the serve output monitor use, so a slow-burn corruption
cannot drag its own baseline along).

A non-finite observation is always anomalous once the detector is warm
(a NaN loss has no z-score; it *is* the incident).

On anomaly onset the watcher emits a typed ``anomaly`` trace event,
flips ``tddl_anomaly_active{signal=}`` to 1, bumps
``tddl_anomaly_events_total{signal=}``, fires registered callbacks, and
— once per anomaly *episode* (the transition from no-signal-anomalous to
any-signal-anomalous, NOT per signal: a stall and a NaN landing on the
same step are one incident) — triggers a flight-recorder dump with
reason ``anomaly``.  The gauge returns to 0 on the next clean
observation of that signal.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple


class EwmaDetector:
    """One signal's exponentially weighted baseline + z-scorer."""

    def __init__(self, alpha: float = 0.05, warmup: int = 16,
                 z_threshold: float = 6.0):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        if warmup < 2:
            raise ValueError("warmup must be >= 2")
        if z_threshold <= 0.0:
            raise ValueError("z_threshold must be > 0")
        self.alpha = alpha
        self.warmup = warmup
        self.z_threshold = z_threshold
        self.count = 0           # clean observations absorbed
        self._mean = 0.0
        self._var = 0.0

    @property
    def warm(self) -> bool:
        return self.count >= self.warmup

    @property
    def mean(self) -> float:
        return self._mean

    @property
    def std(self) -> float:
        return math.sqrt(max(self._var, 0.0))

    def score(self, x: float) -> float:
        """z-score of ``x`` against the current baseline (inf for
        non-finite inputs; 0 while the baseline is empty)."""
        if not math.isfinite(x):
            return math.inf
        if self.count == 0:
            return 0.0
        std = self.std
        if std <= 0.0:
            # Degenerate (constant) baseline: any deviation is infinitely
            # surprising; an exact match is not surprising at all.
            return 0.0 if x == self._mean else math.inf
        return abs(x - self._mean) / std

    def observe(self, x: float) -> Tuple[bool, float]:
        """Score ``x``; absorb it iff clean.  Returns (anomalous, z).
        Anomalies only fire once warm — early variance must not page
        anyone."""
        x = float(x)
        z = self.score(x)
        anomalous = self.warm and (not math.isfinite(x)
                                   or z > self.z_threshold)
        if not anomalous and math.isfinite(x):
            self.count += 1
            if self.count == 1:
                self._mean = x
            else:
                delta = x - self._mean
                self._mean += self.alpha * delta
                self._var = ((1 - self.alpha)
                             * (self._var + self.alpha * delta * delta))
        return anomalous, z


#: signal -> (alpha, warmup, z_threshold) defaults.  step_time gets a
#: lower bar (a stalled host is a 10-100x spike, but jitter is real);
#: loss/grad_norm spikes are the guard's territory, so the watcher only
#: flags the far tail.
DEFAULT_SIGNALS: Dict[str, Tuple[float, int, float]] = {
    "step_time": (0.1, 8, 6.0),
    "loss": (0.05, 16, 8.0),
    "grad_norm": (0.05, 16, 8.0),
    "itl": (0.05, 32, 8.0),
}


class AnomalyWatcher:
    """Per-signal EWMA detectors + the emit/gauge/dump/callback plumbing.

    ``dump`` is a callable ``(reason, step=None, extra=None) -> path``
    (``ObsSession.dump_flight``).  Signals not pre-registered are
    auto-registered with :data:`DEFAULT_SIGNALS` (or generic defaults)
    on first observation.
    """

    def __init__(self, signals: Optional[Dict[str, Tuple[float, int, float]]]
                 = None, *, registry: Any = None, trace: Any = None,
                 dump: Optional[Callable[..., Any]] = None):
        self._lock = threading.Lock()
        self._dets: Dict[str, EwmaDetector] = {}
        self._active: Dict[str, bool] = {}
        self.trace = trace
        self.dump = dump
        self.event_total = 0
        self._callbacks: List[Callable[[str, Dict[str, Any]], None]] = []
        self._active_gauge = None
        self._event_counter = None
        if registry is not None:
            self._active_gauge = registry.gauge(
                "tddl_anomaly_active",
                "1 while a signal's latest observation was anomalous",
                labels=("signal",),
            )
            self._event_counter = registry.counter(
                "tddl_anomaly_events_total", "Anomaly onsets, by signal",
                labels=("signal",),
            )
        for name, cfg in (signals if signals is not None
                          else DEFAULT_SIGNALS).items():
            self.watch(name, *cfg)

    def watch(self, signal: str, alpha: float = 0.05, warmup: int = 16,
              z_threshold: float = 6.0) -> EwmaDetector:
        with self._lock:
            if signal in self._dets:
                raise ValueError(f"signal {signal!r} already watched")
            det = EwmaDetector(alpha, warmup, z_threshold)
            self._dets[signal] = det
            self._active[signal] = False
        if self._active_gauge is not None:
            self._active_gauge.set(0.0, signal=signal)
        return det

    def on_anomaly(self, callback: Callable[[str, Dict[str, Any]], None]
                   ) -> None:
        """Register ``callback(signal, info)`` fired at anomaly onset —
        what the supervisor/engine consult beyond the gauges."""
        self._callbacks.append(callback)

    # -- observation -------------------------------------------------------

    def observe(self, signal: str, value: float,
                step: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Feed one observation; returns the anomaly info dict at onset
        (None otherwise)."""
        onset: Optional[Dict[str, Any]] = None
        episode_start = False
        with self._lock:
            det = self._dets.get(signal)
            if det is None:
                cfg = DEFAULT_SIGNALS.get(signal, (0.05, 16, 6.0))
                det = EwmaDetector(*cfg)
                self._dets[signal] = det
                self._active[signal] = False
            anomalous, z = det.observe(value)
            if anomalous and not self._active[signal]:
                episode_start = not any(self._active.values())
                onset = {
                    "signal": signal, "zscore": z, "value": float(value),
                    "baseline_mean": det.mean, "step": step,
                }
                self.event_total += 1
            self._active[signal] = anomalous
        if self._active_gauge is not None:
            self._active_gauge.set(1.0 if anomalous else 0.0, signal=signal)
        if onset is not None:
            if self._event_counter is not None:
                self._event_counter.inc(signal=signal)
            if self.trace is not None:
                from trustworthy_dl_tpu.obs.events import EventType

                self.trace.emit(
                    EventType.ANOMALY, step=step, signal=signal,
                    zscore=(z if math.isfinite(z) else None),
                    value=(float(value) if math.isfinite(float(value))
                           else None),
                )
            for cb in self._callbacks:
                cb(signal, onset)
            if episode_start and self.dump is not None:
                self.dump("anomaly", step=step,
                          extra={"signal": signal,
                                 "zscore": z if math.isfinite(z) else None})
        return onset

    # -- reads -------------------------------------------------------------

    @property
    def active(self) -> List[str]:
        with self._lock:
            return sorted(s for s, a in self._active.items() if a)

    @property
    def any_active(self) -> bool:
        with self._lock:
            return any(self._active.values())

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "active": sorted(s for s, a in self._active.items() if a),
                "event_total": self.event_total,
                "signals": {
                    s: {"count": d.count, "mean": d.mean, "std": d.std,
                        "z_threshold": d.z_threshold,
                        "active": self._active[s]}
                    for s, d in sorted(self._dets.items())
                },
            }

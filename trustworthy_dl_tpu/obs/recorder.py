"""Flight recorder: a bounded ring buffer of recent trace events,
dumped to disk when something goes wrong.

The trace JSONL is the full flight log; the recorder is the black box —
always on, O(capacity) memory, and cheap enough to run even when no
``--obs-dir`` was given.  The supervisor dumps it next to the
checkpoint directory on rollback, non-finite guard trip and preemption,
so every recovery leaves a queryable post-mortem artifact: what the
last N events were, in order, with correlation ids intact.
"""

from __future__ import annotations

import collections
import json
import os
import threading
from typing import Any, Dict, List, Optional


class FlightRecorder:
    """Ring buffer of event dicts (see :mod:`obs.events`)."""

    def __init__(self, capacity: int = 2048):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._total = 0
        self._dumps = 0

    def record(self, event: Dict[str, Any]) -> None:
        with self._lock:
            self._events.append(event)
            self._total += 1

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def counts(self) -> Dict[str, int]:
        """Retained events by type — what a drill asserts against."""
        out: Dict[str, int] = {}
        for event in self.events():
            key = event.get("type", "unknown")
            out[key] = out.get(key, 0) + 1
        return out

    @property
    def total_recorded(self) -> int:
        """Events ever seen (retained + evicted by the ring bound)."""
        return self._total

    def dump(self, directory: str, reason: str,
             step: Optional[int] = None,
             extra: Optional[Dict[str, Any]] = None) -> str:
        """Write the retained events (+ run metadata) as one JSON file
        under ``directory``; returns the path.  Filenames embed reason /
        step / a per-recorder dump index so repeated incidents never
        overwrite each other."""
        from trustworthy_dl_tpu.obs.meta import run_metadata

        os.makedirs(directory, exist_ok=True)
        with self._lock:
            self._dumps += 1
            index = self._dumps
        name = f"flight_{index:03d}_{reason}"
        if step is not None:
            name += f"_step{int(step)}"
        path = os.path.join(directory, name + ".json")
        events = self.events()
        payload: Dict[str, Any] = {
            "reason": reason,
            "step": int(step) if step is not None else None,
            "capacity": self.capacity,
            "num_events": len(events),
            "total_recorded": self._total,
            "run_metadata": run_metadata(),
            "events": events,
        }
        if extra:
            payload.update(extra)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2)
        os.replace(tmp, path)  # a torn post-mortem is worse than none
        return path

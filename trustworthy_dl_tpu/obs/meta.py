"""Run-metadata stamp shared by every artifact writer.

VERDICT weak #5: experiment artifacts shipped without platform /
jax-version metadata, so a published number could not be tied to the
hardware that produced it (MLPerf-style run stamping — PAPERS.md).
``run_metadata()`` is the one shared helper; the fast-tier contract test
(tests/test_obs.py) fails any ``experiments/`` or ``bench.py`` artifact
writer that does not reference it.

Device discovery is cached per process (``jax.devices()`` initialises
the backend — call this only where the backend is already expected to be
live, e.g. bench's watchdogged inner body, never its probe-first parent)
and degrades to ``platform: "unavailable"`` instead of raising: a
metadata stamp must never be the reason an artifact is lost.
"""

from __future__ import annotations

import functools
import platform as _platform
import sys
import time
from typing import Any, Dict

RUN_METADATA_SCHEMA = "tddl-obs-v1"

#: Keys every stamped artifact must carry (the contract test checks the
#: helper is used; unit tests check the helper emits these).
RUN_METADATA_KEYS = (
    "schema", "platform", "device_kind", "num_devices", "jax_version",
    "python_version", "framework_version", "hostname", "timestamp",
)


@functools.lru_cache(maxsize=1)
def _device_info() -> Dict[str, Any]:
    """Backend identity, resolved once per process."""
    try:
        import jax

        devices = jax.devices()
        return {
            "platform": devices[0].platform,
            "device_kind": getattr(devices[0], "device_kind", "unknown"),
            "num_devices": len(devices),
            "jax_version": jax.__version__,
        }
    except Exception as exc:  # dead backend must not kill the artifact
        try:
            import jax

            jax_version = jax.__version__
        except Exception:
            jax_version = "unknown"
        return {
            "platform": "unavailable",
            "device_kind": "unknown",
            "num_devices": 0,
            "jax_version": jax_version,
            "backend_error": f"{type(exc).__name__}: {str(exc)[:120]}",
        }


def run_metadata(host_only: bool = False) -> Dict[str, Any]:
    """The metadata block every published JSON artifact embeds.

    ``host_only=True`` skips device discovery entirely (platform
    ``"unprobed"``) — for writers that must never touch the backend,
    like bench's probe-first parent emitting a SKIP record while the
    backend is the very thing that is wedged."""
    from trustworthy_dl_tpu import __version__

    meta = {
        "schema": RUN_METADATA_SCHEMA,
        "python_version": sys.version.split()[0],
        "framework_version": __version__,
        "hostname": _platform.node(),
        "timestamp": time.time(),
    }
    if host_only:
        try:
            import importlib.metadata as _md

            jax_version = _md.version("jax")
        except Exception:
            jax_version = "unknown"
        meta.update({
            "platform": "unprobed",
            "device_kind": "unknown",
            "num_devices": 0,
            "jax_version": jax_version,
        })
        return meta
    meta.update(_device_info())
    return meta

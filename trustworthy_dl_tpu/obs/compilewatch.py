"""Compile watcher: every XLA compilation as a typed, metered event —
and the compile-once pins enforced at RUNTIME, not just in pytest.

The serving engine's fused decode step and the trainer's jitted train
step are both built to compile exactly once (block tables and trust
masks are traced VALUES; geometry never changes mid-run).  The test
suite pins that with ``_cache_size()`` deltas, but production only
found out when tokens/sec fell off a cliff: a recompile storm inside
the decode loop is silent in every artifact the obs plane produced
before this module.

Two pieces:

* :class:`CompileRegistry` — a ``jax.monitoring`` duration listener
  that records every XLA compilation in the process: per-stage counts
  and wall time (``tddl_compile_total`` /
  ``tddl_compile_seconds{stage=}``) plus one typed ``compile`` trace
  event per backend compile.  Listeners in jax are process-global and
  irremovable one-by-one, so ONE module-level dispatcher is registered
  lazily and fans out to the currently-installed registries —
  ``install()`` / ``uninstall()`` are cheap and test-safe.
* :class:`CompileWatcher` — the runtime contract.  A hot loop wraps its
  jitted dispatch in ``watcher.guard(scope)``; compiles landing inside
  the first ``warmup_calls`` guarded calls of a scope are warmup (the
  legitimate first build), any compile after that is a **storm**: a
  typed ``compile_storm`` event, a ``tddl_compile_storms_total{scope=}``
  bump, and a once-per-episode flight dump (consecutive storming calls
  are one episode; a clean guarded call closes it).  A legitimate
  rebuild (elastic topology change rebuilding the train step) calls
  ``reset(scope)`` so the next compile is warmup again.

Host-only at import time: jax is imported lazily inside ``install()``
(the obs CLI must keep importing this package without jax).
"""

from __future__ import annotations

import collections
import contextlib
import logging
import threading
from contextlib import contextmanager
from typing import Any, Dict, Iterator, Optional

from trustworthy_dl_tpu.obs.events import EventType

logger = logging.getLogger(__name__)

#: The jax.monitoring duration event that fires once per actual XLA
#: backend compilation (tracing/lowering stages fire their own events,
#: recorded per stage but not counted as "a compile").
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_COMPILE_PREFIX = "/jax/core/compile/"

_DISPATCH_LOCK = threading.Lock()
_ACTIVE: "set[CompileRegistry]" = set()
_DISPATCHER_INSTALLED = False


def _dispatch_duration(event: str, duration: float, **_kw: Any) -> None:
    for registry in list(_ACTIVE):
        registry._on_duration(event, duration)


def _install_dispatcher() -> None:
    global _DISPATCHER_INSTALLED
    with _DISPATCH_LOCK:
        if _DISPATCHER_INSTALLED:
            return
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(
            _dispatch_duration
        )
        _DISPATCHER_INSTALLED = True


def _stage_name(event: str) -> str:
    stage = event.rsplit("/", 1)[-1]
    return stage[:-len("_duration")] if stage.endswith("_duration") \
        else stage


class CompileRegistry:
    """Process-wide XLA compilation record for one obs session.

    ``total`` / ``total_seconds`` count backend compiles only — the
    number a recompile storm moves; per-stage counts (jaxpr trace,
    MLIR lowering, backend compile) live in ``by_stage`` and the
    ``tddl_compile_seconds{stage=}`` counter.
    """

    def __init__(self, trace: Any = None, registry: Any = None,
                 keep: int = 256):
        self.trace = trace
        self._lock = threading.Lock()
        self.total = 0
        self.total_seconds = 0.0
        self.by_stage: Dict[str, Dict[str, float]] = {}
        self.recent: collections.deque = collections.deque(maxlen=keep)
        self._installed = False
        self._count_metric = None
        self._seconds_metric = None
        if registry is not None:
            self._count_metric = registry.counter(
                "tddl_compile_total",
                "XLA backend compilations observed via jax.monitoring",
            )
            self._seconds_metric = registry.counter(
                "tddl_compile_seconds",
                "Wall time spent compiling, by jax.monitoring stage",
                labels=("stage",),
            )

    # -- lifecycle ---------------------------------------------------------

    def install(self) -> "CompileRegistry":
        """Start receiving compile events (idempotent).  Imports jax."""
        _install_dispatcher()
        with _DISPATCH_LOCK:
            _ACTIVE.add(self)
        self._installed = True
        return self

    def uninstall(self) -> None:
        with _DISPATCH_LOCK:
            _ACTIVE.discard(self)
        self._installed = False

    # -- listener ----------------------------------------------------------

    def _on_duration(self, event: str, seconds: float) -> None:
        if not event.startswith(_COMPILE_PREFIX):
            return
        stage = _stage_name(event)
        is_compile = event == BACKEND_COMPILE_EVENT
        with self._lock:
            entry = self.by_stage.setdefault(stage,
                                             {"count": 0, "seconds": 0.0})
            entry["count"] += 1
            entry["seconds"] += float(seconds)
            if is_compile:
                self.total += 1
                self.total_seconds += float(seconds)
                self.recent.append((stage, float(seconds)))
        if self._seconds_metric is not None:
            self._seconds_metric.inc(float(seconds), stage=stage)
        if is_compile:
            if self._count_metric is not None:
                self._count_metric.inc()
            if self.trace is not None:
                self.trace.emit(EventType.COMPILE, key=stage,
                                seconds=float(seconds))

    # -- reporting ---------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "total": self.total,
                "seconds": round(self.total_seconds, 6),
                "by_stage": {k: {"count": int(v["count"]),
                                 "seconds": round(v["seconds"], 6)}
                             for k, v in sorted(self.by_stage.items())},
            }


class _ScopeState:
    __slots__ = ("calls", "storms", "episode_open")

    def __init__(self) -> None:
        self.calls = 0
        self.storms = 0
        self.episode_open = False


class CompileWatcher:
    """Turns the compile-once pins into a production contract (module
    docstring).  ``dump`` has the :meth:`ObsSession.dump_flight`
    signature; every storm EPISODE produces exactly one dump."""

    def __init__(self, compiles: CompileRegistry, trace: Any = None,
                 registry: Any = None, dump: Any = None,
                 warmup_calls: int = 1):
        if warmup_calls < 1:
            raise ValueError("warmup_calls must be >= 1")
        self.compiles = compiles
        self.trace = trace
        self.dump = dump
        self.warmup_calls = warmup_calls
        self._scopes: Dict[str, _ScopeState] = {}
        self._lock = threading.Lock()
        self._storm_metric = None
        if registry is not None:
            self._storm_metric = registry.counter(
                "tddl_compile_storms_total",
                "Post-warmup recompiles inside a guarded hot loop",
                labels=("scope",),
            )

    def _scope(self, name: str) -> _ScopeState:
        with self._lock:
            state = self._scopes.get(name)
            if state is None:
                state = self._scopes[name] = _ScopeState()
            return state

    def reset(self, scope: str) -> None:
        """Back to cold: the next compile in ``scope`` is warmup again
        (call at LEGITIMATE rebuild points — elastic topology changes,
        ``reset_for_run`` — so a planned recompile is not a storm)."""
        with self._lock:
            self._scopes.pop(scope, None)

    @property
    def storm_total(self) -> int:
        with self._lock:
            return sum(s.storms for s in self._scopes.values())

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {name: {"calls": s.calls, "storms": s.storms,
                           "warm": s.calls >= self.warmup_calls}
                    for name, s in sorted(self._scopes.items())}

    @contextmanager
    def guard(self, scope: str, step: Optional[int] = None
              ) -> Iterator[None]:
        """Wrap ONE dispatch of a compile-once program.  Compiles inside
        the first ``warmup_calls`` guarded calls are absorbed; later
        ones storm."""
        before = self.compiles.total
        try:
            yield
        finally:
            self._after(scope, before, step)

    def _after(self, scope: str, before: int,
               step: Optional[int]) -> None:
        state = self._scope(scope)
        delta = self.compiles.total - before
        warm = state.calls >= self.warmup_calls
        state.calls += 1
        if delta <= 0:
            state.episode_open = False
            return
        if not warm:
            return
        state.storms += delta
        logger.warning(
            "compile storm: %d recompile(s) inside the %r loop after "
            "warmup (step %s) — the compile-once contract is broken",
            delta, scope, step,
        )
        if self._storm_metric is not None:
            self._storm_metric.inc(delta, scope=scope)
        if self.trace is not None:
            self.trace.emit(EventType.COMPILE_STORM, step=step,
                            scope=scope, compiles=int(delta))
        if not state.episode_open:
            state.episode_open = True
            if self.dump is not None:
                self.dump("compile_storm", step=step,
                          extra={"scope": scope, "compiles": int(delta)})


_NULL_CONTEXT = contextlib.nullcontext()


def guarded(watcher: Optional[CompileWatcher], scope: str,
            step: Optional[int] = None):
    """``watcher.guard(...)`` or a shared no-op context — the one-liner
    hot loops use so the unwatched path stays allocation-free
    (``nullcontext`` is stateless and reentrant; one module-level
    instance serves every caller)."""
    if watcher is None:
        return _NULL_CONTEXT
    return watcher.guard(scope, step=step)

"""obs — unified telemetry: metrics registry, structured trace bus,
flight recorder, and the step-time/MFU reporter (SURVEY §5.1 tracing,
§6 measurement contract).

The framework trains, serves and self-heals; this package makes it
*explain itself*: every claim the repo publishes — detection overhead,
recovery counts, step-time, MFU — is backed by an emitted,
machine-readable record rather than a builder-transcribed number.

Four pieces, composable separately and bundled by :class:`ObsSession`:

* :mod:`obs.registry` — process-wide counters/gauges/histograms with
  labels, JSON snapshot + Prometheus text export.  Absorbs the ad-hoc
  metrics previously scattered over ``utils/metrics.py``,
  ``serve/engine.py`` (TTFT/ITL/occupancy), ``engine/supervisor.py``
  (retries/rollbacks/restarts) and ``chaos/injector.py`` (faults).
* :mod:`obs.events` — typed JSONL trace events with monotonic
  timestamps and step/request correlation ids, validated against a
  per-type schema.
* :mod:`obs.recorder` — a bounded ring buffer of recent events the
  supervisor dumps next to the checkpoint directory on rollback, guard
  trip or preemption, so every recovery has a post-mortem artifact.
* :mod:`obs.report` — named-phase step-time breakdown + model-FLOPs
  utilization (MFU), written as ``obs_report.json``; also the shared
  ``run_metadata()`` stamp every experiment artifact carries.

Since the active-plane PR the package also WATCHES what it records:

* :mod:`obs.spans` — hierarchical request/step spans emitted through the
  trace bus, exportable as a Chrome/Perfetto timeline;
* :mod:`obs.attribution` — the per-request attribution ledger
  (replica/slot/blocks/weight-tier/verdict per served stream) +
  ``verify_attribution`` against the block allocator's journal;
* :mod:`obs.slo` — bounded-memory P² percentile estimators and
  declarative target/window/burn-rate SLO rules
  (``tddl_slo_burn_rate{slo=}``);
* :mod:`obs.anomaly` — EWMA/z-score anomaly detection on step-time /
  loss / grad-norm / ITL (``tddl_anomaly_active{signal=}``), with
  flight-recorder dumps on breach/anomaly episodes.

Metric naming convention: ``tddl_<subsystem>_<what>[_unit]`` —
e.g. ``tddl_train_loss``, ``tddl_serve_ttft_seconds``,
``tddl_supervisor_rollbacks_total``.
"""

from trustworthy_dl_tpu.obs.anomaly import AnomalyWatcher, EwmaDetector
from trustworthy_dl_tpu.obs.attribution import (
    AttributionLedger,
    read_ledger,
    token_hash,
    verify_attribution,
)
from trustworthy_dl_tpu.obs.compilewatch import (
    CompileRegistry,
    CompileWatcher,
)
from trustworthy_dl_tpu.obs.events import (
    EVENT_SCHEMAS,
    EventType,
    TraceBus,
    read_jsonl_rotated,
)
from trustworthy_dl_tpu.obs.forensics import (
    IncidentAssembler,
    blast_radius,
    load_incidents,
)
from trustworthy_dl_tpu.obs.hbm import (
    CostLedger,
    HbmMonitor,
    analyze_program,
    live_buffer_bytes,
)
from trustworthy_dl_tpu.obs.sentinel import (
    PerfLedger,
    PerfSentinel,
    fingerprint as perf_fingerprint,
)
from trustworthy_dl_tpu.obs.meta import run_metadata
from trustworthy_dl_tpu.obs.recorder import FlightRecorder
from trustworthy_dl_tpu.obs.registry import (
    MetricsRegistry,
    get_registry,
)
from trustworthy_dl_tpu.obs.report import PHASES, StepTimeReporter, \
    mfu_from_throughput, peak_flops_per_chip
from trustworthy_dl_tpu.obs.session import ObsSession
from trustworthy_dl_tpu.obs.slo import (
    P2Quantile,
    SLORule,
    SLOWatcher,
    StreamingPercentiles,
    default_serve_rules,
)
from trustworthy_dl_tpu.obs.spans import (
    SpanTracker,
    chrome_trace_from_events,
)
from trustworthy_dl_tpu.obs.verdicts import VERDICT_OUTCOMES, VerdictStore

__all__ = [
    "AnomalyWatcher",
    "AttributionLedger",
    "CompileRegistry",
    "CompileWatcher",
    "CostLedger",
    "EVENT_SCHEMAS",
    "EventType",
    "EwmaDetector",
    "FlightRecorder",
    "HbmMonitor",
    "IncidentAssembler",
    "MetricsRegistry",
    "ObsSession",
    "P2Quantile",
    "PHASES",
    "PerfLedger",
    "PerfSentinel",
    "SLORule",
    "SLOWatcher",
    "SpanTracker",
    "StepTimeReporter",
    "StreamingPercentiles",
    "TraceBus",
    "VERDICT_OUTCOMES",
    "VerdictStore",
    "analyze_program",
    "blast_radius",
    "chrome_trace_from_events",
    "default_serve_rules",
    "get_registry",
    "live_buffer_bytes",
    "load_incidents",
    "mfu_from_throughput",
    "peak_flops_per_chip",
    "perf_fingerprint",
    "read_jsonl_rotated",
    "read_ledger",
    "run_metadata",
    "token_hash",
    "verify_attribution",
]

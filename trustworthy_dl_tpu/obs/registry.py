"""Process-wide metrics registry: counters, gauges, histograms with
labels; JSON snapshot and Prometheus-text export.

This replaces the ad-hoc per-subsystem metric piles (``utils.metrics``
record lists, serve-engine summary dicts, supervisor counters, chaos
``counts()``) with one typed surface.  Design constraints:

* **Host-only and cheap** — a metric update is a dict write under a
  lock; nothing here ever touches jax or the hot device path.
* **Bounded cardinality** — each metric refuses to grow past
  ``max_series`` label combinations (a label explosion is a bug, and a
  silent one OOMs long-lived servers; here it raises at the source).
* **Deterministic snapshots** — ``snapshot()`` round-trips through JSON
  (``MetricsRegistry.from_snapshot``) so a metrics file can be diffed,
  asserted on in tests, and re-served.

Naming convention (enforced shape, advisory prefix):
``tddl_<subsystem>_<what>[_unit]``, Prometheus-compatible characters
only; counters end in ``_total``, durations in ``_seconds``.
"""

from __future__ import annotations

import json
import math
import re
import threading
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: Default latency buckets (seconds): 1 ms .. 60 s, roughly log-spaced.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid metric name {name!r}")
    return name


def _label_key(label_names: Tuple[str, ...],
               labels: Mapping[str, Any]) -> Tuple[str, ...]:
    if set(labels) != set(label_names):
        raise ValueError(
            f"metric declared labels {label_names}, got {sorted(labels)}"
        )
    return tuple(str(labels[n]) for n in label_names)


class _Metric:
    """One named metric: a family of series keyed by label values."""

    def __init__(self, registry: "MetricsRegistry", kind: str, name: str,
                 help: str, label_names: Sequence[str],
                 buckets: Optional[Sequence[float]] = None):
        self.kind = kind
        self.name = _check_name(name)
        self.help = help
        self.label_names = tuple(label_names)
        for label in self.label_names:
            if not _LABEL_RE.match(label):
                raise ValueError(f"invalid label name {label!r}")
        if buckets is not None:
            buckets = tuple(sorted(float(b) for b in buckets))
            if not buckets:
                raise ValueError("histogram needs at least one bucket")
        self.buckets = buckets
        self._registry = registry
        self._series: Dict[Tuple[str, ...], Any] = {}

    def _get_series(self, labels: Mapping[str, Any]) -> Tuple[str, ...]:
        key = _label_key(self.label_names, labels)
        if key not in self._series:
            if len(self._series) >= self._registry.max_series:
                raise ValueError(
                    f"metric {self.name!r} exceeded the label-cardinality "
                    f"bound ({self._registry.max_series} series); a label "
                    "carrying unbounded values (ids, paths) is a bug"
                )
            if self.kind == "histogram":
                self._series[key] = {
                    "bucket_counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0, "count": 0,
                }
            else:
                self._series[key] = 0.0
        return key

    # -- update ops (called via the handle methods below) ------------------

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._registry._lock:
            key = self._get_series(labels)
            self._series[key] += float(amount)

    def set(self, value: float, **labels: Any) -> None:
        with self._registry._lock:
            key = self._get_series(labels)
            self._series[key] = float(value)

    def observe(self, value: float, **labels: Any) -> None:
        value = float(value)
        with self._registry._lock:
            key = self._get_series(labels)
            series = self._series[key]
            idx = len(self.buckets)
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    idx = i
                    break
            series["bucket_counts"][idx] += 1
            series["sum"] += value
            series["count"] += 1

    # -- reads -------------------------------------------------------------

    def value(self, **labels: Any) -> Any:
        with self._registry._lock:
            key = _label_key(self.label_names, labels)
            value = self._series.get(key)
            return dict(value) if isinstance(value, dict) else value


class MetricsRegistry:
    """A set of named metrics with snapshot/export.

    One process-wide default instance exists (:func:`get_registry`);
    tests that assert absolute values should build their own.
    """

    def __init__(self, max_series: int = 1024):
        self.max_series = max_series
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.RLock()

    # -- registration ------------------------------------------------------

    def _register(self, kind: str, name: str, help: str,
                  labels: Sequence[str],
                  buckets: Optional[Sequence[float]] = None) -> _Metric:
        norm_buckets = tuple(sorted(float(b) for b in buckets)) \
            if buckets is not None else None
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if existing.kind != kind or \
                        existing.label_names != tuple(labels) or \
                        existing.buckets != norm_buckets:
                    # Bucket drift matters as much as kind drift: a
                    # silently-returned histogram with someone else's
                    # bounds bins every later observe() wrong.
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}{existing.label_names} "
                        f"(buckets={existing.buckets}); cannot "
                        f"re-register as {kind}{tuple(labels)} "
                        f"(buckets={norm_buckets})"
                    )
                return existing
            metric = _Metric(self, kind, name, help, labels, buckets)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> _Metric:
        return self._register("counter", name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> _Metric:
        return self._register("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> _Metric:
        return self._register("histogram", name, help, labels, buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    # -- export ------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serialisable dump of every metric and series."""
        out: Dict[str, Any] = {}
        with self._lock:
            for name, metric in sorted(self._metrics.items()):
                series = []
                for key in sorted(metric._series):
                    value = metric._series[key]
                    series.append({
                        "labels": dict(zip(metric.label_names, key)),
                        "value": dict(value) if isinstance(value, dict)
                        else value,
                    })
                entry: Dict[str, Any] = {
                    "kind": metric.kind,
                    "help": metric.help,
                    "label_names": list(metric.label_names),
                    "series": series,
                }
                if metric.buckets is not None:
                    entry["buckets"] = list(metric.buckets)
                out[name] = entry
        return {"metrics": out}

    @classmethod
    def from_snapshot(cls, snap: Mapping[str, Any],
                      max_series: int = 1024) -> "MetricsRegistry":
        """Rebuild a registry whose ``snapshot()`` equals ``snap`` — the
        round-trip contract a persisted metrics file relies on."""
        registry = cls(max_series=max_series)
        for name, entry in snap.get("metrics", {}).items():
            metric = registry._register(
                entry["kind"], name, entry.get("help", ""),
                entry.get("label_names", ()), entry.get("buckets"),
            )
            for row in entry.get("series", ()):
                key = _label_key(metric.label_names, row["labels"])
                value = row["value"]
                metric._series[key] = dict(value) if isinstance(value, dict) \
                    else float(value)
        return registry

    def snapshot_to_json(self, path: str, extra: Optional[Dict] = None
                         ) -> Dict[str, Any]:
        """Write the snapshot (+ run metadata) to ``path``; returns it."""
        from trustworthy_dl_tpu.obs.meta import run_metadata
        from trustworthy_dl_tpu.utils.io import atomic_write_json

        snap = self.snapshot()
        snap["run_metadata"] = run_metadata()
        if extra:
            snap.update(extra)
        atomic_write_json(path, snap)
        return snap

    def prometheus_text(self) -> str:
        """Prometheus exposition format (text/plain version 0.0.4)."""

        def fmt_labels(names: Tuple[str, ...], key: Tuple[str, ...],
                       extra: Tuple[Tuple[str, str], ...] = ()) -> str:
            pairs = list(zip(names, key)) + list(extra)
            if not pairs:
                return ""
            body = ",".join(
                '{}="{}"'.format(
                    n, v.replace("\\", r"\\").replace('"', r"\"")
                ) for n, v in pairs
            )
            return "{" + body + "}"

        def fmt_value(v: float) -> str:
            if math.isinf(v):
                return "+Inf" if v > 0 else "-Inf"
            return repr(v) if isinstance(v, float) else str(v)

        lines: List[str] = []
        with self._lock:
            for name, metric in sorted(self._metrics.items()):
                if metric.help:
                    lines.append(f"# HELP {name} {metric.help}")
                lines.append(f"# TYPE {name} {metric.kind}")
                for key in sorted(metric._series):
                    value = metric._series[key]
                    if metric.kind != "histogram":
                        lines.append(
                            f"{name}{fmt_labels(metric.label_names, key)} "
                            f"{fmt_value(value)}"
                        )
                        continue
                    cumulative = 0
                    for bound, count in zip(
                        list(metric.buckets) + [float("inf")],
                        value["bucket_counts"],
                    ):
                        cumulative += count
                        le = "+Inf" if math.isinf(bound) else f"{bound:g}"
                        lines.append(
                            f"{name}_bucket"
                            f"{fmt_labels(metric.label_names, key, (('le', le),))}"
                            f" {cumulative}"
                        )
                    suffix = fmt_labels(metric.label_names, key)
                    lines.append(f"{name}_sum{suffix} "
                                 f"{fmt_value(value['sum'])}")
                    lines.append(f"{name}_count{suffix} {value['count']}")
        return "\n".join(lines) + "\n"


_DEFAULT_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem defaults to."""
    return _DEFAULT_REGISTRY

"""ObsSession — the one handle a run threads through its layers.

Bundles the four telemetry pieces with a shared output directory::

    session = ObsSession("runs/exp7/obs", metrics_snapshot_every=50)
    trainer.attach_obs(session)          # events + phase timing
    TrainingSupervisor(trainer, obs=session, ...)  # recovery events + dumps
    ...
    session.finalize()                   # snapshot + obs_report.json

Artifacts under ``obs_dir``:

* ``trace.jsonl`` — the structured event stream (obs/events.py)
* ``metrics_snapshot.json`` — latest registry snapshot (rewritten at the
  ``metrics_snapshot_every`` step cadence and at finalize)
* ``metrics.prom`` — Prometheus text exposition of the same registry
* ``obs_report.json`` — step-time breakdown + MFU (obs/report.py).
  Under the async host pipeline (``async_host_depth`` > 0) the report's
  ``host`` phase is the time the loop blocked on lagged metrics + host
  bookkeeping — the dispatch-gap number the pipeline collapses
* ``flight_*.json`` — flight-recorder dumps (obs/recorder.py); the
  supervisor writes its incident dumps next to the *checkpoints*
  instead, via ``dump_flight(directory=...)``

``obs_dir=None`` is a valid in-memory mode: events still flow to the
flight recorder and metrics to the registry; only the files are skipped.

Each session owns a FRESH registry by default (pass ``registry=`` to
share one): the snapshot a run publishes must describe *that run*, and
the process-wide default registry accumulates across every run in the
process (repeated experiment cells, threshold sweeps) — summed counters
and cross-run percentiles presented as one run's metrics would be
silently wrong.  ``trainer.attach_obs`` re-binds the trainer's
collector onto the session registry for the same reason.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional

from trustworthy_dl_tpu.obs.events import EventType, TraceBus
from trustworthy_dl_tpu.utils.io import atomic_write_json, \
    atomic_write_text
from trustworthy_dl_tpu.obs.recorder import FlightRecorder
from trustworthy_dl_tpu.obs.registry import MetricsRegistry
from trustworthy_dl_tpu.obs.report import StepTimeReporter

logger = logging.getLogger(__name__)

#: Extra artifacts the active plane adds under ``obs_dir``:
#: ``attribution.jsonl`` (per-request attribution ledger),
#: ``slo_status.json`` (SLO/anomaly watcher rollup at finalize),
#: ``trace_events.json`` (Chrome/Perfetto span timeline at finalize).


class ObsSession:
    def __init__(self, obs_dir: Optional[str] = None, *,
                 registry: Optional[MetricsRegistry] = None,
                 recorder_capacity: int = 2048,
                 metrics_snapshot_every: int = 0,
                 validate_events: bool = True,
                 trace_max_bytes: int = 0,
                 perf_ledger: Optional[str] = None,
                 cost_analysis: Optional[bool] = None):
        self.obs_dir = str(obs_dir) if obs_dir else None
        if self.obs_dir:
            os.makedirs(self.obs_dir, exist_ok=True)
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.recorder = FlightRecorder(recorder_capacity)
        # ``trace_max_bytes`` (or TDDL_TRACE_MAX_BYTES) bounds the live
        # trace file: past the cap it is sealed as trace.<n>.jsonl and a
        # fresh segment opens (obs/events.py rotation; readers walk the
        # segments in order).
        if trace_max_bytes == 0:
            trace_max_bytes = int(os.environ.get("TDDL_TRACE_MAX_BYTES",
                                                 "0"))
        self.trace = TraceBus(
            os.path.join(self.obs_dir, "trace.jsonl")
            if self.obs_dir else None,
            recorder=self.recorder, registry=self.registry,
            validate=validate_events, max_bytes=trace_max_bytes,
        )
        self.step_timer = StepTimeReporter(registry=self.registry)
        self.metrics_snapshot_every = int(metrics_snapshot_every)
        self._finalized = False
        # Active-plane attachments (None until enabled — the passive
        # recorder stays exactly as cheap as before).
        self.spans: Any = None            # obs.spans.SpanTracker
        self.slo: Any = None              # obs.slo.SLOWatcher
        self.anomaly: Any = None          # obs.anomaly.AnomalyWatcher
        self.ledger: Any = None           # obs.attribution.AttributionLedger
        # Performance tier (None until enabled): the compile registry/
        # watcher pair and the HBM monitor.  The cost ledger defaults ON
        # for artifact-producing sessions (obs_dir set) and OFF for
        # in-memory ones: its one lowering per analyzed program is cheap
        # but not free, and a bench arm's ObsSession(None) must not pay
        # it inside a measured loop.
        self.compiles: Any = None         # obs.compilewatch.CompileRegistry
        self.compilewatch: Any = None     # obs.compilewatch.CompileWatcher
        self.hbm: Any = None              # obs.hbm.HbmMonitor
        # Forensics tier (None until enabled): the incident assembler
        # pairs a structured post-mortem with every flight dump; the
        # verdict store is the durable cross-run trust history.
        self.forensics: Any = None        # obs.forensics.IncidentAssembler
        self.verdicts: Any = None         # obs.verdicts.VerdictStore
        if cost_analysis is None:
            cost_analysis = self.obs_dir is not None
        self.cost_ledger: Any = None
        if cost_analysis:
            from trustworthy_dl_tpu.obs.hbm import CostLedger

            self.cost_ledger = CostLedger()
        self.step_timer.cost_ledger = self.cost_ledger
        # Perf-fingerprint ledger path: explicit arg, else
        # TDDL_PERF_LEDGER (the cross-run trajectory file), else a
        # run-local PERF_LEDGER.jsonl beside the other artifacts.
        if perf_ledger is None:
            perf_ledger = os.environ.get("TDDL_PERF_LEDGER") or (
                os.path.join(self.obs_dir, "PERF_LEDGER.jsonl")
                if self.obs_dir else None
            )
        self.perf_ledger_path = perf_ledger
        self.perf_verdict: Optional[Dict[str, Any]] = None
        self.trace.emit(EventType.RUN_START, obs_dir=self.obs_dir)

    # -- active plane ------------------------------------------------------

    def enable_spans(self) -> Any:
        """Attach a SpanTracker to the trace bus AND the step timer (the
        trainer's per-phase laps become ``train.*`` spans for free)."""
        if self.spans is None:
            from trustworthy_dl_tpu.obs.spans import SpanTracker

            self.spans = SpanTracker(trace=self.trace)
            self.step_timer.spans = self.spans
        return self.spans

    def install_watchers(self, slo_rules: Any = None,
                         anomaly_signals: Any = None) -> tuple:
        """Construct the SLO and anomaly watchers wired to this
        session's trace/registry/flight-recorder.  ``slo_rules`` default
        to :func:`obs.slo.default_serve_rules`; ``anomaly_signals`` to
        :data:`obs.anomaly.DEFAULT_SIGNALS`.  Returns ``(slo, anomaly)``
        (idempotent — repeated calls return the existing watchers)."""
        from trustworthy_dl_tpu.obs.anomaly import AnomalyWatcher
        from trustworthy_dl_tpu.obs.slo import SLOWatcher, \
            default_serve_rules

        if self.slo is None:
            self.slo = SLOWatcher(
                default_serve_rules() if slo_rules is None else slo_rules,
                registry=self.registry, trace=self.trace,
                dump=self.dump_flight,
            )
        if self.anomaly is None:
            self.anomaly = AnomalyWatcher(
                anomaly_signals, registry=self.registry, trace=self.trace,
                dump=self.dump_flight,
            )
        return self.slo, self.anomaly

    def enable_compile_watch(self, warmup_calls: int = 1) -> Any:
        """Install the jax.monitoring compile listener + the runtime
        compile-once watcher (obs/compilewatch.py).  Hot loops that
        received this session guard their jitted dispatch; idempotent.
        Imports jax — call only where a backend is expected."""
        if self.compilewatch is None:
            from trustworthy_dl_tpu.obs.compilewatch import (
                CompileRegistry,
                CompileWatcher,
            )

            self.compiles = CompileRegistry(
                trace=self.trace, registry=self.registry
            ).install()
            self.compilewatch = CompileWatcher(
                self.compiles, trace=self.trace, registry=self.registry,
                dump=self.dump_flight, warmup_calls=warmup_calls,
            )
        return self.compilewatch

    def enable_hbm(self, budget_bytes: Optional[int] = None,
                   reserve_fraction: float = 0.0) -> Any:
        """Attach the live-HBM monitor (gauges + watermark + the pool
        headroom gate the serve engine consults).  Idempotent."""
        if self.hbm is None:
            from trustworthy_dl_tpu.obs.hbm import HbmMonitor

            self.hbm = HbmMonitor(
                registry=self.registry, trace=self.trace,
                budget_bytes=budget_bytes,
                reserve_fraction=reserve_fraction,
            )
        return self.hbm

    def enable_forensics(self, verdict_path: Optional[str] = None,
                         directory: Optional[str] = None) -> Any:
        """Attach the incident assembler + durable verdict store.  Each
        flight dump then gets a paired ``incident_NNN_<reason>.json``
        assembled from this session's trace/ledger artifacts.  Verdict
        path resolution mirrors the perf ledger: explicit arg, else
        ``TDDL_VERDICT_STORE`` (the cross-run trust-history file), else
        a run-local ``VERDICTS.jsonl`` beside the other artifacts (None
        ⇒ in-memory incidents only).  Idempotent."""
        if self.forensics is None:
            from trustworthy_dl_tpu.obs.forensics import IncidentAssembler
            from trustworthy_dl_tpu.obs.verdicts import VerdictStore

            if verdict_path is None:
                verdict_path = os.environ.get("TDDL_VERDICT_STORE") or (
                    os.path.join(self.obs_dir, "VERDICTS.jsonl")
                    if self.obs_dir else None
                )
            if verdict_path:
                self.verdicts = VerdictStore(
                    verdict_path, registry=self.registry, trace=self.trace)
            self.forensics = IncidentAssembler(
                directory or self.obs_dir, trace=self.trace,
                trace_path=self.trace.jsonl_path,
                ledger=self.ledger, perf_ledger=None,
                verdicts=self.verdicts, registry=self.registry,
            )
        return self.forensics

    def open_ledger(self, keep: int = 4096) -> Any:
        """Open the per-request attribution ledger (JSONL beside the
        trace when ``obs_dir`` is set; in-memory ring otherwise)."""
        if self.ledger is None:
            from trustworthy_dl_tpu.obs.attribution import AttributionLedger

            self.ledger = AttributionLedger(
                os.path.join(self.obs_dir, "attribution.jsonl")
                if self.obs_dir else None, keep=keep,
            )
            if self.forensics is not None:
                # Enable order is free: a ledger opened after forensics
                # still feeds blast-radius computation.
                self.forensics.ledger = self.ledger
        return self.ledger

    # -- cadence hooks -----------------------------------------------------

    def on_step(self, step: int) -> None:
        """Called by the trainer once per accounted step."""
        total = self.step_timer.last_step_total
        if total is not None:
            if self.anomaly is not None:
                self.anomaly.observe("step_time", total, step=step)
            if self.slo is not None:
                self.slo.observe("step_time_s", total, step=step)
        if (self.metrics_snapshot_every > 0
                and step % self.metrics_snapshot_every == 0):
            self.snapshot_metrics(step=step)

    # -- artifacts ---------------------------------------------------------

    def snapshot_metrics(self, step: Optional[int] = None
                         ) -> Optional[str]:
        if not self.obs_dir:
            return None
        path = os.path.join(self.obs_dir, "metrics_snapshot.json")
        self.registry.snapshot_to_json(
            path, extra={"step": step} if step is not None else None
        )
        atomic_write_text(os.path.join(self.obs_dir, "metrics.prom"),
                          self.registry.prometheus_text())
        self.trace.emit(EventType.METRICS_SNAPSHOT, step=step, path=path)
        return path

    def dump_flight(self, reason: str, step: Optional[int] = None,
                    directory: Optional[str] = None,
                    extra: Optional[Dict[str, Any]] = None
                    ) -> Optional[str]:
        """Dump the ring buffer; ``directory`` defaults to ``obs_dir``
        (the supervisor passes the checkpoint dir so the post-mortem
        lands next to the state it explains)."""
        directory = directory or self.obs_dir
        if not directory:
            return None
        path = self.recorder.dump(directory, reason, step=step, extra=extra)
        # Emitted AFTER the dump so the dump never contains its own
        # announcement but the trace records where it went.
        self.trace.emit(EventType.FLIGHT_DUMP, step=step, path=path,
                        reason=reason)
        if self.forensics is not None:
            # The paired post-mortem: same index as the flight dump,
            # assembled from whatever the trace has recorded so far.
            self.forensics.assemble(reason, step=step, flight_path=path,
                                    directory=directory, extra=extra)
        return path

    def write_report(self) -> Optional[Dict[str, Any]]:
        if not self.obs_dir:
            return self.step_timer.report()
        path = os.path.join(self.obs_dir, "obs_report.json")
        report = self.step_timer.write(path)
        logger.info("obs: report written to %s (%d steps)", path,
                    report.get("num_steps", 0))
        return report

    def write_slo_status(self) -> Optional[Dict[str, Any]]:
        """Watcher rollup (SLO burn + anomaly baselines) as
        ``slo_status.json`` — what the obs CLI pretty-prints."""
        if self.slo is None and self.anomaly is None:
            return None
        status: Dict[str, Any] = {}
        if self.slo is not None:
            status["slo"] = self.slo.status()
        if self.anomaly is not None:
            status["anomaly"] = self.anomaly.status()
        if self.obs_dir:
            atomic_write_json(
                os.path.join(self.obs_dir, "slo_status.json"), status)
        return status

    def perf_fingerprint(self) -> Dict[str, Any]:
        """The compact perf fingerprint this run appends to the rolling
        ledger (obs/sentinel.py): step time, tokens/s (when the timer
        knows the model), compile counts/seconds, HBM watermark."""
        from trustworthy_dl_tpu.obs.meta import run_metadata
        from trustworthy_dl_tpu.obs.sentinel import fingerprint

        timer = self.step_timer
        mean = timer.step_time_mean
        tokens_per_s = None
        if mean and timer.has_model_info and timer.tokens_per_step \
                and timer.model_kind == "lm":
            tokens_per_s = timer.tokens_per_step / mean
        compiles = self.compiles
        hbm = self.hbm
        if hbm is not None:
            hbm.sweep()
        return fingerprint(
            "session",
            metric=timer.model_kind if timer.has_model_info else None,
            tokens_per_s=tokens_per_s,
            step_time_s=mean,
            phase_fractions=timer.phase_fractions() or None,
            compile_total=compiles.total if compiles else None,
            compile_seconds=(round(compiles.total_seconds, 6)
                             if compiles else None),
            hbm_watermark_bytes=(hbm.watermark_bytes or None)
            if hbm is not None else None,
            run_metadata=run_metadata(),
            extra={"num_steps": timer.num_steps},
        )

    def write_perf(self) -> Optional[Dict[str, Any]]:
        """Sentinel pass + ledger append: compare this run's fingerprint
        against the rolling ledger's noise band (typed
        ``perf_regression`` events on breach), then append the
        fingerprint — verdict stamped on it — as the newest entry."""
        if not self.perf_ledger_path:
            return None
        from trustworthy_dl_tpu.obs.sentinel import PerfLedger, PerfSentinel

        ledger = PerfLedger(self.perf_ledger_path)
        fp = self.perf_fingerprint()
        sentinel = PerfSentinel(ledger, trace=self.trace,
                                registry=self.registry)
        self.perf_verdict = sentinel.check(fp)
        fp["regressed"] = self.perf_verdict["regressed"]
        ledger.append(fp)
        if self.perf_verdict["regressed"]:
            logger.warning("perf sentinel: regression outside the noise "
                           "band — %s", [
                               c["metric"] for c in
                               self.perf_verdict["checks"]
                               if c.get("regressed")
                           ])
        return self.perf_verdict

    def finalize(self) -> None:
        """Final snapshot + report + active-plane artifacts + close the
        trace file.  Idempotent."""
        if self._finalized:
            return
        self._finalized = True
        self.snapshot_metrics()
        self.write_report()
        self.write_slo_status()
        self.write_perf()
        if self.spans is not None and self.obs_dir:
            self.spans.export_chrome(
                os.path.join(self.obs_dir, "trace_events.json")
            )
        if self.ledger is not None:
            self.ledger.close()
        if self.compiles is not None:
            # Stop the process-global dispatcher from feeding a finished
            # session (tests build many; a dead registry must not keep
            # counting other runs' compiles).
            self.compiles.uninstall()
        self.trace.emit(EventType.RUN_END)  # last event in the trace
        self.trace.close()

"""Step-time breakdown + MFU/roofline reporter (``obs_report.json``).

VERDICT round 5: GPT-2-medium sits at ~29 % MFU with no artifact
explaining where the other ~65 % goes.  This module is that artifact's
producer: named-phase wall-clock accounting on the host step loop, and
model-FLOPs utilization computed from the model config — attached by
the trainer (``--obs-dir``), bench.py and the experiment runner.

Phase semantics (the canonical names in :data:`PHASES`):

* Host-measurable phases — ``data`` (loader + host batch assembly +
  shard placement), ``compute`` (dispatch + device execution of the
  fused step, synced at the loss read; dispatch-only under the async
  host pipeline), ``detection`` (host-side verdict processing /
  incident records, synchronous loop), ``host`` (async-pipeline drain:
  time blocked on the lagged metrics landing + the host bookkeeping —
  the number the pipeline exists to collapse; compare it across
  ``async_host_depth`` 0 vs K in ``bench.py``'s ``TDDL_BENCH_ASYNC=1``
  A/B), ``host_sync``, ``checkpoint`` — are accounted by
  :class:`StepTimeReporter` per step.
* Device-internal phases — ``forward``, ``backward``, ``optimizer`` —
  live *inside* the one jitted program and are only separable in the
  XLA trace timeline; ``utils.profiling.phase_annotation`` uses the
  same names so a ``profile_dir`` trace and this report line up.

MFU uses the standard ~6 FLOPs/param/token transformer-training
estimate (fwd 2 + bwd 4; remat recompute not counted, so achieved
hardware FLOPs are a lower bound) against a per-``device_kind`` peak
table.  Unknown device kinds fall back to ``TDDL_PEAK_FLOPS_PER_CHIP``
or a nominal CPU estimate — the figure is always computed, and
``peak_flops_source`` says how much to trust it.
"""

from __future__ import annotations

import json
import os
import time
from contextlib import contextmanager
from typing import Any, Deque, Dict, Iterator, Optional

import collections

import numpy as np

#: Canonical phase names — host-measured and trace-timeline both.
PHASES = ("data", "forward", "backward", "optimizer", "detection",
          "host", "host_sync", "compute", "checkpoint", "other")

#: Peak dense bf16 FLOP/s per chip by jax ``device_kind`` (marketing
#: peaks; MFU denominators, not guarantees).  Matched by substring so
#: kinds like "TPU v5 lite" and "TPU v5e" both resolve.
PEAK_FLOPS_BF16 = (
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v6e", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

#: Nominal per-core CPU fallback (order-of-magnitude only) so a CPU-mesh
#: dev run still produces a number instead of a null.
CPU_NOMINAL_FLOPS = 5e10


def peak_flops_per_chip(device_kind: str) -> "tuple[float, str]":
    """(peak FLOP/s, source) for one chip of ``device_kind``."""
    kind = (device_kind or "").lower()
    for token, peak in PEAK_FLOPS_BF16:
        if token in kind:
            return peak, f"bf16-peak-table:{token}"
    env = os.environ.get("TDDL_PEAK_FLOPS_PER_CHIP")
    if env:
        return float(env), "env:TDDL_PEAK_FLOPS_PER_CHIP"
    return CPU_NOMINAL_FLOPS, "cpu-nominal-estimate"


def mfu_from_throughput(n_params: int, tokens_per_s_per_chip: float,
                        device_kind: Optional[str] = None) -> Dict[str, Any]:
    """MFU block from an already-measured throughput (bench.py's path)."""
    if device_kind is None:
        from trustworthy_dl_tpu.obs.meta import run_metadata

        device_kind = run_metadata()["device_kind"]
    peak, source = peak_flops_per_chip(device_kind)
    achieved = 6.0 * float(n_params) * float(tokens_per_s_per_chip)
    return {
        "n_params": int(n_params),
        "tokens_per_s_per_chip": float(tokens_per_s_per_chip),
        "model_flops_per_s_per_chip": achieved,
        "peak_flops_per_chip": peak,
        "peak_flops_source": source,
        "device_kind": device_kind,
        "mfu": achieved / peak if peak > 0 else None,
    }


class StepTimeReporter:
    """Lap-based per-step phase accounting.

    Usage (the trainer's loop)::

        reporter.lap("data")       # time since last mark -> "data"
        ... dispatch + sync ...
        reporter.lap("compute")
        ... host verdicts ...
        reporter.lap("detection")
        reporter.finish_step()

    ``lap(name)`` attributes the wall time since the previous mark to
    ``name`` (repeat laps into the same phase accumulate);
    ``finish_step()`` closes the step.  Steps the caller must not
    account (guard-rejected, stale batches) call ``discard_step()``.
    Per-step records are ring-bounded; per-phase aggregates stream into
    the registry as ``tddl_phase_time_seconds{phase=}``.  (End-to-end
    step time already has a registry series —
    ``tddl_<ns>_step_time_seconds`` from ``MetricsCollector.tick`` — so
    the reporter deliberately adds no second one.)
    """

    def __init__(self, registry: Any = None, max_steps: int = 4096):
        self._steps: Deque[Dict[str, float]] = collections.deque(
            maxlen=max_steps
        )
        self._current: Dict[str, float] = {}
        self._laps: list = []          # (phase, start, end) this step
        self._mark: Optional[float] = None
        #: Optional obs.spans.SpanTracker: when attached (ObsSession
        #: enable_spans), finish_step synthesizes a ``train.step`` span
        #: plus one child per recorded lap from the SAME perf_counter
        #: marks the phase accounting used — the trainer loop needs no
        #: extra instrumentation for its timeline.
        self.spans: Any = None
        #: Optional obs.hbm.CostLedger — per-program XLA cost blocks
        #: (flops / bytes / temp allocation) stamped into the report,
        #: and the source of the analyzed-FLOPs MFU that replaces the
        #: nominal 6·params·tokens guess when a ``train_step`` entry
        #: exists.
        self.cost_ledger: Any = None
        self.last_step_total: Optional[float] = None
        self.n_params: Optional[int] = None
        self.tokens_per_step: Optional[int] = None
        self.model_kind: str = "lm"
        self.num_chips: int = 1
        self._phase_hist = None
        if registry is not None:
            self._phase_hist = registry.histogram(
                "tddl_phase_time_seconds",
                "Per-phase step-time breakdown", labels=("phase",),
            )

    # -- model info (for MFU) ---------------------------------------------

    @property
    def has_model_info(self) -> bool:
        return self.n_params is not None

    def set_model_info(self, n_params: int, tokens_per_step: int,
                       model_kind: str = "lm", num_chips: int = 1) -> None:
        self.n_params = int(n_params)
        self.tokens_per_step = int(tokens_per_step)
        self.model_kind = model_kind
        self.num_chips = max(int(num_chips), 1)

    # -- timing ------------------------------------------------------------

    def lap(self, phase: str) -> None:
        if phase not in PHASES:
            raise ValueError(f"unknown phase {phase!r}; one of {PHASES}")
        now = time.perf_counter()
        if self._mark is not None:
            self._current[phase] = self._current.get(phase, 0.0) \
                + (now - self._mark)
            self._laps.append((phase, self._mark, now))
        self._mark = now

    @contextmanager
    def phase(self, name: str) -> Iterator[None]:
        """Scoped alternative to ``lap`` for non-loop call sites."""
        if name not in PHASES:
            raise ValueError(f"unknown phase {name!r}; one of {PHASES}")
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            self._current[name] = self._current.get(name, 0.0) + (t1 - t0)
            self._laps.append((name, t0, t1))
            self._mark = time.perf_counter()

    def finish_step(self, step: Optional[int] = None) -> None:
        record = self._current
        laps = self._laps
        self._current = {}
        self._laps = []
        self._mark = time.perf_counter()
        if not record:
            return
        record["_total"] = sum(record.values())
        self.last_step_total = record["_total"]
        self._steps.append(record)
        if self._phase_hist is not None:
            for phase, seconds in record.items():
                if not phase.startswith("_"):
                    self._phase_hist.observe(seconds, phase=phase)
        if self.spans is not None and laps:
            # One root span per accounted step, one child per lap, all
            # from the marks the phase accounting already took — the
            # Chrome timeline and obs_report.json agree by construction.
            root = self.spans.add(
                "train.step", laps[0][1], laps[-1][2], kind="train",
                step=step,
            )
            for phase, t0, t1 in laps:
                self.spans.add(f"train.{phase}", t0, t1, kind="train",
                               parent_id=root.span_id, step=step)

    def discard_step(self) -> None:
        """Drop the accumulating step (rejected/retried — its duration
        would poison the per-phase distribution)."""
        self._current = {}
        self._laps = []
        self.last_step_total = None  # nothing fresh for watcher feeds
        self._mark = time.perf_counter()

    @property
    def num_steps(self) -> int:
        return len(self._steps)

    @property
    def step_time_mean(self) -> Optional[float]:
        if not self._steps:
            return None
        return float(np.mean([s["_total"] for s in self._steps]))

    def phase_fractions(self) -> Dict[str, float]:
        """Per-phase share of the accounted wall time (the fingerprint's
        compact view of the full ``phases`` report block)."""
        steps = list(self._steps)
        if not steps:
            return {}
        grand = sum(s["_total"] for s in steps)
        out: Dict[str, float] = {}
        for phase in PHASES:
            total = sum(s.get(phase, 0.0) for s in steps)
            if total > 0.0 and grand > 0.0:
                out[phase] = total / grand
        return out

    # -- reporting ---------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        """The ``obs_report.json`` payload: per-phase breakdown + MFU."""
        from trustworthy_dl_tpu.obs.meta import run_metadata

        steps = list(self._steps)
        out: Dict[str, Any] = {
            "num_steps": len(steps),
            "run_metadata": run_metadata(),
        }
        if steps:
            totals = np.asarray([s["_total"] for s in steps])
            out["step_time_s"] = {
                "mean": float(totals.mean()),
                "p50": float(np.percentile(totals, 50)),
                "p95": float(np.percentile(totals, 95)),
                "max": float(totals.max()),
            }
            grand_total = float(totals.sum())
            phases: Dict[str, Any] = {}
            for phase in PHASES:
                values = np.asarray([s.get(phase, 0.0) for s in steps])
                total = float(values.sum())
                if total <= 0.0:
                    continue
                phases[phase] = {
                    "total_s": total,
                    "mean_s": float(values.mean()),
                    "p50_s": float(np.percentile(values, 50)),
                    "p95_s": float(np.percentile(values, 95)),
                    "fraction": total / grand_total if grand_total else 0.0,
                }
            out["phases"] = phases
        if self.has_model_info and steps:
            mean_step = out["step_time_s"]["mean"]
            if self.model_kind == "lm" and self.tokens_per_step:
                tokens_per_s = self.tokens_per_step / mean_step
                out["mfu"] = mfu_from_throughput(
                    self.n_params, tokens_per_s / self.num_chips
                )
                out["mfu"]["tokens_per_step"] = self.tokens_per_step
                out["mfu"]["num_chips"] = self.num_chips
            else:
                # No comparable FLOPs-per-sample formula for convs; the
                # report still carries the throughput inputs.
                out["mfu"] = {
                    "n_params": self.n_params,
                    "samples_per_step": self.tokens_per_step,
                    "mfu": None,
                    "note": "MFU defined for LM (6 FLOPs/param/token) "
                            "only",
                }
        ledger = self.cost_ledger
        if ledger:
            out["cost_ledger"] = ledger.to_dict()
            analyzed = self._analyzed_mfu(out)
            if analyzed is not None:
                out["mfu_analyzed"] = analyzed
        return out

    def _analyzed_mfu(self, out: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """MFU from XLA's OWN flop count of the train step program
        (``cost_ledger['train_step'].flops`` per execution) over the
        measured mean step time — no 6 FLOPs/param/token modelling, no
        samples-vs-tokens ambiguity, and it covers remat recompute and
        the detection battery the nominal estimate ignores.  The peak
        denominator stays the per-device_kind table (its source is
        named, as always)."""
        flops = self.cost_ledger.flops("train_step") \
            if self.cost_ledger is not None else None
        mean_step = (out.get("step_time_s") or {}).get("mean")
        if not flops or not mean_step:
            return None
        from trustworthy_dl_tpu.obs.meta import run_metadata

        device_kind = run_metadata()["device_kind"]
        peak, source = peak_flops_per_chip(device_kind)
        achieved = flops / mean_step / max(self.num_chips, 1)
        return {
            "flops_per_step": flops,
            "flops_source": "xla-cost-analysis",
            "achieved_flops_per_s_per_chip": achieved,
            "peak_flops_per_chip": peak,
            "peak_flops_source": source,
            "num_chips": self.num_chips,
            "mfu": achieved / peak if peak > 0 else None,
        }

    def write(self, path: str) -> Dict[str, Any]:
        report = self.report()
        tmp = str(path) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=2)
        os.replace(tmp, path)
        return report

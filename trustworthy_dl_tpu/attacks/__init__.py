from trustworthy_dl_tpu.attacks.adversarial import (
    ATTACK_KINDS,
    AdversarialAttacker,
    AttackPlan,
    null_plan,
    plan_from_config,
    poison_batch,
    poison_gradients,
)
from trustworthy_dl_tpu.core.config import AttackConfig

__all__ = [
    "ATTACK_KINDS",
    "AdversarialAttacker",
    "AttackConfig",
    "AttackPlan",
    "null_plan",
    "plan_from_config",
    "poison_batch",
    "poison_gradients",
]

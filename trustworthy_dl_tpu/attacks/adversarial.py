"""Adversarial attack injection — the implied ``attacks.adversarial_attacks``
module (imported at experiment_runner.py:23; API from call sites
:90-97,157-160,187-188,231,285,597-598).

Two layers:

* ``AttackPlan`` — a static-shape pytree consumed *inside* the jitted train
  step.  Fault injection is deterministic per (step, node): a node in
  ``target_mask`` gets its batch corrupted (data poisoning / backdoor
  trigger) before the forward and/or its gradients perturbed (gradient
  poisoning / Byzantine) after the backward, keyed on the step counter —
  SURVEY §5.3's "shard_map-level gradient-perturbation hook keyed by device
  index (deterministic, testable)".
* ``AdversarialAttacker`` — host class with the reference's exact API
  (activate_attacks / is_active / apply_attacks / get_attack_statistics /
  get_final_statistics / cleanup), which also compiles its config into
  AttackPlans for the engine.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from trustworthy_dl_tpu.core.config import AttackConfig

logger = logging.getLogger(__name__)

ATTACK_KINDS = ("gradient_poisoning", "data_poisoning", "byzantine", "backdoor")


class AttackPlan(NamedTuple):
    """Device-side attack schedule for one training run.  All fields are
    arrays so the plan can be donated to the jitted step; ``active`` flips at
    ``start_step`` without recompilation."""

    target_mask: jax.Array    # bool[n] nodes under attack
    start_step: jax.Array     # i32[]  first attacked step
    active: jax.Array         # bool[] master switch (activate_attacks())
    intensity: jax.Array      # f32[]
    grad_poison: jax.Array    # bool[] scale+noise gradients
    data_poison: jax.Array    # bool[] corrupt inputs / flip labels
    byzantine: jax.Array      # bool[] replace gradients with noise
    backdoor: jax.Array       # bool[] trigger patch + fixed target label
    # Adaptive-adversary knobs (VERDICT r4 missing #3):
    ramp: jax.Array           # f32[] intensity increase per attacked step
    #                           (slow-boil: starts at `intensity`, grows)
    collude: jax.Array        # bool[] coordinated perturbations: all
    #                           attacked nodes submit the SAME noise
    #                           direction instead of independent draws

    def is_live(self, step: jax.Array) -> jax.Array:
        return self.active & (step >= self.start_step)

    def effective_intensity(self, step: jax.Array) -> jax.Array:
        """Slow-boil schedule: base + ramp · steps-since-start (0 before
        the start step)."""
        since = jnp.maximum(step - self.start_step, 0).astype(jnp.float32)
        return self.intensity + self.ramp * since


def null_plan(num_nodes: int) -> AttackPlan:
    return AttackPlan(
        target_mask=jnp.zeros((num_nodes,), bool),
        start_step=jnp.zeros((), jnp.int32),
        active=jnp.zeros((), bool),
        intensity=jnp.zeros((), jnp.float32),
        grad_poison=jnp.zeros((), bool),
        data_poison=jnp.zeros((), bool),
        byzantine=jnp.zeros((), bool),
        backdoor=jnp.zeros((), bool),
        ramp=jnp.zeros((), jnp.float32),
        collude=jnp.zeros((), bool),
    )


def plan_from_config(config: AttackConfig, num_nodes: int,
                     active: bool = False) -> AttackPlan:
    mask = np.zeros((num_nodes,), bool)
    for node in config.target_nodes:
        if 0 <= node < num_nodes:
            mask[node] = True
    kinds = set(config.attack_types)
    return AttackPlan(
        target_mask=jnp.asarray(mask),
        start_step=jnp.asarray(config.start_step, jnp.int32),
        active=jnp.asarray(active),
        intensity=jnp.asarray(config.intensity, jnp.float32),
        grad_poison=jnp.asarray("gradient_poisoning" in kinds),
        data_poison=jnp.asarray("data_poisoning" in kinds),
        byzantine=jnp.asarray("byzantine" in kinds),
        backdoor=jnp.asarray("backdoor" in kinds),
        ramp=jnp.asarray(config.intensity_ramp, jnp.float32),
        collude=jnp.asarray(config.collude),
    )


# ---------------------------------------------------------------------------
# In-step injectors (pure)
# ---------------------------------------------------------------------------


def poison_batch(plan: AttackPlan, batch: Dict[str, jax.Array], step: jax.Array,
                 rng: jax.Array, num_classes: int) -> Dict[str, jax.Array]:
    """Corrupt the per-node batch {'input':[n,b,...], 'target':[n,b,...]} for
    attacked nodes.  Data poisoning: additive noise on float inputs (token
    scramble on int inputs) and label shift.  Backdoor: constant trigger
    patch on a corner + fixed label 0."""
    live = plan.is_live(step)
    node_hit = plan.target_mask & live
    intensity = plan.effective_intensity(step)
    x, y = batch["input"], batch["target"]
    n = x.shape[0]
    mask_x = node_hit.reshape((n,) + (1,) * (x.ndim - 1))
    mask_y = node_hit.reshape((n,) + (1,) * (y.ndim - 1))

    k_noise, k_scramble = jax.random.split(rng)
    if jnp.issubdtype(x.dtype, jnp.floating):
        noisy = x + intensity * jax.random.normal(k_noise, x.shape, x.dtype)
        if x.ndim >= 4:  # [n, b, H, W, C] images: backdoor trigger patch
            trig = x.at[..., :3, :3, :].set(2.0)
        else:
            trig = x
    else:
        vocab_guess = jnp.maximum(jnp.max(x) + 1, num_classes)
        scramble = jax.random.randint(k_scramble, x.shape, 0, vocab_guess, x.dtype)
        flip = jax.random.bernoulli(k_noise, jnp.minimum(intensity, 1.0),
                                    x.shape)
        noisy = jnp.where(flip, scramble, x)
        trig = x.at[..., :4].set(0)

    x = jnp.where(mask_x & plan.data_poison, noisy, x)
    x = jnp.where(mask_x & plan.backdoor, trig, x)
    y_shift = (y + 1) % jnp.maximum(num_classes, 2)
    y = jnp.where(mask_y & plan.data_poison, y_shift, y)
    y = jnp.where(mask_y & plan.backdoor, jnp.zeros_like(y), y)
    return {"input": x, "target": y}


def poison_gradients(plan: AttackPlan, grads: Any, step: jax.Array,
                     rng: jax.Array) -> Any:
    """Perturb per-node gradients ([n, ...] leaves) of attacked nodes.

    Gradient poisoning: scale by (1 + 20·intensity) and add Gaussian noise —
    a norm-inflation attack, the exact class the reference's
    gradient-consistency signal is blind to (distributed_trainer.py:266-268)
    and its detector z-scores must catch.  Byzantine: replace with pure
    noise of comparable scale.
    """
    live = plan.is_live(step)
    node_hit = plan.target_mask & live
    intensity = plan.effective_intensity(step)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    keys = jax.random.split(rng, len(leaves))

    out = []
    for leaf, key in zip(leaves, keys):
        mask = node_hit.reshape((leaf.shape[0],) + (1,) * (leaf.ndim - 1))
        scale = 1.0 + 20.0 * intensity
        indep = jax.random.normal(key, leaf.shape, leaf.dtype)
        # Colluding group: every attacked node submits the SAME
        # perturbation direction (one shared draw broadcast over the node
        # axis) — the coordinated-poisoning threat the honest-majority
        # median/MAD cross-section has to survive (engine/step.py's
        # _cross_sectional_score assumption).
        shared = jnp.broadcast_to(
            jax.random.normal(key, leaf.shape[1:], leaf.dtype)[None],
            leaf.shape,
        )
        noise = jnp.where(plan.collude, shared, indep)
        poisoned = leaf * scale + intensity * noise
        byz = noise * (jnp.sqrt(jnp.mean(leaf**2)) * 10.0 + 1.0)
        leaf = jnp.where(mask & plan.grad_poison, poisoned, leaf)
        leaf = jnp.where(mask & plan.byzantine, byz, leaf)
        out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def corrupt_stage_compute(plan: AttackPlan, blocks: Any, step: jax.Array,
                          rng: jax.Array) -> Any:
    """Byzantine *compute* corruption for stage-parallel execution: the
    attacked stage's transform is perturbed (its block params get rms-scaled
    noise for this step's forward) — modelling a node that computes garbage
    activations — while the stored parameters stay clean.  This is the
    failure mode the pipeline canary probe exists to catch: unlike gradient
    attacks, it corrupts everything downstream of the stage
    (SURVEY §7.4(4))."""
    live = plan.is_live(step) & plan.byzantine
    intensity = plan.effective_intensity(step)
    leaves, treedef = jax.tree_util.tree_flatten(blocks)
    keys = jax.random.split(rng, len(leaves))
    out = []
    for leaf, key in zip(leaves, keys):
        mask = (plan.target_mask & live).reshape(
            (leaf.shape[0],) + (1,) * (leaf.ndim - 1)
        )
        rms = jnp.sqrt(jnp.mean(leaf.astype(jnp.float32) ** 2)) + 1e-8
        noise = jax.random.normal(key, leaf.shape, leaf.dtype) * (
            rms * (1.0 + 10.0 * intensity)
        ).astype(leaf.dtype)
        out.append(jnp.where(mask, leaf + noise, leaf))
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# Host API (reference parity)
# ---------------------------------------------------------------------------


class AdversarialAttacker:
    """Host-facing attacker with the reference's implied API
    (experiment_runner.py:90-97,157-160,187-188,231,285,597-598)."""

    def __init__(self, config: AttackConfig):
        self.config = config
        self._active = False
        self._applied = 0
        self._steps_attacked: List[int] = []
        self._rng = np.random.default_rng(config.seed)
        logger.info(
            "AdversarialAttacker initialized: types=%s targets=%s intensity=%s",
            config.attack_types, config.target_nodes, config.intensity,
        )

    def activate_attacks(self) -> None:
        if not self._active:
            logger.warning("Attacks ACTIVATED: %s", self.config.attack_types)
        self._active = True

    def deactivate_attacks(self) -> None:
        self._active = False

    def is_active(self) -> bool:
        return self._active

    def plan(self, num_nodes: int) -> AttackPlan:
        """Compile into the in-step schedule (identity == coordinate —
        valid before any elastic topology change)."""
        return plan_from_config(self.config, num_nodes, active=self._active)

    def plan_for(self, node_map: List[int]) -> AttackPlan:
        """Compile the schedule for a LIVE topology: ``node_map[i]`` is the
        original identity at mesh coordinate i (the trainer's mapping
        after evictions/readmissions), so the mask bit lands on the
        targeted identity wherever it currently sits."""
        plan = plan_from_config(self.config, len(node_map),
                                active=self._active)
        targets = set(self.config.target_nodes)
        mask = np.array([nid in targets for nid in node_map], bool)
        return plan._replace(target_mask=jnp.asarray(mask))

    def apply_attacks(self, batch: Dict[str, np.ndarray], batch_idx: int
                      ) -> Dict[str, np.ndarray]:
        """Host-side data poisoning for host-driven loops
        (experiment_runner.py:187-188).  Gradient attacks happen in-step via
        the plan; this corrupts the raw batch the way ``poison_batch`` does,
        applied to the whole batch (host loops have no node axis yet)."""
        if not self._active:
            return batch
        kinds = set(self.config.attack_types)
        if not kinds & {"data_poisoning", "backdoor"}:
            return batch
        x = np.array(batch["input"])
        y = np.array(batch["target"])
        if "data_poisoning" in kinds:
            if np.issubdtype(x.dtype, np.floating):
                x = x + self.config.intensity * self._rng.normal(
                    size=x.shape
                ).astype(x.dtype)
            else:
                flip = self._rng.random(x.shape) < min(self.config.intensity, 1.0)
                x = np.where(
                    flip,
                    self._rng.integers(0, max(int(x.max()) + 1, 2), x.shape),
                    x,
                ).astype(x.dtype)
            y = ((y + 1) % max(int(y.max()) + 1, 2)).astype(y.dtype)
        if "backdoor" in kinds:
            # Trigger patch + fixed target label, mirroring poison_batch.
            if np.issubdtype(x.dtype, np.floating) and x.ndim >= 4:
                x[..., :3, :3, :] = 2.0
            elif not np.issubdtype(x.dtype, np.floating):
                x[..., :4] = 0
            y = np.zeros_like(y)
        self._applied += 1
        self._steps_attacked.append(batch_idx)
        return {"input": x, "target": y}

    def get_attack_statistics(self) -> Dict[str, Any]:
        return {
            "active": self._active,
            "attack_types": list(self.config.attack_types),
            "target_nodes": list(self.config.target_nodes),
            "intensity": self.config.intensity,
            "batches_poisoned": self._applied,
        }

    def get_final_statistics(self) -> Dict[str, Any]:
        stats = self.get_attack_statistics()
        stats["total_attack_steps"] = len(self._steps_attacked)
        return stats

    def cleanup(self) -> None:
        self._active = False
        logger.info("AdversarialAttacker cleanup completed")

"""Rolling statistical baselines as a fixed-shape ring buffer pytree.

The reference keeps a deque of the last ``history_size`` stat dicts per node
and recomputes baseline mean/std over the window every step
(attack_detector.py:49-55,241-290).  Inside a jitted step we cannot grow
deques, so the window is a ring buffer [n, K, S] with a per-node write count;
baseline mean/std are masked reductions over the valid window — numerically
identical to the reference's window math.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from trustworthy_dl_tpu.detect.stats import NUM_GRADIENT_STATS


class BaselineState(NamedTuple):
    """Rolling window of per-node stat vectors."""

    ring: jax.Array   # f32[n, K, S] — circular history of stat vectors
    count: jax.Array  # i32[n] — total writes per node (monotonic)

    @property
    def num_nodes(self) -> int:
        return self.ring.shape[0]

    @property
    def window(self) -> int:
        return self.ring.shape[1]


def init_baseline_state(
    num_nodes: int,
    window: int = 1000,
    num_stats: int = NUM_GRADIENT_STATS,
) -> BaselineState:
    return BaselineState(
        ring=jnp.zeros((num_nodes, window, num_stats), jnp.float32),
        count=jnp.zeros((num_nodes,), jnp.int32),
    )


def push_stats(state: BaselineState, stats: jax.Array,
               mask: Optional[jax.Array] = None) -> BaselineState:
    """Append one stat vector per node ([n, S]); ``mask`` ([n] bool) skips
    nodes that produced no signal this step."""
    n, window, _ = state.ring.shape
    if mask is None:
        mask = jnp.ones((n,), bool)
    idx = state.count % window
    current = state.ring[jnp.arange(n), idx]
    new_row = jnp.where(mask[:, None], stats.astype(jnp.float32), current)
    ring = state.ring.at[jnp.arange(n), idx].set(new_row)
    return BaselineState(ring=ring, count=state.count + mask.astype(jnp.int32))


def baseline_moments(state: BaselineState) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(mean[n,S], std[n,S], valid_count[n]) over the valid window — the
    baseline the z-scores compare against (attack_detector.py:254-266).
    Population std, matching np.std."""
    n, window, s = state.ring.shape
    valid = jnp.minimum(state.count, window)                       # [n]
    slot = jnp.arange(window)[None, :]                             # [1, K]
    mask = (slot < valid[:, None]).astype(jnp.float32)[..., None]  # [n, K, 1]
    denom = jnp.maximum(valid.astype(jnp.float32), 1.0)[:, None]   # [n, 1]
    mean = jnp.sum(state.ring * mask, axis=1) / denom
    var = jnp.sum(((state.ring - mean[:, None, :]) ** 2) * mask, axis=1) / denom
    return mean, jnp.sqrt(var), valid


def zscores(stats: jax.Array, mean: jax.Array, std: jax.Array) -> jax.Array:
    """Per-stat |z| with zero-variance stats reporting z=0 and flagged
    invalid by the caller via ``std > 0`` (attack_detector.py:315-318)."""
    safe = jnp.where(std > 0, std, 1.0)
    return jnp.where(std > 0, jnp.abs(stats - mean) / safe, 0.0)

"""Tensor/gradient statistics as XLA reductions.

Re-implements the reference's host-numpy statistics battery
(attack_detector.py:185-239) as pure jnp so the per-node stats run inside the
compiled step (SURVEY §7.1 "detection inside the step").  Stat order is fixed
and indexed by name so the rule-based attack classifier
(attack_detector.py:350-363) can address columns.

The 12 tensor stats (attack_detector.py:187-200): mean, std, min, max,
median, skewness, kurtosis, p25, p75, L1/L2/Linf norms.  Gradient stats add
num_gradients, grad-norm mean/std/max and mean pairwise cosine similarity
(attack_detector.py:202-239) for 17 total.

Order statistics (median/percentiles) imply a sort, which is the expensive
part on TPU (SURVEY §7.4(2)); ``exact_order_stats=False`` substitutes
Gaussian-assumption approximations (median≈mean, p25/p75≈mean∓0.6745·std) —
tests always run the exact path.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

TENSOR_STAT_NAMES: Tuple[str, ...] = (
    "mean",
    "std",
    "min",
    "max",
    "median",
    "skewness",
    "kurtosis",
    "percentile_25",
    "percentile_75",
    "norm_l1",
    "norm_l2",
    "norm_inf",
)

GRADIENT_STAT_NAMES: Tuple[str, ...] = TENSOR_STAT_NAMES + (
    "num_gradients",
    "grad_norms_mean",
    "grad_norms_std",
    "grad_norms_max",
    "cosine_similarity",
)

NUM_TENSOR_STATS = len(TENSOR_STAT_NAMES)      # 12
NUM_GRADIENT_STATS = len(GRADIENT_STAT_NAMES)  # 17

STAT_INDEX = {name: i for i, name in enumerate(GRADIENT_STAT_NAMES)}


def tensor_statistics(x: jax.Array, exact_order_stats: bool = True) -> jax.Array:
    """f32[12] statistics of a flattened tensor (attack_detector.py:185-200).

    skew/kurtosis use the biased (population) estimators, matching
    scipy.stats.skew/kurtosis defaults (bias=True, Fisher kurtosis).
    """
    x = x.reshape(-1).astype(jnp.float32)
    mean = jnp.mean(x)
    centered = x - mean
    var = jnp.mean(centered**2)
    std = jnp.sqrt(var)
    safe_std = jnp.where(std > 0, std, 1.0)
    m3 = jnp.mean(centered**3)
    m4 = jnp.mean(centered**4)
    skew = jnp.where(std > 0, m3 / safe_std**3, 0.0)
    kurt = jnp.where(std > 0, m4 / safe_std**4 - 3.0, -3.0)
    if exact_order_stats:
        median = jnp.median(x)
        p25 = jnp.percentile(x, 25)
        p75 = jnp.percentile(x, 75)
    else:
        median = mean
        p25 = mean - 0.6744898 * std
        p75 = mean + 0.6744898 * std
    absx = jnp.abs(x)
    return jnp.stack(
        [
            mean,
            std,
            jnp.min(x),
            jnp.max(x),
            median,
            skew,
            kurt,
            p25,
            p75,
            jnp.sum(absx),
            jnp.sqrt(jnp.sum(x * x)),
            jnp.max(absx),
        ]
    )


def tensor_statistics_sampled(x: jax.Array, max_sort: int = 65536) -> jax.Array:
    """f32[12] statistics with exact moments/extrema/norms over the full
    tensor but order statistics (median/p25/p75) over a strided subsample of
    at most ``max_sort`` elements.

    This is the engine's hot-path variant: sorts dominate the detector cost
    on TPU once tensors reach model-gradient sizes (SURVEY §7.4(2)); a fixed
    deterministic subsample keeps the rolling baselines self-consistent, so
    z-scores retain their meaning while the sort stays O(max_sort).
    """
    x = x.reshape(-1).astype(jnp.float32)
    full = tensor_statistics(x, exact_order_stats=False)
    n = x.shape[0]
    if n <= max_sort:
        sample = x
    else:
        stride = n // max_sort
        sample = jax.lax.slice(x, (0,), (max_sort * stride,), (stride,))
    median = jnp.median(sample)
    p25 = jnp.percentile(sample, 25)
    p75 = jnp.percentile(sample, 75)
    idx_med = TENSOR_STAT_NAMES.index("median")
    idx_p25 = TENSOR_STAT_NAMES.index("percentile_25")
    idx_p75 = TENSOR_STAT_NAMES.index("percentile_75")
    return full.at[idx_med].set(median).at[idx_p25].set(p25).at[idx_p75].set(p75)


def _stats_from_raw_moments(s1, s2, s3, s4, mn, mx, l1, linf, count,
                            median, p25, p75) -> jax.Array:
    """Assemble the f32[12] battery from raw-moment sums.

    Raw-moment (uncentered) formulas trade a little precision for a single
    pass over the data; gradients are near zero-mean so cancellation is
    negligible, and the z-score baselines only need self-consistency.
    """
    n = jnp.maximum(count, 1.0)
    mean = s1 / n
    ex2, ex3, ex4 = s2 / n, s3 / n, s4 / n
    var = jnp.maximum(ex2 - mean * mean, 0.0)
    std = jnp.sqrt(var)
    safe = jnp.where(std > 0, std, 1.0)
    m3 = ex3 - 3.0 * mean * ex2 + 2.0 * mean**3
    m4 = ex4 - 4.0 * mean * ex3 + 6.0 * mean**2 * ex2 - 3.0 * mean**4
    skew = jnp.where(std > 0, m3 / safe**3, 0.0)
    kurt = jnp.where(std > 0, m4 / safe**4 - 3.0, -3.0)
    return jnp.stack([mean, std, mn, mx, median, skew, kurt, p25, p75,
                      l1, jnp.sqrt(s2), linf])


def strided_sample_of_leaves(leaves: Sequence[jax.Array], max_sort: int,
                             n_chunks: int = 16) -> jax.Array:
    """Deterministic ≤~max_sort-element subsample across flattened leaves,
    proportional to leaf size — the order-statistics sample without ever
    concatenating the full vectors.  Shapes are static (leaf sizes are trace
    constants), so this jits cleanly.

    Each leaf contributes up to ``n_chunks`` *contiguous* chunks spread
    evenly across its extent: contiguous slices are straight DMA reads on
    TPU, where an element-strided gather costs nearly a full pass over the
    leaf (measured ~3× the whole moment battery for GPT-2-sized tensors).
    Self-consistency across steps — not unbiasedness — is what the z-score
    baselines need."""
    total = sum(int(f.shape[0]) for f in leaves)
    if total <= max_sort:
        return jnp.concatenate(leaves) if len(leaves) > 1 else leaves[0]
    out = []
    for f in leaves:
        sz = int(f.shape[0])
        if sz == 0:
            continue
        q = min(max(1, (sz * max_sort) // total), sz)
        chunks = max(1, min(n_chunks, q // 1024))
        clen = q // chunks
        if clen == 0:
            chunks, clen = 1, q
        span = sz // chunks
        for i in range(chunks):
            off = min(i * span, sz - clen)
            out.append(jax.lax.slice(f, (off,), (off + clen,)))
    return jnp.concatenate(out) if len(out) > 1 else out[0]


def quantiles_from_sorted(sorted_x: jax.Array, qs: Sequence[float]
                          ) -> List[jax.Array]:
    """Linear-interpolated quantiles from an already-sorted vector — one
    sort shared across median/p25/p75 instead of three (XLA does not
    reliably CSE repeated sorts)."""
    n = sorted_x.shape[0]
    out = []
    for q in qs:
        pos = (n - 1) * (q / 100.0)
        lo = int(pos)
        hi = min(lo + 1, n - 1)
        frac = pos - lo
        out.append(sorted_x[lo] * (1.0 - frac) + sorted_x[hi] * frac)
    return out


def leafwise_statistics(
    leaves: Sequence[jax.Array], max_sort: int = 16384
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """(stats f32[12], per-leaf L2 norms f32[k], finite bool[], sample) over
    a list of flattened f32 leaves, streaming — one fused reduction pass per
    leaf, never materialising the concatenated vector.  ``sample`` is the
    ≤max_sort order-statistics subsample, returned for reuse (e.g. the
    intra-step cosine signal).

    This is the engine's hot-path battery: the previous implementation
    concatenated every gradient leaf (O(P) extra HBM write+read per node per
    step, P = parameter count) before reducing; here moments combine across
    leaves from per-leaf sums, and only the ≤max_sort order-statistics
    subsample is ever gathered.  The finite flag derives from s1/s2 (NaN/Inf
    anywhere propagates into both), so no separate isfinite pass."""
    from trustworthy_dl_tpu.ops import fused_stats as fs

    use_pallas = fs.pallas_enabled()

    def moments(f):
        if use_pallas and f.dtype == jnp.float32 and \
                int(f.size) >= fs.BLOCK_ROWS * fs.LANES:
            # Native tier (SURVEY §7.1): one explicit HBM pass for all eight
            # reductions via the Pallas kernel; XLA handles the tail.
            return fs.fused_moments(f)
        x = f if f.dtype == jnp.float32 else None
        # Shared x² subexpression; f32 accumulators even for bf16 inputs,
        # with the cast fused into the reductions (no materialised copy).
        x2 = (f * f).astype(jnp.float32) if x is None else x * x
        xf = f.astype(jnp.float32) if x is None else f
        return (jnp.sum(xf), jnp.sum(x2), jnp.sum(x2 * xf), jnp.sum(x2 * x2),
                jnp.min(f).astype(jnp.float32), jnp.max(f).astype(jnp.float32),
                jnp.sum(jnp.abs(xf)), jnp.max(jnp.abs(f)).astype(jnp.float32))

    per_leaf = [moments(f) for f in leaves]
    s1 = jnp.stack([m[0] for m in per_leaf]).sum()
    s2_leaf = jnp.stack([m[1] for m in per_leaf])
    s2 = s2_leaf.sum()
    s3 = jnp.stack([m[2] for m in per_leaf]).sum()
    s4 = jnp.stack([m[3] for m in per_leaf]).sum()
    mn = jnp.stack([m[4] for m in per_leaf]).min()
    mx = jnp.stack([m[5] for m in per_leaf]).max()
    l1 = jnp.stack([m[6] for m in per_leaf]).sum()
    linf = jnp.stack([m[7] for m in per_leaf]).max()
    count = jnp.asarray(float(sum(int(f.shape[0]) for f in leaves)),
                        jnp.float32)
    sample = strided_sample_of_leaves(leaves, max_sort).astype(jnp.float32)
    sorted_sample = jnp.sort(sample)
    p25, median, p75 = quantiles_from_sorted(sorted_sample, (25, 50, 75))
    stats = _stats_from_raw_moments(s1, s2, s3, s4, mn, mx, l1, linf, count,
                                    median, p25, p75)
    finite = jnp.isfinite(s1) & jnp.isfinite(s2)
    return stats, jnp.sqrt(s2_leaf), finite, sample


def combine_microbatch_stats(stacked: jax.Array) -> jax.Array:
    """Combine per-microbatch stat batteries [accum, k] -> f32[k] for
    gradient accumulation: order statistics keep their own reducers (min
    for ``min``, max for ``max``/``norm_inf``) so a single corrupted
    microbatch's extreme values survive the combine — a mean-of-maxes both
    diverges from full-batch semantics and attenuates exactly the signals
    most sensitive to a one-microbatch corruption — while the sum-moment
    columns (mean/std/skew/kurt/l1/l2 and the quantile approximations)
    average, matching fused_moments' own tail-combine logic."""
    out = jnp.mean(stacked, axis=0)
    mins = jnp.min(stacked, axis=0)
    maxs = jnp.max(stacked, axis=0)
    out = out.at[STAT_INDEX["min"]].set(mins[STAT_INDEX["min"]])
    for name in ("max", "norm_inf"):
        out = out.at[STAT_INDEX[name]].set(maxs[STAT_INDEX[name]])
    return out


def chunked_cosine_mean(flat: jax.Array, chunks: int = 4) -> jax.Array:
    """Mean pairwise cosine similarity among equal chunks of one flattened
    gradient vector — the engine's O(P) stand-in for the reference's
    O(k²·P) tensor-pairwise battery (attack_detector.py:225-239); it tracks
    directional instability of the gradient within a step and feeds the same
    'cosine_similarity' baseline column."""
    n = flat.shape[0] // chunks
    if n == 0:
        return jnp.asarray(1.0, jnp.float32)
    mat = flat[: n * chunks].reshape(chunks, n)
    norms = jnp.sqrt(jnp.sum(mat * mat, axis=1))
    normed = mat / jnp.maximum(norms, 1e-12)[:, None]
    sim = normed @ normed.T
    off = (jnp.sum(sim) - jnp.trace(sim)) / (chunks * (chunks - 1))
    return off


def _pairwise_cosine_mean(flat_grads: Sequence[jax.Array]) -> jax.Array:
    """Mean pairwise cosine similarity (attack_detector.py:225-239)."""
    k = len(flat_grads)
    if k < 2:
        return jnp.asarray(1.0, jnp.float32)
    sims = []
    norms = [jnp.sqrt(jnp.sum(g * g)) for g in flat_grads]
    for i in range(k):
        for j in range(i + 1, k):
            denom = jnp.maximum(norms[i] * norms[j], 1e-12)
            sims.append(jnp.sum(flat_grads[i] * flat_grads[j]) / denom)
    return jnp.mean(jnp.stack(sims))


def gradient_statistics(
    gradients: Sequence[jax.Array],
    exact_order_stats: bool = True,
    max_cosine_pairs_tensors: int = 8,
) -> jax.Array:
    """f32[17] statistics over a list of gradient tensors
    (attack_detector.py:202-223).

    The reference computes all O(k²) pairwise cosine similarities over every
    parameter tensor; for large models we cap the pairwise set to the first
    ``max_cosine_pairs_tensors`` tensors (configurable; tests use small k so
    the math is exact).
    """
    grads = [g.reshape(-1).astype(jnp.float32) for g in jax.tree_util.tree_leaves(gradients)]
    if not grads:
        return jnp.zeros((NUM_GRADIENT_STATS,), jnp.float32)
    all_flat = jnp.concatenate(grads)
    base = tensor_statistics(all_flat, exact_order_stats)
    norms = jnp.stack([jnp.sqrt(jnp.sum(g * g)) for g in grads])
    cos = _pairwise_cosine_mean(grads[:max_cosine_pairs_tensors])
    extra = jnp.stack(
        [
            jnp.asarray(float(len(grads)), jnp.float32),
            jnp.mean(norms),
            jnp.std(norms),
            jnp.max(norms),
            cos,
        ]
    )
    return jnp.concatenate([base, extra])


def padded_tensor_statistics(x: jax.Array, exact_order_stats: bool = True
                             ) -> jax.Array:
    """f32[17]: tensor stats padded to gradient-stat width so output and
    gradient baselines share one DetectorState layout (padding columns hold
    neutral values and are masked out of z-scoring via their zero baseline
    std)."""
    base = tensor_statistics(x, exact_order_stats)
    pad = jnp.zeros((NUM_GRADIENT_STATS - NUM_TENSOR_STATS,), jnp.float32)
    return jnp.concatenate([base, pad])


def pairwise_cosine_matrix(outputs: jax.Array) -> jax.Array:
    """[n, n] cosine similarity between per-node flattened outputs [n, d]
    (attack_detector.py:365-379)."""
    norms = jnp.sqrt(jnp.sum(outputs * outputs, axis=-1, keepdims=True))
    normed = outputs / jnp.maximum(norms, 1e-12)
    return normed @ normed.T


def byzantine_verdicts(outputs: jax.Array, threshold: float = 0.5) -> jax.Array:
    """bool[n]: node flagged Byzantine when its mean similarity to the other
    nodes drops below ``threshold`` (attack_detector.py:143-162).  Requires
    >=3 nodes, like the reference."""
    n = outputs.shape[0]
    if n < 3:
        return jnp.zeros((n,), bool)
    sim = pairwise_cosine_matrix(outputs)
    off_diag_mean = (jnp.sum(sim, axis=1) - jnp.diagonal(sim)) / (n - 1)
    return off_diag_mean < threshold


def backdoor_divergence(model_outputs: jax.Array, expected_outputs: jax.Array
                        ) -> jax.Array:
    """Batchmean KL(log_softmax(model) ‖ softmax(expected))
    (attack_detector.py:164-183)."""
    logp = jax.nn.log_softmax(model_outputs, axis=-1)
    q = jax.nn.softmax(expected_outputs, axis=-1)
    kl = jnp.sum(q * (jnp.log(jnp.maximum(q, 1e-12)) - logp), axis=-1)
    batch = model_outputs.reshape(-1, model_outputs.shape[-1]).shape[0]
    return jnp.sum(kl) / batch


def detect_backdoor(model_outputs: jax.Array, expected_outputs: jax.Array,
                    threshold: float = 2.0) -> jax.Array:
    """bool: divergence above threshold (attack_detector.py:179)."""
    return backdoor_divergence(model_outputs, expected_outputs) > threshold

"""Gradient verification — the implied ``GradientVerifier`` module.

The reference imports ``..security.gradient_verification.GradientVerifier``
(distributed_trainer.py:21) whose only call site is
``verify_gradients(node_gradients, node_id, step) -> bool``
(distributed_trainer.py:199-201).  No implementation exists in the snapshot,
so this is a fresh design with two layers:

* a pure, in-step check (``verify_gradients_array``): gradients are valid iff
  finite everywhere and their global L2 norm is not an extreme outlier vs the
  node's rolling norm history (z < ``norm_z_threshold``).  This deliberately
  catches gradient *inflation*, which the reference's gradient-consistency
  trust signal cannot see (distributed_trainer.py:266-268; SURVEY §7.5).
* a host class with the reference call signature, backed by the same math.
"""

from __future__ import annotations

import logging
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

DEFAULT_NORM_Z = 6.0   # generous: verification should fire on blatant tampering
DEFAULT_WARMUP = 10


class VerifierState(NamedTuple):
    """Per-node rolling gradient-norm statistics (Welford)."""

    count: jax.Array  # i32[n]
    mean: jax.Array   # f32[n] running mean of log-norms
    m2: jax.Array     # f32[n] running sum of squared deviations


def init_verifier_state(num_nodes: int) -> VerifierState:
    return VerifierState(
        count=jnp.zeros((num_nodes,), jnp.int32),
        mean=jnp.zeros((num_nodes,), jnp.float32),
        m2=jnp.zeros((num_nodes,), jnp.float32),
    )


def _log_norm(grad_norms: jax.Array) -> jax.Array:
    return jnp.log(jnp.maximum(grad_norms, 1e-30))


def norm_suspicions(
    state: VerifierState,
    grad_norms: jax.Array,
    norm_z_threshold: float = DEFAULT_NORM_Z,
    warmup: int = DEFAULT_WARMUP,
) -> jax.Array:
    """bool[n] raw statistical verdict — pure read, NO state change.

    Norms are compared in log-space so the z-score is scale-free.  Small-
    sample confidence widening (same rationale as the detector's
    SMALL_SAMPLE_WIDEN): z against a young Welford baseline is heavy-
    tailed; inflation attacks score z in the tens to hundreds, so widening
    only suppresses early-training flares.

    Split from absorption (``absorb_norms``) deliberately: the verdict the
    engine finally acts on is gated further (cross-sectional outlier check,
    canary suppression, detector candidates), and the baseline must absorb
    according to that FINAL judgement — verdict-then-absorb as one fused
    call either poisons the baseline with samples later deemed suspect, or
    starves it of samples later deemed legitimate (e.g. a shared norm
    shift every node exhibits at once), freezing the z forever.
    """
    log_norm = _log_norm(grad_norms)
    cnt = state.count.astype(jnp.float32)
    std = jnp.sqrt(state.m2 / jnp.maximum(cnt, 1.0))
    z = jnp.where(std > 0, jnp.abs(log_norm - state.mean) / std, 0.0)
    warm = state.count >= warmup
    thr_eff = norm_z_threshold * (1.0 + 8.0 / jnp.maximum(cnt, 1.0))
    return warm & (z >= thr_eff)


FLEET_WARMUP = 8
FLEET_LATCH_LIMIT = 50  # forced absorption after this many raw steps


def fleet_surge_update(
    state: VerifierState,
    median_norm: jax.Array,
    raw_streak: jax.Array,
    norm_z_threshold: float = DEFAULT_NORM_Z,
) -> Tuple[jax.Array, VerifierState, jax.Array]:
    """Fleet-level norm-surge verdict + absorption in one place, sharing
    the per-node verifier's conventions (log-space, m2/count variance,
    std>0 guard, small-sample threshold widening) so the two z-scores
    stay comparable.

    ``median_norm`` is f32[1] (the cross-sectional median gradient norm),
    ``raw_streak`` i32[1] (consecutive raw-surge steps so far).  Returns
    (raw bool[1], new_state, new_streak).

    ONE-SIDED: only an UPWARD departure counts — attacks inflate norms,
    while a clean run's norms decay downward as the loss falls, and a
    two-sided test against a lagging Welford mean would latch on that
    legitimate drift.

    Absorption is clean-only (a surge must not drag its own baseline) —
    BUT with an escape hatch: after ``FLEET_LATCH_LIMIT`` consecutive raw
    steps the sample absorbs anyway, so a *persistent legitimate*
    fleet-wide shift (LR-schedule bump, batch-regime change) re-baselines
    after a bounded alarm window instead of freezing the z forever
    (the starvation failure mode the per-node docstring above warns
    about; the per-node path escapes via the cross-sectional gate, which
    the fleet signal by construction cannot use)."""
    log_m = _log_norm(median_norm)
    cnt = state.count.astype(jnp.float32)
    std = jnp.sqrt(state.m2 / jnp.maximum(cnt, 1.0))
    z = jnp.where(std > 0, (log_m - state.mean) / std, 0.0)  # one-sided
    thr_eff = norm_z_threshold * (1.0 + 8.0 / jnp.maximum(cnt, 1.0))
    raw = (state.count >= FLEET_WARMUP) & (z >= thr_eff)
    new_streak = jnp.where(raw, raw_streak + 1, 0)
    absorb_mask = ~raw | (raw_streak >= FLEET_LATCH_LIMIT)
    new_state = absorb_norms(state, median_norm, absorb_mask)
    return raw, new_state, new_streak


class FleetEpisodeTracker:
    """Host-side bookkeeping for fleet norm-surge episodes.

    The in-step alarm (``fleet_surge_update``) is a bool per step; this
    tracker turns it into *episodes* (open on the rising edge, close on
    the falling edge) and — critically — records HOW each episode ended.
    After ``FLEET_LATCH_LIMIT`` consecutive raw steps the baseline starts
    force-absorbing the surged norms (the bounded-alarm escape hatch), so
    the z falling back under threshold can mean two very different
    things:

    * ``"recovered"``            — norms actually returned to baseline;
    * ``"absorbed-while-raw"``   — the surge NEVER stopped; the latch
      re-baselined it.  The model may now be training on poisoned
      gradients that look statistically normal — an operator must treat
      this as an unresolved incident, not an all-clear.

    The distinction comes from the raw streak: it only exceeds
    ``FLEET_LATCH_LIMIT`` when forced absorption began while the alarm
    was still raw."""

    def __init__(self, latch_limit: int = FLEET_LATCH_LIMIT):
        self.latch_limit = latch_limit
        self.episodes: List[dict] = []
        self._open = False
        self._peak_streak = 0

    @property
    def alarm_open(self) -> bool:
        return self._open

    def update(self, alert: bool, raw_streak: int, step: int,
               extra: Optional[dict] = None) -> Optional[dict]:
        """Feed one step's (debounced alert, raw streak).  Returns the
        episode dict on the step it OPENS (for host-side side effects:
        logging, state-machine flips), else None."""
        if alert:
            self._peak_streak = max(self._peak_streak, int(raw_streak))
            if not self._open:
                self._open = True
                episode = {"step": int(step), "resolution": None,
                           **(extra or {})}
                self.episodes.append(episode)
                return episode
        elif self._open:
            self._open = False
            episode = self.episodes[-1]
            episode["resolved_step"] = int(step)
            episode["peak_raw_streak"] = self._peak_streak
            if self._peak_streak >= self.latch_limit:
                episode["resolution"] = "absorbed-while-raw"
                logger.error(
                    "fleet norm-surge episode (opened step %d) closed at "
                    "step %d by FORCED ABSORPTION at the %d-step latch "
                    "limit — the surge did not recover, the baseline "
                    "re-anchored onto it; treat as unresolved",
                    episode["step"], int(step), self.latch_limit,
                )
            else:
                episode["resolution"] = "recovered"
                logger.info(
                    "fleet norm-surge episode (opened step %d) recovered "
                    "at step %d (peak raw streak %d)",
                    episode["step"], int(step), self._peak_streak,
                )
            self._peak_streak = 0
        return None


def absorb_norms(state: VerifierState, grad_norms: jax.Array,
                 mask: jax.Array) -> VerifierState:
    """Welford-absorb this step's log-norms where ``mask`` holds (the
    caller's final 'clean this step' judgement)."""
    log_norm = _log_norm(grad_norms)
    new_count = state.count + mask.astype(jnp.int32)
    delta = log_norm - state.mean
    new_mean = jnp.where(
        mask,
        state.mean + delta / jnp.maximum(new_count.astype(jnp.float32), 1.0),
        state.mean,
    )
    new_m2 = jnp.where(mask, state.m2 + delta * (log_norm - new_mean), state.m2)
    return VerifierState(count=new_count, mean=new_mean, m2=new_m2)


def verify_gradients_array(
    state: VerifierState,
    grad_norms: jax.Array,
    all_finite: jax.Array,
    norm_z_threshold: float = DEFAULT_NORM_Z,
    warmup: int = DEFAULT_WARMUP,
    update_mask: Optional[jax.Array] = None,
) -> Tuple[VerifierState, jax.Array, jax.Array]:
    """One-shot verify-and-absorb composition (host API / standalone use).

    ``grad_norms``: f32[n] global L2 norm of each node's gradients.
    ``all_finite``: bool[n] no NaN/Inf anywhere in the node's gradients.
    Returns (new_state, valid bool[n], norm_suspect bool[n]); the baseline
    absorbs exactly the valid samples (a poisoned norm must not poison its
    own baseline).  The engine uses the split norm_suspicions/absorb_norms
    pair instead so external gates can refine the verdict first.
    """
    if update_mask is None:
        update_mask = jnp.ones_like(all_finite, dtype=bool)
    suspect = norm_suspicions(state, grad_norms, norm_z_threshold, warmup)
    valid = all_finite.astype(bool) & ~suspect & update_mask
    return absorb_norms(state, grad_norms, valid), valid, suspect


class GradientVerifier:
    """Host-facing verifier with the reference's implied call signature
    (distributed_trainer.py:199-201)."""

    def __init__(self, norm_z_threshold: float = DEFAULT_NORM_Z,
                 warmup: int = DEFAULT_WARMUP, max_nodes: int = 256):
        self.norm_z_threshold = norm_z_threshold
        self.warmup = warmup
        self._state = init_verifier_state(max_nodes)
        self._max_nodes = max_nodes

    def verify_gradients(self, gradients: Sequence[Any], node_id: int, step: int
                         ) -> bool:
        if gradients is None or len(gradients) == 0:
            return False
        flats = [np.asarray(g, np.float32).reshape(-1) for g in gradients]
        all_finite = all(np.all(np.isfinite(f)) for f in flats)
        norm = float(np.sqrt(sum(float(np.sum(f * f)) for f in flats)))
        norms = jnp.zeros((self._max_nodes,), jnp.float32).at[node_id].set(norm)
        finite = jnp.zeros((self._max_nodes,), bool).at[node_id].set(all_finite)
        mask = jnp.zeros((self._max_nodes,), bool).at[node_id].set(True)
        self._state, valid, _ = verify_gradients_array(
            self._state, norms, finite, self.norm_z_threshold, self.warmup, mask
        )
        ok = bool(valid[node_id])
        if not ok:
            logger.warning(
                "Gradient verification failed for node %d at step %d", node_id, step
            )
        return ok

    def reset_node(self, node_id: int) -> None:
        self._state = VerifierState(
            count=self._state.count.at[node_id].set(0),
            mean=self._state.mean.at[node_id].set(0.0),
            m2=self._state.m2.at[node_id].set(0.0),
        )

"""Attack detection — pure in-step verdict math plus host API parity.

The pure layer (``anomaly_verdicts``) reproduces the reference's z-score
pipeline (attack_detector.py:292-342) over BaselineState windows: per-stat
|z| vs the rolling baseline, evidence at z>3, attack iff mean z > 2.5,
confidence = min(1, score/5), with the 10-entry warm-up gate
(attack_detector.py:91,126).  The rule-based attack-type classifier follows
attack_detector.py:350-363 exactly.

The host ``AttackDetector`` class keeps the reference's full public API
(detect_output_anomaly / detect_gradient_poisoning / detect_byzantine_behavior
/ detect_backdoor_attack / update_detection_models / detect_with_ml_models /
statistics / export) for drop-in use, delegating the math to the pure layer.
Unlike the reference, the Byzantine and backdoor checks ARE wired into the
training engine (engine/step.py) — SURVEY §7.5.
"""

from __future__ import annotations

import enum
import json
import logging
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trustworthy_dl_tpu.detect import baseline as bl
from trustworthy_dl_tpu.utils.io import atomic_write_json
from trustworthy_dl_tpu.detect import stats as st
from trustworthy_dl_tpu.detect.baseline import BaselineState

logger = logging.getLogger(__name__)

# Detection thresholds (attack_detector.py:320,330,338,158,179).
EVIDENCE_Z = 3.0       # 3-sigma evidence rule
ANOMALY_SCORE = 2.5    # mean-z attack threshold
CONFIDENCE_SCALE = 5.0
BYZANTINE_SIMILARITY = 0.5
BACKDOOR_KL = 2.0
WARMUP = 10            # min history before verdicts fire
# Small-sample confidence widening: a z-score against a k-sample rolling
# baseline is heavy-tailed for small k, so the verdict threshold scales by
# (1 + K/k) — ~3x at k=4, ~1.16x at k=50, asymptotically the reference's
# constant.  Real attacks score 1-2 orders of magnitude over threshold
# (norm inflation lands at mean-z ≈ 300), so the widening only suppresses
# the early-training flares a constant threshold false-fires on.
SMALL_SAMPLE_WIDEN = 8.0


class AttackType(enum.IntEnum):
    """Attack taxonomy (attack_detector.py:20-26)."""

    DATA_POISONING = 0
    MODEL_POISONING = 1
    GRADIENT_POISONING = 2
    BYZANTINE = 3
    BACKDOOR = 4
    ADVERSARIAL_INPUT = 5

    @property
    def label(self) -> str:
        return self.name.lower()


@dataclass
class AttackDetectionResult:
    """Result of attack detection (attack_detector.py:28-36)."""

    is_attack: bool
    attack_type: Optional[AttackType]
    confidence: float
    evidence: Dict[str, Any]
    timestamp: float
    node_id: int


class Verdicts(NamedTuple):
    """Vectorised detection outcome for all nodes in one step."""

    is_attack: jax.Array      # bool[n]
    attack_type: jax.Array    # i32[n]  AttackType codes (valid iff is_attack)
    confidence: jax.Array     # f32[n]
    score: jax.Array          # f32[n]  mean |z|
    z: jax.Array              # f32[n, S] per-stat |z|
    evidence_mask: jax.Array  # bool[n, S] z > 3


def _rule_hits(z: jax.Array, evidence_mask: jax.Array
               ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(l2_hit, std_hit, shape_hit) — THE reference rule predicates
    (attack_detector.py:350-363), shared by classify_attack and the
    attribution ladder's _rule_fired so their thresholds can never
    drift apart.  Evidence requires the 3-sigma record first (the
    reference only inspects stats present in the evidence dict)."""
    i_l2 = st.STAT_INDEX["norm_l2"]
    i_std = st.STAT_INDEX["std"]
    i_skew = st.STAT_INDEX["skewness"]
    i_kurt = st.STAT_INDEX["kurtosis"]
    l2_hit = evidence_mask[..., i_l2] & (z[..., i_l2] > 5.0)
    std_hit = evidence_mask[..., i_std] & (z[..., i_std] > 4.0)
    shape_hit = evidence_mask[..., i_skew] | evidence_mask[..., i_kurt]
    return l2_hit, std_hit, shape_hit


def classify_attack(z: jax.Array, evidence_mask: jax.Array) -> jax.Array:
    """Rule-based classifier (attack_detector.py:350-363), vectorised.

    Branch order: norm_l2 z>5 → GRADIENT_POISONING; std z>4 → DATA_POISONING;
    skew/kurtosis evidence → ADVERSARIAL_INPUT; else BYZANTINE.
    """
    l2_hit, std_hit, shape_hit = _rule_hits(z, evidence_mask)
    return jnp.select(
        [l2_hit, std_hit, shape_hit],
        [
            jnp.int32(AttackType.GRADIENT_POISONING),
            jnp.int32(AttackType.DATA_POISONING),
            jnp.int32(AttackType.ADVERSARIAL_INPUT),
        ],
        default=jnp.int32(AttackType.BYZANTINE),
    )


def _rule_fired(z: jax.Array, evidence_mask: jax.Array) -> jax.Array:
    """bool[n]: did any of the reference's classification rules
    (attack_detector.py:350-363) actually trip — as opposed to falling
    through to the default branch?  Same predicates as classify_attack
    (shared via _rule_hits)."""
    l2_hit, std_hit, shape_hit = _rule_hits(z, evidence_mask)
    return l2_hit | std_hit | shape_hit


def attribute_attack(grad_v: "Verdicts", out_v: "Verdicts",
                     byz: jax.Array, backdoor: jax.Array,
                     loss_outlier: Optional[jax.Array] = None) -> jax.Array:
    """i32[n] attack-type attribution ladder (VERDICT r3 weak #7).

    The reference's rule classifier keeps its labels wherever one of its
    rules actually fired (parity, attack_detector.py:350-363); its
    *default* branch — which stamped "byzantine" on every confirmation
    whose fixed z>5/z>4 thresholds hadn't tripped yet, i.e. most FIRST
    detections — is replaced by the explicit consensus checks, the
    loss-detachment signature (a node whose shard loss detached from the
    fleet is training on corrupted DATA), and finally the
    dominant-signature family (classify_attack_dominant)."""
    grad_rule = grad_v.is_attack & _rule_fired(grad_v.z,
                                               grad_v.evidence_mask)
    out_rule = out_v.is_attack & _rule_fired(out_v.z, out_v.evidence_mask)
    if loss_outlier is None:
        loss_outlier = jnp.zeros_like(byz)
    return jnp.select(
        [grad_rule, out_rule, backdoor, byz, loss_outlier],
        [
            grad_v.attack_type,
            out_v.attack_type,
            jnp.full_like(grad_v.attack_type, int(AttackType.BACKDOOR)),
            jnp.full_like(grad_v.attack_type, int(AttackType.BYZANTINE)),
            jnp.full_like(grad_v.attack_type,
                          int(AttackType.DATA_POISONING)),
        ],
        default=classify_attack_dominant(grad_v.z, out_v.z),
    )


def classify_attack_dominant(z_grad: jax.Array, z_out: jax.Array
                             ) -> jax.Array:
    """Best-effort family attribution for confirmations the rule
    classifier cannot label (VERDICT r3 weak #7): when NEITHER battery's
    own verdict fired — the confirmation came from the hard
    cross-sectional outlier, norm-verification, or consensus checks — the
    reference's fixed-threshold rules (z>5 / z>4,
    attack_detector.py:350-363) usually haven't tripped yet, and the
    default branch mislabelled every first detection "byzantine".  Here
    the family whose signature columns carry the dominant z wins:
    gradient-norm columns → GRADIENT_POISONING, dispersion →
    DATA_POISONING, shape (skew/kurtosis) → ADVERSARIAL_INPUT; BYZANTINE
    only when no signature stands out (z < 1), i.e. when the evidence
    genuinely is consensus-only."""
    idx = st.STAT_INDEX
    norm_sig = jnp.maximum(
        jnp.maximum(z_grad[..., idx["norm_l2"]],
                    z_grad[..., idx["norm_l1"]]),
        z_grad[..., idx["norm_inf"]],
    )
    data_sig = jnp.maximum(z_out[..., idx["std"]], z_grad[..., idx["std"]])
    shape_sig = jnp.maximum(
        jnp.maximum(z_out[..., idx["skewness"]],
                    z_out[..., idx["kurtosis"]]),
        jnp.maximum(z_grad[..., idx["skewness"]],
                    z_grad[..., idx["kurtosis"]]),
    )
    fams = jnp.stack([norm_sig, data_sig, shape_sig], axis=-1)
    types = jnp.asarray([
        int(AttackType.GRADIENT_POISONING),
        int(AttackType.DATA_POISONING),
        int(AttackType.ADVERSARIAL_INPUT),
    ], jnp.int32)
    best = jnp.argmax(fams, axis=-1)
    return jnp.where(
        jnp.max(fams, axis=-1) >= 1.0,
        types[best],
        jnp.int32(AttackType.BYZANTINE),
    )


def anomaly_verdicts(
    current_stats: jax.Array,
    state: BaselineState,
    warmup: int = WARMUP,
    score_threshold: float = ANOMALY_SCORE,
) -> Verdicts:
    """Detect statistical anomalies for all nodes ([n, S] current stats vs
    their rolling baselines).  Matches attack_detector.py:292-342 with the
    baseline computed over the window *before* this step's stats are pushed
    (the reference appends first, then builds the baseline including the
    current sample — see ``push_then_detect`` for that exact ordering)."""
    mean, std, valid = bl.baseline_moments(state)
    z = bl.zscores(current_stats, mean, std)
    usable = std > 0
    n_usable = jnp.maximum(jnp.sum(usable, axis=-1), 1)
    score = jnp.sum(jnp.where(usable, z, 0.0), axis=-1) / n_usable
    warm = valid >= warmup
    threshold_eff = score_threshold * (
        1.0 + SMALL_SAMPLE_WIDEN / jnp.maximum(valid.astype(jnp.float32), 1.0)
    )
    is_attack = (score > threshold_eff) & warm
    evidence = (z > EVIDENCE_Z) & usable
    return Verdicts(
        is_attack=is_attack,
        attack_type=classify_attack(z, evidence),
        confidence=jnp.minimum(1.0, score / CONFIDENCE_SCALE),
        score=score,
        z=z,
        evidence_mask=evidence,
    )


def push_then_detect(
    state: BaselineState,
    current_stats: jax.Array,
    mask: Optional[jax.Array] = None,
    warmup: int = WARMUP,
    score_threshold: float = ANOMALY_SCORE,
) -> Tuple[BaselineState, Verdicts]:
    """Reference ordering: append this step's stats to history, rebuild the
    baseline over the window (now containing the current sample), then score
    (attack_detector.py:84-100,119-135)."""
    state = bl.push_stats(state, current_stats, mask)
    verdicts = anomaly_verdicts(current_stats, state, warmup, score_threshold)
    if mask is not None:
        verdicts = verdicts._replace(
            is_attack=verdicts.is_attack & mask.astype(bool)
        )
    return state, verdicts


# ---------------------------------------------------------------------------
# Host-facing API (reference parity: attack_detector.py:38-487)
# ---------------------------------------------------------------------------


class AttackDetector:
    """Comprehensive attack detection system for distributed training."""

    def __init__(self, detection_threshold: float = 0.8, history_size: int = 1000,
                 exact_order_stats: bool = True):
        self.detection_threshold = detection_threshold
        self.history_size = history_size
        self.exact_order_stats = exact_order_stats

        self.output_history: Dict[int, deque] = defaultdict(
            lambda: deque(maxlen=history_size)
        )
        self.gradient_history: Dict[int, deque] = defaultdict(
            lambda: deque(maxlen=history_size)
        )
        self.loss_history: Dict[int, deque] = defaultdict(
            lambda: deque(maxlen=history_size)
        )
        self.output_baselines: Dict[int, Dict] = defaultdict(dict)
        self.gradient_baselines: Dict[int, Dict] = defaultdict(dict)
        self.anomaly_detectors: Dict[int, Any] = {}
        self.clustering_models: Dict[int, Any] = {}
        self._model_keys: Dict[int, list] = {}  # fit-time feature order
        self.detection_stats = {
            "total_detections": 0,
            "false_positives": 0,
            "true_positives": 0,
            "attack_types": defaultdict(int),
        }
        logger.info("AttackDetector initialized")

    # -- stats helpers ---------------------------------------------------

    def _stats_dict(self, names: Sequence[str], values: np.ndarray) -> Dict[str, float]:
        return {name: float(v) for name, v in zip(names, values)}

    def calculate_tensor_statistics(self, tensor: Any) -> Dict[str, float]:
        """12-stat dict (attack_detector.py:185-200)."""
        arr = jnp.asarray(np.asarray(tensor), jnp.float32)
        vals = np.asarray(st.tensor_statistics(arr, self.exact_order_stats))
        return self._stats_dict(st.TENSOR_STAT_NAMES, vals)

    def calculate_gradient_statistics(self, gradients: Sequence[Any]) -> Dict[str, float]:
        """17-stat dict (attack_detector.py:202-223)."""
        if not gradients:
            return {}
        grads = [jnp.asarray(np.asarray(g), jnp.float32) for g in gradients]
        vals = np.asarray(st.gradient_statistics(grads, self.exact_order_stats))
        return self._stats_dict(st.GRADIENT_STAT_NAMES, vals)

    # -- detection entry points (reference API) --------------------------

    def detect_output_anomaly(self, output: Any, node_id: int, step: int) -> bool:
        """attack_detector.py:71-107."""
        if output is None:
            return True
        stats_d = self.calculate_tensor_statistics(output)
        self.output_history[node_id].append(
            {"step": step, "stats": stats_d, "timestamp": time.time()}
        )
        if len(self.output_history[node_id]) < WARMUP:
            return False
        self._update_baseline(node_id, self.output_history, self.output_baselines)
        result = self._detect_statistical_anomaly(
            stats_d, self.output_baselines[node_id], node_id
        )
        if result.is_attack:
            logger.warning(
                "Output anomaly detected on node %d: %s", node_id, result.attack_type
            )
            self.detection_stats["total_detections"] += 1
            self.detection_stats["attack_types"][result.attack_type.label] += 1
        return result.is_attack

    def detect_gradient_poisoning(self, gradients: Sequence[Any], node_id: int,
                                  step: int) -> bool:
        """attack_detector.py:109-141."""
        if gradients is None or len(gradients) == 0:
            return False
        stats_d = self.calculate_gradient_statistics(gradients)
        self.gradient_history[node_id].append(
            {"step": step, "stats": stats_d, "timestamp": time.time()}
        )
        if len(self.gradient_history[node_id]) < WARMUP:
            return False
        self._update_baseline(node_id, self.gradient_history, self.gradient_baselines)
        result = self._detect_statistical_anomaly(
            stats_d, self.gradient_baselines[node_id], node_id
        )
        if result.is_attack:
            logger.warning("Gradient poisoning detected on node %d", node_id)
            self.detection_stats["total_detections"] += 1
        return result.is_attack

    def detect_byzantine_behavior(self, node_outputs: Dict[int, Any], step: int
                                  ) -> List[int]:
        """Cross-node pairwise-similarity outlier check
        (attack_detector.py:143-162)."""
        if len(node_outputs) < 3:
            return []
        ids = sorted(node_outputs)
        flat = [np.asarray(node_outputs[i], np.float32).reshape(-1) for i in ids]
        lengths = {f.shape[0] for f in flat}
        if len(lengths) == 1 and 0 not in lengths:
            # Equal shapes — the reference's only case (attack_detector.py:
            # 365-379) and the common one: single vectorized device call.
            verdicts = np.asarray(
                st.byzantine_verdicts(jnp.asarray(np.stack(flat)),
                                      BYZANTINE_SIMILARITY)
            )
        else:
            # Ragged outputs (this build's extension): each pair's dot runs
            # over its common prefix but is normalised by both FULL norms —
            # mass outside the shared support cannot be cross-checked, so
            # it counts AGAINST its owner.  This is the only assignment of
            # the unverifiable tail that is Byzantine-safe: a global
            # truncation width hands the shortest node control of every
            # pair's support, a plain per-pair prefix cosine lets an
            # attacker echo an honest prefix and hide a payload in the
            # suffix, and a near-empty output scores ~0 here rather than
            # shrinking anyone else's comparison.
            n = len(flat)
            norms = np.array([np.linalg.norm(f) for f in flat])
            sims = np.zeros((n, n), np.float64)
            for a in range(n):
                for c in range(a + 1, n):
                    w = min(flat[a].shape[0], flat[c].shape[0])
                    denom = norms[a] * norms[c]
                    s = float(flat[a][:w] @ flat[c][:w] / denom) \
                        if w and denom > 0 else 0.0
                    sims[a, c] = sims[c, a] = s
            mean_sim = sims.sum(axis=1) / (n - 1)
            verdicts = mean_sim < BYZANTINE_SIMILARITY
        byzantine = [i for i, flag in zip(ids, verdicts) if flag]
        for node_id in byzantine:
            logger.warning("Byzantine behavior detected on node %d", node_id)
        return byzantine

    def detect_backdoor_attack(self, model_outputs: Any, expected_outputs: Any,
                               node_id: int) -> bool:
        """KL-divergence backdoor check (attack_detector.py:164-183)."""
        if model_outputs is None or expected_outputs is None:
            return False
        flagged = bool(
            st.detect_backdoor(
                jnp.asarray(np.asarray(model_outputs), jnp.float32),
                jnp.asarray(np.asarray(expected_outputs), jnp.float32),
                BACKDOOR_KL,
            )
        )
        if flagged:
            logger.warning("Potential backdoor attack detected on node %d", node_id)
        return flagged

    # -- baseline & scoring ---------------------------------------------

    def _update_baseline(self, node_id: int, history: Dict[int, deque],
                         baselines: Dict[int, Dict]) -> None:
        """Window aggregate per stat (attack_detector.py:241-290)."""
        entries = list(history[node_id])
        if len(entries) < WARMUP:
            return
        agg: Dict[str, List[float]] = defaultdict(list)
        for entry in entries:
            for name, value in entry["stats"].items():
                agg[name].append(value)
        baselines[node_id] = {
            name: {
                "mean": float(np.mean(vals)),
                "std": float(np.std(vals)),
                "min": float(np.min(vals)),
                "max": float(np.max(vals)),
                "percentile_5": float(np.percentile(vals, 5)),
                "percentile_95": float(np.percentile(vals, 95)),
            }
            for name, vals in agg.items()
        }

    def _detect_statistical_anomaly(self, current_stats: Dict[str, float],
                                    baseline: Dict[str, Dict], node_id: int
                                    ) -> AttackDetectionResult:
        """attack_detector.py:292-342."""
        if not baseline:
            return AttackDetectionResult(False, None, 0.0, {}, time.time(), node_id)
        scores = []
        evidence: Dict[str, Any] = {}
        for name, value in current_stats.items():
            base = baseline.get(name)
            if base is None or base["std"] <= 0:
                continue
            z = abs((value - base["mean"]) / base["std"])
            scores.append(z)
            if z > EVIDENCE_Z:
                evidence[name] = {
                    "z_score": z,
                    "current_value": value,
                    "baseline_mean": base["mean"],
                    "baseline_std": base["std"],
                }
        overall = float(np.mean(scores)) if scores else 0.0
        is_attack = overall > ANOMALY_SCORE
        attack_type = self._classify_attack_type(evidence)
        return AttackDetectionResult(
            is_attack=is_attack,
            attack_type=attack_type if is_attack else None,
            confidence=min(1.0, overall / CONFIDENCE_SCALE),
            evidence=evidence,
            timestamp=time.time(),
            node_id=node_id,
        )

    def _classify_attack_type(self, evidence: Dict) -> Optional[AttackType]:
        """attack_detector.py:350-363."""
        if not evidence:
            return None
        if "norm_l2" in evidence and evidence["norm_l2"]["z_score"] > 5:
            return AttackType.GRADIENT_POISONING
        if "std" in evidence and evidence["std"]["z_score"] > 4:
            return AttackType.DATA_POISONING
        if "skewness" in evidence or "kurtosis" in evidence:
            return AttackType.ADVERSARIAL_INPUT
        return AttackType.BYZANTINE

    # -- ML-model path (attack_detector.py:381-425) ----------------------

    # Hyperparameters pinned to the reference's values so verdicts are
    # comparable (attack_detector.py:388-397); the surrounding machinery —
    # feature ordering, refit cadence, unsupported-env gating — is ours.
    ML_MIN_SAMPLES = 50
    ML_ISOFOREST_KW = dict(contamination=0.1, random_state=42, n_estimators=100)
    ML_DBSCAN_KW = dict(eps=0.5, min_samples=5)

    @staticmethod
    def _joined_stats(out_entry: Optional[Dict],
                      grad_entry: Optional[Dict]) -> Dict[str, float]:
        """One feature row from the output battery and (when present) the
        gradient battery, namespaced so the two 17-stat dicts can't
        collide."""
        row: Dict[str, float] = {}
        if out_entry is not None:
            row.update({f"out:{k}": v for k, v in out_entry["stats"].items()})
        if grad_entry is not None:
            row.update({f"grad:{k}": v for k, v in grad_entry["stats"].items()})
        return row

    def _node_feature_matrix(self, node_id: int) -> Optional[tuple]:
        """(keys, [t, d] matrix) of one node's joined stat-battery history
        (output AND gradient batteries — the engine appends both once per
        step), with a stable (sorted-key) column order.  The keys are
        stored with the fitted model so inference indexes the query dict in
        fit-time order.  Histories of unequal length (host-API standalone
        use appends only one stream) are aligned at their newest entries."""
        out_h = self.output_history.get(node_id)
        if out_h is None or len(out_h) < self.ML_MIN_SAMPLES:
            return None
        # Deques index in O(n): materialise once so the join stays O(t).
        grad_h = list(self.gradient_history.get(node_id) or ())
        offset = len(out_h) - len(grad_h)
        joined = [
            self._joined_stats(
                entry,
                grad_h[i - offset] if 0 <= i - offset < len(grad_h) else None,
            )
            for i, entry in enumerate(out_h)
        ]
        keys = sorted(joined[-1])
        return keys, np.asarray(
            [[row.get(k, 0.0) for k in keys] for row in joined],
            dtype=np.float64,
        )

    def latest_features(self, node_id: int) -> Optional[Dict[str, float]]:
        """The newest joined feature row — what detect_with_ml_models should
        score at epoch cadence."""
        out_h = self.output_history.get(node_id)
        if not out_h:
            return None
        grad_h = self.gradient_history.get(node_id)
        return self._joined_stats(out_h[-1], grad_h[-1] if grad_h else None)

    def update_detection_models(self, fit_clustering: bool = False) -> None:
        """Refit the per-node unsupervised detectors at epoch cadence; a
        no-op on nodes without enough history or when sklearn is absent.

        ``fit_clustering`` also refits the per-node DBSCAN models.  Off by
        default as a deliberate deviation: the reference fits DBSCAN on
        every update but no code path (theirs or ours) ever queries it
        (attack_detector.py:395-397 — the 'defined but never called'
        disease, SURVEY §7.5), and the O(t²) fit over 1000x17 histories is
        the dominant cost of the ML tier."""
        try:
            from sklearn.cluster import DBSCAN
            from sklearn.ensemble import IsolationForest
        except ImportError:
            logger.debug("detect: no sklearn in env, ML tier stays off")
            return
        fitted = 0
        for node_id in list(self.output_history):
            features = self._node_feature_matrix(node_id)
            if features is None:
                continue
            keys, matrix = features
            self._model_keys[node_id] = keys
            self.anomaly_detectors[node_id] = IsolationForest(
                **self.ML_ISOFOREST_KW
            ).fit(matrix)
            if fit_clustering:
                self.clustering_models[node_id] = DBSCAN(
                    **self.ML_DBSCAN_KW
                ).fit(matrix)
            fitted += 1
        if fitted:
            logger.info("detect: refit ML detectors for %d node(s)", fitted)

    def detect_with_ml_models(self, stats: Dict[str, float], node_id: int) -> bool:
        """Score one stat vector against the node's fitted IsolationForest;
        False when no model exists yet (warm-up / sklearn-less env)."""
        model = self.anomaly_detectors.get(node_id)
        if model is None:
            return False
        if stats and not any(":" in k for k in stats):
            # Raw (un-namespaced) battery dict from the standalone host
            # path: it is an output battery by contract.
            stats = {f"out:{k}": v for k, v in stats.items()}
        keys = self._model_keys.get(node_id) or sorted(stats)
        vec = np.asarray(
            [stats.get(k, 0.0) for k in keys], dtype=np.float64
        )[None, :]
        verdict = bool(model.predict(vec)[0] == -1)
        if verdict:
            logger.debug(
                "detect: ML verdict anomalous for node %d (score=%.4f)",
                node_id,
                float(model.decision_function(vec)[0]),
            )
        return verdict

    # -- statistics / maintenance (attack_detector.py:427-487) -----------

    def get_detection_statistics(self) -> Dict:
        total = self.detection_stats["total_detections"]
        return {
            "total_detections": total,
            "false_positive_rate": self.detection_stats["false_positives"]
            / max(1, total),
            "true_positive_rate": self.detection_stats["true_positives"]
            / max(1, total),
            "attack_type_distribution": dict(self.detection_stats["attack_types"]),
            "nodes_monitored": len(self.output_history),
            "average_history_length": float(
                np.mean([len(h) for h in self.output_history.values()])
            )
            if self.output_history
            else 0,
        }

    def set_detection_threshold(self, threshold: float) -> None:
        self.detection_threshold = float(np.clip(threshold, 0.0, 1.0))
        logger.info("Detection threshold updated to %s", self.detection_threshold)

    def reset_node_history(self, node_id: int) -> None:
        if node_id in self.output_history:
            self.output_history[node_id].clear()
        if node_id in self.gradient_history:
            self.gradient_history[node_id].clear()
        self.output_baselines.pop(node_id, None)
        self.gradient_baselines.pop(node_id, None)
        logger.info("Detection history reset for node %d", node_id)

    def export_detection_data(self, filepath: str) -> None:
        export_data = {
            "detection_stats": {
                **{k: v for k, v in self.detection_stats.items() if k != "attack_types"},
                "attack_types": dict(self.detection_stats["attack_types"]),
            },
            "baselines": {
                "output": {str(k): v for k, v in self.output_baselines.items()},
                "gradient": {str(k): v for k, v in self.gradient_baselines.items()},
            },
            "history_lengths": {
                str(node_id): len(history)
                for node_id, history in self.output_history.items()
            },
        }
        atomic_write_json(filepath, export_data)
        logger.info("Detection data exported to %s", filepath)

    def cleanup(self) -> None:
        self.output_history.clear()
        self.gradient_history.clear()
        self.loss_history.clear()
        self.anomaly_detectors.clear()
        self.clustering_models.clear()
        logger.info("AttackDetector cleanup completed")

"""Data loading — the implied ``utils.data_loader.get_dataloader``
(imported at experiment_runner.py:24; call shape at :100-110 and
distributed_trainer.py:395-398: iterables of ``{'input','target'}`` dict
batches).

This environment is zero-egress, so each dataset has two tiers:

* real data if present under ``$TDDL_DATA_DIR`` —
  ``openwebtext.bin`` (a flat uint16/uint32 token memmap, nanoGPT layout) or
  ``cifar10/`` (numpy ``.npz`` with x_train/y_train/x_test/y_test);
* otherwise a deterministic *learnable* synthetic source — an affine
  next-token process for LM data, class-conditional Gaussian images for
  CIFAR — so integration tests can assert that loss actually decreases
  (replacing the reference's fabricated loss curves,
  experiment_runner.py:201-216).
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Any, Dict, Iterator, Optional

import numpy as np

from trustworthy_dl_tpu import native


class ArrayDataLoader:
    """Deterministic batched iterator over {'input','target'} arrays.

    Epoch shuffles and per-batch row gathers run on the native C++ tier
    (trustworthy_dl_tpu/native) when the library is available, with bit-exact
    Python fallbacks — batch contents are identical either way."""

    def __init__(self, inputs: np.ndarray, targets: np.ndarray,
                 batch_size: int, shuffle: bool = True, seed: int = 0,
                 drop_last: bool = True):
        assert len(inputs) == len(targets)
        self.inputs = np.ascontiguousarray(inputs)
        self.targets = np.ascontiguousarray(targets)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self._epoch = 0

    def __len__(self) -> int:
        n = len(self.inputs) // self.batch_size
        if not self.drop_last and len(self.inputs) % self.batch_size:
            n += 1
        return n

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        if self.shuffle:
            idx = native.permutation(self.seed + self._epoch, len(self.inputs))
        else:
            idx = np.arange(len(self.inputs), dtype=np.int64)
        self._epoch += 1
        # ``batch_size`` is re-read every batch so a live re-size (elastic
        # topology change mid-epoch, trainer._resize_loader) takes effect
        # on the next batch, not the next epoch.
        start = 0
        while start < len(idx):
            bs = self.batch_size
            sel = idx[start:start + bs]
            start += bs
            if len(sel) == 0 or (self.drop_last and len(sel) < bs):
                break
            yield {
                "input": native.gather_rows(self.inputs, sel),
                "target": native.gather_rows(self.targets, sel),
            }


class TokenStreamLoader:
    """Random-window batches over a contiguous token stream — the
    nanoGPT-style LM sampler: every batch draws ``batch_size`` windows of
    ``seq_len + 1`` tokens at fresh splitmix-derived offsets (native
    multi-threaded gather, bit-exact fallback), so an "epoch" is a step
    budget rather than a fixed partition of the stream.

    Deterministic: batch k of epoch e depends only on (seed, e, k).
    ``freeze_epoch=True`` pins every iteration to epoch 0 — a validation
    loader must yield the SAME windows on every call, otherwise val loss is
    computed on a fresh sample each epoch and any abandoned ``iter()``
    silently shifts subsequent data."""

    def __init__(self, stream: np.ndarray, batch_size: int, seq_len: int,
                 steps_per_epoch: int, seed: int = 0,
                 freeze_epoch: bool = False):
        self.stream = np.ascontiguousarray(stream, np.int32)
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.steps_per_epoch = steps_per_epoch
        self.seed = seed
        self.freeze_epoch = freeze_epoch
        self._epoch = 0

    def __len__(self) -> int:
        return self.steps_per_epoch

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        epoch = 0 if self.freeze_epoch else self._epoch
        if not self.freeze_epoch:
            self._epoch += 1
        mask = (1 << 64) - 1
        # Two splitmix rounds fold (seed, epoch, step) into the batch seed:
        # a linear small-prime mix would collide across (epoch, step)
        # pairs (e.g. epoch e step P == epoch e+1 step 0) and silently
        # repeat batches on long epochs.
        k_epoch = int(native.splitmix_fill(
            ((self.seed & ((1 << 32) - 1)) << 32 | (epoch & ((1 << 32) - 1))),
            1,
        )[0])
        for step in range(self.steps_per_epoch):
            seed = int(native.splitmix_fill((k_epoch + step) & mask, 1)[0])
            inputs, targets = native.window_gather(
                self.stream, self.seq_len, self.batch_size, seed
            )
            yield {"input": inputs, "target": targets}


class PrefetchLoader:
    """Background-thread prefetch over any batch iterable: batch k+1
    assembles on the host (native gathers) while batch k trains on device —
    double buffering for the input pipeline (depth configurable)."""

    def __init__(self, loader: Any, depth: int = 2):
        self.loader = loader
        self.depth = max(1, depth)

    def __len__(self) -> int:
        return len(self.loader)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        sentinel = object()
        errbox: list = []

        def produce() -> None:
            try:
                for batch in self.loader:
                    # Bounded put that notices consumer cancellation — a
                    # plain q.put would block forever if the consumer
                    # abandoned iteration with the queue full.
                    while not stop.is_set():
                        try:
                            q.put(batch, timeout=0.05)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as exc:  # surface in the consumer
                errbox.append(exc)
            finally:
                # The sentinel needs the same cancellation-aware bounded put
                # as batches: with the queue still holding undelivered
                # batches a put_nowait would drop the sentinel and leave a
                # live consumer blocked on q.get() forever.
                while not stop.is_set():
                    try:
                        q.put(sentinel, timeout=0.05)
                        break
                    except queue.Full:
                        continue

        worker = threading.Thread(target=produce, daemon=True)
        worker.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                yield item
        finally:
            # Runs on normal exhaustion AND on early exit (break / GC of the
            # generator): release the producer and reap the thread.
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            worker.join(timeout=5)
        if errbox:
            raise errbox[0]


# ---------------------------------------------------------------------------
# Synthetic sources (deterministic, learnable)
# ---------------------------------------------------------------------------


def _synthetic_tokens(num_tokens: int, vocab_size: int, seed: int) -> np.ndarray:
    """Affine next-token process with 10% uniform noise: t_{i+1} =
    (a*t_i + b) mod V usually — low-entropy enough that a model visibly
    learns, noisy enough that loss stays finite and non-zero.  Generated by
    the native tier (C++ when available, bit-exact numpy otherwise)."""
    return native.synthetic_tokens(num_tokens, vocab_size, seed)


def _synthetic_images(num: int, num_classes: int, shape, seed: int):
    """Class-conditional Gaussian images: per-class fixed mean pattern +
    noise.  Linearly separable → any conv net's loss drops fast."""
    rng = np.random.default_rng(seed)
    h, w, c = shape
    prototypes = rng.normal(0, 1, size=(num_classes, h, w, c)).astype(np.float32)
    labels = rng.integers(0, num_classes, num).astype(np.int32)
    images = prototypes[labels] + rng.normal(0, 0.7, size=(num, h, w, c)).astype(
        np.float32
    )
    return images, labels


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def get_dataloader(
    dataset_name: str,
    split: str = "train",
    batch_size: int = 32,
    seq_len: int = 128,
    vocab_size: int = 50257,
    num_examples: Optional[int] = None,
    seed: int = 0,
    data_dir: Optional[str] = None,
    sampling: str = "epoch",
    image_size: Optional[int] = None,
) -> Any:
    """Reference signature (experiment_runner.py:100-110) with TPU-side
    extensions (seq_len/vocab_size for LM synthesis; ``sampling``:
    "epoch" partitions the stream into fixed shuffled windows,
    "windows" draws fresh random windows every batch — the nanoGPT-style
    sampler via the native gather, better coverage on real corpora;
    ``image_size``: side length for the SYNTHETIC vision tier — conv
    models pool globally, so scenario tests can run on smaller frames
    at a fraction of the compute; ignored for real .npz data)."""
    name = dataset_name.lower()
    if sampling not in ("epoch", "windows"):
        raise ValueError(
            f"sampling must be 'epoch' or 'windows', got {sampling!r}"
        )
    data_dir = data_dir or os.environ.get("TDDL_DATA_DIR", "")
    split_seed = seed + (0 if split == "train" else 10_000)

    if name in ("openwebtext", "wikitext", "lm", "synthetic_lm"):
        n = num_examples or (2048 if split == "train" else 256)
        bin_path = os.path.join(data_dir, f"{name}.bin") if data_dir else ""
        txt_path = os.path.join(data_dir, f"{name}.txt") if data_dir else ""
        if bin_path and os.path.exists(bin_path):
            tokens = np.memmap(bin_path, dtype=np.uint16, mode="r")
            # Hold out the final 5% for validation.
            cut = int(len(tokens) * 0.95)
            tokens = tokens[:cut] if split == "train" else tokens[cut:]
            tokens = np.asarray(tokens, np.int32)
        elif txt_path and os.path.exists(txt_path):
            # Byte-level tier: any plain-text corpus trains without a
            # tokenizer — ids are raw UTF-8 bytes (256 ≤ every GPT vocab).
            if vocab_size < 256:
                raise ValueError(
                    f"byte-level corpus {txt_path} needs vocab_size >= 256 "
                    f"(got {vocab_size}): byte ids would exceed the "
                    "embedding table"
                )
            raw = np.memmap(txt_path, dtype=np.uint8, mode="r")
            cut = int(len(raw) * 0.95)
            tokens = np.asarray(raw[:cut] if split == "train" else raw[cut:],
                                np.int32)
        else:
            tokens = _synthetic_tokens(n * (seq_len + 1) + 1,
                                       min(vocab_size, 512), split_seed)
        if sampling == "windows":
            steps = max(n // max(batch_size, 1), 1)
            return TokenStreamLoader(tokens, batch_size, seq_len,
                                     steps_per_epoch=steps, seed=split_seed,
                                     freeze_epoch=(split != "train"))
        usable = (len(tokens) - 1) // seq_len
        usable = min(usable, n)
        window = tokens[: usable * seq_len + 1]
        inputs = np.stack([window[i * seq_len:(i + 1) * seq_len]
                           for i in range(usable)])
        targets = np.stack([window[i * seq_len + 1:(i + 1) * seq_len + 1]
                            for i in range(usable)])
        return ArrayDataLoader(inputs, targets, batch_size, shuffle=True,
                               seed=split_seed)

    if name in ("cifar10", "cifar-10", "cifar100", "imagenet", "synthetic_vision"):
        if sampling == "windows":
            raise ValueError(
                "sampling='windows' is a token-stream sampler; vision "
                "datasets use epoch sampling"
            )
        num_classes = 100 if "100" in name else (1000 if "imagenet" in name else 10)
        side = image_size or (224 if "imagenet" in name else 32)
        shape = (side, side, 3)
        n = num_examples or (2048 if split == "train" else 512)
        npz_path = os.path.join(data_dir, "cifar10", "cifar10.npz") if data_dir else ""
        if name.startswith("cifar10") and npz_path and os.path.exists(npz_path):
            blob = np.load(npz_path)
            if split == "train":
                images, labels = blob["x_train"], blob["y_train"]
            else:
                images, labels = blob["x_test"], blob["y_test"]
            images = (images.astype(np.float32) / 127.5) - 1.0
            labels = labels.reshape(-1).astype(np.int32)
        else:
            images, labels = _synthetic_images(n, num_classes, shape, split_seed)
        return ArrayDataLoader(images, labels, batch_size, shuffle=True,
                               seed=split_seed)

    raise ValueError(f"unknown dataset {dataset_name!r}")

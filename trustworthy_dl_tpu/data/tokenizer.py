"""Byte-level BPE tokenizer — the raw-text ingestion tier the reference
implies but never ships (``get_dataloader('openwebtext', ...)`` at
experiment_runner.py:100-110 presumes tokenized data; README.md:80 tells the
user to "prepare" it elsewhere).

GPT-2-style byte-level BPE, self-contained and offline:

* the byte→unicode table and merge algorithm follow the GPT-2 scheme, and
  the on-disk format is GPT-2's exact ``vocab.json`` + ``merges.txt`` — so
  a user who HAS OpenAI's files drops them in and gets the canonical
  50257-token vocabulary;
* this zero-egress build cannot vendor those files, so ``train_bpe`` learns
  a merge table from the corpus itself (the standard BPE trainer:
  iteratively merge the most frequent adjacent pair).  A corpus-fit vocab
  is what nanoGPT-class training wants anyway;
* ``prepare_data`` is the .txt → .bin pipeline: learn/load a tokenizer,
  encode, write a uint16 token memmap in the loader's nanoGPT layout
  (data/loader.py), plus the tokenizer files next to it.

Console entry: ``trustworthy-dl-prepare-data`` (cli shim in
trustworthy_dl_tpu/cli.py).
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

try:  # GPT-2's exact pre-tokenizer needs \p classes (regex module).
    import regex as _re

    _PAT = _re.compile(
        r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+|"""
        r""" ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"""
    )
except ImportError:  # std-re fallback: same shape with unicode classes
    import re as _re

    _PAT = _re.compile(
        r"""'s|'t|'re|'ve|'m|'ll|'d| ?[^\W\d_]+| ?\d+|"""
        r""" ?[^\s\w]+|\s+(?!\S)|\s+""",
        _re.UNICODE,
    )


def bytes_to_unicode() -> Dict[int, str]:
    """GPT-2's reversible byte→printable-unicode table: the 188 printable
    latin-1 bytes map to themselves, the rest shift into U+0100+ so every
    byte sequence round-trips through a unicode string."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("\xa1"), ord("\xac") + 1))
          + list(range(ord("\xae"), ord("\xff") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


_BYTE_ENCODER = bytes_to_unicode()
_BYTE_DECODER = {v: k for k, v in _BYTE_ENCODER.items()}

# Which tokenizer's merge table is installed in the native encoder
# (generation number; None = fallback / not installed).
_NATIVE_TABLE_OWNER: Optional[int] = None
_TABLE_GEN = iter(range(1, 1 << 62))
# Word-cache bound per tokenizer (entries, str -> ids).
_CACHE_CAP = 262_144


def _word_to_units(word: str) -> Tuple[str, ...]:
    """Pre-token → tuple of byte-units in the unicode alphabet."""
    return tuple(_BYTE_ENCODER[b] for b in word.encode("utf-8"))


def train_bpe(
    text: str,
    vocab_size: int = 8192,
    min_pair_count: int = 2,
) -> Tuple[Dict[str, int], List[Tuple[str, str]]]:
    """Learn a byte-level BPE vocabulary from ``text``.

    Standard BPE trainer over pre-tokenized words: start from the 256 byte
    units, repeatedly merge the most frequent adjacent pair until
    ``vocab_size`` entries (or no pair occurs ``min_pair_count`` times).
    Returns (vocab: token→id, merges: ordered pair list) in GPT-2's
    conventions (ids dense from 0, merge rank = list order)."""
    units = sorted(set(_BYTE_ENCODER.values()))
    if vocab_size < len(units):
        raise ValueError(
            f"vocab_size {vocab_size} < byte alphabet {len(units)}"
        )
    # Word histogram (BPE trains on word types, weighted by count).
    word_counts = Counter()
    for m in _PAT.findall(text):
        word_counts[_word_to_units(m)] += 1
    # Incremental trainer state: the global pair histogram plus an
    # inverted index pair -> words containing it.  Each merge touches only
    # the words that actually contain the merged pair, keeping training
    # near-linear instead of O(vocab_size × word_types) full rescans.
    words: Dict[Tuple[str, ...], int] = dict(word_counts)
    pair_counts: Counter = Counter()
    pair_words: Dict[Tuple[str, str], set] = {}
    for word, cnt in words.items():
        for pair in zip(word, word[1:]):
            pair_counts[pair] += cnt
            pair_words.setdefault(pair, set()).add(word)

    # Lazy max-heap over pair counts: entries go stale when a count
    # changes; pops validate against pair_counts and re-push the current
    # value.  Keeps best-pair selection O(log P) per merge instead of a
    # full histogram scan.
    import heapq

    heap: List[Tuple[int, Tuple[str, str]]] = [
        (-c, p) for p, c in pair_counts.items()
    ]
    heapq.heapify(heap)

    def _bump(pair: Tuple[str, str]) -> None:
        c = pair_counts.get(pair)
        if c:
            heapq.heappush(heap, (-c, pair))

    def _remove_word(word: Tuple[str, ...], cnt: int) -> None:
        for pair in zip(word, word[1:]):
            pair_counts[pair] -= cnt
            if pair_counts[pair] <= 0:
                del pair_counts[pair]
            ws = pair_words.get(pair)
            if ws is not None:
                ws.discard(word)
                if not ws:
                    del pair_words[pair]

    def _add_word(word: Tuple[str, ...], cnt: int) -> None:
        for pair in zip(word, word[1:]):
            pair_counts[pair] += cnt
            pair_words.setdefault(pair, set()).add(word)
            _bump(pair)

    merges: List[Tuple[str, str]] = []
    vocab: Dict[str, int] = {u: i for i, u in enumerate(units)}

    while len(vocab) < vocab_size and heap:
        neg, (a, b) = heapq.heappop(heap)
        cnt = pair_counts.get((a, b), 0)
        if -neg != cnt:  # stale entry: re-queue at the live count
            _bump((a, b))
            continue
        if cnt < min_pair_count:
            break
        merged = a + b
        merges.append((a, b))
        vocab[merged] = len(vocab)
        affected = list(pair_words.get((a, b), ()))
        for word in affected:
            c = words.pop(word)
            _remove_word(word, c)
            out = []
            i = 0
            while i < len(word):
                if i < len(word) - 1 and word[i] == a and word[i + 1] == b:
                    out.append(merged)
                    i += 2
                else:
                    out.append(word[i])
                    i += 1
            t = tuple(out)
            words[t] = words.get(t, 0) + c
            _add_word(t, c)
    return vocab, merges


class BPETokenizer:
    """GPT-2-style byte-level BPE encoder/decoder.

    ``vocab`` maps token strings (in the byte-unicode alphabet) to ids;
    ``merges`` is the ordered merge list.  File format matches GPT-2's
    ``vocab.json`` / ``merges.txt``, so OpenAI's published files load
    directly for exact-vocabulary parity."""

    def __init__(self, vocab: Dict[str, int],
                 merges: Sequence[Tuple[str, str]]):
        self.vocab = dict(vocab)
        self.decoder = {i: t for t, i in self.vocab.items()}
        self.ranks = {tuple(m): r for r, m in enumerate(merges)}
        # Id-space merge table: encoding runs over token ids, not strings
        # (enables the native C++ batch encoder; the Python loop uses the
        # same table so both tiers are bit-exact).  Merges whose product
        # is absent from the vocabulary are excluded — they could never
        # produce an emittable token.
        self._id_ranks: Dict[Tuple[int, int], Tuple[int, int]] = {}
        lefts, rights, prods = [], [], []
        for (a, b), rank in sorted(self.ranks.items(), key=lambda kv: kv[1]):
            ia, ib, ip = (self.vocab.get(a), self.vocab.get(b),
                          self.vocab.get(a + b))
            if ia is None or ib is None or ip is None:
                continue
            if (ia, ib) not in self._id_ranks:
                self._id_ranks[(ia, ib)] = (rank, ip)
            lefts.append(ia)
            rights.append(ib)
            prods.append(ip)
        self._merge_arrays = (np.asarray(lefts, np.int32),
                              np.asarray(rights, np.int32),
                              np.asarray(prods, np.int32))
        self._table_gen = next(_TABLE_GEN)
        self._cache: Dict[str, List[int]] = {}  # matched word -> ids

    # -- core BPE (id space) -------------------------------------------

    def _bpe_ids(self, word: Tuple[int, ...]) -> List[int]:
        """Python merge loop — bit-exact mirror of the native encoder."""
        parts = list(word)
        while len(parts) > 1:
            best, best_i = None, None
            for i, pair in enumerate(zip(parts, parts[1:])):
                hit = self._id_ranks.get(pair)
                if hit is not None and (best is None or hit[0] < best[0]):
                    best, best_i = hit, i
            if best_i is None:
                break
            parts[best_i:best_i + 2] = [best[1]]
        return parts

    def _encode_words(self, words: List[Tuple[int, ...]]
                      ) -> List[List[int]]:
        """Batch-encode unit-id words: one native call for the whole batch
        (the per-word merge loop dominates corpus tokenization in Python),
        falling back to the Python loop when the native tier is absent."""
        from trustworthy_dl_tpu import native

        # The native encoder holds ONE merge table; re-install when a
        # different tokenizer instance was active (cheap: one pass over
        # the merge list).
        global _NATIVE_TABLE_OWNER
        if _NATIVE_TABLE_OWNER != self._table_gen:
            _NATIVE_TABLE_OWNER = (
                self._table_gen if native.bpe_load(*self._merge_arrays)
                else None
            )
        if _NATIVE_TABLE_OWNER != self._table_gen:
            return [self._bpe_ids(w) for w in words]
        offsets = np.zeros(len(words) + 1, np.int64)
        for i, w in enumerate(words):
            offsets[i + 1] = offsets[i] + len(w)
        flat = np.fromiter(
            (u for w in words for u in w), np.int32, count=int(offsets[-1])
        )
        out, out_offsets = native.bpe_encode(flat, offsets)
        return [out[out_offsets[i]:out_offsets[i + 1]].tolist()
                for i in range(len(words))]

    # -- public API ----------------------------------------------------

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def encode(self, text: str) -> List[int]:
        words = _PAT.findall(text)
        cache = self._cache
        # Misses keyed on the MATCHED STRING (dict preserves first-seen
        # order): repeated words skip unit mapping entirely — on natural
        # text (Zipfian) that is nearly all of them.
        fresh = [m for m in dict.fromkeys(words) if m not in cache]
        local: Dict[str, List[int]] = {}
        if fresh:
            unit_words = [
                tuple(self.vocab[u] for u in _word_to_units(m))
                for m in fresh
            ]
            local = dict(zip(fresh, self._encode_words(unit_words)))
            # Bounded cache: stop inserting at the cap (never evict —
            # entries resolved earlier in THIS call must stay reachable);
            # the per-call overlay below serves the overflow.
            budget = _CACHE_CAP - len(cache)
            if budget > 0:
                for m in fresh[:budget]:
                    cache[m] = local[m]
        out: List[int] = []
        for m in words:
            ids = cache.get(m)
            out.extend(local[m] if ids is None else ids)
        return out

    def decode(self, ids: Iterable[int]) -> str:
        """Ids -> text.  Ids outside the vocabulary (e.g. sampled from a
        model whose embedding table is larger than this tokenizer) decode
        to U+FFFD instead of raising — decode must never crash on model
        output."""
        data = bytearray()
        for i in ids:
            token = self.decoder.get(int(i))
            if token is None:
                data += b"\xef\xbf\xbd"  # U+FFFD replacement character
            else:
                data += bytes(_BYTE_DECODER[c] for c in token)
        return data.decode("utf-8", errors="replace")

    # -- persistence (GPT-2 file format) -------------------------------

    def save(self, directory: str) -> None:
        os.makedirs(directory, exist_ok=True)
        with open(os.path.join(directory, "vocab.json"), "w",
                  encoding="utf-8") as f:
            json.dump(self.vocab, f, ensure_ascii=False)
        with open(os.path.join(directory, "merges.txt"), "w",
                  encoding="utf-8") as f:
            f.write("#version: 0.2\n")
            for (a, b), _ in sorted(self.ranks.items(),
                                    key=lambda kv: kv[1]):
                f.write(f"{a} {b}\n")

    @classmethod
    def load(cls, directory: str) -> "BPETokenizer":
        with open(os.path.join(directory, "vocab.json"),
                  encoding="utf-8") as f:
            vocab = json.load(f)
        merges: List[Tuple[str, str]] = []
        with open(os.path.join(directory, "merges.txt"),
                  encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line or line.startswith("#"):
                    continue
                a, _, b = line.partition(" ")
                merges.append((a, b))
        return cls(vocab, merges)

    @classmethod
    def train(cls, text: str, vocab_size: int = 8192) -> "BPETokenizer":
        vocab, merges = train_bpe(text, vocab_size)
        return cls(vocab, merges)


def prepare_data(
    txt_path: str,
    out_path: Optional[str] = None,
    vocab_size: int = 8192,
    tokenizer_dir: Optional[str] = None,
    val_fraction: float = 0.0,
) -> Dict[str, object]:
    """.txt corpus → uint16 token memmap (.bin, nanoGPT layout) + tokenizer
    files — the offline ``prepare`` step the reference's README hand-waves.

    If ``tokenizer_dir`` already holds vocab.json/merges.txt (e.g. OpenAI's
    GPT-2 files), they are used as-is; otherwise a BPE vocabulary is
    trained on the corpus and saved there.  ``val_fraction`` > 0
    additionally writes a ``*_val.bin`` split."""
    with open(txt_path, encoding="utf-8", errors="replace") as f:
        text = f.read()
    if out_path is None:
        out_path = os.path.splitext(txt_path)[0] + ".bin"
    if tokenizer_dir is None:
        tokenizer_dir = os.path.join(os.path.dirname(os.path.abspath(
            out_path)), "tokenizer")

    if os.path.exists(os.path.join(tokenizer_dir, "vocab.json")):
        tok = BPETokenizer.load(tokenizer_dir)
    else:
        tok = BPETokenizer.train(text, vocab_size)
        tok.save(tokenizer_dir)

    ids = tok.encode(text)
    if tok.vocab_size > np.iinfo(np.uint16).max + 1:
        raise ValueError(
            f"vocab {tok.vocab_size} exceeds uint16 memmap range"
        )
    arr = np.asarray(ids, np.uint16)
    if val_fraction > 0:
        cut = int(len(arr) * (1.0 - val_fraction))
        train_arr, val_arr = arr[:cut], arr[cut:]
        val_path = os.path.splitext(out_path)[0] + "_val.bin"
        val_arr.tofile(val_path)
    else:
        train_arr, val_path = arr, None
    train_arr.tofile(out_path)
    return {
        "out_path": out_path,
        "val_path": val_path,
        "tokenizer_dir": tokenizer_dir,
        # Tokens actually in out_path (the val split is carved out of it).
        "num_tokens": int(len(train_arr)),
        "val_tokens": int(len(arr) - len(train_arr)),
        "vocab_size": tok.vocab_size,
    }

from trustworthy_dl_tpu.data.loader import (
    ArrayDataLoader,
    PrefetchLoader,
    TokenStreamLoader,
    get_dataloader,
)
from trustworthy_dl_tpu.data.tokenizer import (
    BPETokenizer,
    prepare_data,
    train_bpe,
)

__all__ = ["ArrayDataLoader", "BPETokenizer", "PrefetchLoader",
           "TokenStreamLoader", "get_dataloader", "prepare_data",
           "train_bpe"]

from trustworthy_dl_tpu.data.loader import ArrayDataLoader, get_dataloader

__all__ = ["ArrayDataLoader", "get_dataloader"]

from trustworthy_dl_tpu.data.loader import (
    ArrayDataLoader,
    PrefetchLoader,
    TokenStreamLoader,
    get_dataloader,
)

__all__ = ["ArrayDataLoader", "PrefetchLoader", "TokenStreamLoader",
           "get_dataloader"]

from trustworthy_dl_tpu.data.loader import (
    ArrayDataLoader,
    PrefetchLoader,
    get_dataloader,
)

__all__ = ["ArrayDataLoader", "PrefetchLoader", "get_dataloader"]

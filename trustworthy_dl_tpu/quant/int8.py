"""Symmetric int8 quantization primitives for the serving engine.

Two quantization tiers, both weight-of-evidence standards from the
serving literature, both shaped for XLA's static-shape world:

* **KV-cache int8** (KVQuant / vLLM ``kv_cache_dtype="int8"`` practice):
  K/V rows store int8 with a per-(head, position) f32 scale — the scale
  of a cached key factors OUT of the attention dot product (it is
  constant along the contracted Dh axis), so dequantisation never
  materialises an f32 copy of the cache: scores are computed against the
  int8 values and multiplied by the scale vector afterwards.  HBM per
  slot roughly halves (Dh bytes + 4 scale bytes vs 2·Dh bf16 bytes per
  cached position), which at fixed HBM doubles MAX_SLOTS — continuous-
  batching throughput is slot-bound under load.

* **Weight-only int8** for the decode matmuls (LLM.int8 / AWQ-style W8
  without the activation half): per-OUTPUT-channel symmetric scales, so
  the scale also factors out of the contraction and the matmul runs
  ``x @ w_int8`` with one f32 multiply per output column at the end.
  b=1..MAX_SLOTS decode is weight-bandwidth-bound; int8 weights halve
  the bytes streamed per token vs bf16.  Embedding table and lm head
  stay high precision (their numerics dominate token choice).

Everything here is pure jnp and runs on the CPU test backend; the
optional Pallas fused dequant-matmul tile lives in
``ops/fused_dequant_matmul.py`` behind the same ``pallas_enabled()``
gate as ``fused_stats``.

Error contract: symmetric round-to-nearest over a [-amax, amax] range
gives per-element error <= amax/254 (half an int8 step of amax/127).
All-zero channels store scale 0 and reproduce exact zeros.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from trustworthy_dl_tpu.models import gpt2
from trustworthy_dl_tpu.models import layers as L

logger = logging.getLogger(__name__)

Params = Dict[str, Any]

#: Accepted ServeConfig / engine dtype knob values.  "model" follows the
#: model's compute dtype (the pre-quantization behaviour).
KV_DTYPES = ("model", "bfloat16", "float32", "int8")
WEIGHT_DTYPES = ("model", "int8")

#: Largest int8 magnitude used by the symmetric scheme (clip range
#: [-127, 127]; -128 is never emitted so the range stays symmetric).
QMAX = 127.0

#: Mosaic int8 sublane width: a compiled int8 VMEM tile's second-to-minor
#: dim must be a multiple of 32 (= 32/itemsize; f32 needs 8, bf16 16 —
#: ``ops.paged_attention.kv_sublane`` is the per-dtype rule).  The
#: paged-attention eligibility gate reads that rule — an int8 KV pool
#: streams its [BLOCK, Dh] tiles through the kernel only when
#: ``block_size`` tiles, otherwise serving falls back (loudly) to the
#: jnp gather path.
INT8_SUBLANE = 32


def validate_dtypes(kv_dtype: str, weight_dtype: str) -> None:
    """Loud construction-time validation — an unknown dtype string must
    fail where the operator typed it, not at trace time inside a jitted
    serving program."""
    if kv_dtype not in KV_DTYPES:
        raise ValueError(
            f"kv_dtype must be one of {KV_DTYPES}, got {kv_dtype!r}"
        )
    if weight_dtype not in WEIGHT_DTYPES:
        raise ValueError(
            f"weight_dtype must be one of {WEIGHT_DTYPES}, got "
            f"{weight_dtype!r}"
        )


def resolve_kv_dtype(kv_dtype: str, cfg: gpt2.GPT2Config) -> Any:
    """Map a ServeConfig kv_dtype string to the array dtype the slot
    pool stores (``jnp.int8`` selects the quantized variant)."""
    if kv_dtype not in KV_DTYPES:
        raise ValueError(
            f"kv_dtype must be one of {KV_DTYPES}, got {kv_dtype!r}"
        )
    return {
        "model": cfg.dtype,
        "bfloat16": jnp.bfloat16,
        "float32": jnp.float32,
        "int8": jnp.int8,
    }[kv_dtype]


# ---------------------------------------------------------------------------
# Core primitives: symmetric per-channel quantize / dequantize
# ---------------------------------------------------------------------------


def quantize_int8(x: jax.Array, axis: int = -1
                  ) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-channel int8: reduce |max| over ``axis``.

    Returns ``(q int8, scale f32)`` with ``scale = amax / 127`` shaped
    like ``x`` minus ``axis``.  All-zero channels keep scale 0 (their
    dequantisation is exactly zero); rounding is round-half-to-even
    (jnp.rint), clipped to [-127, 127]."""
    x = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=axis)
    scale = amax / QMAX
    safe = jnp.where(scale > 0.0, scale, 1.0)
    q = jnp.clip(jnp.rint(x / jnp.expand_dims(safe, axis)), -QMAX, QMAX)
    return q.astype(jnp.int8), scale


def dequantize_int8(q: jax.Array, scale: jax.Array, axis: int = -1,
                    dtype: Any = jnp.float32) -> jax.Array:
    """Inverse of :func:`quantize_int8` (up to the rounding error)."""
    return (q.astype(jnp.float32) * jnp.expand_dims(scale, axis)
            ).astype(dtype)


def quantize_kv(kv: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Quantize K or V rows ``[..., Dh]`` per cached position (scale over
    the head dim) — the serving cache's per-(head, position) scheme."""
    return quantize_int8(kv, axis=-1)


# ---------------------------------------------------------------------------
# Weight-only int8 decode view
# ---------------------------------------------------------------------------


def quantize_dense(d: Params) -> Params:
    """``{"w": [..., in, out], "b": [..., out]}`` -> ``{"w_q": int8,
    "scale": f32 [..., out], "b"}`` — per-output-channel symmetric
    (reduced over the ``in`` axis), so the scale factors out of the
    ``x @ w`` contraction exactly.  Leading axes (the model's stacked
    [L, ...] block layout) pass through untouched."""
    q, scale = quantize_int8(d["w"].astype(jnp.float32), axis=-2)
    return {"w_q": q, "scale": scale, "b": d["b"]}


def is_quantized_dense(d: Params) -> bool:
    return isinstance(d, dict) and "w_q" in d


def qdense(d: Params, x: jax.Array, dtype: Any = jnp.float32) -> jax.Array:
    """Dense dispatcher for the decode path: plain ``{"w","b"}`` params
    go through ``layers.dense`` unchanged; weight-only-int8 params
    (``{"w_q","scale","b"}``) run the dequant-matmul — via the Pallas
    fused tile on TPU when shapes tile (``ops.fused_dequant_matmul``),
    else the jnp contraction with f32 accumulation.  The branch is on
    pytree *structure*, resolved at trace time — a quantized and an
    unquantized engine each still compile exactly one decode program."""
    if not is_quantized_dense(d):
        return L.dense(d, x, dtype)
    from trustworthy_dl_tpu.ops.fused_dequant_matmul import (
        dequant_matmul,
    )

    lead = x.shape[:-1]
    k = x.shape[-1]
    y = dequant_matmul(x.reshape(-1, k), d["w_q"], d["scale"])
    y = y.reshape(*lead, -1).astype(dtype) + d["b"].astype(dtype)
    return y


def quantize_decode_view(params: Params, cfg: gpt2.GPT2Config,
                         view: Optional[Params] = None) -> Params:
    """Weight-only int8 decode view: the attention projections and MLP
    matmuls carry int8 weights + per-output-channel f32 scales; the
    embedding table, position table, layernorms and (tied) lm head keep
    the precision ``models/generate._decode_view`` gives them — token
    choice is dominated by the final projection's numerics, and the
    embedding gather streams one row per token, not the whole table.

    Conversion happens ONCE here (engine construction); the decode
    programs then stream int8 weight bytes every token.  Pass ``view``
    when a dense decode view is already built (the engine also feeds it
    to the parity probe / error histogram) to skip rebuilding it."""
    from trustworthy_dl_tpu.models import generate as gen

    if view is None:
        view = gen._decode_view(params, cfg)
    blocks = view["blocks"]
    out = dict(view)
    out["blocks"] = {
        "ln_1": blocks["ln_1"],
        "ln_2": blocks["ln_2"],
        "attn": {"qkv": quantize_dense(blocks["attn"]["qkv"]),
                 "proj": quantize_dense(blocks["attn"]["proj"])},
        "mlp": {"fc": quantize_dense(blocks["mlp"]["fc"]),
                "proj": quantize_dense(blocks["mlp"]["proj"])},
    }
    return out


def draft_decode_view(params: Params, cfg: gpt2.GPT2Config,
                      dense_view: Optional[Params] = None,
                      qview: Optional[Params] = None) -> Params:
    """The int8 self-draft weight view for speculative decoding
    (serve/scheduler's draft program): the SAME weights the engine
    serves, quantized to the weight-only int8 tier — a draft model that
    costs nothing to train, nothing extra to store beyond the int8
    copy, and half the decode weight bandwidth per drafted token.

    Reuse contract (no second weight walk): pass ``qview`` when the
    engine already built its weight-only int8 view (``weight_dtype=
    "int8"`` — it IS the draft, returned as-is), else pass
    ``dense_view`` (the engine's already-pre-cast dense decode view) so
    quantization reuses it instead of re-walking the master weights."""
    if qview is not None:
        return qview
    return quantize_decode_view(params, cfg, view=dense_view)


def weight_roundtrip_errors(params: Params, cfg: gpt2.GPT2Config,
                            qview: Optional[Params] = None) -> List[float]:
    """Max relative quantization error per decode-path weight matrix
    (‖w − deq(q(w))‖_inf / ‖w‖_inf) — the numbers the engine feeds its
    quantization-error histogram, and the per-matrix safety gate for the
    weight-only tier.  Pass ``qview`` (a :func:`quantize_decode_view`
    result over the same weights) to reuse its w_q/scale instead of
    re-quantizing — the engine already paid that pass at construction."""
    errs: List[float] = []
    blocks = params["blocks"]
    qblocks = qview["blocks"] if qview is not None else None
    for group, name in (("attn", "qkv"), ("attn", "proj"),
                        ("mlp", "fc"), ("mlp", "proj")):
        w = blocks[group][name]["w"].astype(jnp.float32)
        if qblocks is not None:
            q = qblocks[group][name]["w_q"]
            scale = qblocks[group][name]["scale"]
        else:
            q, scale = quantize_int8(w, axis=-2)
        err = jnp.max(
            jnp.abs(w - q.astype(jnp.float32) * scale[..., None, :])
        )
        denom = jnp.maximum(jnp.max(jnp.abs(w)), 1e-12)
        errs.append(float(err / denom))
    return errs


# ---------------------------------------------------------------------------
# Parity gate — the safety latch in front of the int8 KV swap
# ---------------------------------------------------------------------------

#: A greedy token flip is tolerated only when the reference path's own
#: top-1 margin is below this (a near-tie, where ANY numerics change —
#: flash vs XLA attention included — can flip the argmax).  A decisive
#: flip fails the probe.
PARITY_MARGIN_TOL = 0.05


def kv_parity_probe(view: Params, cfg: gpt2.GPT2Config,
                    prompt_len: int = 8, decode_tokens: int = 4) -> bool:
    """Construction-time greedy parity check: decode a few tokens over a
    deterministic prompt twice — full-precision KV vs int8 KV, SAME
    weight view — and require the greedy argmax to agree at every step
    (flips are tolerated only under a near-tie top-1 margin,
    PARITY_MARGIN_TOL; see tests/test_quant.py for the pinned tiny-GPT2
    fixture).  Runs eagerly on purpose: a jitted probe would add compiled
    programs to the serving process (the decode compile-count pin says
    the engine compiles exactly one decode program).

    The reference token is teacher-forced into both paths each step so
    one tolerated near-tie cannot cascade into stream divergence."""
    from trustworthy_dl_tpu.models import generate as gen

    max_len = prompt_len + decode_tokens
    prompt = (jnp.arange(prompt_len, dtype=jnp.int32)
              % cfg.vocab_size)[None, :]
    ref_cache = gen.init_cache(cfg, 1, max_len)
    q_cache = gen.init_cache(cfg, 1, max_len, kv_dtype=jnp.int8)
    ref_logits, ref_cache = gen._apply_with_cache(view, prompt, ref_cache,
                                                  cfg)
    q_logits, q_cache = gen._apply_with_cache(view, prompt, q_cache, cfg)
    for step in range(decode_tokens):
        ref_top2 = jax.lax.top_k(ref_logits[0], 2)[0]
        ref_tok = int(jnp.argmax(ref_logits[0]))
        q_tok = int(jnp.argmax(q_logits[0]))
        if q_tok != ref_tok:
            margin = float(ref_top2[0] - ref_top2[1])
            if margin >= PARITY_MARGIN_TOL:
                logger.warning(
                    "int8 KV parity probe failed: greedy token %d != %d "
                    "at top-1 margin %.4f (tolerance %.4f)",
                    q_tok, ref_tok, margin, PARITY_MARGIN_TOL,
                )
                return False
        if step == decode_tokens - 1:
            break  # nothing left to compare — skip the dead advance
        tok = jnp.asarray([[ref_tok]], jnp.int32)   # teacher-force
        ref_logits, ref_cache = gen._apply_with_cache(view, tok, ref_cache,
                                                      cfg)
        q_logits, q_cache = gen._apply_with_cache(view, tok, q_cache, cfg)
    return True

"""Quantization tier for the serving engine (beyond-reference).

``int8`` holds the symmetric per-channel primitives plus the two serving
applications: the int8 KV cache (per-(head, position) scales — halves KV
bytes per slot, doubling the continuous-batching slot pool at fixed HBM)
and the weight-only int8 decode view (per-output-channel scales — halves
the weight bytes streamed per decode token).  The Pallas fused
dequant-matmul tile lives in ``ops/fused_dequant_matmul.py``; everything
here is pure jnp and CPU-testable.

Safety: the int8 KV swap is parity-gated (``kv_parity_probe`` — greedy
tokens must match the full-precision path at engine construction, with
automatic fallback to the model-dtype pool on failure), and unknown
dtype knob values fail loudly at config construction
(``validate_dtypes``).
"""

from trustworthy_dl_tpu.quant.int8 import (
    KV_DTYPES,
    PARITY_MARGIN_TOL,
    QMAX,
    WEIGHT_DTYPES,
    dequantize_int8,
    draft_decode_view,
    is_quantized_dense,
    kv_parity_probe,
    qdense,
    quantize_decode_view,
    quantize_dense,
    quantize_int8,
    quantize_kv,
    resolve_kv_dtype,
    validate_dtypes,
    weight_roundtrip_errors,
)

__all__ = [
    "KV_DTYPES",
    "PARITY_MARGIN_TOL",
    "QMAX",
    "WEIGHT_DTYPES",
    "dequantize_int8",
    "draft_decode_view",
    "is_quantized_dense",
    "kv_parity_probe",
    "qdense",
    "quantize_decode_view",
    "quantize_dense",
    "quantize_int8",
    "quantize_kv",
    "resolve_kv_dtype",
    "validate_dtypes",
    "weight_roundtrip_errors",
]

"""Checkpoint save AND restore via Orbax.

The reference only ever saves (torch.save of model/optimizer/trust state,
distributed_trainer.py:448-463) — there is no load path anywhere in the
snapshot, and the checkpoints/ directory is assumed to exist (SURVEY §3.5,
§7.5).  Here both directions exist, the directory is created, and the
payload is the *entire* TrainState pytree — params, optimizer state, trust
world-view, detector baselines, verifier and monitor state, step/rng — so a
resume restores the security posture, not just the weights.

Restore is sharding-aware: pass the live (possibly resharded) state template
and Orbax places leaves onto the template's shardings, which is what lets a
post-reassignment resume come back on a different device set (SURVEY §5.4).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

import jax
import numpy as np

logger = logging.getLogger(__name__)


def _latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.startswith("checkpoint_step_"):
            try:
                steps.append(int(name.rsplit("_", 1)[1]))
            except ValueError:
                continue
    return max(steps) if steps else None


def _merge_into_template(template: Any, raw: Any) -> Any:
    """Graft a restored raw tree (nested dicts/lists of host arrays, as
    Orbax saves them) onto ``template`` by container key: same-named slots
    take the saved value (placed with the template leaf's sharding),
    missing slots keep the template's (freshly-initialised) value, and
    saved keys with no template slot are dropped.  This is the
    forward/backward-compat path for checkpoint structure drift."""
    if raw is None:
        return template
    # Leaf in the template: adopt the saved value (cast/placed like the
    # template leaf); container mismatches fall through to the walk below.
    if hasattr(template, "dtype") and not isinstance(template, (dict,)):
        leaf = raw
        if hasattr(leaf, "dtype"):
            # The fallback exists for STRUCTURE drift only.  A shape
            # mismatch means topology drift (different node count) — keep
            # that loud: silently adopting a [8, ...] row block onto a
            # 4-node template would defer the failure to an opaque XLA
            # error in the first step (use the elastic topology sidecar
            # for cross-topology resume).
            if tuple(np.shape(leaf)) != tuple(np.shape(template)):
                raise ValueError(
                    f"checkpoint leaf shape {np.shape(leaf)} does not "
                    f"match template {np.shape(template)} — topology "
                    "drift, not structure drift; restore via the "
                    "topology sidecar (load_checkpoint handles this)"
                )
            # No host round-trip: an already-sharded jax leaf (the
            # metadata-guided fallback restores straight onto the
            # template's shardings) passes through / re-places on device.
            arr = leaf if leaf.dtype == template.dtype else \
                leaf.astype(template.dtype)
            sharding = getattr(template, "sharding", None)
            if sharding is not None:
                return jax.device_put(arr, sharding)
            return jax.numpy.asarray(arr)
        return template
    if isinstance(template, dict):
        raw_map = raw if isinstance(raw, dict) else {}
        return {
            k: _merge_into_template(v, raw_map.get(k))
            for k, v in template.items()
        }
    if isinstance(template, tuple):
        fields = getattr(template, "_fields", None)
        if fields is not None:  # NamedTuple: saved as a dict of fields
            raw_map = raw if isinstance(raw, dict) else {}
            return type(template)(**{
                f: _merge_into_template(getattr(template, f),
                                        raw_map.get(f))
                for f in fields
            })
        raw_seq = raw if isinstance(raw, (list, tuple, dict)) else []
        if isinstance(raw_seq, dict):  # tuples serialise as {"0": ..}
            raw_seq = [raw_seq.get(str(i)) for i in range(len(template))]
        raw_seq = list(raw_seq) + [None] * (len(template) - len(raw_seq))
        return tuple(
            _merge_into_template(v, r) for v, r in zip(template, raw_seq)
        )
    if isinstance(template, list):
        raw_seq = raw if isinstance(raw, (list, tuple)) else []
        raw_seq = list(raw_seq) + [None] * (len(template) - len(raw_seq))
        return [
            _merge_into_template(v, r) for v, r in zip(template, raw_seq)
        ]
    return template


def _template_paths(node: Any, prefix: tuple = ()) -> set:
    """Key-path set of a live template pytree, normalised to the string
    keys Orbax serialises with (namedtuples as field dicts, sequences as
    stringified indices) so it is directly comparable with
    ``_saved_paths``."""
    if hasattr(node, "dtype") and not isinstance(node, dict):
        return {prefix}
    fields = getattr(node, "_fields", None)
    if fields is not None:
        out = set()
        for f in fields:
            out |= _template_paths(getattr(node, f), prefix + (f,))
        return out
    if isinstance(node, dict):
        out = set()
        for k, v in node.items():
            out |= _template_paths(v, prefix + (str(k),))
        return out
    if isinstance(node, (list, tuple)):
        out = set()
        for i, v in enumerate(node):
            out |= _template_paths(v, prefix + (str(i),))
        return out
    return {prefix}


def _saved_paths(node: Any, prefix: tuple = ()) -> set:
    """Key-path set of a saved checkpoint's structure metadata (nested
    dicts/sequences with ArrayMetadata leaves), normalised like
    ``_template_paths`` (sequence positions as stringified indices)."""
    if isinstance(node, dict):
        out = set()
        for k, v in node.items():
            out |= _saved_paths(v, prefix + (str(k),))
        return out
    if isinstance(node, (list, tuple)):
        out = set()
        for i, v in enumerate(node):
            out |= _saved_paths(v, prefix + (str(i),))
        return out
    return {prefix}


def _saved_abstract(meta_node: Any, template_node: Any) -> Any:
    """Abstract restore tree mirroring the SAVED structure, with shardings
    grafted from ``template_node`` wherever a same-named leaf of the same
    shape exists.  This keeps the merge fallback viable at scale: leaves
    the template knows restore directly onto their (possibly ZeRO-1)
    shardings instead of materialising unsharded on one device; only
    saved-only leaves (about to be dropped by the merge) land unplaced."""
    if isinstance(meta_node, dict):
        if hasattr(template_node, "_fields"):
            tmpl = {f: getattr(template_node, f)
                    for f in template_node._fields}
        elif isinstance(template_node, dict):
            tmpl = template_node
        elif isinstance(template_node, (list, tuple)):
            tmpl = {str(i): v for i, v in enumerate(template_node)}
        else:
            tmpl = {}
        return {k: _saved_abstract(v, tmpl.get(k))
                for k, v in meta_node.items()}
    shape = tuple(meta_node.shape)
    sharding = None
    if template_node is not None and hasattr(template_node, "dtype") and \
            tuple(np.shape(template_node)) == shape:
        sharding = getattr(template_node, "sharding", None)
    return jax.ShapeDtypeStruct(shape, meta_node.dtype, sharding=sharding)


class CheckpointManager:
    """Step-addressed checkpoints under ``directory`` (path layout mirrors
    the reference's ``checkpoints/checkpoint_step_{N}`` naming,
    distributed_trainer.py:461)."""

    def __init__(self, directory: str = "checkpoints"):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._ckptr = ocp.StandardCheckpointer()

    def path_for(self, step: int) -> str:
        return os.path.join(self.directory, f"checkpoint_step_{step}")

    # -- topology sidecar -------------------------------------------------
    # After an elastic eviction the live node count differs from the
    # config's; a resume must rebuild THAT topology before Orbax can place
    # leaves (SURVEY §5.4: "restore must tolerate a different live-device
    # set than at save time").  The sidecar records it.

    def _meta_path(self, step: int) -> str:
        return os.path.join(self.directory, f"topology_{step}.json")

    def save_metadata(self, step: int, meta: dict) -> None:
        import json

        with open(self._meta_path(step), "w") as f:
            json.dump(meta, f)

    def load_metadata(self, step: int) -> Optional[dict]:
        import json

        path = self._meta_path(step)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    def save(self, state: Any, step: int, force: bool = False,
             block: bool = True) -> str:
        """``block=False`` returns as soon as the device→host copy is done
        (Orbax's async path): disk serialisation overlaps the next training
        steps instead of stalling them.  Buffer donation stays safe — the
        step only donates the on-device arrays, which Orbax has already
        snapshotted to host.  A later save/restore (or ``wait``) joins the
        in-flight write."""
        path = self.path_for(step)
        # Join any previous in-flight async save BEFORE inspecting the
        # destination: Orbax commits async writes by rename, so an
        # in-flight save of this same step only becomes visible to the
        # exists() check once joined (skip/force decisions would otherwise
        # race the commit).
        self._ckptr.wait_until_finished()
        if os.path.exists(path):
            if not force:
                logger.info("Checkpoint already exists: %s", path)
                return path
            import shutil

            shutil.rmtree(path)
        self._ckptr.save(path, state)
        if block:
            self._ckptr.wait_until_finished()
        logger.info("Checkpoint %s: %s",
                    "saved" if block else "saving (async)", path)
        return path

    def wait(self) -> None:
        """Join any in-flight async save."""
        self._ckptr.wait_until_finished()

    def restore(self, template: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure/shardings of ``template``.  ``step``
        defaults to the latest available.

        Structure drift between versions (a TrainState field added — e.g.
        ``clean_streak`` in round 3 — or an optimizer-state leaf removed,
        like the constant schedule's count) falls back to a merge-by-name
        restore: saved leaves land where the template has a same-named
        slot, template values fill anything the checkpoint lacks, and
        extra saved keys are ignored."""
        self._ckptr.wait_until_finished()  # join an in-flight async save
        if step is None:
            step = _latest_step(self.directory)
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}"
                )
        path = self.path_for(step)
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                np.shape(x), x.dtype, sharding=getattr(x, "sharding", None)
            )
            if hasattr(x, "dtype")
            else x,
            template,
        )
        try:
            state = self._ckptr.restore(path, abstract)
        except Exception as exc:
            # The merge fallback exists for STRUCTURE drift only (a
            # TrainState field added/removed between versions).  Verify via
            # the saved metadata that the structures genuinely differ before
            # reinterpreting the failure — a transient I/O error or
            # corrupted array on a structure-identical checkpoint must stay
            # loud, not silently keep freshly-initialised template values.
            try:
                saved_tree = self._saved_tree(path)
                drifted = _saved_paths(saved_tree) != _template_paths(
                    template
                )
            except Exception:
                raise exc  # metadata unreadable: not structure drift
            if not drifted:
                raise
            logger.warning(
                "Strict restore failed (%s: %s); checkpoint structure "
                "differs from the template — retrying with merge-by-name "
                "(fields missing from the checkpoint keep their "
                "initialised values)", type(exc).__name__, str(exc)[:200],
            )
            raw = self._ckptr.restore(
                path, _saved_abstract(saved_tree, template)
            )
            state = _merge_into_template(template, raw)
        logger.info("Checkpoint restored: %s", path)
        return state

    def _saved_tree(self, path: str) -> Any:
        """Structure metadata of a saved checkpoint (dict tree of
        ArrayMetadata with .shape/.dtype)."""
        meta = self._ckptr.metadata(path)
        item = getattr(meta, "item_metadata", meta)
        return getattr(item, "tree", item)

    def latest_step(self) -> Optional[int]:
        return _latest_step(self.directory)

"""Checkpoint save AND restore via Orbax.

The reference only ever saves (torch.save of model/optimizer/trust state,
distributed_trainer.py:448-463) — there is no load path anywhere in the
snapshot, and the checkpoints/ directory is assumed to exist (SURVEY §3.5,
§7.5).  Here both directions exist, the directory is created, and the
payload is the *entire* TrainState pytree — params, optimizer state, trust
world-view, detector baselines, verifier and monitor state, step/rng — so a
resume restores the security posture, not just the weights.

Restore is sharding-aware: pass the live (possibly resharded) state template
and Orbax places leaves onto the template's shardings, which is what lets a
post-reassignment resume come back on a different device set (SURVEY §5.4).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

import jax
import numpy as np

logger = logging.getLogger(__name__)


def _latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.startswith("checkpoint_step_"):
            try:
                steps.append(int(name.rsplit("_", 1)[1]))
            except ValueError:
                continue
    return max(steps) if steps else None


class CheckpointManager:
    """Step-addressed checkpoints under ``directory`` (path layout mirrors
    the reference's ``checkpoints/checkpoint_step_{N}`` naming,
    distributed_trainer.py:461)."""

    def __init__(self, directory: str = "checkpoints"):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._ckptr = ocp.StandardCheckpointer()

    def path_for(self, step: int) -> str:
        return os.path.join(self.directory, f"checkpoint_step_{step}")

    # -- topology sidecar -------------------------------------------------
    # After an elastic eviction the live node count differs from the
    # config's; a resume must rebuild THAT topology before Orbax can place
    # leaves (SURVEY §5.4: "restore must tolerate a different live-device
    # set than at save time").  The sidecar records it.

    def _meta_path(self, step: int) -> str:
        return os.path.join(self.directory, f"topology_{step}.json")

    def save_metadata(self, step: int, meta: dict) -> None:
        import json

        with open(self._meta_path(step), "w") as f:
            json.dump(meta, f)

    def load_metadata(self, step: int) -> Optional[dict]:
        import json

        path = self._meta_path(step)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    def save(self, state: Any, step: int, force: bool = False,
             block: bool = True) -> str:
        """``block=False`` returns as soon as the device→host copy is done
        (Orbax's async path): disk serialisation overlaps the next training
        steps instead of stalling them.  Buffer donation stays safe — the
        step only donates the on-device arrays, which Orbax has already
        snapshotted to host.  A later save/restore (or ``wait``) joins the
        in-flight write."""
        path = self.path_for(step)
        # Join any previous in-flight async save BEFORE inspecting the
        # destination: Orbax commits async writes by rename, so an
        # in-flight save of this same step only becomes visible to the
        # exists() check once joined (skip/force decisions would otherwise
        # race the commit).
        self._ckptr.wait_until_finished()
        if os.path.exists(path):
            if not force:
                logger.info("Checkpoint already exists: %s", path)
                return path
            import shutil

            shutil.rmtree(path)
        self._ckptr.save(path, state)
        if block:
            self._ckptr.wait_until_finished()
        logger.info("Checkpoint %s: %s",
                    "saved" if block else "saving (async)", path)
        return path

    def wait(self) -> None:
        """Join any in-flight async save."""
        self._ckptr.wait_until_finished()

    def restore(self, template: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure/shardings of ``template``.  ``step``
        defaults to the latest available."""
        self._ckptr.wait_until_finished()  # join an in-flight async save
        if step is None:
            step = _latest_step(self.directory)
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}"
                )
        path = self.path_for(step)
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                np.shape(x), x.dtype, sharding=getattr(x, "sharding", None)
            )
            if hasattr(x, "dtype")
            else x,
            template,
        )
        state = self._ckptr.restore(path, abstract)
        logger.info("Checkpoint restored: %s", path)
        return state

    def latest_step(self) -> Optional[int]:
        return _latest_step(self.directory)

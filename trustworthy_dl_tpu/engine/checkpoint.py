"""Checkpoint save AND restore via Orbax.

The reference only ever saves (torch.save of model/optimizer/trust state,
distributed_trainer.py:448-463) — there is no load path anywhere in the
snapshot, and the checkpoints/ directory is assumed to exist (SURVEY §3.5,
§7.5).  Here both directions exist, the directory is created, and the
payload is the *entire* TrainState pytree — params, optimizer state, trust
world-view, detector baselines, verifier and monitor state, step/rng — so a
resume restores the security posture, not just the weights.

Restore is sharding-aware: pass the live (possibly resharded) state template
and Orbax places leaves onto the template's shardings, which is what lets a
post-reassignment resume come back on a different device set (SURVEY §5.4).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trustworthy_dl_tpu.obs.events import EventType

logger = logging.getLogger(__name__)


def _payload_steps(directory: str) -> List[int]:
    """Steps with a payload directory present (committed or not).  Orbax
    tmp dirs and our ``.staging`` dirs fail the int parse and are
    ignored."""
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        if name.startswith("checkpoint_step_"):
            try:
                steps.append(int(name.rsplit("_", 1)[1]))
            except ValueError:
                continue
    return steps


def _crc32_file(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _atomic_write_json(path: str, payload: Any) -> None:
    """tmp file + fsync + ``os.replace``: readers see either the old
    content or the new, never a truncated file (a preemption mid-write
    used to leave broken JSON that wedged every later resume)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _unlink(path: str) -> None:
    try:
        os.unlink(path)
    except FileNotFoundError:
        pass


def _merge_into_template(template: Any, raw: Any) -> Any:
    """Graft a restored raw tree (nested dicts/lists of host arrays, as
    Orbax saves them) onto ``template`` by container key: same-named slots
    take the saved value (placed with the template leaf's sharding),
    missing slots keep the template's (freshly-initialised) value, and
    saved keys with no template slot are dropped.  This is the
    forward/backward-compat path for checkpoint structure drift."""
    if raw is None:
        return template
    # Leaf in the template: adopt the saved value (cast/placed like the
    # template leaf); container mismatches fall through to the walk below.
    if hasattr(template, "dtype") and not isinstance(template, (dict,)):
        leaf = raw
        if hasattr(leaf, "dtype"):
            # The fallback exists for STRUCTURE drift only.  A shape
            # mismatch means topology drift (different node count) — keep
            # that loud: silently adopting a [8, ...] row block onto a
            # 4-node template would defer the failure to an opaque XLA
            # error in the first step (use the elastic topology sidecar
            # for cross-topology resume).
            if tuple(np.shape(leaf)) != tuple(np.shape(template)):
                raise ValueError(
                    f"checkpoint leaf shape {np.shape(leaf)} does not "
                    f"match template {np.shape(template)} — topology "
                    "drift, not structure drift; restore via the "
                    "topology sidecar (load_checkpoint handles this)"
                )
            # No host round-trip: an already-sharded jax leaf (the
            # metadata-guided fallback restores straight onto the
            # template's shardings) passes through / re-places on device.
            arr = leaf if leaf.dtype == template.dtype else \
                leaf.astype(template.dtype)
            sharding = getattr(template, "sharding", None)
            if sharding is not None:
                return jax.device_put(arr, sharding)
            return jax.numpy.asarray(arr)
        return template
    if isinstance(template, dict):
        raw_map = raw if isinstance(raw, dict) else {}
        return {
            k: _merge_into_template(v, raw_map.get(k))
            for k, v in template.items()
        }
    if isinstance(template, tuple):
        fields = getattr(template, "_fields", None)
        if fields is not None:  # NamedTuple: saved as a dict of fields
            raw_map = raw if isinstance(raw, dict) else {}
            return type(template)(**{
                f: _merge_into_template(getattr(template, f),
                                        raw_map.get(f))
                for f in fields
            })
        raw_seq = raw if isinstance(raw, (list, tuple, dict)) else []
        if isinstance(raw_seq, dict):  # tuples serialise as {"0": ..}
            raw_seq = [raw_seq.get(str(i)) for i in range(len(template))]
        raw_seq = list(raw_seq) + [None] * (len(template) - len(raw_seq))
        return tuple(
            _merge_into_template(v, r) for v, r in zip(template, raw_seq)
        )
    if isinstance(template, list):
        raw_seq = raw if isinstance(raw, (list, tuple)) else []
        raw_seq = list(raw_seq) + [None] * (len(template) - len(raw_seq))
        return [
            _merge_into_template(v, r) for v, r in zip(template, raw_seq)
        ]
    return template


def _template_paths(node: Any, prefix: tuple = ()) -> set:
    """Key-path set of a live template pytree, normalised to the string
    keys Orbax serialises with (namedtuples as field dicts, sequences as
    stringified indices) so it is directly comparable with
    ``_saved_paths``."""
    if hasattr(node, "dtype") and not isinstance(node, dict):
        return {prefix}
    fields = getattr(node, "_fields", None)
    if fields is not None:
        out = set()
        for f in fields:
            out |= _template_paths(getattr(node, f), prefix + (f,))
        return out
    if isinstance(node, dict):
        out = set()
        for k, v in node.items():
            out |= _template_paths(v, prefix + (str(k),))
        return out
    if isinstance(node, (list, tuple)):
        out = set()
        for i, v in enumerate(node):
            out |= _template_paths(v, prefix + (str(i),))
        return out
    return {prefix}


def _saved_paths(node: Any, prefix: tuple = ()) -> set:
    """Key-path set of a saved checkpoint's structure metadata (nested
    dicts/sequences with ArrayMetadata leaves), normalised like
    ``_template_paths`` (sequence positions as stringified indices)."""
    if isinstance(node, dict):
        out = set()
        for k, v in node.items():
            out |= _saved_paths(v, prefix + (str(k),))
        return out
    if isinstance(node, (list, tuple)):
        out = set()
        for i, v in enumerate(node):
            out |= _saved_paths(v, prefix + (str(i),))
        return out
    return {prefix}


def _saved_abstract(meta_node: Any, template_node: Any) -> Any:
    """Abstract restore tree mirroring the SAVED structure, with shardings
    grafted from ``template_node`` wherever a same-named leaf of the same
    shape exists.  This keeps the merge fallback viable at scale: leaves
    the template knows restore directly onto their (possibly ZeRO-1)
    shardings instead of materialising unsharded on one device; only
    saved-only leaves (about to be dropped by the merge) land unplaced."""
    if isinstance(meta_node, dict):
        if hasattr(template_node, "_fields"):
            tmpl = {f: getattr(template_node, f)
                    for f in template_node._fields}
        elif isinstance(template_node, dict):
            tmpl = template_node
        elif isinstance(template_node, (list, tuple)):
            tmpl = {str(i): v for i, v in enumerate(template_node)}
        else:
            tmpl = {}
        return {k: _saved_abstract(v, tmpl.get(k))
                for k, v in meta_node.items()}
    shape = tuple(meta_node.shape)
    sharding = None
    if template_node is not None and hasattr(template_node, "dtype") and \
            tuple(np.shape(template_node)) == shape:
        sharding = getattr(template_node, "sharding", None)
    return jax.ShapeDtypeStruct(shape, meta_node.dtype, sharding=sharding)


class CheckpointManager:
    """Step-addressed checkpoints under ``directory`` (path layout mirrors
    the reference's ``checkpoints/checkpoint_step_{N}`` naming,
    distributed_trainer.py:461).

    Every save is *verified*: after the payload lands, a manifest of
    per-file sizes + CRC32 checksums is written atomically — the
    manifest's existence IS the COMMIT marker.  ``latest_step()`` and
    ``restore(step=None)`` walk backward past uncommitted (crashed
    mid-save) and corrupt (checksum-mismatch) checkpoints instead of
    raising, so a truncated latest checkpoint costs one save interval of
    progress, never the run.  Pre-manifest checkpoints (older writers)
    are accepted as "legacy" — unverifiable but not skipped.

    ``chaos`` optionally wires a ``chaos.FaultInjector`` into the commit
    path (crash-before-COMMIT / post-commit bit-rot drills).
    """

    def __init__(self, directory: str = "checkpoints", chaos: Any = None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._ckptr = ocp.StandardCheckpointer()
        self.chaos = chaos
        # Optional obs TraceBus (obs/events.py): COMMIT outcomes are the
        # durability decision a post-mortem needs — emitted here because
        # only the manager knows whether the manifest actually landed.
        self.trace: Any = None
        # One in-flight async save awaiting its COMMIT (manifest write and,
        # for force-overwrites, the staging swap).  Committed by the next
        # join point: save / restore / wait / latest_step.
        self._pending: Optional[Dict[str, Any]] = None

    def path_for(self, step: int) -> str:
        return os.path.join(self.directory, f"checkpoint_step_{step}")

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, f"manifest_{step}.json")

    def _inflight_path(self, step: int) -> str:
        return os.path.join(self.directory, f"inflight_{step}")

    # -- topology sidecar -------------------------------------------------
    # After an elastic eviction the live node count differs from the
    # config's; a resume must rebuild THAT topology before Orbax can place
    # leaves (SURVEY §5.4: "restore must tolerate a different live-device
    # set than at save time").  The sidecar records it.

    def _meta_path(self, step: int) -> str:
        return os.path.join(self.directory, f"topology_{step}.json")

    def save_metadata(self, step: int, meta: dict) -> None:
        # Atomic (tmp + os.replace): a preemption mid-write must not leave
        # truncated JSON that breaks every later resume.
        _atomic_write_json(self._meta_path(step), meta)

    def load_metadata(self, step: int) -> Optional[dict]:
        path = self._meta_path(step)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    def save(self, state: Any, step: int, force: bool = False,
             block: bool = True) -> str:
        """``block=False`` returns as soon as the device→host copy is done
        (Orbax's async path): disk serialisation overlaps the next training
        steps instead of stalling them.  Buffer donation stays safe — the
        step only donates the on-device arrays, which Orbax has already
        snapshotted to host.  A later save/restore (or ``wait``) joins the
        in-flight write AND commits it (manifest written last — a crash
        before the join leaves the save uncommitted, and restore walks
        past it).

        ``force=True`` overwrites via a staging directory swapped in only
        at commit: a failed overwrite never loses the last good state
        (the old payload used to be rmtree'd *before* the new save)."""
        path = self.path_for(step)
        os.makedirs(self.directory, exist_ok=True)  # tolerate external rm
        # Join (and commit) any previous in-flight async save BEFORE
        # inspecting the destination, so skip/force decisions never race
        # the commit of this same step.
        self._join()
        exists = os.path.exists(path)
        # Full integrity check, not just the commit marker: a re-save at
        # an existing step (post-rollback replay) must replace a
        # bit-rotten-but-committed checkpoint instead of skipping and
        # leaving the corruption in place forever.  The CRC read only
        # happens when a payload already exists at this step — never on
        # the common fresh-step save.
        usable, reason = self.check_integrity(step) if exists else (
            False, "missing payload"
        )
        if exists and usable and not force:
            logger.info("Checkpoint already exists: %s", path)
            return path
        staging = None
        if exists and not usable:
            # Uncommitted or corrupt leftovers: clear and rewrite.
            logger.warning("Clearing unusable checkpoint at step %d "
                           "(%s): %s", step, reason, path)
            shutil.rmtree(path)
            _unlink(self._manifest_path(step))
            _unlink(self._inflight_path(step))
        elif exists:
            # Force-overwrite of a good checkpoint: write to a staging
            # path and swap at commit.
            staging = path + ".staging"
            if os.path.exists(staging):
                shutil.rmtree(staging)
        target = staging if staging is not None else path
        if not block:
            # Snapshot before the async write: on CPU-backed platforms
            # Orbax's "device→host copy" can zero-copy ALIAS the live
            # buffers, and the caller's next donated train step then
            # rewrites them mid-write — the checkpoint silently contains
            # future-step bytes (test_async_checkpoint_roundtrip was
            # flaky at the seed for exactly this).  An eager device copy
            # hands the writer buffers nobody will ever donate.
            state = jax.tree_util.tree_map(
                lambda a: jnp.copy(a) if hasattr(a, "dtype") else a, state
            )
        # tddl-lint: disable=atomic-write — presence-only marker: its
        # existence (not its bytes) distinguishes a crashed save from a
        # legacy dir; the manifest is the real COMMIT record.
        with open(self._inflight_path(step), "w") as f:
            f.write("save in flight; the manifest is the COMMIT marker\n")
        self._ckptr.save(target, state)
        self._pending = {"step": step, "target": target, "final": path}
        if block:
            self._join()
        logger.info("Checkpoint %s: %s",
                    "saved" if block else "saving (async)", path)
        return path

    def _join(self) -> None:
        """Join any in-flight async save and COMMIT it: swap staging into
        place (force-overwrites), write the checksum manifest atomically,
        drop the in-flight marker.  Everything before the manifest write
        is invisible to restore — that ordering is the crash-safety
        contract."""
        self._ckptr.wait_until_finished()
        pending, self._pending = self._pending, None
        if pending is None:
            return
        step, target, final = (pending["step"], pending["target"],
                               pending["final"])
        if self.chaos is not None and not self.chaos.on_checkpoint_commit(
            step
        ):
            if self.trace is not None:
                self.trace.emit(EventType.CKPT_COMMIT, step=step,
                                committed=False,
                                reason="chaos_crash_before_commit")
            return  # drill: died pre-COMMIT — payload left uncommitted
        if target != final:
            # Retire the old checkpoint only now that its replacement is
            # fully on disk.  Manifest goes first: a crash inside this
            # window demotes the old payload to "uncommitted" (walked
            # past) rather than leaving a trusted-but-half-swapped state.
            _unlink(self._manifest_path(step))
            if os.path.exists(final):
                shutil.rmtree(final)
            os.replace(target, final)
        self._write_manifest(step, final)
        _unlink(self._inflight_path(step))
        if self.trace is not None:
            self.trace.emit(EventType.CKPT_COMMIT, step=step,
                            committed=True)
        if self.chaos is not None:
            self.chaos.on_checkpoint_saved(step, final)

    def _write_manifest(self, step: int, path: str) -> None:
        files: Dict[str, Dict[str, int]] = {}
        for dirpath, _, names in os.walk(path):
            for name in sorted(names):
                p = os.path.join(dirpath, name)
                rel = os.path.relpath(p, path)
                files[rel] = {"size": os.path.getsize(p),
                              "crc32": _crc32_file(p)}
        _atomic_write_json(self._manifest_path(step),
                           {"step": step, "files": files})

    def check_integrity(self, step: int, verify: bool = True
                        ) -> Tuple[bool, str]:
        """(ok, reason) for one step: committed (manifest present) and —
        with ``verify`` — every manifest entry's size and CRC32 matching
        the bytes on disk.  Legacy checkpoints (no manifest, no in-flight
        marker: written before manifests existed) are accepted but
        unverifiable."""
        path = self.path_for(step)
        if not os.path.isdir(path):
            return False, "missing payload"
        mpath = self._manifest_path(step)
        if not os.path.exists(mpath):
            if os.path.exists(self._inflight_path(step)):
                return False, "uncommitted (save died before COMMIT)"
            return True, "legacy (pre-manifest, unverifiable)"
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as exc:
            return False, f"unreadable manifest ({exc})"
        if not verify:
            return True, "committed"
        for rel, meta in manifest.get("files", {}).items():
            p = os.path.join(path, rel)
            if not os.path.exists(p):
                return False, f"missing shard {rel}"
            if os.path.getsize(p) != meta["size"]:
                return False, f"size mismatch on {rel}"
            if _crc32_file(p) != meta["crc32"]:
                return False, f"checksum mismatch on {rel}"
        return True, "verified"

    def wait(self) -> None:
        """Join (and commit) any in-flight async save."""
        self._join()

    def restore(self, template: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure/shardings of ``template``.

        ``step=None`` walks the available steps newest-first and restores
        the most recent checkpoint that is committed, passes its integrity
        manifest, AND actually loads — a truncated/bit-rotten latest
        checkpoint falls back to the prior verified step without operator
        input.  An *explicit* ``step`` stays loud: an integrity failure on
        a checkpoint the operator named raises instead of silently
        substituting an older one.

        Structure drift between versions (a TrainState field added — e.g.
        ``clean_streak`` in round 3 — or an optimizer-state leaf removed,
        like the constant schedule's count) falls back to a merge-by-name
        restore: saved leaves land where the template has a same-named
        slot, template values fill anything the checkpoint lacks, and
        extra saved keys are ignored."""
        self._join()  # join + commit an in-flight async save
        if step is None:
            skipped = []
            for s in sorted(_payload_steps(self.directory), reverse=True):
                ok, reason = self.check_integrity(s)
                if not ok:
                    logger.warning(
                        "Skipping checkpoint step %d: %s", s, reason
                    )
                    skipped.append((s, reason))
                    continue
                try:
                    return self._restore_step(template, s)
                except Exception as exc:  # corrupt beyond the checksums
                    logger.warning(
                        "Restore of checkpoint step %d failed (%s: %s); "
                        "walking back to an older checkpoint",
                        s, type(exc).__name__, str(exc)[:200],
                    )
                    skipped.append((s, f"{type(exc).__name__}"))
            if skipped:
                raise FileNotFoundError(
                    f"no restorable checkpoint under {self.directory} "
                    f"(skipped: {skipped})"
                )
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}"
            )
        ok, reason = self.check_integrity(step)
        if not ok:
            raise RuntimeError(
                f"checkpoint step {step} failed its integrity check "
                f"({reason}); refusing an explicit-step restore — use "
                "restore(step=None) to fall back to the latest verified "
                "checkpoint"
            )
        return self._restore_step(template, step)

    def _restore_step(self, template: Any, step: int) -> Any:
        path = self.path_for(step)
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                np.shape(x), x.dtype, sharding=getattr(x, "sharding", None)
            )
            if hasattr(x, "dtype")
            else x,
            template,
        )
        try:
            state = self._ckptr.restore(path, abstract)
        except Exception as exc:
            # The merge fallback exists for STRUCTURE drift only (a
            # TrainState field added/removed between versions).  Verify via
            # the saved metadata that the structures genuinely differ before
            # reinterpreting the failure — a transient I/O error or
            # corrupted array on a structure-identical checkpoint must stay
            # loud, not silently keep freshly-initialised template values.
            try:
                saved_tree = self._saved_tree(path)
                drifted = _saved_paths(saved_tree) != _template_paths(
                    template
                )
            except Exception:
                raise exc  # metadata unreadable: not structure drift
            if not drifted:
                raise
            logger.warning(
                "Strict restore failed (%s: %s); checkpoint structure "
                "differs from the template — retrying with merge-by-name "
                "(fields missing from the checkpoint keep their "
                "initialised values)", type(exc).__name__, str(exc)[:200],
            )
            raw = self._ckptr.restore(
                path, _saved_abstract(saved_tree, template)
            )
            state = _merge_into_template(template, raw)
        logger.info("Checkpoint restored: %s", path)
        return state

    def _saved_tree(self, path: str) -> Any:
        """Structure metadata of a saved checkpoint (dict tree of
        ArrayMetadata with .shape/.dtype)."""
        meta = self._ckptr.metadata(path)
        item = getattr(meta, "item_metadata", meta)
        return getattr(item, "tree", item)

    def verified_steps(self) -> List[int]:
        """All restorable steps, newest first — the rollback candidate
        list (integrity-checked; legacy pre-manifest checkpoints
        included)."""
        self._join()
        return [s for s in sorted(_payload_steps(self.directory),
                                  reverse=True)
                if self.check_integrity(s)[0]]

    def latest_step(self, verified: bool = True) -> Optional[int]:
        """Latest step whose checkpoint is restorable.  With ``verified``
        (default) uncommitted and checksum-failing checkpoints are walked
        past — the caller gets the newest step a restore would actually
        land on, not the newest directory name.  ``verified=False`` is
        the raw listing (cheap, no file reads)."""
        self._join()  # an in-flight async save is not "latest" until committed
        for s in sorted(_payload_steps(self.directory), reverse=True):
            if not verified:
                return s
            ok, reason = self.check_integrity(s)
            if ok:
                return s
            logger.warning("latest_step: skipping step %d: %s", s, reason)
        return None

"""Train-step state pytrees: everything the compiled step carries.

The reference scatters training state across Python objects mutated per batch
(trainer fields, TrustManager dicts, detector deques — distributed_trainer.py
:68-96).  Here the complete world-view is one immutable pytree threaded
through the jitted step, which is what makes per-batch detection free of host
round-trips (SURVEY §7.1) and makes checkpointing trivially complete
(orbax saves the whole pytree, including the trust world-view, matching the
reference's checkpoint payload at distributed_trainer.py:448-463).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from trustworthy_dl_tpu.detect.baseline import BaselineState, init_baseline_state
from trustworthy_dl_tpu.detect.stats import NUM_GRADIENT_STATS
from trustworthy_dl_tpu.detect.verifier import VerifierState, init_verifier_state
from trustworthy_dl_tpu.trust.state import TrustState, init_trust_state


class MonitorState(NamedTuple):
    """NodeMonitor equivalent (implied module, call sites
    distributed_trainer.py:234-235,259): per-node expected output mean/std
    and expected gradient norms, as running averages.

    Only samples that passed verification/detection are absorbed, so an
    attacker cannot drag its own expected-behaviour baseline toward the
    attack (a deliberate hardening over the reference, whose NodeMonitor
    semantics are unspecified)."""

    count: jax.Array          # i32[n] samples absorbed
    out_mean_avg: jax.Array   # f32[n] running mean of output means
    out_std_avg: jax.Array    # f32[n] running mean of output stds
    grad_norm_avg: jax.Array  # f32[n, L] running mean of per-leaf grad norms

    @property
    def warm(self) -> jax.Array:
        return self.count >= 5


def init_monitor_state(num_nodes: int, num_leaves: int) -> MonitorState:
    return MonitorState(
        count=jnp.zeros((num_nodes,), jnp.int32),
        out_mean_avg=jnp.zeros((num_nodes,), jnp.float32),
        out_std_avg=jnp.zeros((num_nodes,), jnp.float32),
        grad_norm_avg=jnp.zeros((num_nodes, num_leaves), jnp.float32),
    )


def update_monitor(state: MonitorState, out_mean: jax.Array, out_std: jax.Array,
                   leaf_norms: jax.Array, absorb: jax.Array) -> MonitorState:
    """Running-average update for nodes with ``absorb`` True."""
    new_count = state.count + absorb.astype(jnp.int32)
    w = 1.0 / jnp.maximum(new_count.astype(jnp.float32), 1.0)
    upd = lambda avg, x, wexp: jnp.where(
        absorb.reshape(absorb.shape + (1,) * (avg.ndim - 1)),
        avg + (x - avg) * wexp, avg,
    )
    return MonitorState(
        count=new_count,
        out_mean_avg=upd(state.out_mean_avg, out_mean, w),
        out_std_avg=upd(state.out_std_avg, out_std, w),
        grad_norm_avg=upd(state.grad_norm_avg, leaf_norms, w[:, None]),
    )


class TrainState(NamedTuple):
    """The full training world-view."""

    params: Any
    opt_state: Any
    trust: TrustState
    out_baseline: BaselineState
    grad_baseline: BaselineState
    verifier: VerifierState
    monitor: MonitorState
    prev_suspects: jax.Array  # bool[n] candidate verdicts from previous step
    step: jax.Array          # i32[]
    epoch: jax.Array         # i32[]
    rng: jax.Array
    # Pipeline-mode canary probe state (parallel/pipeline.py:CanaryState);
    # None in data-parallel mode, where cross-node checks need no probe.
    canary: Any = None
    # i32[n] consecutive clean steps per node — drives the in-step
    # COMPROMISED -> RECOVERING probation (trust_manager.py:198-206
    # semantics; config.recovery_probation_steps).
    clean_streak: Any = None
    # Fleet-level norm-surge alarm (majority-attack backstop): Welford
    # baseline (VerifierState, 1 row) of the cross-sectional MEDIAN
    # log-norm, plus the consecutive raw-surge streak (i32[1]) driving
    # the 2-step debounce AND the bounded-latch escape hatch
    # (detect/verifier.py:fleet_surge_update).  The per-node median/MAD
    # gate goes blind at >= 50 % contamination
    # (tests/test_adaptive_attacker.py boundary); the fleet median's own
    # temporal z still sees the surge, so the engine can raise an
    # UNATTRIBUTED alarm instead of staying silent.
    fleet_norm: Any = None
    fleet_raw_streak: Any = None


def init_train_state(
    rng: jax.Array,
    params: Any,
    opt_state: Any,
    num_nodes: int,
    trust_threshold: float = 0.7,
    initial_trust: float = 1.0,
    decay_rate: float = 0.01,
    recovery_rate: float = 0.005,
    detector_window: int = 1000,
    num_monitor_leaves: Optional[int] = None,
    canary: Any = None,
) -> TrainState:
    """``num_monitor_leaves`` overrides the per-node gradient-norm vector
    width (pipeline mode monitors only each stage's block-slice leaves,
    not the full param tree)."""
    num_leaves = (
        num_monitor_leaves
        if num_monitor_leaves is not None
        else len(jax.tree_util.tree_leaves(params))
    )
    return TrainState(
        params=params,
        opt_state=opt_state,
        trust=init_trust_state(
            num_nodes, trust_threshold, initial_trust, decay_rate, recovery_rate
        ),
        out_baseline=init_baseline_state(num_nodes, detector_window,
                                         NUM_GRADIENT_STATS),
        grad_baseline=init_baseline_state(num_nodes, detector_window,
                                          NUM_GRADIENT_STATS),
        verifier=init_verifier_state(num_nodes),
        monitor=init_monitor_state(num_nodes, num_leaves),
        prev_suspects=jnp.zeros((num_nodes,), bool),
        step=jnp.zeros((), jnp.int32),
        epoch=jnp.zeros((), jnp.int32),
        rng=rng,
        canary=canary,
        clean_streak=jnp.zeros((num_nodes,), jnp.int32),
        fleet_norm=init_verifier_state(1),
        fleet_raw_streak=jnp.zeros((1,), jnp.int32),
    )


def fleet_scalar_fields(state: TrainState) -> dict:
    """The fleet-alarm state leaves that migrate like scalars (replicated)
    — ONE definition shared by every placement/migration site
    (trainer._place_on_mesh, elastic migrate_state, restaff) so a new
    field can never be silently dropped by one of them."""
    return {
        k: v for k, v in dict(
            fleet_norm=state.fleet_norm,
            fleet_raw_streak=state.fleet_raw_streak,
        ).items() if v is not None
    }


def zero1_place_opt_state(opt_state: Any, mesh: Any) -> Any:
    """ZeRO-1-style placement: shard every optimizer-moment leaf over the
    mesh's data axis on its first evenly-divisible dimension; small or
    indivisible leaves (step counts, odd shapes) replicate.

    This is annotation-only — the update math is untouched; GSPMD
    partitions the moment update and gathers the applied params, so an
    n-way data mesh keeps only 1/n of the Adam moments per chip (the
    reference has no distributed-memory story at all: every node held a
    full optimizer copy, distributed_trainer.py:90-91).

    Thin delegate kept for back-compat: the placement rule itself lives
    in the registry (core/sharding.py:place_zero_sharded), shared with
    FSDP param placement and elastic migration so no call site can
    drift."""
    from trustworthy_dl_tpu.core import sharding as shreg
    from trustworthy_dl_tpu.core.mesh import DATA_AXIS

    return shreg.place_zero_sharded(opt_state, mesh, DATA_AXIS)

"""DistributedTrainer — the L4 orchestrator, TPU-native.

API parity with the reference trainer (distributed_trainer.py:63-527):
``train`` / ``train_epoch`` / ``validate`` / ``get_training_stats`` /
``save_checkpoint`` / ``load_checkpoint`` (new — the reference had no load
path) / ``cleanup``, the same host-facing component objects (TrustManager,
NodeMonitor, GradientVerifier, AttackDetector, MetricsCollector), and the
same attack/reassignment bookkeeping.

Execution is re-designed: instead of a sequential Python loop over node
partitions (:148-175), every batch runs one jitted SPMD step
(engine/step.py) over a device mesh; the host loop only feeds batches,
reacts to verdicts (recording attack/reassignment history, flipping the
TrainingState machine) and syncs reporting state at epoch cadence.
"""

from __future__ import annotations

import dataclasses
import enum
import logging
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from trustworthy_dl_tpu.attacks.adversarial import AttackPlan, null_plan
from trustworthy_dl_tpu.core import sharding as shreg
from trustworthy_dl_tpu.core.config import NodeConfig, TrainingConfig
from trustworthy_dl_tpu.core.mesh import DATA_AXIS, STAGE_AXIS, \
    bind_mode_mesh, build_mesh
from trustworthy_dl_tpu.data.loader import PrefetchLoader
from trustworthy_dl_tpu.detect.detector import AttackDetector, AttackType
from trustworthy_dl_tpu.detect.stats import (
    GRADIENT_STAT_NAMES,
    NUM_TENSOR_STATS,
    TENSOR_STAT_NAMES,
)
from trustworthy_dl_tpu.detect.verifier import FleetEpisodeTracker, \
    GradientVerifier
from trustworthy_dl_tpu.engine.checkpoint import CheckpointManager
from trustworthy_dl_tpu.engine.optimizer import build_optimizer
from trustworthy_dl_tpu.engine.state import TrainState, \
    fleet_scalar_fields, init_train_state
from trustworthy_dl_tpu.engine.step import StepMetrics, \
    build_node_eval_step, \
    build_train_step
from trustworthy_dl_tpu.models.factory import ModelFactory
from trustworthy_dl_tpu.obs.compilewatch import guarded
from trustworthy_dl_tpu.obs.events import EventType
from trustworthy_dl_tpu.trust.manager import TrustManager
from trustworthy_dl_tpu.trust.state import NodeStatus
from trustworthy_dl_tpu.utils.metrics import MetricsCollector
from trustworthy_dl_tpu.utils.monitor import NodeMonitor
from trustworthy_dl_tpu.utils.profiling import enable_nan_debugging, \
    step_annotation, trace

logger = logging.getLogger(__name__)


def _sklearn_available() -> bool:
    try:
        import sklearn  # noqa: F401
        return True
    except ImportError:
        return False


class TrainingState(enum.Enum):
    """Trainer lifecycle (distributed_trainer.py:30-35)."""

    INITIALIZING = "initializing"
    TRAINING = "training"
    UNDER_ATTACK = "under_attack"
    RECOVERING = "recovering"
    COMPLETED = "completed"


class DistributedTrainer:
    """Main distributed training orchestrator with adversarial attack
    mitigation."""

    def __init__(self, config: TrainingConfig,
                 mesh: Optional[jax.sharding.Mesh] = None,
                 model_overrides: Optional[Dict[str, Any]] = None):
        self.config = config
        self.training_state = TrainingState.INITIALIZING
        if config.debug_nans:
            enable_nan_debugging()
        if config.compilation_cache_dir:
            from trustworthy_dl_tpu.utils.compile_cache import (
                enable_persistent_cache,
            )

            enable_persistent_cache(config.compilation_cache_dir)

        # Epoch-cadence ML tier, gated once on sklearn availability:
        # without it the refit is a permanent no-op, so the per-step
        # battery feed (device->host transfers + dict building on the hot
        # path) would be pure waste.
        self._ml_enabled = config.ml_detectors and _sklearn_available()
        # Fleet size the jitted steps are built for (reset_for_run guard).
        self._constructed_num_nodes = config.num_nodes
        self._init_host_state()

        # Model / optimizer / mesh / step.
        model_overrides = dict(model_overrides or {})
        if config.parallelism == "sequence" and config.model_name.startswith(
            "gpt"
        ):
            model_overrides.setdefault("attn_impl", "ring")
        if config.lm_head_chunk >= 0 and config.model_name.startswith("gpt"):
            # -1 = model default ("auto" dispatch); 0 = force materialised;
            # >0 = force that chunk width.
            model_overrides.setdefault("lm_head_chunk", config.lm_head_chunk)
        if config.model_name.startswith("gpt"):
            if config.remat:
                model_overrides.setdefault("remat", True)
                model_overrides.setdefault("remat_policy",
                                           config.remat_policy)
        self.model = ModelFactory().create_model(
            config.model_name, **model_overrides
        )
        self.optimizer = build_optimizer(config)
        self.mesh = mesh if mesh is not None else build_mesh(
            config.num_nodes, config.parallelism, config.mesh_shape,
            dcn_mesh_shape=config.dcn_mesh_shape,
        )
        bind_mode_mesh(self.mesh, config.parallelism)
        if config.parallelism == "expert" and \
                "-moe" not in self.config.model_name:
            logger.warning(
                "parallelism='expert' with non-MoE model %r: the "
                "'expert' mesh axis will carry no sharded computation",
                self.config.model_name,
            )
        if config.parallelism == "model":
            from trustworthy_dl_tpu.parallel.pipeline import (
                build_pipeline_eval_step,
                build_pipeline_train_step,
                choose_num_microbatches,
            )

            if config.num_microbatches == 0:  # auto schedule depth
                # Resolve into a COPY: the trainer owns (and mutates) its
                # config, but the caller's object must stay pristine — a
                # second trainer built from it (different mesh, different
                # dp) needs the 0 sentinel intact to re-resolve.
                config = self.config = dataclasses.replace(
                    config,
                    num_microbatches=choose_num_microbatches(
                        config.batch_size, config.num_nodes,
                        self.mesh.shape.get(DATA_AXIS, 1),
                    ),
                )
            self._train_step = jax.jit(
                build_pipeline_train_step(self.model, config, self.optimizer,
                                          self.mesh),
                donate_argnums=(0,),
            )
            self._eval_step = jax.jit(
                build_pipeline_eval_step(self.model, config, self.mesh)
            )
        else:
            self._train_step = jax.jit(
                build_train_step(self.model, config, self.optimizer),
                donate_argnums=(0,),
            )
            self._eval_step = jax.jit(build_node_eval_step(self.model))
        self.checkpointer = CheckpointManager(config.checkpoint_dir)

        self.state: Optional[TrainState] = None
        logger.info(
            "Initialized DistributedTrainer with %d nodes (%s parallelism, "
            "mesh %s)", config.num_nodes, config.parallelism,
            dict(zip(self.mesh.axis_names, self.mesh.devices.shape)),
        )

    # ------------------------------------------------------------------
    # Setup
    # ------------------------------------------------------------------

    def _init_host_state(self) -> None:
        """Per-run host world-view, shared verbatim by the constructor and
        ``reset_for_run`` so the two can never drift: any host attribute a
        run mutates MUST be (re)initialised here, or a later
        ``reset_for_run`` would leak one run's state into the next."""
        config = self.config
        self.current_epoch = 0
        self.global_step = 0

        # Host-facing components (reference: distributed_trainer.py:74-84).
        self.trust_manager = TrustManager(
            num_nodes=config.num_nodes,
            trust_threshold=config.trust_threshold,
            initial_trust=config.initial_trust,
            decay_rate=config.trust_decay_rate,
            recovery_rate=config.trust_recovery_rate,
            alpha=config.trust_alpha,
        )
        self.node_monitor = NodeMonitor()
        self.gradient_verifier = GradientVerifier()
        self.attack_detector = AttackDetector(
            exact_order_stats=config.exact_order_stats
        )
        self.metrics_collector = MetricsCollector(
            tensorboard_dir=config.tensorboard_dir
        )
        self._warned_trim = False
        self._trimmed_sizes: set = set()

        # Node configurations (reference: :85-87).  On TPU, rank == mesh
        # coordinate along the node axis.
        self.node_configs: Dict[int, NodeConfig] = {
            i: NodeConfig(node_id=i, rank=i, world_size=config.num_nodes,
                          device_id=i, model_partition=f"shard_{i}")
            for i in range(config.num_nodes)
        }

        self.attack_history: List[Dict] = []
        self.reassignment_history: List[Dict] = []
        # Fleet-level norm-surge episodes (unattributed majority-attack
        # alarms) — separate from attack_history, whose records name a
        # node and feed per-node precision/recall accounting.  The tracker
        # also records HOW each episode closed ("recovered" vs
        # "absorbed-while-raw" at the latch limit — see
        # detect/verifier.FleetEpisodeTracker).
        self._fleet_tracker = FleetEpisodeTracker()
        self.fleet_alerts: List[Dict] = self._fleet_tracker.episodes
        # Epoch-cadence ML-tier verdicts (original node id -> bool).
        self.ml_flags: Dict[int, bool] = {}
        # Mesh coordinate -> ORIGINAL node id.  Identity until elastic
        # eviction removes coordinates (elastic/reassignment.py); all host
        # bookkeeping (trust manager, histories, reports) keys on original
        # ids so identities survive resharding.
        self.node_map: List[int] = list(range(config.num_nodes))
        # Nodes currently in a recorded-compromised episode: a sustained
        # attack fires the detector every batch, but we record the incident
        # and trigger reassignment only on the clean→compromised transition
        # (the reference re-records per batch, which grows history without
        # bound on long runs).
        self._open_incidents: set = set()
        # Elastic-readmission bookkeeping: original id -> eviction step /
        # the device its coordinate occupied (None in dev mode), and the
        # per-original-id injection bits so a readmitted node's attack
        # schedule survives the mask compaction/expansion round-trip.
        self._evicted_at: Dict[int, int] = {}
        self._evicted_devices: Dict[int, Any] = {}
        self._plan_bits: Dict[int, bool] = {}
        # Pipeline restaff: healthy survivors a stage-count repartition
        # could not seat (id -> their parked devices); re-staffed by the
        # next restaff (elastic/restaff.py).
        self._idle_pool: Dict[int, Any] = {}
        # Loader auto-resize after topology changes (per-node microbatch
        # captured lazily from the first batch seen).
        self._active_loader: Any = None
        self._per_node_batch: Optional[int] = None
        self._trim_grace = 0
        self.attack_plan: AttackPlan = null_plan(config.num_nodes)
        # Robustness hook points (chaos/ + engine/supervisor.py).  Both are
        # per-run host state so reset_for_run detaches them: ``chaos`` is a
        # chaos.FaultInjector consulted in the step loop (fault injection);
        # ``step_guard`` is a supervisor implementing ``after_step(trainer,
        # node_batch, metrics) -> Optional[StepMetrics]`` — returning None
        # rejects the step (the trainer must not account it).
        self.chaos: Any = None
        self.step_guard: Any = None
        # Telemetry (obs/): an ObsSession attached via ``attach_obs``.
        # Per-run like chaos/step_guard — a reset detaches it so a stale
        # session never records a fresh run's events against old
        # correlation ids.  ``_last_status`` backs the trust-transition
        # event stream (emit on change, not per step).
        self.obs: Any = None
        self._last_status: Optional[np.ndarray] = None
        # Async host pipeline (engine/async_host.py): while a LAGGED step
        # drains, ``_drain_ctx`` carries that step's packed fleet-norm
        # streak (the live state is up to K steps ahead) and collects
        # elastic evictions for deferred application at the frontier.
        # None whenever the synchronous path runs — per-run state like
        # chaos/step_guard so a reset can never leak a stale context.
        self._drain_ctx: Any = None
        # A supervisor also wires its injector into the checkpointer's
        # commit hooks; detach that too on reset, or a previous run's
        # UNFIRED checkpoint faults would fire in the next clean run.
        # (hasattr: the constructor calls this before the checkpointer
        # exists.)
        if hasattr(self, "checkpointer"):
            self.checkpointer.chaos = None
            self.checkpointer.trace = None

    def initialize(self, seed: Optional[int] = None) -> TrainState:
        """Init params/optimizer/world-view.  Params are replicated over the
        mesh; per-node batches shard over the data axis."""
        seed = self.config.seed if seed is None else seed
        rng = jax.random.PRNGKey(seed)
        k_params, k_state = jax.random.split(rng)
        params = self.model.init(k_params)
        num_monitor_leaves = None
        if self.config.parallelism == "model":
            # Stage-major stacking: [L, ...] -> [S, L/S, ...], sharded over
            # the 'stage' mesh axis — the reference's layer partitioning
            # (distributed_trainer.py:126-134) as a sharding.
            from trustworthy_dl_tpu.parallel.pipeline import stack_stages

            params = dict(params)
            params["blocks"] = stack_stages(params["blocks"],
                                            self.config.num_nodes)
            num_monitor_leaves = len(
                jax.tree_util.tree_leaves(params["blocks"])
            )
            stage_sharding = shreg.row_sharding(self.mesh, STAGE_AXIS)
            params["blocks"] = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, stage_sharding), params["blocks"]
            )
        if self.config.parallelism in ("tensor", "hybrid"):
            from trustworthy_dl_tpu.parallel.tensor_parallel import (
                apply_tp_sharding,
            )

            # No-op when the mesh has no 'model' axis (hybrid without TP).
            params = apply_tp_sharding(params, self.mesh)
        opt_state = self.optimizer.init(params)
        canary = None
        if self.config.parallelism == "model":
            from trustworthy_dl_tpu.parallel.pipeline import (
                init_canary_state,
                make_canary,
            )

            canary = init_canary_state(
                self.config.num_nodes,
                make_canary(self.model.config, self.config.canary_tokens),
            )
        self.state = self._place_on_mesh(init_train_state(
            k_state, params, opt_state,
            num_nodes=self.config.num_nodes,
            trust_threshold=self.config.trust_threshold,
            initial_trust=self.config.initial_trust,
            decay_rate=self.config.trust_decay_rate,
            recovery_rate=self.config.trust_recovery_rate,
            detector_window=self.config.detector_history,
            num_monitor_leaves=num_monitor_leaves,
            canary=canary,
        ))
        self.training_state = TrainingState.TRAINING
        # The default (null) plan rides every step dispatch too — commit
        # it to the mesh once, like set_attack_plan does for real plans.
        self.attack_plan = self._place_plan(self.attack_plan)
        return self.state

    def reset_for_run(self, seed: Optional[int] = None) -> TrainState:
        """Fresh run on the SAME jitted step: re-initialises device state
        (params/optimizer/trust/detector baselines) AND the host
        world-view (trust manager, detector histories, incident records,
        metrics, step counter) without touching the compiled train/eval
        steps — repeated experiment cells (e.g. the detection-envelope
        sweep) pay the XLA compile once instead of per cell.

        Only valid while the topology is unchanged (no eviction in the
        previous run); it raises otherwise, because the compiled step is
        shaped for the constructor's node count.  The guard compares
        against the CONSTRUCTOR's fleet size — an eviction of a trailing
        node leaves node_map an identity map, so identity alone cannot
        detect it."""
        if self.config.num_nodes != self._constructed_num_nodes or \
                self.node_map != list(range(self._constructed_num_nodes)):
            raise RuntimeError(
                "reset_for_run after a topology change; rebuild the "
                "trainer instead"
            )
        self._init_host_state()
        return self.initialize(seed=seed)

    def _place_on_mesh(self, state: TrainState) -> TrainState:
        """Explicit mesh placement of the whole TrainState, every rule
        resolved through the sharding registry (core/sharding.py):
        per-node rows shard over the node axis ('stage' under pipelining,
        'data' otherwise) via the shared ``row_placer``, ZeRO/FSDP state
        shards via the shared ``place_zero_sharded``, leaves already laid
        out on this mesh (stage-stacked blocks, TP params and their
        optimizer mirrors) keep their shardings, and everything else
        replicates.  Elastic migration (elastic/reassignment.py) calls
        the SAME helpers, so an evict/readmit cycle reproduces exactly
        these shardings.

        Freshly-initialised arrays would otherwise sit uncommitted on
        device 0 — fine for the first jitted step (GSPMD replicates them),
        but a checkpoint restored into that template comes back COMMITTED
        to device 0 and the next step fails mixing it with mesh-sharded
        arrays.  Explicit placement makes init and resume identical."""
        mesh = self.mesh
        if len(list(mesh.devices.flat)) <= 1:
            return state
        node_axis = STAGE_AXIS if self.config.parallelism == "model" else \
            DATA_AXIS
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        n = self.config.num_nodes
        repl = shreg.replicated_sharding(mesh)

        def keep_or_repl(leaf):
            sh = getattr(leaf, "sharding", None)
            if isinstance(sh, NamedSharding) and sh.mesh == mesh:
                return leaf  # already mesh-placed (stage/TP layouts)
            return jax.device_put(leaf, repl)

        place_row = shreg.row_placer(mesh, node_axis, n)

        per_node = dict(
            trust=state.trust, out_baseline=state.out_baseline,
            grad_baseline=state.grad_baseline, verifier=state.verifier,
            monitor=state.monitor, prev_suspects=state.prev_suspects,
            clean_streak=state.clean_streak,
        )
        if state.canary is not None:
            per_node["canary"] = state.canary
        placed = {k: jax.tree_util.tree_map(place_row, v)
                  for k, v in per_node.items()}
        data_sharded = self.config.parallelism == "data" and \
            sizes.get(DATA_AXIS, 1) > 1
        if self.config.shard_params and data_sharded:
            # FSDP: weights shard over the data axis by the same registry
            # rule as the moments; GSPMD gathers per-layer where needed.
            params = shreg.place_zero_sharded(state.params, mesh, DATA_AXIS)
        else:
            params = jax.tree_util.tree_map(keep_or_repl, state.params)
        if data_sharded and (self.config.shard_opt_state
                             or self.config.shard_params):
            # ZeRO-1 (and FSDP, which subsumes it): one shared spelling
            # with elastic migration — see place_zero_sharded.
            opt_state = shreg.place_zero_sharded(state.opt_state, mesh,
                                                 DATA_AXIS)
        else:
            opt_state = jax.tree_util.tree_map(keep_or_repl, state.opt_state)
        shared = {
            "params": params,
            "opt_state": opt_state,
        }
        scalars = jax.tree_util.tree_map(
            lambda l: jax.device_put(l, repl),
            {"step": state.step, "epoch": state.epoch, "rng": state.rng,
             **fleet_scalar_fields(state)},
        )
        return state._replace(**placed, **shared, **scalars)

    def _place_plan(self, plan: AttackPlan) -> AttackPlan:
        """Commit the attack plan's leaves onto the mesh ONCE, in the
        layout the compiled step infers (per-node [n] rows over the node
        axis, scalars replicated).  An uncommitted plan is re-placed by
        the runtime at EVERY dispatch — an implicit per-step transfer the
        async pipeline's transfer-guard test pins out of the hot path."""
        mesh = self.mesh
        if len(list(mesh.devices.flat)) <= 1:
            return plan
        node_axis = STAGE_AXIS if self.config.parallelism == "model" else \
            DATA_AXIS
        # Same registry rule as the TrainState's per-node rows.
        place = shreg.row_placer(mesh, node_axis, self.config.num_nodes)
        return jax.tree_util.tree_map(place, plan)

    def set_attack_plan(self, plan: AttackPlan,
                        target_ids: Optional[Sequence[int]] = None) -> None:
        """Install the experiment's fault-injection schedule.

        ``target_ids`` optionally names the targeted ORIGINAL identities —
        pass it when identities may be off-mesh at install time (evicted
        before activation): the coordinate-space mask cannot carry their
        bit, and without it a later readmission would wrongly re-enter
        them as clean."""
        self.attack_plan = self._place_plan(plan)
        if target_ids is not None:
            targets = {int(i) for i in target_ids}
            self._plan_bits = {
                nid: nid in targets
                for nid in set(self.node_map) | targets
            }
        else:
            mask = np.asarray(plan.target_mask)
            self._plan_bits = {
                self.node_map[i]: bool(mask[i])
                for i in range(min(len(mask), len(self.node_map)))
            }

    def attach_obs(self, session: Any) -> None:
        """Install an :class:`obs.ObsSession`: step/trust/detection/
        checkpoint events flow to its trace bus (and flight recorder),
        and the step loop feeds its phase timer.  Also wires the
        checkpointer and any already-installed chaos injector so commit
        and fault events share the run's correlation ids, and re-binds
        the metrics collector onto the session's (per-run) registry."""
        self.obs = session
        self.checkpointer.trace = session.trace
        self.metrics_collector.bind_registry(session.registry)
        if self.chaos is not None:
            self.chaos.trace = session.trace

    def _obs_note_model_info(self, node_batch: Dict[str, Any]) -> None:
        """Lazily give the step timer what MFU needs: param count and
        work units per step (tokens for LMs, samples for vision)."""
        timer = self.obs.step_timer
        if timer.has_model_info:
            return
        first = node_batch.get("input")
        if first is None:
            first = next(iter(node_batch.values()))
        if self.model.kind == "lm":
            # [n, b, T] (node split) or [B, T] (pipeline): size = tokens.
            units = int(np.prod(first.shape))
        elif self.config.parallelism == "model":
            units = int(first.shape[0])
        else:
            units = int(first.shape[0] * first.shape[1])
        timer.set_model_info(
            self.model.num_params(self.state.params), units,
            model_kind=self.model.kind,
            num_chips=len(list(self.mesh.devices.flat)),
        )
        ledger = getattr(self.obs, "cost_ledger", None)
        if ledger is not None and "train_step" not in ledger.programs:
            # XLA's own cost view of THE train step (obs/hbm.py):
            # analyzed FLOPs/bytes from one lowering pass (no backend
            # compile unless TDDL_OBS_MEMORY_ANALYSIS=1 adds the
            # temp-allocation block) — obs_report.json's cost ledger
            # and the analyzed-FLOPs MFU come from this entry.
            ledger.analyze("train_step", self._train_step, self.state,
                           node_batch, self.attack_plan)

    # ------------------------------------------------------------------
    # Batch plumbing
    # ------------------------------------------------------------------

    def _node_batch(self, batch: Dict[str, np.ndarray],
                    for_eval: bool = False
                    ) -> Optional[Dict[str, jax.Array]]:
        """[B, ...] -> [n, B//n, ...] with the node axis laid over the
        mesh's data axis — the reference's per-node data split, as sharding.
        Pipeline mode keeps the global batch (microbatching is internal) but
        trims B to a multiple of num_microbatches.  Returns None for a
        stale undersized batch during a topology-growth transition (the
        caller skips it).

        ``for_eval``: validation has no accumulation quantum and must not
        crash on a ragged final batch (drop_last=False loaders).  In
        non-pipeline modes a batch whose size doesn't divide by n is
        evaluated as a single replicated node row (no example dropped);
        in pipeline mode the stage ring's shapes are fixed, so a tail
        smaller than the microbatch quantum is SKIPPED (None) and a
        larger ragged tail is trimmed to the quantum — the closest the
        pipe can get without a per-tail-shape recompile of all S stages.
        Eval never feeds the training-side trim warnings."""
        if self.config.parallelism == "model":
            m = self.config.num_microbatches
            # DP pipeline replica rows (TPU (group, S) mesh) additionally
            # shard each microbatch over the data axis, so mb must divide
            # by the row count.
            dp = self.mesh.shape.get(DATA_AXIS, 1)
            quantum = m * dp
            out = {}
            for key, arr in batch.items():
                b = (arr.shape[0] // quantum) * quantum
                if b == 0:
                    if for_eval:
                        return None  # sub-quantum tail: skip, don't crash
                    raise ValueError(
                        f"batch size {arr.shape[0]} < num_microbatches x "
                        f"dp rows = {quantum}"
                    )
                out[key] = jnp.asarray(np.asarray(arr[:b]))
            return out
        n = self.config.num_nodes
        out = {}
        if for_eval:
            lead = min(arr.shape[0] for arr in batch.values())
            if lead == 0:
                return None
            # Ragged tail: one replicated node row — every example is
            # still evaluated (the row count change costs one extra
            # compile per distinct tail shape, bounded by the loader).
            n_eval = n if lead % n == 0 else 1
            for key, arr in batch.items():
                reshaped = np.asarray(arr[:lead]).reshape(
                    (n_eval, lead // n_eval) + arr.shape[1:]
                )
                out[key] = self._shard_node_rows(reshaped, n_eval)
            return out
        accum = max(self.config.grad_accum_steps, 1)
        # Trim ragged batches (drop_last=False loaders) to a multiple of
        # nodes × accumulation steps — same trimming contract as the node
        # split and the pipeline microbatch branch.  Trim bookkeeping runs
        # once per BATCH (input/target share the leading size), keyed on
        # the size: a single ragged tail is normal and stays silent, the
        # same size trimmed on a second batch means the loader's batch
        # size never divides nodes×accum — warn once per trainer.
        lead = min(arr.shape[0] for arr in batch.values())
        b = (lead // (n * accum)) * n * accum
        if b == 0:
            if self._trim_grace > 0:
                # Stale pre-resize batch after a GROWTH transition
                # (readmission): too small to split over the larger fleet.
                # Skip it rather than crash — the resized loader's batches
                # are already behind it in the queue.
                self._trim_grace -= 1
                return None
            raise ValueError(
                f"batch size {lead} < num_nodes x grad_accum_steps = "
                f"{n * accum}"
            )
        if b < lead and not self._warned_trim:
            if self._trim_grace > 0:
                # Transitional old-size batches right after a topology
                # resize (prefetch queue backlog) — expected, not a
                # persistent mismatch.
                self._trim_grace -= 1
            elif lead in self._trimmed_sizes:
                self._warned_trim = True
                logger.warning(
                    "batches of %d are persistently trimmed to %d "
                    "(num_nodes=%d x grad_accum_steps=%d); pick a "
                    "divisible batch size to avoid dropping examples",
                    lead, b, n, accum,
                )
            else:
                self._trimmed_sizes.add(lead)
        for key, arr in batch.items():
            reshaped = np.asarray(arr[:b]).reshape((n, b // n) + arr.shape[1:])
            out[key] = self._shard_node_rows(reshaped, n)
        return out

    def _shard_node_rows(self, reshaped: np.ndarray, rows: int) -> jax.Array:
        """Place a node-split [rows, ...] array: leading axis over the
        mesh's data axis when the row count tiles it, replicated
        otherwise."""
        data_size = dict(
            zip(self.mesh.axis_names, self.mesh.devices.shape)
        ).get(DATA_AXIS, 1)
        if data_size > 1 and rows % data_size == 0:
            sharding = shreg.row_sharding(self.mesh, DATA_AXIS,
                                          reshaped.ndim)
            return jax.device_put(reshaped, sharding)
        return jnp.asarray(reshaped)

    # ------------------------------------------------------------------
    # Training (distributed_trainer.py:382-433,465-492)
    # ------------------------------------------------------------------

    def train_epoch(self, dataloader: Iterable[Dict[str, np.ndarray]],
                    epoch: int) -> float:
        if self.state is None:
            self.initialize()
        self.current_epoch = epoch
        epoch_loss, num_batches = 0.0, 0
        # Per-epoch loader binding: the per-node microbatch is re-derived
        # from THIS loader's first batch, so a later epoch with a
        # different-sized loader is never resized against a stale capture.
        self._per_node_batch = None

        if self.config.prefetch_depth > 0 and not isinstance(
            dataloader, PrefetchLoader
        ):
            # Host/device overlap: the next batch's host-side assembly
            # (native row gathers) runs while the current step trains.
            dataloader = PrefetchLoader(dataloader,
                                        depth=self.config.prefetch_depth)
        self._active_loader = dataloader
        timer = self.obs.step_timer if self.obs is not None else None
        if timer is not None:
            timer.discard_step()  # anchor the first step's "data" lap

        # Async host pipeline (engine/async_host.py): at depth K > 0 the
        # loop below dispatches step k+1 before step k's host-facing
        # metrics have landed — the bookkeeping drains lagged through the
        # same host path, and the mandatory full drains (checkpoint saves,
        # epoch end via the finally, guard rollbacks, elastic transitions,
        # preemption unwind) keep the verified-checkpoint semantics exact.
        pipe = None
        depth = max(int(getattr(self.config, "async_host_depth", 0)), 0)
        if depth > 0:
            from trustworthy_dl_tpu.engine.async_host import (
                AsyncHostPipeline,
            )

            pipe = AsyncHostPipeline(self, depth)

        try:
            for batch_idx, batch in enumerate(dataloader):
                self.global_step += 1
                if self._per_node_batch is None and \
                        self.config.parallelism != "model":
                    lead = min(arr.shape[0] for arr in batch.values())
                    accum = max(self.config.grad_accum_steps, 1)
                    per = lead // (self.config.num_nodes * accum)
                    if per > 0:
                        self._per_node_batch = per
                if self.chaos is not None:
                    # Fault-injection hooks (chaos/injector.py): a lost
                    # batch (simulated data-iterator failure) rides the
                    # stale-batch skip path; on_step_start may stall
                    # (straggler) or raise SimulatedPreemption for the
                    # supervisor to catch.
                    batch = self.chaos.on_batch(self.global_step, batch)
                    if batch is None:
                        self.global_step -= 1
                        continue
                    self.chaos.on_step_start(self.global_step)
                node_batch = self._node_batch(batch)
                if node_batch is None:  # stale undersized batch
                    self.global_step -= 1
                    if timer is not None:
                        timer.discard_step()
                    continue
                if timer is not None:
                    self._obs_note_model_info(node_batch)
                    timer.lap("data")  # loader + host assembly + placement
                # Compile-once runtime contract (obs/compilewatch.py):
                # the dispatch runs under the watcher's "train_step"
                # guard — the first guarded step's compile is warmup,
                # any later recompile storms (rebuild sites reset the
                # scope so planned recompiles stay silent).
                compilewatch = getattr(self.obs, "compilewatch", None) \
                    if self.obs is not None else None
                with step_annotation(self.global_step), \
                        guarded(compilewatch, "train_step",
                                step=self.global_step):
                    self.state, metrics = self._train_step(
                        self.state, node_batch, self.attack_plan
                    )
                if self.chaos is not None:
                    self.state, metrics = self.chaos.on_step_end(
                        self.global_step, self.state, metrics
                    )

                if pipe is not None:
                    # Asynchronous accounting: pack + start the D2H copy,
                    # then drain only what has fallen out of the window.
                    # Guard checks / records / readmission run lagged
                    # inside the drain.
                    pipe.push(epoch, batch_idx, node_batch, metrics,
                              self.state)
                    dispatched = self.global_step
                    if timer is not None:
                        timer.lap("compute")  # dispatch only — no sync
                    pipe.drain()
                    ckpt_step = dispatched % \
                        self.config.checkpoint_interval == 0
                    if ckpt_step:
                        pipe.drain(0)  # mandatory full drain before a save
                    if timer is not None:
                        # Both drains land here: blocked-on-lagged-metrics
                        # time is the "host" phase even on save steps (the
                        # save itself is the "checkpoint" lap below).
                        timer.lap("host")
                    if ckpt_step:
                        # Save only when the frontier step survived the
                        # drain intact: a rollback moved the counter (and
                        # re-saving the checkpoint just restored would be
                        # pure waste), and a guard-rejected frontier step
                        # must not be enshrined as "verified".
                        if self.global_step == dispatched and \
                                pipe.last_rejected_step != dispatched:
                            self.save_checkpoint()
                    if timer is not None:
                        timer.lap("checkpoint")
                        if pipe.consume_rejection():
                            # Same contract as the synchronous path: a
                            # rejected step's wall time (rollback restore)
                            # would poison the phase distribution.
                            timer.discard_step()
                        else:
                            timer.finish_step(step=self.global_step)
                        self.obs.on_step(self.global_step)
                    continue

                # Synchronous path (async_host_depth=0): every step blocks
                # on the host pulls before the next dispatch.
                if self.step_guard is not None:
                    metrics = self.step_guard.after_step(self, node_batch,
                                                         metrics)
                    if metrics is None:
                        # Step rejected (non-finite / wedged) — possibly
                        # rolled back to a verified checkpoint (global_step
                        # restored by load_checkpoint).  Nothing to
                        # account.  A rejected step's wall time (retries,
                        # rollback restore) would poison the phase
                        # distribution — drop it.
                        if timer is not None:
                            timer.discard_step()
                        continue
                self.metrics_collector.tick()
                # tddl-lint: disable=host-sync — the sync path's ONE
                # deliberate pull; async_host_depth>0 takes the packed
                # D2H pipeline instead.
                loss = float(metrics.loss)  # host sync closes the step
                if timer is not None:
                    timer.lap("compute")  # dispatch + device step + sync
                self._record_batch(metrics, epoch, loss)
                self._maybe_readmit()
                if timer is not None:
                    timer.lap("detection")  # host verdicts/incidents
                epoch_loss += loss
                num_batches += 1

                if self.global_step % self.config.checkpoint_interval == 0:
                    self.save_checkpoint()
                if timer is not None:
                    timer.lap("checkpoint")
                    timer.finish_step(step=self.global_step)
                    self.obs.on_step(self.global_step)
                if batch_idx % 10 == 0:
                    logger.info("Epoch %d, Batch %d, Loss: %.4f",
                                epoch, batch_idx, loss)
        finally:
            if pipe is not None:
                # Mandatory full drain: epoch aggregation, the epoch-end
                # host sync below, and — on a preemption/supervisor unwind
                # — the save-on-signal all need a caught-up host view.
                pipe.drain(0)
                epoch_loss += pipe.epoch_loss
                num_batches += pipe.num_batches

        # Epoch-cadence host sync: reporting objects absorb device state.
        self.sync_host_state()
        self._epoch_intelligence()
        avg = epoch_loss / max(num_batches, 1)
        self.metrics_collector.collect_epoch_metrics({
            "epoch": epoch,
            "avg_loss": avg,
            "num_batches": num_batches,
            "system_trust": self.trust_manager.calculate_system_trust(),
        })
        logger.info("Epoch %d completed. Average loss: %.4f", epoch, avg)
        return avg

    def _epoch_intelligence(self) -> None:
        """Epoch-cadence host intelligence the reference defined but never
        called (SURVEY §7.5): adaptive trust thresholds
        (trust_manager.py:333-348) pushed back into the device state, and
        ML-detector refit + secondary verdicts (attack_detector.py:381-425)."""
        if self.config.adaptive_thresholds:
            self.trust_manager.adaptive_threshold_adjustment()
            threshold = jnp.asarray(
                self.trust_manager.trust_threshold, jnp.float32
            )
            if len(list(self.mesh.devices.flat)) > 1:
                # Same replicated placement as init/_place_on_mesh: a
                # bare jnp scalar is an UNCOMMITTED SingleDeviceSharding
                # leaf, which changes the jitted step's input signature
                # and silently recompiled the whole train step on the
                # first step of every post-adjustment epoch (caught by
                # the compile watcher's train_step guard).
                threshold = jax.device_put(
                    threshold, shreg.replicated_sharding(self.mesh)
                )
            self.state = self.state._replace(
                trust=self.state.trust._replace(threshold=threshold)
            )
        if self._ml_enabled:
            self.attack_detector.update_detection_models()
            self.ml_flags = {}
            for orig in self.node_map:
                features = self.attack_detector.latest_features(orig)
                if features:
                    self.ml_flags[orig] = self.attack_detector.detect_with_ml_models(
                        features, orig
                    )
            if any(self.ml_flags.values()):
                logger.warning(
                    "ML detectors flagged nodes: %s",
                    [n for n, v in self.ml_flags.items() if v],
                )

    def _record_batch(self, metrics: StepMetrics, epoch: int, loss: float
                      ) -> None:
        attacked = np.asarray(metrics.attacked)
        trust = np.asarray(metrics.trust_scores)
        id_of = self.node_map  # coordinate -> original node id
        if self.obs is not None:
            grad_norm = float(np.asarray(metrics.grad_norm))
            self.obs.trace.emit(
                EventType.TRAIN_STEP, step=self.global_step, epoch=epoch,
                loss=loss,
                grad_norm=grad_norm,
                system_trust=float(np.asarray(metrics.system_trust)),
            )
            if self.obs.anomaly is not None:
                # Anomaly watcher feed: only guard-ACCEPTED steps reach
                # this path, so the EWMA baseline is the healthy run —
                # drift/spikes that pass the (non-finite-only) guard
                # still flag here; NaNs reach the watcher through the
                # supervisor's guard-trip feed instead.
                self.obs.anomaly.observe("loss", loss,
                                         step=self.global_step)
                self.obs.anomaly.observe("grad_norm", grad_norm,
                                         step=self.global_step)
            # Trust-state transitions: emitted on CHANGE (keyed by
            # original identity), not per step — the trace stays joinable
            # on step id without carrying n gauges per row.
            status_now = np.asarray(metrics.status)
            prev = self._last_status
            if prev is not None and len(prev) == len(status_now):
                for coord in np.nonzero(status_now != prev)[0]:
                    self.obs.trace.emit(
                        EventType.TRUST_TRANSITION, step=self.global_step,
                        node=int(id_of[int(coord)]),
                        from_status=NodeStatus(int(prev[coord])).name,
                        to_status=NodeStatus(int(status_now[coord])).name,
                        trust=float(trust[int(coord)]),
                    )
            self._last_status = status_now.copy()
        self.metrics_collector.collect_batch_metrics(
            {
                "loss": loss,
                "step": self.global_step,
                "epoch": epoch,
                "trust_scores": {
                    id_of[i]: float(trust[i]) for i in range(len(trust))
                },
                # Model diagnostics (e.g. MoE capacity-drop fraction).
                # ``model_aux`` is a None sentinel when absent (mutable {}
                # NamedTuple defaults are a shared instance) — normalise.
                **{k: float(v)
                   for k, v in (getattr(metrics, "model_aux", None)
                                or {}).items()},
            }
        )
        # Feed the stat batteries into the host detector's history — the
        # training corpus for the epoch-cadence ML tier
        # (attack_detector.py:381-425, which the reference never called).
        if self._ml_enabled:
            out_stats = np.asarray(metrics.out_stats)
            grad_stats = np.asarray(metrics.grad_stats)
            for coord, orig in enumerate(id_of):
                # Output batteries carry 12 real stats + 5 zero pads
                # (shape-matched to the 17-stat gradient battery inside the
                # step); label only the real columns so the key set agrees
                # with the host detector's own output-history entries.
                self.attack_detector.output_history[orig].append(
                    {"stats": dict(zip(
                        TENSOR_STAT_NAMES,
                        out_stats[coord][:NUM_TENSOR_STATS],
                    ))}
                )
                self.attack_detector.gradient_history[orig].append(
                    {"stats": dict(zip(GRADIENT_STAT_NAMES, grad_stats[coord]))}
                )

        # Fleet-level norm-surge alarm (majority-attack backstop): the
        # in-step verdict is unattributed — with >= 50 % of the fleet
        # poisoning together the median itself lies, so no node is gated
        # or evicted; the episode is recorded for operator action and the
        # training-state machine flips to UNDER_ATTACK.
        fleet_alert = getattr(metrics, "fleet_alert", None)
        if fleet_alert is not None:
            if self._drain_ctx is not None:
                # Lagged drain: the live state is up to K steps ahead of
                # this record — use the streak packed with the step itself.
                streak = self._drain_ctx.fleet_streak
            else:
                streak = getattr(self.state, "fleet_raw_streak", None)
            streak = int(np.asarray(streak)[0]) if streak is not None else 0
            opened = self._fleet_tracker.update(
                bool(np.asarray(fleet_alert)), streak, self.global_step,
                extra={
                    "epoch": epoch,
                    "median_grad_norm": float(
                        np.median(np.asarray(metrics.grad_norm))
                    ),
                },
            )
            if opened is not None:
                logger.error(
                    "FLEET-LEVEL norm surge at step %d: the "
                    "cross-sectional median gradient norm departed "
                    "its own history — consistent with a "
                    "majority/coordinated attack the per-node gate "
                    "cannot attribute", self.global_step,
                )
                self.training_state = TrainingState.UNDER_ATTACK
                if self.obs is not None:
                    self.obs.trace.emit(
                        EventType.FLEET_ALERT, step=self.global_step,
                        median_grad_norm=opened.get("median_grad_norm"),
                    )

        # Host incidents fire only on confirmed evidence: debounced verdicts
        # (metrics.attacked already folds in sustained norm-verification
        # failures) or non-finite gradients.  A single-step statistical blip
        # is excluded from that step's aggregate in-step but is NOT an
        # incident.
        finite = np.asarray(metrics.finite)
        flagged = attacked | ~finite
        # Close incidents for nodes the device-side state machine has
        # rehabilitated, so a later re-attack records a fresh incident.
        # (Evicted nodes have no coordinate and stay closed-out forever.)
        status = np.asarray(metrics.status)
        coord_of = {orig: i for i, orig in enumerate(id_of)}
        for orig in list(self._open_incidents):
            coord = coord_of.get(orig)
            if coord is not None and not flagged[coord] and status[
                coord
            ] != int(NodeStatus.COMPROMISED):
                self._open_incidents.discard(orig)
        evict_coords: List[int] = []
        if flagged.any():
            types = np.asarray(metrics.attack_type)
            # All nodes flagged THIS step are unfit reassignment targets,
            # even before their own incident is processed (nodes 1 and 3
            # confirmed in the same step must not be handed each other's
            # shards).
            flagged_ids = {id_of[int(c)] for c in np.nonzero(flagged)[0]}
            for coord in np.nonzero(flagged)[0]:
                orig = id_of[int(coord)]
                if orig in self._open_incidents:
                    continue
                self._open_incidents.add(orig)
                self._handle_detected_attack(
                    orig,
                    attack_type=AttackType(int(types[coord])).label
                    if attacked[coord] else "gradient_verification_failure",
                    metrics=metrics,
                    coord=int(coord),
                    exclude=flagged_ids,
                )
                evict_coords.append(int(coord))
        if self._drain_ctx is not None:
            # Lagged drain (async pipeline): resharding mid-window would
            # orphan the in-flight entries' packed metrics (their node
            # count predates the surgery) — collect the coordinates and
            # let the pipeline apply them at the frontier after its
            # mandatory full drain.
            self._drain_ctx.evict_coords.update(evict_coords)
        else:
            self._apply_evictions(evict_coords)

    def _apply_evictions(self, evict_coords: Sequence[int]) -> None:
        """Elastic reaction to confirmed compromises: evict the flagged
        mesh coordinates and reshard (or restaff the pipeline).  Split out
        of ``_record_batch`` so the async drain can defer it to a
        full-drain point; the synchronous path calls it immediately with
        identical semantics."""
        evict_coords = list(evict_coords)
        if (evict_coords and self.config.elastic_resharding
                and len(evict_coords) < self.config.num_nodes):
            from trustworthy_dl_tpu.elastic.reassignment import (
                elastic_supported,
                evict_and_reshard,
            )

            evict_record = None
            if elastic_supported(self.config):
                record = evict_record = evict_and_reshard(self,
                                                          evict_coords)
                record["step"] = self.global_step
                self.reassignment_history.append(record)
                for orig in record["evicted_nodes"]:
                    self._evicted_at[int(orig)] = self.global_step
                self._resize_loader()
            elif self.config.parallelism == "model":
                # Model-parallel restaff: the compromised stage's layer
                # shard migrates to trusted hardware and the model
                # repartitions — ALL layers keep training
                # (elastic/restaff.py), not the freeze+relabel the
                # reference ships.
                from trustworthy_dl_tpu.elastic.restaff import (
                    restaff_pipeline,
                )

                record = evict_record = restaff_pipeline(self,
                                                         evict_coords)
                record["step"] = self.global_step
                self.reassignment_history.append(record)
                for orig in record["evicted_nodes"]:
                    # Start the cool-off clock: a cooled-off stage
                    # identity re-enters the restaff candidate pool
                    # (_maybe_readmit).
                    self._evicted_at[int(orig)] = self.global_step
            if evict_record is not None and self.obs is not None:
                self.obs.trace.emit(
                    EventType.ELASTIC_EVICT, step=self.global_step,
                    nodes=[int(n) for n in evict_record["evicted_nodes"]],
                    live_nodes=self.config.num_nodes,
                )

    def _readmit_due(self) -> bool:
        """Cheap predicate: would ``_maybe_readmit`` act right now?  The
        async drain polls this to decide when a readmission (a topology
        change) forces a mandatory full drain — without paying the import
        and record machinery on every step."""
        cfg = self.config
        if not (cfg.elastic_resharding and cfg.readmit_after_steps > 0
                and self._evicted_at):
            return False
        return any(self.global_step - when >= cfg.readmit_after_steps
                   for when in self._evicted_at.values())

    def _maybe_readmit(self) -> None:
        """Re-admit evicted coordinates whose cool-off has elapsed
        (config.readmit_after_steps) — the elastic counterpart of the
        in-step probation: without it a false-positive eviction costs 1/n
        of the fleet for the rest of the run.  Mode-agnostic like the
        reference's recovery ladder (trust_manager.py:198-206):
        data/tensor/sequence restore the coordinate (and its device
        group); model mode returns the identity to the restaff candidate
        pool and regrows the stage count when the arithmetic allows."""
        cfg = self.config
        if not (cfg.elastic_resharding and cfg.readmit_after_steps > 0
                and self._evicted_at):
            return
        due = sorted(
            nid for nid, when in self._evicted_at.items()
            if self.global_step - when >= cfg.readmit_after_steps
        )
        if not due:
            return
        from trustworthy_dl_tpu.elastic.reassignment import (
            elastic_supported,
            readmit_and_reshard,
        )

        if elastic_supported(cfg):
            record = readmit_and_reshard(self, due)
            record["step"] = self.global_step
            self.reassignment_history.append(record)
            self._resize_loader()
        elif cfg.parallelism == "model":
            self._readmit_stages(due)
        if self.obs is not None:
            self.obs.trace.emit(
                EventType.ELASTIC_READMIT, step=self.global_step,
                nodes=[int(n) for n in due],
                live_nodes=self.config.num_nodes,
            )

    def _readmit_stages(self, due: Sequence[int]) -> None:
        """Model-mode return path: cooled-off evicted stage identities
        re-enter the restaff candidate pool on probation (RECOVERING with
        the 0.5 readmission trust floor), and an immediate restaff
        re-expands S' -> S when the layer arithmetic allows; otherwise the
        identity waits in the idle pool for the next restaff."""
        from trustworthy_dl_tpu.elastic.restaff import (
            choose_stage_count,
            restaff_pipeline,
        )

        for nid in due:
            self._idle_pool[nid] = self._evicted_devices.pop(nid, []) or []
            self._evicted_at.pop(nid, None)
            self._open_incidents.discard(nid)
            self.trust_manager.begin_probation(nid)
        blocks = self.state.params["blocks"]
        lead = jax.tree_util.tree_leaves(blocks)[0]
        num_layers = lead.shape[0] * lead.shape[1]
        grown = choose_stage_count(
            num_layers, self.config.num_nodes + len(self._idle_pool)
        )
        if grown > self.config.num_nodes:
            record = restaff_pipeline(self, [])
            record["step"] = self.global_step
            self.reassignment_history.append(record)

    def _resize_loader(self) -> None:
        """Re-size the live data pipeline after a topology change so batch
        sizes divide nodes × accum again — without this, every post-change
        batch is trimmed and silently drops the same samples' worth of data
        each step.  Works on any loader exposing a ``batch_size``
        attribute (all bundled loaders); foreign loaders keep the trimming
        fallback with its warning."""
        import dataclasses

        loader = self._active_loader
        if loader is None or self._per_node_batch is None or \
                self.config.parallelism == "model":
            return
        accum = max(self.config.grad_accum_steps, 1)
        new_bs = self._per_node_batch * self.config.num_nodes * accum
        target = loader.loader if isinstance(loader, PrefetchLoader) else loader
        if hasattr(target, "batch_size") and target.batch_size != new_bs:
            logger.info(
                "Loader re-sized for new topology: batch %d -> %d "
                "(%d nodes x %d/node x %d accum)", target.batch_size,
                new_bs, self.config.num_nodes, self._per_node_batch, accum,
            )
            target.batch_size = new_bs
            self.config = dataclasses.replace(self.config,
                                              batch_size=new_bs)
            # A few old-size batches may already sit in the prefetch queue
            # (and the current epoch of an epoch-partitioned loader keeps
            # its size until re-iterated): tolerate that transition without
            # tripping the persistent-trim warning.
            self._warned_trim = False
            self._trimmed_sizes.clear()
            self._trim_grace = max(self.config.prefetch_depth, 1) + 1

    def _handle_detected_attack(self, node_id: int, attack_type: str,
                                metrics: StepMetrics,
                                coord: Optional[int] = None,
                                exclude: Optional[set] = None) -> None:
        """Host-side reaction (distributed_trainer.py:273-322): record the
        incident, mirror compromise into the host TrustManager, trigger
        reassignment.  The in-step mitigation (grad gating) already happened
        on device in the same step.  ``node_id`` is the ORIGINAL id;
        ``coord`` its current mesh coordinate (equal until eviction)."""
        coord = node_id if coord is None else coord
        logger.error("Attack detected on node %d (%s)", node_id, attack_type)
        # Ground-truth accounting: the injection plan knows whether this
        # node was actually under attack this step, so the host detector's
        # TP/FP counters report reality (the reference initialised them and
        # never incremented either — its rates were always 0.0).
        plan = self.attack_plan
        mask = np.asarray(plan.target_mask)
        live = bool(plan.active) and (self.global_step - 1) >= int(
            plan.start_step
        )
        is_tp = live and coord < len(mask) and bool(mask[coord])
        ds = self.attack_detector.detection_stats
        ds["total_detections"] += 1
        ds["attack_types"][attack_type] += 1
        ds["true_positives" if is_tp else "false_positives"] += 1
        if self.obs is not None:
            self.obs.trace.emit(
                EventType.DETECTION_VERDICT, step=self.global_step,
                node=int(node_id), attack_type=attack_type,
                ground_truth_positive=is_tp,
                out_score=float(np.asarray(metrics.out_score)[coord]),
                grad_score=float(np.asarray(metrics.grad_score)[coord]),
            )
        self.attack_history.append(
            {
                "node_id": node_id,
                "timestamp": time.time(),
                "step": self.global_step,
                "attack_type": attack_type,
                "output_stats": {
                    "anomaly_score": float(np.asarray(metrics.out_score)[coord]),
                    "gradient_score": float(np.asarray(metrics.grad_score)[coord]),
                },
            }
        )
        self.trust_manager.mark_compromised(node_id, attack_type)
        from trustworthy_dl_tpu.elastic.reassignment import (
            elastic_supported,
        )

        if not (self.config.elastic_resharding
                and (elastic_supported(self.config)
                     or self.config.parallelism == "model")):
            # Legacy greedy handoff (relabel) — elastic mode replaces it
            # with the real group eviction (ELASTIC_MODES) or stage
            # restaff (model) in _record_batch.
            self.reassign_node_tasks(node_id, exclude=exclude)
        self.training_state = TrainingState.UNDER_ATTACK

    # ------------------------------------------------------------------
    # Reassignment (distributed_trainer.py:324-380)
    # ------------------------------------------------------------------

    def reassign_node_tasks(self, compromised_node_id: int,
                            exclude: Optional[set] = None) -> None:
        unfit = set(exclude or ()) | {compromised_node_id}
        trusted = self.trust_manager.get_trusted_nodes()
        trusted = [n for n in trusted if n not in unfit]
        if not trusted:
            logger.error("No trusted nodes available for reassignment")
            return
        best = max(trusted, key=self.trust_manager.get_trust_score)
        migration_time = self.estimate_migration_time(compromised_node_id, best)
        self.perform_task_reassignment(compromised_node_id, best)
        self.reassignment_history.append(
            {
                "from_node": compromised_node_id,
                "to_node": best,
                "timestamp": time.time(),
                "migration_time": migration_time,
                "step": self.global_step,
            }
        )

    def estimate_migration_time(self, source_node: int, target_node: int
                                ) -> float:
        """Migration model (distributed_trainer.py:354-365): bytes / rate +
        setup.  The reference hardcodes 1 GB/s + 2 s — on TPU the transfer
        rides ICI, so the rate is configurable via ``migration_gbps`` (the
        elastic subsystem measures it; see elastic/reassignment.py)."""
        if self.state is None:
            return 2.0
        n_params = self.model.num_params(self.state.params)
        # In data-parallel the migrating unit is the node's optimizer+param
        # replica share; in stage parallel it is the stage slice.
        shard = n_params / max(self.config.num_nodes, 1)
        transfer = shard * 4 / (self.config.migration_gbps * 1024**3)
        return transfer + 2.0

    def perform_task_reassignment(self, source_node: int, target_node: int
                                  ) -> None:
        """In SPMD data-parallel the compromised node's contribution is
        already zero-weighted inside the step (the immediate mitigation,
        SURVEY §5.3); reassignment relabels the shard ownership so the
        recovered data shard flows to the target node.  Real device-set
        resharding lives in elastic/reassignment.py."""
        self.node_configs[target_node].model_partition = (
            f"shard_{source_node}+{self.node_configs[target_node].model_partition}"
        )
        logger.info("Task reassignment completed: %d -> %d",
                    source_node, target_node)

    # ------------------------------------------------------------------
    # Epochs / validation / stats
    # ------------------------------------------------------------------

    def train(self, train_dataloader, val_dataloader=None,
              num_epochs: Optional[int] = None) -> Dict[str, Any]:
        if num_epochs is None:
            num_epochs = self.config.num_epochs
        logger.info("Starting training for %d epochs", num_epochs)
        if self.state is None:
            self.initialize()
        self.training_state = TrainingState.TRAINING
        history = []
        with trace(self.config.profile_dir):
            for epoch in range(num_epochs):
                avg_loss = self.train_epoch(train_dataloader, epoch)
                record = {"epoch": epoch, "train_loss": avg_loss}
                if val_dataloader is not None:
                    val = self.validate(val_dataloader)
                    record.update(val_loss=val)
                    logger.info("Validation loss: %.4f", val)
                if self.training_state == TrainingState.UNDER_ATTACK:
                    logger.info(
                        "Training under attack - implementing recovery measures"
                    )
                    self.training_state = TrainingState.RECOVERING
                history.append(record)
        self.training_state = TrainingState.COMPLETED
        logger.info("Training completed successfully")
        return {"epochs": history, "stats": self.get_training_stats()}

    def validate(self, val_dataloader) -> float:
        """Mean validation loss (reference signature,
        distributed_trainer.py:494-508)."""
        return self.validate_metrics(val_dataloader)["loss"]

    def validate_metrics(self, val_dataloader) -> Dict[str, float]:
        """Full validation metrics: loss, accuracy, and (for LMs)
        perplexity — the eval step already computes them; the reference
        only surfaced loss."""
        total, acc, examples = 0.0, 0.0, 0
        for batch in val_dataloader:
            # Node-split + 'data'-axis sharding exactly like training
            # (model mode trims to a microbatch multiple instead), so on
            # an n-chip mesh each chip evaluates 1/n of the batch rather
            # than replicating the whole thing.
            batch = self._node_batch(batch, for_eval=True)
            if batch is None:  # empty / stale batch
                continue
            out = self._eval_step(self.state.params, batch)
            # Example-weighted mean: a ragged tail batch must count by
            # its size, not as a full batch.
            first = next(iter(batch.values()))
            # Model mode feeds the global batch [B, ...]; other modes the
            # node split [rows, per_row, ...].
            weight = int(first.shape[0]) if \
                self.config.parallelism == "model" else \
                int(first.shape[0] * first.shape[1])
            total += float(out["loss"]) * weight
            acc += float(out["accuracy"]) * weight
            examples += weight
        n = max(examples, 1)
        metrics = {"loss": total / n, "accuracy": acc / n}
        if self.model.kind == "lm":
            metrics["perplexity"] = float(np.exp(min(metrics["loss"], 30.0)))
        return metrics

    def sync_host_state(self) -> None:
        """Epoch-cadence absorption of device state into the host reporting
        objects (TrustManager / NodeMonitor).  After elastic eviction the
        device arrays cover only surviving coordinates; ``node_map``
        routes them to their original host ids."""
        if self.state is None:
            return
        self.trust_manager.sync_from_device(self.state.trust,
                                            node_ids=self.node_map)
        self.node_monitor.sync_from_device(self.state.monitor,
                                           node_ids=self.node_map)

    def get_training_stats(self) -> Dict[str, Any]:
        """distributed_trainer.py:510-521."""
        return {
            "current_epoch": self.current_epoch,
            "global_step": self.global_step,
            "training_state": self.training_state.value,
            "trust_scores": {
                i: self.trust_manager.get_trust_score(i)
                for i in range(self.config.num_nodes)
            },
            "attack_count": len(self.attack_history),
            "reassignment_count": len(self.reassignment_history),
            "fleet_alert_count": len(self.fleet_alerts),
            "metrics": self.metrics_collector.get_summary(),
            "trust_threshold": self.trust_manager.trust_threshold,
            "ml_flags": dict(self.ml_flags),
            "predicted_reliability": {
                i: self.trust_manager.predict_node_reliability(i)
                for i in range(self.config.num_nodes)
            },
        }

    # ------------------------------------------------------------------
    # Checkpointing (distributed_trainer.py:448-463 + restore, new)
    # ------------------------------------------------------------------

    def save_checkpoint(self) -> Optional[str]:
        if self.state is None:
            return None
        # Never persist non-finite params over the last good checkpoint:
        # "verified" means integrity-verified AND taken from sane state.
        # Without this gate, corruption landing exactly on a save step
        # would poison the rollback target itself — the supervisor would
        # then restore NaN state forever while reporting recovery.  Cost
        # is one reduction per param leaf at save cadence, not per step.
        finite = all(
            bool(jnp.all(jnp.isfinite(leaf)))
            for leaf in jax.tree_util.tree_leaves(self.state.params)
            if jnp.issubdtype(leaf.dtype, jnp.floating)
        )
        if not finite:
            logger.error(
                "Refusing to checkpoint non-finite params at step %d; "
                "keeping the last good checkpoint", self.global_step,
            )
            return None
        # Sidecar and payload must stay in sync: CheckpointManager.save
        # skips an existing COMMITTED step directory, so a pre-existing
        # payload (a reused checkpoint_dir) must not get its topology
        # overwritten — but uncommitted junk from a crashed save IS
        # rewritten by save(), so its sidecar must be rewritten with it.
        already = self.checkpointer.check_integrity(
            self.global_step, verify=False
        )[0]
        path = self.checkpointer.save(
            self.state, self.global_step,
            block=not self.config.async_checkpoint,
        )
        if self.obs is not None:
            self.obs.trace.emit(EventType.CKPT_SAVE, step=self.global_step,
                                path=path,
                                blocking=not self.config.async_checkpoint)
        if already:
            logger.warning(
                "Checkpoint step %d already existed; keeping its sidecar "
                "(payload was not rewritten)", self.global_step,
            )
            return path
        # Topology sidecar: after elastic eviction the live node count and
        # coordinate->identity map differ from the constructor config; a
        # resume needs them BEFORE it can shape the restore template.
        self.checkpointer.save_metadata(self.global_step, {
            "num_nodes": self.config.num_nodes,
            "node_map": list(self.node_map),
            "parallelism": self.config.parallelism,
            # The live mesh's device ids: after an eviction the mesh is NOT
            # "the first n devices" (the evicted chip is missing from the
            # middle), and a resume that guessed would collide with the
            # evicted device on readmission.
            "mesh_devices": [d.id for d in self.mesh.devices.flat],
            # Evicted identities have no device row anymore; their
            # compromised standing must survive the resume on the host.
            "compromised_nodes": sorted(
                int(i) for i in self.trust_manager.get_compromised_nodes()
            ),
            # Elastic bookkeeping: a pending readmission cool-off and
            # idle-pool identities must survive a resume — without them an
            # eviction silently becomes permanent despite
            # readmit_after_steps>0, and parked restaff survivors can never
            # re-enter.  Devices persist by id and re-resolve on the
            # resumed host.
            "evicted_at": {
                str(nid): int(step)
                for nid, step in self._evicted_at.items()
            },
            "evicted_devices": {
                str(nid): [d.id for d in (devs or [])]
                for nid, devs in self._evicted_devices.items()
            },
            "idle_pool": {
                str(nid): [d.id for d in devs]
                for nid, devs in self._idle_pool.items()
            },
        })
        return path

    def _adopt_topology(self, meta: Dict[str, Any]) -> None:
        """Rebuild mesh/step/template for a checkpoint saved on a different
        (post-eviction) node count — SURVEY §5.4's resume requirement."""
        import dataclasses

        from trustworthy_dl_tpu.elastic.reassignment import ELASTIC_MODES

        if self.config.parallelism not in ELASTIC_MODES + ("model",):
            raise NotImplementedError(
                "post-eviction resume onto a different node count is only "
                "defined for the modes eviction itself supports "
                "(elastic/reassignment.py ELASTIC_MODES + "
                "elastic/restaff.py)"
            )
        n = int(meta["num_nodes"])
        logger.info(
            "Checkpoint topology has %d node(s) (config says %d): adopting "
            "the saved topology for resume", n, self.config.num_nodes,
        )
        from trustworthy_dl_tpu.elastic.reassignment import (
            _check_hybrid_elastic,
            elastic_mesh_shape,
        )

        self.config = dataclasses.replace(
            self.config, num_nodes=n,
            mesh_shape=elastic_mesh_shape(self.config, n),
        )
        if self.config.parallelism == "hybrid":
            # Only elastic-eligible hybrid layouts can have produced a
            # different-topology checkpoint; a multi-slice/stage hybrid
            # must fail loudly here rather than silently rebuild a
            # single-slice mesh without its DCN extents.
            _check_hybrid_elastic(self.config)
        # Rebuild the SAVED device set when the sidecar has it: post-
        # eviction the live mesh is missing a chip from the middle, and a
        # first-n guess would seat the evicted device twice once it is
        # readmitted.
        devices = None
        ids = meta.get("mesh_devices")
        if ids is not None:
            by_id = {d.id: d for d in jax.devices()}
            devs = [by_id[i] for i in ids if i in by_id]
            if len(devs) == len(ids):
                devices = devs
        self.mesh = build_mesh(n, self.config.parallelism,
                               self.config.mesh_shape, devices=devices,
                               dcn_mesh_shape=self.config.dcn_mesh_shape)
        bind_mode_mesh(self.mesh, self.config.parallelism)
        if self.config.parallelism == "model":
            from trustworthy_dl_tpu.parallel.pipeline import (
                build_pipeline_eval_step,
                build_pipeline_train_step,
            )

            self._train_step = jax.jit(
                build_pipeline_train_step(self.model, self.config,
                                          self.optimizer, self.mesh),
                donate_argnums=(0,),
            )
            self._eval_step = jax.jit(
                build_pipeline_eval_step(self.model, self.config, self.mesh)
            )
        else:
            self._train_step = jax.jit(
                build_train_step(self.model, self.config, self.optimizer),
                donate_argnums=(0,),
            )
            self._eval_step = jax.jit(build_node_eval_step(self.model))
        self.node_map = [int(i) for i in meta["node_map"]]
        # Any attack plan was shaped for the constructor's node count;
        # injection targets are per-run anyway — reset, caller re-plans.
        # Placed on the rebuilt mesh here (initialize() would re-place it
        # too, but the invariant "attack_plan is always mesh-committed"
        # must not depend on which caller runs next).
        self.attack_plan = self._place_plan(null_plan(n))
        self.state = None  # template must be rebuilt with the new shapes
        if self.obs is not None and \
                getattr(self.obs, "compilewatch", None) is not None:
            # The step was legitimately rebuilt for the new topology —
            # its next compile is warmup, not a storm.
            self.obs.compilewatch.reset("train_step")

    def load_checkpoint(self, step: Optional[int] = None) -> TrainState:
        """Restore the full world-view — weights AND trust state — then
        mirror into the host objects.  A checkpoint written after elastic
        eviction (fewer live nodes than the constructor config) restores
        onto the saved topology."""
        if step is None:
            step = self.checkpointer.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.config.checkpoint_dir}"
                )
        meta = self.checkpointer.load_metadata(step)
        if meta and int(meta["num_nodes"]) != self.config.num_nodes:
            self._adopt_topology(meta)
        if self.state is None:
            self.initialize()
        self.state = self.checkpointer.restore(self.state, step)
        # Two resume hazards fixed here, in order:
        # 1. Ownership: on CPU-backed platforms the checkpoint reader can
        #    hand back arrays that zero-copy alias ITS host memory, and
        #    the train step's donate_argnums would then free buffers XLA
        #    does not own (observed as intermittent heap corruption a few
        #    dozen donated steps after any resume).  The eager copy
        #    re-homes every leaf into runtime-owned buffers.
        # 2. Placement: a leaf the host replaced mid-run with an
        #    uncommitted array (e.g. _epoch_intelligence's threshold
        #    push-back) restores COMMITTED to device 0, and the next step
        #    would refuse to mix it with mesh-committed params —
        #    _place_on_mesh re-pins everything exactly like initialize().
        self.state = self._place_on_mesh(
            jax.tree_util.tree_map(jnp.copy, self.state)
        )
        if meta:
            self.node_map = [int(i) for i in meta["node_map"]]
            # Original ids can exceed the constructor's node count (e.g. a
            # fresh trainer built with the post-eviction live count): grow
            # the host bookkeeping so no live identity is silently dropped
            # by the sync scatter's bounds filter.
            max_id = max(
                self.node_map + [int(i) for i in
                                 meta.get("compromised_nodes", [])],
                default=-1,
            )
            if max_id >= self.trust_manager.num_nodes:
                self.trust_manager.initialize_node(max_id)
            live = set(self.node_map)
            for node_id in meta.get("compromised_nodes", []):
                node_id = int(node_id)
                if node_id not in live and (
                    self.trust_manager.get_node_status(node_id)
                    != NodeStatus.COMPROMISED
                ):
                    # Evicted before the save: no device row to sync from,
                    # so restore the host-side standing directly (once —
                    # repeated restores must not duplicate attack records).
                    self.trust_manager.mark_compromised(
                        node_id, attack_type="restored_from_checkpoint"
                    )
            # Rehydrate elastic bookkeeping so pending readmission
            # cool-offs and parked idle-pool identities survive the resume
            # (devices re-resolve by id; one lost to a host change degrades
            # to the dev-mode no-device path rather than dropping the
            # identity).
            by_id = {d.id: d for d in jax.devices()}
            self._evicted_at = {
                int(k): int(v)
                for k, v in meta.get("evicted_at", {}).items()
            }
            self._evicted_devices = {
                int(k): [by_id[i] for i in ids if i in by_id]
                for k, ids in meta.get("evicted_devices", {}).items()
            }
            self._idle_pool = {
                int(k): [by_id[i] for i in ids if i in by_id]
                for k, ids in meta.get("idle_pool", {}).items()
            }
        self.global_step = int(self.state.step)
        # A restore redraws the fleet's status rows; transition tracking
        # must re-anchor or the first post-resume step emits bogus diffs.
        self._last_status = None
        if self.obs is not None:
            self.obs.trace.emit(EventType.CKPT_RESTORE, step=step,
                                restored_step=self.global_step)
        self.sync_host_state()
        return self.state

    def cleanup(self) -> None:
        """distributed_trainer.py:523-527."""
        self.checkpointer.wait()  # join any in-flight async save
        self.metrics_collector.close()  # flush + release the TB writer
        self.state = None
        logger.info("Distributed training cleanup completed")

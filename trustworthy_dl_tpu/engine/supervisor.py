"""Self-healing training supervisor — keeps a run alive through the
failures the trust stack does not cover.

The in-step security machinery neutralises a *lying node* (trust-gated
aggregation, per-node finite gate), but the trainer itself still dies — or
silently wedges — on systemic faults: fleet-wide non-finite state (lr
blow-up, corrupted params/optimizer), preempted hosts, truncated
checkpoints.  The supervisor wraps ``DistributedTrainer`` with the recovery
ladder production systems use (Gemini SOSP '23, Bamboo NSDI '23):

1. **step guard** — after every step, reject it if the aggregate loss or
   gradient norm is non-finite, or if *no* node produced finite gradients
   (the in-step gate then froze the params, so the reported masked loss of
   0.0 would otherwise look healthy while the run is wedged);
2. **bounded retries** — re-run the same batch up to ``max_retries`` times
   with exponential backoff (transient faults clear; persistent state
   corruption does not);
3. **verified-checkpoint rollback** — after ``rollback_after`` consecutive
   bad steps, restore the latest checkpoint that passes its integrity
   manifest (``CheckpointManager`` walks past corrupt/uncommitted saves)
   and continue;
4. **preemption handling** — a preemption signal (real SIGTERM or a chaos
   ``SimulatedPreemption``) triggers save-on-signal and a capped
   auto-resume restart loop.

The guard only accepts steps, so periodic checkpoints are written from
healthy state — "verified" means integrity-verified AND
taken-while-training-was-sane.  Wire a ``chaos.FaultInjector`` through the
constructor to drill the whole ladder deterministically
(``examples/chaos_drill.py``).

Async drain contract: under the trainer's async host pipeline
(``TrainingConfig.async_host_depth`` > 0, engine/async_host.py) the
guard runs LAGGED — ``after_step(..., lagged=True)`` arrives up to K
steps after the step executed, with ``trainer.state`` already at the
dispatch frontier.  Rung 2 (in-place retries) is skipped in that mode
and a rollback lands on a checkpoint that predates the whole in-flight
window (saves force a full drain, so every verified checkpoint covers a
guard-accepted prefix).  Drills asserting ``FaultPlan.predict``'s exact
retry counts must run at depth 0.
"""

from __future__ import annotations

import logging
import math
import time
from typing import Any, Dict, List, Optional

import numpy as np

from trustworthy_dl_tpu.chaos.injector import FaultInjector, \
    SimulatedPreemption
from trustworthy_dl_tpu.engine.step import StepMetrics
from trustworthy_dl_tpu.engine.trainer import DistributedTrainer, \
    TrainingState
from trustworthy_dl_tpu.obs.events import EventType
from trustworthy_dl_tpu.obs.registry import get_registry

logger = logging.getLogger(__name__)


class PreemptionSignal(Exception):
    """Raised inside the step loop when a real termination signal
    (SIGTERM) arrived — same recovery path as a simulated preemption."""


class TrainingSupervisor:
    """Wraps a :class:`DistributedTrainer` with the skip/retry/rollback/
    restart ladder.  Construction attaches the supervisor as the trainer's
    ``step_guard`` (and wires ``chaos`` into the trainer and its
    checkpointer); drive training through :meth:`run`.

    ``backoff_base_s`` is the first retry's sleep (doubled per attempt);
    0 disables sleeping, which is what drills and tests want.
    ``handle_signals=True`` installs a SIGTERM handler (main thread only)
    so a real preemption notice takes the save-on-signal path.

    ``obs`` optionally threads an :class:`obs.ObsSession` through the
    whole recovery ladder: every guard trip / retry / rollback / restart
    is emitted as a trace event, recovery counters land in the metrics
    registry, and the flight recorder is dumped NEXT TO THE CHECKPOINTS
    on rollback, guard trip and preemption — the post-mortem artifact a
    recovery claim is checked against.  Construction also calls
    ``trainer.attach_obs(obs)`` so trainer- and supervisor-side events
    share one trace.
    """

    def __init__(self, trainer: DistributedTrainer, *,
                 max_retries: int = 2, rollback_after: int = 3,
                 max_restarts: int = 3, backoff_base_s: float = 0.0,
                 chaos: Optional[FaultInjector] = None,
                 handle_signals: bool = False,
                 obs: Any = None):
        if max_retries < 0 or rollback_after < 1 or max_restarts < 0:
            raise ValueError(
                "max_retries >= 0, rollback_after >= 1, max_restarts >= 0"
            )
        self.trainer = trainer
        self.max_retries = max_retries
        self.rollback_after = rollback_after
        self.max_restarts = max_restarts
        self.backoff_base_s = backoff_base_s
        self.chaos = chaos
        self.handle_signals = handle_signals
        self.obs = obs

        self.retries = 0
        self.rollbacks = 0
        self.rollback_steps: List[int] = []
        self.restarts = 0
        self.preemptions = 0
        self.bad_steps = 0
        self._bad_streak = 0
        self._preempt_flag = False
        self._old_handler: Any = None

        # Recovery counters live in the process-wide registry whether or
        # not a full ObsSession is attached — one export surface for the
        # numbers report() also returns.
        registry = obs.registry if obs is not None else get_registry()
        self._counters = registry.counter(
            "tddl_supervisor_actions_total",
            "Supervisor recovery-ladder actions, by action",
            labels=("action",),
        )

        trainer.step_guard = self
        if chaos is not None:
            trainer.chaos = chaos
            trainer.checkpointer.chaos = chaos
        if obs is not None:
            trainer.attach_obs(obs)
            if chaos is not None:
                chaos.trace = obs.trace

    # -- step guard --------------------------------------------------------

    @staticmethod
    def _is_bad(metrics: StepMetrics) -> bool:
        """A step the run must not build on: non-finite aggregate loss or
        gradient norm, or a fleet with zero finite-gradient nodes.  The
        last case matters because the in-step gate masks the reported loss
        to 0.0 when every node is excluded — finite, but the params froze
        and (with corrupted state) will never unfreeze on their own."""
        loss = float(np.asarray(metrics.loss))
        grad_norm = float(np.asarray(metrics.grad_norm))
        if not math.isfinite(loss) or not math.isfinite(grad_norm):
            return True
        finite = np.asarray(metrics.finite)
        return bool(finite.size) and not bool(finite.any())

    #: The async drain (engine/async_host.py) checks this attribute to
    #: know it may pass ``lagged=True`` — duck-typed guards without it
    #: keep receiving the original three-argument call.
    lagged_aware = True

    def after_step(self, trainer: DistributedTrainer, node_batch: Any,
                   metrics: StepMetrics, lagged: bool = False
                   ) -> Optional[StepMetrics]:
        """Trainer step-guard hook.  Returns the metrics the trainer should
        account, or None when the step was rejected (and possibly rolled
        back — ``trainer.global_step`` then already points at the restored
        step).

        ``lagged=True`` is the async-pipeline drain contract
        (``TrainingConfig.async_host_depth`` > 0): the verdict arrives up
        to K steps after the step ran, with ``trainer.state`` already at
        the dispatch frontier.  In that mode the in-place retry rung is
        SKIPPED — re-running a K-step-old batch against the frontier state
        is not the same computation, and with corrupted state it would
        only burn the retry budget — so a bad lagged step counts
        immediately toward the rollback streak.  The rollback target is
        still sound: checkpoint saves force a full drain first, so the
        newest verified checkpoint always predates the in-flight window
        (the K-step rollback caveat — README §Performance).  Deterministic
        drills asserting ``FaultPlan.predict``'s exact retry counts must
        therefore run at depth 0."""
        if self._preempt_flag:
            self._preempt_flag = False
            raise PreemptionSignal("SIGTERM received")
        if not self._is_bad(metrics):
            self._bad_streak = 0
            return metrics
        retries = 0 if lagged else self.max_retries
        logger.warning(
            "Supervisor: bad step %d (loss=%s, grad_norm=%s, "
            "finite_nodes=%d/%d)%s — retrying up to %d time(s)",
            trainer.global_step, float(np.asarray(metrics.loss)),
            float(np.asarray(metrics.grad_norm)),
            int(np.asarray(metrics.finite).sum()),
            int(np.asarray(metrics.finite).size),
            " [lagged verdict: in-place retries skipped]" if lagged else "",
            retries,
        )
        if self.obs is not None:
            self.obs.trace.emit(
                EventType.GUARD_TRIP, step=trainer.global_step,
                loss=float(np.asarray(metrics.loss)),
                grad_norm=float(np.asarray(metrics.grad_norm)),
                finite_nodes=int(np.asarray(metrics.finite).sum()),
            )
            if getattr(self.obs, "anomaly", None) is not None:
                # Rejected steps never reach the trainer's accepted-step
                # feed — route the bad observations (NaN loss IS the
                # anomaly) to the watcher here so the incident flips
                # tddl_anomaly_active and dumps the flight recorder.
                self.obs.anomaly.observe(
                    "loss", float(np.asarray(metrics.loss)),
                    step=trainer.global_step,
                )
                self.obs.anomaly.observe(
                    "grad_norm", float(np.asarray(metrics.grad_norm)),
                    step=trainer.global_step,
                )
        self._counters.inc(action="guard_trip")
        for attempt in range(retries):
            self.retries += 1
            self._counters.inc(action="retry")
            if self.obs is not None:
                self.obs.trace.emit(EventType.SUPERVISOR_RETRY,
                                    step=trainer.global_step,
                                    attempt=attempt + 1)
            if self.backoff_base_s > 0:
                time.sleep(self.backoff_base_s * (2 ** attempt))
            trainer.state, metrics = trainer._train_step(
                trainer.state, node_batch, trainer.attack_plan
            )
            if not self._is_bad(metrics):
                logger.info("Supervisor: retry %d recovered step %d",
                            attempt + 1, trainer.global_step)
                self._bad_streak = 0
                return metrics
        self.bad_steps += 1
        self._bad_streak += 1
        self._counters.inc(action="bad_step")
        if self.obs is not None and self._bad_streak == 1:
            # One dump per incident (the streak's first definitively-bad
            # step), not per bad step — bounded post-mortems; the
            # rollback, if it comes, writes its own.
            self.obs.dump_flight(
                "guard_trip", step=trainer.global_step,
                directory=trainer.config.checkpoint_dir,
            )
        if self._bad_streak >= self.rollback_after:
            self._rollback(trainer)
        return None

    def _rollback(self, trainer: DistributedTrainer) -> None:
        """Restore the newest restorable checkpoint and clear the bad
        streak.  Walks the verified candidates newest-first: integrity
        manifests catch bit-rot, but a checkpoint can still fail to
        deserialize (legacy/unverifiable payloads, structure damage
        beyond the checksums) — such a failure falls back to the next
        older candidate instead of killing the run."""
        import jax

        # Quiesce in-flight step executions before dropping the live state:
        # the guard only materialised the small verdict outputs, and
        # freeing a still-being-written output buffer mid-restore races the
        # async runtime (observed as heap corruption on the CPU client).
        jax.block_until_ready(trainer.state)
        bad_step = trainer.global_step  # where the run was when it broke
        candidates = trainer.checkpointer.verified_steps()
        if not candidates:
            raise RuntimeError(
                f"{self._bad_streak} consecutive bad steps and no verified "
                "checkpoint to roll back to (run() writes one at start; "
                "direct train() callers must save one themselves)"
            )
        logger.error(
            "Supervisor: %d consecutive unrecoverable steps — rolling "
            "back from step %d (candidates: %s)",
            self._bad_streak, trainer.global_step, candidates[:5],
        )
        for step in candidates:
            try:
                trainer.load_checkpoint(step)
                break
            except Exception as exc:
                logger.error(
                    "Supervisor: restore of checkpoint step %d failed "
                    "(%s: %s); trying the next older checkpoint",
                    step, type(exc).__name__, str(exc)[:200],
                )
        else:
            raise RuntimeError(
                f"every candidate checkpoint failed to restore "
                f"({candidates})"
            )
        trainer.training_state = TrainingState.RECOVERING
        self.rollbacks += 1
        self.rollback_steps.append(trainer.global_step)
        self._bad_streak = 0
        self._counters.inc(action="rollback")
        if self.obs is not None:
            self.obs.trace.emit(
                EventType.SUPERVISOR_ROLLBACK, step=bad_step,
                restored_step=trainer.global_step,
            )
            self.obs.dump_flight(
                "rollback", step=trainer.global_step,
                directory=trainer.config.checkpoint_dir,
                # The ladder's position travels with the artifact: the
                # paired forensic incident reconciles these against the
                # supervisor_* events without re-deriving the streak.
                extra={"bad_step": bad_step,
                       "restored_step": trainer.global_step,
                       "rollbacks": self.rollbacks,
                       "retries": self.retries,
                       "bad_steps": self.bad_steps},
            )

    # -- restart loop ------------------------------------------------------

    def run(self, train_dataloader, val_dataloader=None,
            num_epochs: Optional[int] = None) -> Dict[str, Any]:
        """``DistributedTrainer.train`` semantics plus the survival ladder;
        the result dict gains a ``"supervisor"`` report.  Guarantees a
        verified checkpoint exists before the first step so rollback always
        has a target."""
        trainer = self.trainer
        if num_epochs is None:
            num_epochs = trainer.config.num_epochs
        if trainer.state is None:
            trainer.initialize()
        trainer.training_state = TrainingState.TRAINING
        # Establish the rollback floor, and RE-CHECK it: the save itself
        # can die before COMMIT (that failure mode is in the chaos menu),
        # in which case one retry rewrites the uncommitted remnants.
        for _ in range(2):
            if trainer.checkpointer.latest_step() is not None:
                break
            trainer.save_checkpoint()
            trainer.checkpointer.wait()
        else:
            if trainer.checkpointer.latest_step() is None:
                raise RuntimeError(
                    "could not establish an initial verified checkpoint "
                    f"under {trainer.config.checkpoint_dir}"
                )
        self._install_signals()
        history: List[Dict[str, Any]] = []
        epoch = 0
        try:
            while epoch < num_epochs:
                try:
                    avg_loss = trainer.train_epoch(train_dataloader, epoch)
                except (SimulatedPreemption, PreemptionSignal) as exc:
                    self.preemptions += 1
                    self._counters.inc(action="preemption")
                    logger.warning(
                        "Supervisor: preemption during epoch %d (%s) — "
                        "saving state", epoch, exc,
                    )
                    # The signal arrived BEFORE the pending step ran, so
                    # the loop counter is one ahead of the state; re-align
                    # the label with the payload or the save would occupy
                    # the NEXT step's slot with this step's state.
                    trainer.global_step = int(np.asarray(
                        trainer.state.step
                    ))
                    if self.obs is not None:
                        self.obs.trace.emit(EventType.PREEMPTION,
                                            step=trainer.global_step,
                                            epoch=epoch)
                    trainer.save_checkpoint()
                    trainer.checkpointer.wait()
                    if self.obs is not None:
                        self.obs.dump_flight(
                            "preemption", step=trainer.global_step,
                            directory=trainer.config.checkpoint_dir,
                        )
                    if self.restarts >= self.max_restarts:
                        raise RuntimeError(
                            f"restart budget exhausted "
                            f"({self.max_restarts}); last preemption: "
                            f"{exc}"
                        ) from exc
                    self.restarts += 1
                    self._counters.inc(action="restart")
                    trainer.load_checkpoint()
                    if self.obs is not None:
                        self.obs.trace.emit(EventType.SUPERVISOR_RESTART,
                                            step=trainer.global_step,
                                            restart=self.restarts)
                    logger.info(
                        "Supervisor: auto-resume %d/%d from step %d",
                        self.restarts, self.max_restarts,
                        trainer.global_step,
                    )
                    # Epoch-granularity resume: the interrupted epoch is
                    # re-run from its first batch (the restored step
                    # counter keeps fault events fire-once and the
                    # checkpoint cadence consistent; batches before the
                    # preemption are trained again, like any
                    # epoch-checkpointing trainer).
                    continue
                record = {"epoch": epoch, "train_loss": avg_loss}
                if val_dataloader is not None:
                    record["val_loss"] = trainer.validate(val_dataloader)
                if trainer.training_state in (TrainingState.UNDER_ATTACK,
                                              TrainingState.RECOVERING):
                    trainer.training_state = TrainingState.TRAINING
                history.append(record)
                epoch += 1
        finally:
            self._restore_signals()
        trainer.training_state = TrainingState.COMPLETED
        return {
            "epochs": history,
            "stats": trainer.get_training_stats(),
            "supervisor": self.report(),
        }

    def report(self) -> Dict[str, Any]:
        """Survival counters, keyed to match ``FaultPlan.predict`` so a
        drill can assert exact equality."""
        out: Dict[str, Any] = {
            "retries": self.retries,
            "rollbacks": self.rollbacks,
            "rollback_steps": list(self.rollback_steps),
            "restarts": self.restarts,
            "preemptions": self.preemptions,
            "bad_steps": self.bad_steps,
        }
        injector = self.chaos or self.trainer.chaos
        if injector is not None:
            counts = injector.counts()
            out["faults_fired"] = counts
            out["dropped_batches"] = counts.get("data_loss", 0)
            out["stalls"] = counts.get("stall", 0)
        # Watcher consultation (obs/anomaly.py, obs/slo.py): the report a
        # fleet controller reads carries what is CURRENTLY anomalous /
        # burning budget, not just lifetime counters.
        if self.obs is not None:
            anomaly = getattr(self.obs, "anomaly", None)
            if anomaly is not None:
                out["anomalies_active"] = anomaly.active
                out["anomaly_events"] = anomaly.event_total
            slo = getattr(self.obs, "slo", None)
            if slo is not None:
                out["slo_breaches_active"] = slo.active
                out["slo_breach_total"] = slo.breach_total
        return out

    # -- signals -----------------------------------------------------------

    def _install_signals(self) -> None:
        if not self.handle_signals:
            return
        import signal

        def handler(signum, frame):
            logger.warning("Supervisor: received signal %d — will "
                           "checkpoint and resume", signum)
            self._preempt_flag = True

        try:
            self._old_handler = signal.signal(signal.SIGTERM, handler)
        except ValueError:  # not the main thread
            logger.warning("Supervisor: cannot install SIGTERM handler "
                           "outside the main thread")
            self._old_handler = None

    def _restore_signals(self) -> None:
        if self._old_handler is None:
            return
        import signal

        signal.signal(signal.SIGTERM, self._old_handler)
        self._old_handler = None

"""Optimizer factory (optax) — replaces the reference's per-node
torch.optim dict (distributed_trainer.py:90-91,441-446).

One optimizer over the replicated params: gradients are already the
trust-gated aggregate by the time they reach the update, which fixes the
reference bug where ``optimizer_step`` ignored the verified gradients
entirely (SURVEY §7.5)."""

from __future__ import annotations

import optax

from trustworthy_dl_tpu.core.config import TrainingConfig


def build_optimizer(config: TrainingConfig) -> optax.GradientTransformation:
    chain = []
    if config.grad_clip_norm and config.grad_clip_norm > 0:
        chain.append(optax.clip_by_global_norm(config.grad_clip_norm))
    name = config.optimizer.lower()
    if name == "adamw":
        chain.append(
            optax.adamw(config.learning_rate, weight_decay=config.weight_decay)
        )
    elif name == "adam":
        chain.append(optax.adam(config.learning_rate))
    elif name == "sgd":
        chain.append(optax.sgd(config.learning_rate, momentum=0.9))
    else:
        raise ValueError(f"unknown optimizer {config.optimizer!r}")
    return optax.chain(*chain)

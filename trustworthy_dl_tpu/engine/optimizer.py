"""Optimizer factory (optax) — replaces the reference's per-node
torch.optim dict (distributed_trainer.py:90-91,441-446).

One optimizer over the replicated params: gradients are already the
trust-gated aggregate by the time they reach the update, which fixes the
reference bug where ``optimizer_step`` ignored the verified gradients
entirely (SURVEY §7.5).

The LR schedule is a real optax schedule traced into the compiled update
— the reference's ``scheduler.step()`` (distributed_trainer.py:478-489)
was called on a scheduler that was never constructed."""

from __future__ import annotations

import optax

from trustworthy_dl_tpu.core.config import TrainingConfig


def build_schedule(config: TrainingConfig):
    """LR schedule from config: optional linear warmup from 0, then
    constant / cosine / linear decay to ``min_lr_ratio * peak`` over
    ``lr_decay_steps`` post-warmup steps.

    A genuinely constant schedule (constant with no warmup) returns the
    bare float: passing a callable makes optax track a
    ``ScaleByScheduleState`` count leaf, silently changing the opt_state
    pytree (and thus the checkpoint format) for the default config."""
    peak = config.learning_rate
    name = config.lr_schedule.lower()
    if name not in ("constant", "cosine", "linear"):
        raise ValueError(f"unknown lr_schedule {config.lr_schedule!r}")
    warmup = max(int(config.warmup_steps), 0)
    decay = max(int(config.lr_decay_steps), 0)
    floor = peak * config.min_lr_ratio
    if name == "constant" or decay == 0:
        if warmup == 0:
            return peak
        body = optax.constant_schedule(peak)
    elif name == "cosine":
        body = optax.cosine_decay_schedule(
            peak, decay, alpha=config.min_lr_ratio
        )
    elif name == "linear":
        body = optax.linear_schedule(peak, floor, decay)
    if warmup == 0:
        return body
    ramp = optax.linear_schedule(0.0, peak, warmup)
    return optax.join_schedules([ramp, body], [warmup])


def build_optimizer(config: TrainingConfig) -> optax.GradientTransformation:
    chain = []
    if config.grad_clip_norm and config.grad_clip_norm > 0:
        chain.append(optax.clip_by_global_norm(config.grad_clip_norm))
    schedule = build_schedule(config)
    name = config.optimizer.lower()
    # Optional reduced-precision FIRST moment (optax mu_dtype): bf16 mu
    # frees 2 bytes/param.  The second moment stays f32 (nu's dynamic
    # range drives the update scale; bf16 there measurably hurts, bf16
    # mu does not — standard large-model practice).
    mu_dtype = None
    if config.moment_dtype:
        import jax.numpy as jnp

        mu_dtype = jnp.dtype(config.moment_dtype)
    if name == "adamw":
        chain.append(
            optax.adamw(schedule, weight_decay=config.weight_decay,
                        mu_dtype=mu_dtype)
        )
    elif name == "adam":
        chain.append(optax.adam(schedule, mu_dtype=mu_dtype))
    elif name == "sgd":
        chain.append(optax.sgd(schedule, momentum=0.9,
                               accumulator_dtype=mu_dtype))
    elif name == "adafactor":
        # Factored second moment (row+column statistics instead of a full
        # per-parameter nu) — the standard large-model memory answer:
        # optimizer state drops from 2x params to near zero.  Honours the
        # same weight_decay and moment_dtype knobs as the other branches
        # (adafactor's momentum is OFF by default; moment_dtype only
        # applies if momentum is enabled via its own default behaviour).
        af_kwargs: dict = {"learning_rate": schedule}
        if config.weight_decay:
            af_kwargs["weight_decay_rate"] = config.weight_decay
        if mu_dtype is not None:
            af_kwargs["dtype_momentum"] = mu_dtype
        chain.append(optax.adafactor(**af_kwargs))
    else:
        raise ValueError(f"unknown optimizer {config.optimizer!r}")
    return optax.chain(*chain)

"""The trusted train step — one jitted SPMD program per batch.

This is the TPU-native re-design of the reference's per-batch loop
(distributed_trainer.py:382-428): forward, detection, backward, gradient
verification, trust update, trust-gated aggregation and the optimizer step
all trace into a single XLA program.  The reference's per-node Python loop
(:148-175) becomes a vmapped node axis; when the node axis is laid over the
mesh's 'data' axis, the trust-gated weighted mean over nodes lowers to a
weighted psum over ICI — the keystone collective (SURVEY §2.5).

Execution order per step (mirroring the reference's loop semantics):
  1. poison batch (attack injection, experiment-controlled)     [:187-188]
  2. per-node forward + loss + output stats                     [:148-175]
  3. per-node grads; poison gradients (injection)               [:177-195]
  4. detector verdicts on output & gradient stat batteries      [:168,:199]
  5. gradient verification (finite + norm z-score)              [:199-205]
  6. mark compromised (detected ∪ unverified)                   [:293,:319]
  7. trust update from output-deviation / gradient-consistency  [:209-226]
  8. trust-gated weighted gradient aggregation  ← fixes :441-446
  9. optimizer update; monitor absorbs clean samples
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from trustworthy_dl_tpu.attacks.adversarial import AttackPlan, poison_batch, \
    poison_gradients
from trustworthy_dl_tpu.core.config import TrainingConfig
from trustworthy_dl_tpu.detect import baseline as bl
from trustworthy_dl_tpu.detect import stats as st
from trustworthy_dl_tpu.detect.detector import Verdicts, anomaly_verdicts
from trustworthy_dl_tpu.detect.verifier import absorb_norms, \
    fleet_surge_update, norm_suspicions
from trustworthy_dl_tpu.engine.state import MonitorState, TrainState, \
    update_monitor
from trustworthy_dl_tpu.models import layers as L
from trustworthy_dl_tpu.models.factory import ModelBundle
from trustworthy_dl_tpu.trust import state as ts

Array = jax.Array


def _gradient_stat_vector(grads: Any, max_sort: int) -> Tuple[Array, Array, Array]:
    """17-stat battery for one node's gradients (+ leaf norms, finite flag).
    Matches detect/stats.gradient_statistics column layout.

    Streaming: per-leaf fused reductions combined via raw moments — the full
    gradient vector is never concatenated (that cost O(P) extra HBM traffic
    per node per step).  Order statistics and the intra-step cosine signal
    run on the deterministic ≤max_sort subsample, keeping the rolling
    baselines self-consistent."""
    leaves = [g.reshape(-1).astype(jnp.float32)
              for g in jax.tree_util.tree_leaves(grads)]
    base, leaf_norms, finite, sample = st.leafwise_statistics(leaves, max_sort)
    extra = jnp.stack(
        [
            jnp.asarray(float(len(leaves)), jnp.float32),
            jnp.mean(leaf_norms),
            jnp.std(leaf_norms),
            jnp.max(leaf_norms),
            st.chunked_cosine_mean(sample),
        ]
    )
    return jnp.concatenate([base, extra]), leaf_norms, finite


def _output_stat_vector(logits: Array, max_sort: int) -> Array:
    """17-padded output battery (12 real stats + zero padding), streaming
    (raw-moment single pass — logits can be b·T·V ≈ 10⁷ elements/node).
    The bf16→f32 cast stays fused inside the reductions: materialising a
    f32 copy of the logits costs more than the whole battery."""
    flat = logits.reshape(-1)
    base, _, _, _ = st.leafwise_statistics([flat], max_sort)
    pad = jnp.zeros((st.NUM_GRADIENT_STATS - st.NUM_TENSOR_STATS,), jnp.float32)
    return jnp.concatenate([base, pad])


def guarded_update(do_update: Array, optimizer: optax.GradientTransformation,
                   grads: Any, opt_state: Any, params: Any
                   ) -> Tuple[Any, Any]:
    """Apply the optimizer only when ``do_update`` (traced bool[]) holds;
    otherwise params AND opt_state pass through unchanged.  Merely zeroing
    the gradients is not a skip for stateful optimizers: AdamW would still
    move every parameter from stale momentum plus decoupled weight decay —
    an update with no trusted gradient behind it."""
    updates, opt_new = optimizer.update(grads, opt_state, params)
    params_new = optax.apply_updates(params, updates)
    sel = lambda new, old: jnp.where(do_update, new, old)
    return (jax.tree_util.tree_map(sel, params_new, params),
            jax.tree_util.tree_map(sel, opt_new, opt_state))


def _median_mad(values: Array) -> Tuple[Array, Array, Array]:
    """[n, d] -> (median [1, d], |dev| [n, d], σ-consistent MAD [1, d]).

    The single cross-node robust-location/scale statistic behind all three
    cross-sectional checks (score gate, hard verdict, log-norm gate) —
    they differ only in the floor applied to the MAD and the aggregation.
    MAD is scaled by 1.4826 to be σ-consistent under normality."""
    med = jnp.median(values, axis=0, keepdims=True)
    abs_dev = jnp.abs(values - med)
    mad = jnp.median(abs_dev, axis=0, keepdims=True) * 1.4826
    return med, abs_dev, mad


def _cross_sectional_score(stats: Array) -> Array:
    """f32[n]: mean robust z of each node's stat vector against the
    *current-step* cross-node distribution (median/MAD).

    Rationale: in SPMD all nodes share parameters, so legitimate training
    dynamics (early-phase drift of logits/gradient scales) shift every
    node's statistics together — temporal z-scores alone read that drift as
    an anomaly.  An actual attack perturbs one node *relative to its peers*,
    which this measure isolates; it assumes a majority of honest nodes
    (standard Byzantine setting).
    """
    _, abs_dev, mad = _median_mad(stats)
    usable = mad[0] > 1e-12
    z = jnp.where(usable[None, :], abs_dev / jnp.maximum(mad, 1e-12), 0.0)
    return jnp.sum(z, axis=1) / jnp.maximum(jnp.sum(usable), 1)


CROSS_SECTIONAL_THRESHOLD = 3.0

# Hard cross-sectional verdict threshold (see _hard_cross_outliers).
HARD_CROSS_Z = 25.0

# Log-norm cross-sectional gate: MAD floor 0.1 in log-space ≈ 10 % norm
# spread (honest per-node batch variation); outlier beyond 3 robust σ.
NORM_CROSS_Z = 3.0
NORM_MAD_FLOOR = 0.1


def _hard_cross_outliers(stats: Array) -> Array:
    """bool[n]: nodes whose battery is an *astronomical* outlier vs their
    peers this step — median/MAD with a floor RELATIVE to the median (5 %),
    so only order-of-magnitude deviations fire, never honest batch noise.

    This is the baseline-poisoning-proof detection path: temporal z-scores
    are blind to an attack live from step 0 (the rolling baseline never
    sees clean data to deviate from), but in SPMD all nodes share params,
    so a node whose gradient/output statistics sit 25+ robust σ from the
    cross-node median is compromised regardless of history.  Assumes a
    majority of honest nodes (standard Byzantine setting); requires ≥4
    nodes like the cross-sectional gate."""
    med, abs_dev, mad = _median_mad(stats)
    floor = jnp.maximum(0.05 * jnp.abs(med), 1e-6)
    z = abs_dev / jnp.maximum(mad, floor)
    return jnp.mean(z, axis=1) > HARD_CROSS_Z


# Cross-node loss outlier: one-sided robust z above which a node's loss
# has detached from the fleet (floor ≈ honest shard-difficulty spread).
LOSS_CROSS_Z = 6.0
LOSS_MAD_FLOOR_REL = 0.05
LOSS_MAD_FLOOR_ABS = 0.02


def _loss_cross_outliers(losses: Array) -> Array:
    """bool[n]: node whose per-shard loss sits far ABOVE the cross-node
    median — the data-poisoning signature the stat batteries cannot see.

    A scrambled-token / shifted-label shard produces gradients and
    activations statistically close to honest ones (measured: full-
    intensity data poisoning moves every battery z < 2), but the node can
    never FIT its corrupted data: all nodes share parameters, so while
    honest shards' losses fall together, the poisoned shard's loss
    detaches upward and stays detached.  One-sided (above median only —
    a lucky low-loss shard is not evidence of attack), median/MAD with a
    relative floor for honest shard-difficulty spread, and the standard
    two-consecutive-steps debounce + warmup gate at the call site.
    This check has no reference analogue: detect_output_anomaly
    (attack_detector.py:71-107) watched output tensors only and was blind
    to exactly this attack class."""
    med = jnp.median(losses)
    dev = losses - med
    mad = jnp.median(jnp.abs(dev)) * 1.4826
    floor = jnp.maximum(LOSS_MAD_FLOOR_REL * jnp.abs(med),
                        LOSS_MAD_FLOOR_ABS)
    z = dev / jnp.maximum(mad, floor)
    return z > LOSS_CROSS_Z


def _norm_cross_outliers(global_norms: Array) -> Array:
    """bool[n]: cross-sectional outlier gate on the per-node log gradient
    norm.  In SPMD all nodes share params, so legitimate norm drift
    (early-training decay, loss-plateau shifts) moves every node's temporal
    z together; a real inflation attack makes the node an outlier vs its
    peers *this step*."""
    log_norm = jnp.log(jnp.maximum(global_norms, 1e-30))
    _, abs_dev, mad = _median_mad(log_norm[:, None])
    z = abs_dev / jnp.maximum(mad, NORM_MAD_FLOOR)
    return z[:, 0] > NORM_CROSS_Z


class StepMetrics(NamedTuple):
    loss: Array               # f32[] aggregate (trust-weighted)
    per_node_loss: Array      # f32[n]
    trust_scores: Array       # f32[n]
    status: Array             # i32[n]
    attacked: Array           # bool[n] confirmed (debounced) verdicts this step
    verified: Array           # bool[n] gradient verification passed
    finite: Array             # bool[n] gradients free of NaN/Inf
    weights: Array            # f32[n] contribution gate actually used
    system_trust: Array       # f32[]
    grad_norm: Array          # f32[]  aggregated gradient norm
    out_score: Array          # f32[n] output anomaly score
    grad_score: Array         # f32[n] gradient anomaly score
    attack_type: Array        # i32[n] classifier output (valid iff attacked)
    byzantine: Array          # bool[n]
    backdoor: Array           # bool[n]
    out_stats: Array          # f32[n, 17] output stat battery (ML-tier feed)
    grad_stats: Array         # f32[n, 17] gradient stat battery
    # Model-specific diagnostics averaged over nodes (e.g. MoE
    # {"moe_drop_fraction"}: share of routed assignments dropped at expert
    # capacity — invisible in the loss on any single step).  None for
    # models/modes that report none — a None SENTINEL, not a shared {}
    # literal: a mutable NamedTuple default is one dict instance shared by
    # every StepMetrics ever constructed without the field, so an in-place
    # mutation by any consumer would leak across steps and trainers.
    # Read sites normalise with ``metrics.model_aux or {}``.
    model_aux: Optional[Dict[str, Array]] = None
    # Fleet-level norm-surge alarm (bool[], debounced) — the
    # majority-attack backstop; None when the step doesn't compute it
    # (pipeline mode, verification off).
    fleet_alert: Any = None


class HostMetricsPacker:
    """Packs the host-facing slice of a step's outputs into ONE flat f32
    device array so the per-step device→host traffic is a single transfer
    whose copy can start asynchronously (``copy_to_host_async``) while the
    next step dispatches — the engine of the async host pipeline
    (engine/async_host.py).

    The synchronous host path pulls ~10 separate arrays per step
    (``float(metrics.loss)`` + per-field ``np.asarray`` in
    ``_record_batch``), each a blocking round-trip.  The packer instead
    concatenates every ``StepMetrics`` leaf (plus the post-step
    ``fleet_raw_streak``, which the drain needs at its *step-time* value —
    by drain time ``trainer.state`` has moved on) into one vector inside a
    tiny jitted program, and ``unpack`` restores the exact original
    dtypes/shapes host-side, so the drained metrics are bit-identical to
    what the synchronous path would have read.

    All packed dtypes survive the f32 round-trip exactly: bool → {0.0, 1.0}
    → bool, and the i32 fields (status, attack_type) hold values far below
    2**24.  The layout is frozen from a template step's structure; a
    topology change (elastic eviction/readmission) changes the node count,
    which ``matches`` detects so the pipeline rebuilds the packer.
    """

    def __init__(self, metrics: StepMetrics, fleet_streak: Any = None):
        self._layout: list = []  # (key, shape, size, dtype)
        offset = 0
        for key, leaf in self._leaves(metrics, fleet_streak):
            size = int(np.prod(leaf.shape)) if leaf.shape else 1
            self._layout.append((key, tuple(leaf.shape), size,
                                 np.dtype(leaf.dtype)))
            offset += size
        self.total = offset
        self.num_nodes = int(metrics.trust_scores.shape[0])
        self._jit_pack = jax.jit(self._pack_impl)

    @staticmethod
    def _leaves(metrics: StepMetrics, fleet_streak: Any):
        """Deterministic (key, array) walk shared by layout and pack."""
        for name in StepMetrics._fields:
            value = getattr(metrics, name)
            if name == "model_aux":
                for k in sorted(value or {}):
                    yield f"model_aux:{k}", value[k]
            elif value is not None:
                yield name, value
        if fleet_streak is not None:
            yield "fleet_raw_streak", fleet_streak

    def matches(self, metrics: StepMetrics, fleet_streak: Any = None) -> bool:
        """Same structure/shapes as the template this packer was built on?"""
        probe = [(k, tuple(v.shape)) for k, v in
                 self._leaves(metrics, fleet_streak)]
        return probe == [(k, s) for k, s, _, _ in self._layout]

    def _pack_impl(self, metrics: StepMetrics, fleet_streak: Any
                   ) -> jax.Array:
        parts = [leaf.astype(jnp.float32).reshape(-1)
                 for _, leaf in self._leaves(metrics, fleet_streak)]
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    def pack(self, metrics: StepMetrics, fleet_streak: Any = None
             ) -> jax.Array:
        """One flat f32[total] device array; dispatch only, no host sync."""
        packed = self._jit_pack(metrics, fleet_streak)
        # Start the device→host copy now so it overlaps the next step's
        # dispatch/execution; by drain time np.asarray is (near) free.
        copy_async = getattr(packed, "copy_to_host_async", None)
        if copy_async is not None:
            copy_async()
        return packed

    def unpack(self, flat: np.ndarray) -> Tuple[StepMetrics, Any]:
        """(StepMetrics with numpy leaves, fleet_raw_streak or None) from
        the pulled flat vector — original dtypes and shapes restored."""
        flat = np.asarray(flat)
        fields: Dict[str, Any] = {"model_aux": None, "fleet_alert": None}
        aux: Dict[str, Any] = {}
        streak = None
        offset = 0
        for key, shape, size, dtype in self._layout:
            chunk = flat[offset:offset + size].astype(dtype).reshape(shape)
            offset += size
            if key.startswith("model_aux:"):
                aux[key.split(":", 1)[1]] = chunk
            elif key == "fleet_raw_streak":
                streak = chunk
            else:
                fields[key] = chunk
        if aux:
            fields["model_aux"] = aux
        return StepMetrics(**fields), streak


def build_train_step(
    bundle: ModelBundle,
    config: TrainingConfig,
    optimizer: optax.GradientTransformation,
    num_classes: Optional[int] = None,
    max_sort: int = 16384,
) -> Callable[[TrainState, Dict[str, Array], AttackPlan],
              Tuple[TrainState, StepMetrics]]:
    """Build the jitted train step for ``num_nodes`` logical nodes.

    The returned function expects batches with a leading node axis:
    {'input': [n, b, ...], 'target': [n, b, ...]} — the trainer reshapes the
    global batch (and shards the node axis over the mesh's 'data' axis on
    real hardware).
    """
    n_nodes = config.num_nodes
    detection = config.attack_detection_enabled
    verification = config.gradient_verification_enabled
    if num_classes is None:
        num_classes = bundle.input_spec.get(
            "num_classes", bundle.input_spec.get("vocab_size", 2)
        )

    def node_loss(params, node_batch):
        # Detector signals ride on `feats` — the node-boundary activations
        # (what the reference's per-partition hook watched,
        # distributed_trainer.py:160-170).  For LMs these are ~65× smaller
        # than the logits, keeping the battery off the CE-loss fusion path.
        model_aux = {}
        if bundle.loss_monitor is not None:
            # Loss-bearing path: lets the model fuse head+CE (the vocab-
            # chunked fused head never materialises logits at all).  A
            # 4th element, when present, is a dict of model diagnostics
            # (MoE capacity-drop fraction) surfaced into StepMetrics.
            out = bundle.loss_monitor(params, node_batch)
            loss, feats, mean_logits = out[:3]
            if len(out) > 3:
                model_aux = out[3]
        elif bundle.apply_monitor is not None:
            logits, feats, mean_logits = bundle.apply_monitor(
                params, node_batch["input"]
            )
            loss = L.cross_entropy_loss(logits, node_batch["target"])
        else:
            logits = bundle.apply(params, node_batch["input"])
            feats = logits
            lead = tuple(range(logits.ndim - 1))
            mean_logits = jnp.mean(logits.astype(jnp.float32), axis=lead)
            loss = L.cross_entropy_loss(logits, node_batch["target"])
        out_stats = _output_stat_vector(feats, max_sort)
        aux = (out_stats, jnp.mean(feats), jnp.std(feats), mean_logits,
               model_aux)
        return loss, aux

    grad_fn = jax.value_and_grad(node_loss, has_aux=True)

    accum = max(int(getattr(config, "grad_accum_steps", 1)), 1)
    if accum > 1:
        base_grad_fn = grad_fn

        def grad_fn(params, node_batch):  # noqa: F811 — accumulated variant
            """Sequential microbatches inside the step (lax.scan):
            gradients/losses are averaged (exactly the full-batch mean for
            equal-size microbatches of a mean loss); mean_logits averages
            (linear, exact); the stat batteries combine across microbatches
            with per-column reducers (combine_microbatch_stats: min/max/linf
            keep their extreme-value semantics, sum-moments average), so
            output-anomaly detection sees every microbatch — a corruption
            confined to a single microbatch still moves the battery at full
            strength."""
            mbs = jax.tree_util.tree_map(
                lambda v: v.reshape((accum, v.shape[0] // accum)
                                    + v.shape[1:]),
                node_batch,
            )

            def body(carry, mb):
                loss_sum, grad_sum, ml_sum = carry
                (loss, aux), g = base_grad_fn(params, mb)
                out_stats, f_mean, f_std, ml, model_aux = aux
                carry = (
                    loss_sum + loss,
                    jax.tree_util.tree_map(jnp.add, grad_sum, g),
                    ml_sum + ml,
                )
                return carry, (out_stats, f_mean, f_std, model_aux)

            init = (
                jnp.zeros((), jnp.float32),
                jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params
                ),
                jnp.zeros((num_classes,), jnp.float32),
            )
            (loss_sum, grad_sum, ml_sum), stacked = jax.lax.scan(
                body, init, mbs
            )
            from trustworthy_dl_tpu.detect.stats import (
                combine_microbatch_stats,
            )

            stacked_stats, f_means, f_stds, stacked_model_aux = stacked
            out_stats = combine_microbatch_stats(stacked_stats)
            f_mean = jnp.mean(f_means, axis=0)
            f_std = jnp.mean(f_stds, axis=0)
            # Model diagnostics are per-microbatch means -> average them.
            model_aux = jax.tree_util.tree_map(
                lambda v: jnp.mean(v, axis=0), stacked_model_aux
            )
            inv = 1.0 / accum
            grads = jax.tree_util.tree_map(lambda g: g * inv, grad_sum)
            aux = (out_stats, f_mean, f_std, ml_sum * inv, model_aux)
            return (loss_sum * inv, aux), grads

    def train_step(state: TrainState, batch: Dict[str, Array],
                   plan: AttackPlan) -> Tuple[TrainState, StepMetrics]:
        rng, k_data, k_grad = jax.random.split(state.rng, 3)
        now = state.step.astype(jnp.float32) * config.time_per_step

        # 1. Attack injection on the data path (before forward, so output
        # anomalies arise organically).  lax.cond skips the corruption work
        # entirely on clean steps while keeping activation recompile-free.
        batch = jax.lax.cond(
            plan.is_live(state.step),
            lambda b: poison_batch(plan, b, state.step, k_data, num_classes),
            lambda b: b,
            batch,
        )

        # 2-3. Per-node forward/backward.  vmap over the node axis — on a
        # ('data',)-sharded mesh each node's compute stays on its device and
        # the later weighted reduction becomes the psum.
        (losses, aux), grads = jax.vmap(grad_fn, in_axes=(None, 0))(
            state.params, batch
        )
        out_stats, out_mean, out_std, mean_logits, model_aux = aux
        # Per-node diagnostics -> fleet mean (capacity health, not a
        # per-node detection signal).
        model_aux = jax.tree_util.tree_map(
            lambda v: jnp.mean(v, axis=0), model_aux
        )
        grads = jax.lax.cond(
            plan.is_live(state.step),
            lambda g: poison_gradients(plan, g, state.step, k_grad),
            lambda g: g,
            grads,
        )

        # Per-node gradient batteries.
        grad_stats, leaf_norms, finite = jax.vmap(
            lambda g: _gradient_stat_vector(g, max_sort)
        )(grads)
        global_norms = jnp.sqrt(
            jnp.sum(leaf_norms * leaf_norms, axis=1)
        )  # f32[n]

        # 4. Gradient verification verdict (distributed_trainer.py:199-205).
        # Pure read — the Welford baseline absorbs AFTER the detector block
        # below, according to the FINAL clean-this-step judgement: a node
        # excluded for a suspect norm must not push its stats into any
        # rolling window (attack drags its own baseline), while a shared
        # legitimate norm shift every node exhibits at once must still be
        # absorbed (else z never recovers and training freezes).
        finite_b = finite.astype(bool)
        if verification:
            norm_suspect = norm_suspicions(state.verifier, global_norms)
            if n_nodes >= 4:
                # Cross-sectional gate (see _norm_cross_outliers): only a
                # node that is also an outlier vs its peers this step stays
                # suspect — shared drift is legitimate.
                norm_suspect = norm_suspect & _norm_cross_outliers(
                    global_norms
                )
        else:
            norm_suspect = jnp.zeros_like(finite_b)
        # The acted-on verdict: finite AND not (gated) norm-suspect.  Uses
        # the post-gate suspicion so a fleet-wide legitimate shift can
        # never zero every node's weight and stall training.
        verified = finite_b & ~norm_suspect

        # 4b. Fleet-level norm-surge alarm (majority-attack backstop).
        # The cross-sectional gate above deliberately clears suspicions
        # every node shares — which also blinds it when >= 50 % of the
        # fleet inflates norms together (the median itself is poisoned;
        # boundary measured in tests/test_adaptive_attacker.py).  The
        # MEDIAN log-norm z-scored against its OWN Welford history sees
        # exactly that case: a fleet-wide 10x surge is steps, not drift.
        # The alarm is UNATTRIBUTED (no node is gated or evicted by it —
        # with a poisoned median there is no trustworthy attribution);
        # the host surfaces it as a fleet incident for operator action.
        # Clean-only absorption: surge steps never enter the baseline.
        if verification and state.fleet_norm is not None:
            fleet_median = jnp.median(global_norms)[None]        # f32[1]
            _, new_fleet_norm, new_fleet_streak = fleet_surge_update(
                state.fleet_norm, fleet_median, state.fleet_raw_streak
            )
            # 2-step debounce, same spirit as the per-node verdicts.
            fleet_alert = (new_fleet_streak >= 2)[0]
        else:
            fleet_alert = None
            new_fleet_norm = state.fleet_norm
            new_fleet_streak = state.fleet_raw_streak

        # 5. Detector verdicts (attack_detector.py:71-141), plus the
        # Byzantine cross-node check (:143-162) and consensus-KL backdoor
        # check (:164-183) the reference defined but never wired in.
        if detection:
            # Deliberate deviation from the reference's ordering
            # (attack_detector.py:84-100 appends the current sample before
            # building the baseline it z-scores against): a single outlier
            # among k window samples is then bounded at z ≤ (k-1)/√k, so
            # with short histories detection *mathematically cannot* fire.
            # We score against the past-only window, then absorb the sample
            # into the baseline only if it wasn't flagged — which also stops
            # an attacker from slow-boiling the baseline toward the attack.
            out_v = anomaly_verdicts(
                out_stats, state.out_baseline, warmup=config.detector_warmup
            )
            grad_v = anomaly_verdicts(
                grad_stats, state.grad_baseline, warmup=config.detector_warmup
            )
            if n_nodes >= 4:
                # Temporal z alone reads shared training drift as anomaly;
                # require the node to also be a cross-node outlier *this
                # step* (see _cross_sectional_score).
                out_cross = _cross_sectional_score(out_stats)
                grad_cross = _cross_sectional_score(grad_stats)
                out_v = out_v._replace(
                    is_attack=out_v.is_attack
                    & (out_cross > CROSS_SECTIONAL_THRESHOLD)
                )
                grad_v = grad_v._replace(
                    is_attack=grad_v.is_attack
                    & (grad_cross > CROSS_SECTIONAL_THRESHOLD)
                )
            # Byzantine cross-node comparison on softmax *signatures* of the
            # mean logits: probability vectors are positive, so honest nodes
            # (same params, same data distribution) sit near cosine 1 while
            # a garbage-output node diverges hard — raw mean logits at init
            # are near-zero noise and would false-positive.  Warm-up gated
            # like the statistical detectors (attack_detector.py:91).
            warm_nodes = state.out_baseline.count >= config.detector_warmup
            if n_nodes >= 3:
                signatures = jax.nn.softmax(mean_logits, axis=-1)
                byz = st.byzantine_verdicts(signatures) & warm_nodes
            else:
                byz = jnp.zeros((n_nodes,), bool)
            # Backdoor: each node's mean output distribution vs the
            # cross-node consensus (replicated-canary style, SURVEY §7.4(4)).
            consensus = jnp.mean(mean_logits, axis=0, keepdims=True)
            kl = jax.vmap(
                lambda m: st.backdoor_divergence(m[None, :], consensus)
            )(mean_logits)
            backdoor = (kl > 2.0) & warm_nodes
            # Per-node loss detachment (see _loss_cross_outliers): the one
            # signal a data-poisoned shard cannot hide.  ≥4 nodes for a
            # meaningful median/MAD, warm-gated like the batteries.
            if n_nodes >= 4:
                loss_outlier = _loss_cross_outliers(losses) & warm_nodes
            else:
                loss_outlier = jnp.zeros((n_nodes,), bool)
            candidates = (out_v.is_attack | grad_v.is_attack | byz
                          | backdoor | loss_outlier)
            if n_nodes >= 4:
                # Hard cross-sectional verdict: catches attacks live from
                # step 0, which the temporal batteries cannot (their
                # baselines never saw clean data) — see _hard_cross_outliers.
                candidates = candidates | _hard_cross_outliers(out_stats) \
                    | _hard_cross_outliers(grad_stats)
            # Absorb this step's stats into the rolling baselines only for
            # nodes with NO suspicion of any kind this step — battery,
            # byzantine/backdoor, verifier norm_suspect, or non-finite
            # gradients — an attacker must not drag its own baseline.
            clean_now = ~(candidates | norm_suspect | ~finite_b)
            out_bl = bl.push_stats(state.out_baseline, out_stats,
                                   mask=clean_now)
            grad_bl = bl.push_stats(state.grad_baseline, grad_stats,
                                    mask=clean_now)
            # Debounce: a candidate node is excluded from this step's
            # aggregation immediately (no poisoned gradient ever lands), but
            # is only *confirmed* compromised — trust nuked, incident
            # recorded — after two consecutive anomalous steps.  Real
            # attacks are sustained; single-step blips from small per-node
            # batches are not.
            attacked = candidates & state.prev_suspects
            out_score, grad_score = out_v.score, grad_v.score
            # Attribution ladder (VERDICT r3 weak #7): reference rule
            # labels where its rules really fired, explicit consensus
            # checks next, dominant-signature family instead of the
            # blanket "byzantine" default — see attribute_attack.
            from trustworthy_dl_tpu.detect.detector import attribute_attack

            attack_type = attribute_attack(grad_v, out_v, byz, backdoor,
                                           loss_outlier)
        else:
            out_bl, grad_bl = state.out_baseline, state.grad_baseline
            attacked = jnp.zeros((n_nodes,), bool)
            candidates = byz = backdoor = attacked
            out_score = grad_score = jnp.zeros((n_nodes,), jnp.float32)
            attack_type = jnp.zeros((n_nodes,), jnp.int32)
            clean_now = verified

        # Statistical norm suspicion joins the debounced candidate set: the
        # node is excluded from THIS step's aggregate (weights gate below)
        # but is only confirmed-compromised on the second consecutive hit —
        # a one-step z blip on a legitimate node must not nuke its trust.
        candidates = candidates | norm_suspect
        attacked = attacked | (norm_suspect & state.prev_suspects)

        # 5b. Verifier baseline absorption — the same clean-this-step rule
        # as the stat baselines (no candidate of any kind): a stats-visible
        # attacker must not drag the norm baseline either, while shared
        # legitimate norm shifts (cross-gate cleared) are absorbed so the
        # temporal z can recover.
        if verification:
            verifier = absorb_norms(state.verifier, global_norms, clean_now)
        else:
            verifier = state.verifier

        # 6. Compromise marking (:273-299,:301-322 → trust_manager.py:183).
        # Immediate only for unambiguous evidence: confirmed (debounced)
        # verdicts and non-finite gradients.
        newly_compromised = attacked | ~finite_b
        trust = ts.mark_compromised(state.trust, newly_compromised)

        # 7. Trust-signal computation against the monitor's expected
        # behaviour (distributed_trainer.py:228-271) and the EMA update.
        warm = state.monitor.warm
        exp_mean = state.monitor.out_mean_avg
        exp_std = jnp.maximum(state.monitor.out_std_avg, 1e-6)
        mean_dev = jnp.abs(out_mean - exp_mean) / exp_std
        std_dev = jnp.abs(out_std - state.monitor.out_std_avg) / exp_std
        output_deviation = jnp.where(
            warm, jnp.minimum(1.0, (mean_dev + std_dev) / 2.0), 0.0
        )
        exp_norms = state.monitor.grad_norm_avg
        per_leaf = jnp.minimum(1.0, leaf_norms / jnp.maximum(exp_norms, 1e-12))
        usable = exp_norms > 0
        cons = jnp.sum(jnp.where(usable, per_leaf, 0.0), axis=1) / jnp.maximum(
            jnp.sum(usable, axis=1), 1
        )
        gradient_consistency = jnp.where(warm, cons, 1.0)
        trust = ts.update_trust(
            trust, output_deviation, gradient_consistency, now,
            alpha=config.trust_alpha,
        )

        # 7b. Probation recovery (trust_manager.py:198-206 wired in): a
        # hard-gated node with recovery_probation_steps consecutive clean
        # steps re-enters as RECOVERING — its weight returns below, and the
        # status machine promotes it to TRUSTED once trust climbs.  A
        # single false positive costs bounded steps, not the run.
        trust, clean_streak = ts.probation_recovery(
            trust, state.clean_streak, verified & ~candidates,
            config.recovery_probation_steps,
        )

        # 8. Trust-gated aggregation — the psum the reference never issued
        # (SURVEY §2.5).  Gated-out nodes are hard-masked with jnp.where,
        # not merely scaled: 0 * NaN = NaN, so a node emitting non-finite
        # gradients would otherwise poison the aggregate despite its zero
        # weight.  When every node is gated out, the update is skipped
        # entirely (zero aggregate) — falling back to uniform weighting
        # would apply the very gradients that failed verification.
        weights = ts.contribution_weights(trust, verified & ~candidates)
        denom = jnp.sum(weights)
        inv = jnp.where(denom > 0, 1.0 / jnp.maximum(denom, 1e-30), 0.0)

        def _gate(g):
            mask = (weights > 0).reshape((n_nodes,) + (1,) * (g.ndim - 1))
            w = (weights * inv).astype(g.dtype)
            return jnp.einsum("n,n...->...", w, jnp.where(mask, g, 0))

        agg = jax.tree_util.tree_map(_gate, grads)

        # 9. Optimizer + monitor absorption (clean samples only).  All
        # nodes gated -> full skip: params and optimizer state both freeze
        # (zeroed grads alone would still let AdamW's momentum/weight-decay
        # move the params).
        params, opt_state = guarded_update(
            denom > 0, optimizer, agg, state.opt_state, state.params
        )
        absorb = verified & ~candidates
        monitor = update_monitor(state.monitor, out_mean, out_std, leaf_norms,
                                 absorb)

        agg_norm = optax.global_norm(agg)
        # Same masking for the reported loss: a gated node's (possibly NaN)
        # loss must not contaminate the aggregate.  All-gated → 0.0, with
        # weights all-zero in the metrics making the cause unambiguous.
        loss = jnp.sum(jnp.where(weights > 0, losses, 0.0) * weights) * inv
        new_state = TrainState(
            params=params,
            opt_state=opt_state,
            trust=trust,
            out_baseline=out_bl,
            grad_baseline=grad_bl,
            verifier=verifier,
            monitor=monitor,
            prev_suspects=candidates,
            step=state.step + 1,
            epoch=state.epoch,
            rng=rng,
            clean_streak=clean_streak,
            fleet_norm=new_fleet_norm,
            fleet_raw_streak=new_fleet_streak,
        )
        metrics = StepMetrics(
            loss=loss,
            per_node_loss=losses,
            trust_scores=trust.scores,
            status=trust.status,
            attacked=attacked,
            verified=verified,
            finite=finite_b,
            weights=weights,
            system_trust=ts.system_trust(trust),
            grad_norm=agg_norm,
            out_score=out_score,
            grad_score=grad_score,
            attack_type=attack_type,
            byzantine=byz,
            backdoor=backdoor,
            out_stats=out_stats,
            grad_stats=grad_stats,
            model_aux=model_aux,
            fleet_alert=fleet_alert,
        )
        return new_state, metrics

    return train_step


def build_eval_step(bundle: ModelBundle
                    ) -> Callable[[Any, Dict[str, Array]], Dict[str, Array]]:
    """Validation step (distributed_trainer.py:494-508): loss + accuracy on
    an un-noded batch, no detection machinery.  LMs with the fused
    vocab-chunked head keep its memory contract in eval too — the
    [B, T, V] logits never materialise."""
    chunk = getattr(bundle.config, "lm_head_chunk", 0)
    if bundle.kind == "lm" and chunk and "moe" not in bundle.name:
        from trustworthy_dl_tpu.models import gpt2 as _g
        from trustworthy_dl_tpu.ops.fused_ce import fused_lm_eval

        cfg = bundle.config

        def eval_step(params, batch):
            # "auto" resolves per shape at trace time (one predicate,
            # gpt2.resolve_lm_head_chunk) — same dispatch as training.
            c = _g.resolve_lm_head_chunk(cfg, int(batch["target"].size))
            if not c:
                logits = bundle.apply(params, batch["input"])
                return {
                    "loss": L.cross_entropy_loss(logits, batch["target"]),
                    "accuracy": L.accuracy(logits, batch["target"]),
                }
            x = _g.embed(params, batch["input"], cfg)
            x = _g.apply_blocks(params["blocks"], x, cfg)
            normed = L.layernorm(params["ln_f"], x)
            loss, acc = fused_lm_eval(normed, params["wte"],
                                      batch["target"], c, cfg.dtype)
            return {"loss": loss, "accuracy": acc}

        return eval_step

    def eval_step(params, batch):
        logits = bundle.apply(params, batch["input"])
        loss = L.cross_entropy_loss(logits, batch["target"])
        acc = L.accuracy(logits, batch["target"])
        return {"loss": loss, "accuracy": acc}

    return eval_step


def build_node_eval_step(bundle: ModelBundle
                         ) -> Callable[[Any, Dict[str, Array]],
                                       Dict[str, Array]]:
    """Validation over the node axis: the batch arrives node-split
    [n, B/n, ...] with the node axis laid over the mesh's 'data' axis —
    exactly like training — so on an n-chip mesh each chip evaluates 1/n
    of the batch instead of replicating the whole thing (the reference
    replicated: distributed_trainer.py:494-508).  Node rows are equal-
    sized, so the mean of per-node means is the global mean."""
    eval_step = build_eval_step(bundle)

    def node_eval_step(params, node_batch):
        out = jax.vmap(lambda b: eval_step(params, b))(node_batch)
        return jax.tree_util.tree_map(jnp.mean, out)

    return node_eval_step

"""Bounded in-flight dispatch with lagged host telemetry.

The fused train step keeps detection *inside* the device program (the
paper's near-zero-overhead claim, BENCH_r02/r03), but the synchronous
host loop threw that away: every step ended with a blocking
``float(metrics.loss)`` followed by ~10 separate device→host pulls in
``_record_batch``, so the accelerator idled through all per-step Python
bookkeeping.  This module closes that dispatch gap the way production
JAX trainers (t5x/MaxText-style) do:

* each step's host-facing outputs are packed into ONE flat device array
  (``engine.step.HostMetricsPacker``) whose device→host copy starts
  asynchronously at dispatch time;
* a bounded deque holds up to ``TrainingConfig.async_host_depth`` steps
  in flight — step k+1 dispatches before step k's metrics land;
* completed entries drain through the EXISTING host path
  (``_record_batch``, step-guard checks, obs trace events) lagged by up
  to K steps, with the entry's own step number restored for the duration
  of its drain so every host record is indistinguishable from the
  synchronous path's.

Drain contract (the invariants the lag must not break):

* **checkpoint saves** — the trainer fully drains before ``save_checkpoint``
  and skips the save if the frontier step was guard-rejected, so a
  verified checkpoint always covers a fully-accounted, guard-accepted
  prefix;
* **epoch end / preemption** — ``train_epoch`` drains in a ``finally``, so
  epoch aggregation, ``sync_host_state`` and the supervisor's
  save-on-signal all observe a caught-up host;
* **guard trips** — the lagged guard skips in-place retries (re-running a
  K-step-old batch against the frontier state is not the same
  computation) and, on rollback, restores the newest verified checkpoint
  — which predates the in-flight window by the checkpoint invariant
  above; the rest of the window is then discarded as an abandoned
  timeline;
* **elastic transitions** — evictions detected while draining are
  deferred: the window drains fully (its packed metrics still carry the
  pre-eviction node count), then the eviction/readmission applies once at
  the dispatch frontier.  The in-step trust gate has already zero-weighted
  the compromised node's gradients throughout the lag, so only the host
  bookkeeping (mesh surgery, history records) moves by up to K steps.

Depth 0 bypasses this module entirely (the pre-pipeline synchronous
loop).  Deterministic chaos drills that assert exact retry counts
(``FaultPlan.predict``) must run at depth 0 — see the lagged-guard note
in ``TrainingSupervisor.after_step``.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
from typing import Any, Deque, Optional, Set

import numpy as np

from trustworthy_dl_tpu.engine.step import HostMetricsPacker, StepMetrics

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class DrainContext:
    """Installed as ``trainer._drain_ctx`` while a lagged entry drains:
    ``_record_batch`` reads the fleet-norm streak from the entry's packed
    snapshot (the live ``trainer.state`` is up to K steps ahead) and
    defers elastic evictions into ``evict_coords`` instead of resharding
    mid-window."""

    fleet_streak: Any = None
    evict_coords: Set[int] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class _InFlight:
    """One dispatched-but-undrained step."""

    step: int
    epoch: int
    batch_idx: int
    node_batch: Any          # kept alive for the (lagged) step guard
    packed: Any              # flat f32 device array, D2H copy in flight
    packer: HostMetricsPacker


class AsyncHostPipeline:
    """The bounded in-flight window for one ``train_epoch`` call.

    ``push`` packs a step's metrics and starts the async device→host
    copy; ``drain`` resolves the oldest entries down to the configured
    depth (or to empty) through the trainer's host path, then applies any
    deferred topology change at the frontier.  ``epoch_loss`` /
    ``num_batches`` accumulate exactly what the synchronous loop's local
    counters would have.
    """

    def __init__(self, trainer: Any, depth: int):
        self.trainer = trainer
        self.depth = int(depth)
        self.entries: Deque[_InFlight] = collections.deque()
        self.packer: Optional[HostMetricsPacker] = None
        self.pending_evicts: Set[int] = set()
        self.epoch_loss = 0.0
        self.num_batches = 0
        self.last_rejected_step: Optional[int] = None
        self._rejected_since_check = False
        self._warned_sync_guard = False

    # -- dispatch side -----------------------------------------------------

    def push(self, epoch: int, batch_idx: int, node_batch: Any,
             metrics: StepMetrics, state: Any) -> None:
        """Pack the step the trainer just dispatched and enqueue it.
        ``state`` is the post-step TrainState — its ``fleet_raw_streak``
        is the step-time value the drain must see."""
        streak = getattr(state, "fleet_raw_streak", None)
        if self.packer is None or not self.packer.matches(metrics, streak):
            # First step, or the node count changed under an elastic
            # transition (applied only at full-drain points, so no mixed
            # layouts ever coexist in the window).
            self.packer = HostMetricsPacker(metrics, streak)
        packed = self.packer.pack(metrics, streak)
        # Retain the batch only for a guard that might retry it (the
        # legacy non-lagged-aware path) — a lagged-aware guard never
        # touches it, and pinning K full device batches for nothing is
        # real HBM at production batch sizes.
        guard = self.trainer.step_guard
        keep_batch = guard is not None and \
            not getattr(guard, "lagged_aware", False)
        self.entries.append(_InFlight(
            step=self.trainer.global_step, epoch=epoch, batch_idx=batch_idx,
            node_batch=node_batch if keep_batch else None,
            packed=packed, packer=self.packer,
        ))

    # -- drain side --------------------------------------------------------

    def drain(self, depth: Optional[int] = None) -> None:
        """Resolve oldest entries until at most ``depth`` (default: the
        configured window) remain, then apply deferred topology changes.
        ``drain(0)`` is the mandatory full drain."""
        target = self.depth if depth is None else int(depth)
        self._drain_until(target)
        self._maybe_apply_topology()

    def consume_rejection(self) -> bool:
        """True when any entry was guard-rejected since the last check —
        the trainer then discards the frontier step's timer laps, like the
        synchronous loop does for rejected steps (retry/rollback wall time
        must not poison the phase distribution)."""
        rejected = self._rejected_since_check
        self._rejected_since_check = False
        return rejected

    def _drain_until(self, target: int) -> None:
        while len(self.entries) > target:
            # Peek-then-pop: if the guard raises mid-drain (a preemption
            # signal), the entry stays queued, so the unwind drain still
            # records it — the host stream must never have a mid-run gap
            # the synchronous path could not produce.
            entry = self.entries[0]
            self._drain_one(entry)
            if self.entries and self.entries[0] is entry:
                self.entries.popleft()

    def _drain_one(self, entry: _InFlight) -> None:
        """Run one lagged step through the host path with its own step
        number restored, exactly as the synchronous loop would have."""
        trainer = self.trainer
        host, streak = entry.packer.unpack(np.asarray(entry.packed))
        frontier = trainer.global_step
        trainer.global_step = entry.step
        try:
            guard = trainer.step_guard
            if guard is not None:
                if getattr(guard, "lagged_aware", False):
                    accepted = guard.after_step(trainer, entry.node_batch,
                                                host, lagged=True)
                else:
                    # Legacy synchronous-only guard running lagged: its
                    # in-place retries re-run an old batch against the
                    # FRONTIER state (not the state that produced it) and
                    # mutate trainer.state under the in-flight window —
                    # tolerated for duck-typed guards, but such runs
                    # should pin async_host_depth=0.
                    if not self._warned_sync_guard:
                        self._warned_sync_guard = True
                        logger.warning(
                            "async pipeline: step guard %s is not "
                            "lagged-aware; its retries run against the "
                            "frontier state — set async_host_depth=0 for "
                            "exact synchronous guard semantics",
                            type(guard).__name__,
                        )
                    accepted = guard.after_step(trainer, entry.node_batch,
                                                host)
                if accepted is not None:
                    # A guard may substitute metrics (a retry-recovered
                    # step); record what it accepted, like the sync loop.
                    host = accepted
                if accepted is None:
                    self.last_rejected_step = entry.step
                    self._rejected_since_check = True
                    if trainer.global_step != entry.step:
                        # Rollback: the guard restored an older verified
                        # checkpoint (global_step re-pointed by
                        # load_checkpoint).  Everything still in flight
                        # was computed on the abandoned timeline — in the
                        # synchronous world those steps never ran.
                        logger.warning(
                            "async pipeline: rollback at lagged step %d — "
                            "discarding %d in-flight step(s)",
                            entry.step, len(self.entries),
                        )
                        self.entries.clear()
                        self.pending_evicts.clear()
                        frontier = trainer.global_step
                    return
            if self.last_rejected_step == entry.step:
                # Training re-advanced to a step number that was rejected
                # on the abandoned timeline; this acceptance supersedes it
                # (a stale marker would suppress that step's checkpoint).
                self.last_rejected_step = None
            trainer.metrics_collector.tick()
            loss = float(host.loss)
            ctx = DrainContext(fleet_streak=streak)
            trainer._drain_ctx = ctx
            try:
                trainer._record_batch(host, entry.epoch, loss)
            finally:
                trainer._drain_ctx = None
            self.pending_evicts.update(ctx.evict_coords)
            self.epoch_loss += loss
            self.num_batches += 1
            if entry.batch_idx % 10 == 0:
                logger.info("Epoch %d, Batch %d, Loss: %.4f",
                            entry.epoch, entry.batch_idx, loss)
        finally:
            trainer.global_step = frontier

    def _maybe_apply_topology(self) -> None:
        """Deferred elastic transitions: mandatory full drain first, then
        evict/readmit once at the dispatch frontier."""
        trainer = self.trainer
        if not self.pending_evicts and not trainer._readmit_due():
            return
        self._drain_until(0)  # may itself add evicts or clear on rollback
        evicts = sorted(self.pending_evicts)
        self.pending_evicts.clear()
        n = trainer.config.num_nodes
        if len(evicts) >= n:
            # Evictions accumulated across the window would empty the
            # fleet — something the per-step path can never request
            # (eviction needs a surviving majority to migrate onto).
            # Keep the highest coordinate in service; the in-step trust
            # gate has its gradients zero-weighted regardless, and the
            # fleet-level alarm covers the everyone-is-compromised case.
            logger.error(
                "async pipeline: %d deferred evictions would empty the "
                "%d-node fleet; keeping coordinate %d in service",
                len(evicts), n, evicts[-1],
            )
            evicts = evicts[:n - 1]
        if evicts:
            trainer._apply_evictions(evicts)
        trainer._maybe_readmit()

from trustworthy_dl_tpu.engine.async_host import AsyncHostPipeline
from trustworthy_dl_tpu.engine.checkpoint import CheckpointManager
from trustworthy_dl_tpu.engine.optimizer import build_optimizer
from trustworthy_dl_tpu.engine.state import MonitorState, TrainState, init_monitor_state, init_train_state, update_monitor
from trustworthy_dl_tpu.engine.step import HostMetricsPacker, StepMetrics, build_eval_step, build_train_step
from trustworthy_dl_tpu.engine.supervisor import PreemptionSignal, TrainingSupervisor
from trustworthy_dl_tpu.engine.trainer import DistributedTrainer, TrainingState

__all__ = [
    "AsyncHostPipeline",
    "CheckpointManager",
    "HostMetricsPacker",
    "DistributedTrainer",
    "MonitorState",
    "PreemptionSignal",
    "StepMetrics",
    "TrainState",
    "TrainingState",
    "TrainingSupervisor",
    "build_eval_step",
    "build_optimizer",
    "build_train_step",
    "init_monitor_state",
    "init_train_state",
    "update_monitor",
]

"""Native ops tier: Pallas TPU kernels (SURVEY §7.1).

The reference has no native code at all (SURVEY §0: pure Python); this
package is where the TPU build drops below XLA when the compiler's fusion
isn't enough.  Current kernels:

* ``fused_stats`` — single-pass detector moment battery (Σx..Σx⁴, min/max,
  L1/L∞) feeding detect/stats.leafwise_statistics.
* ``flash_attention`` — blockwise softmax attention, fwd + bwd, O(T·D)
  memory (``attn_impl="flash"`` in the GPT-2 registry).
"""

from trustworthy_dl_tpu.ops.flash_attention import flash_attention
from trustworthy_dl_tpu.ops.fused_stats import (
    BLOCK_ROWS,
    LANES,
    fused_moments,
    pallas_enabled,
)

__all__ = [
    "BLOCK_ROWS",
    "LANES",
    "flash_attention",
    "fused_moments",
    "pallas_enabled",
]

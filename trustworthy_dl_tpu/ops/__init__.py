"""Native ops tier: Pallas TPU kernels (SURVEY §7.1).

The reference has no native code at all (SURVEY §0: pure Python); this
package is where the TPU build drops below XLA when the compiler's fusion
isn't enough.  Current kernels:

* ``fused_stats`` — single-pass detector moment battery (Σx..Σx⁴, min/max,
  L1/L∞) feeding detect/stats.leafwise_statistics.
* ``flash_attention`` — blockwise softmax attention, fwd + bwd, O(T·D)
  memory (``attn_impl="flash"`` in the GPT-2 registry).
* ``fused_dequant_matmul`` — int8-weight dequant matmul tile for the
  serving engine's weight-only-int8 decode path (quant/): streams int8
  weight tiles HBM→VMEM, upcasts in-register, scales per output channel.
"""

from trustworthy_dl_tpu.ops.flash_attention import flash_attention
from trustworthy_dl_tpu.ops.fused_dequant_matmul import dequant_matmul
from trustworthy_dl_tpu.ops.fused_stats import (
    BLOCK_ROWS,
    LANES,
    fused_moments,
    pallas_enabled,
)

__all__ = [
    "BLOCK_ROWS",
    "LANES",
    "dequant_matmul",
    "flash_attention",
    "fused_moments",
    "pallas_enabled",
]

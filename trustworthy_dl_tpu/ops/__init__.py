"""Native ops tier: Pallas TPU kernels (SURVEY §7.1).

The reference has no native code at all (SURVEY §0: pure Python); this
package is where the TPU build drops below XLA when the compiler's fusion
isn't enough.  Current kernels:

* ``fused_stats`` — single-pass detector moment battery (Σx..Σx⁴, min/max,
  L1/L∞) feeding detect/stats.leafwise_statistics.
* ``flash_attention`` — blockwise softmax attention, fwd + bwd, O(T·D)
  memory (``attn_impl="flash"`` in the GPT-2 registry).
* ``fused_dequant_matmul`` — int8-weight dequant matmul tile for the
  serving engine's weight-only-int8 decode path (quant/): streams int8
  weight tiles HBM→VMEM, upcasts in-register, scales per output channel.
* ``paged_attention`` — the serving-kernel TIER over the engine's block
  pool: ragged paged-decode attention (one program per block-table row,
  int8 KV tiles dequantized in-register, online softmax, early exit at
  each row's true length), the query-tiled chunked-prefill program
  (per-tile causal bounds over the same scalar-prefetch tables), the
  fused speculative-verify tail (logits projection + trust stats in one
  streaming vocab pass), the in-grid adapter low-rank gather (per-slot
  page table as scalar prefetch) and the fused logit trust epilogue
  (entropy / top-1 margin in one pass over the vocab).

All four dispatch through the ONE shared gate below: :func:`pallas_enabled`
(env-var opt-in/out, TPU-backend default) and :func:`pallas_interpret`
(off-TPU kernels run in Pallas interpret mode — tests only).  The gate
lives HERE, above the kernel imports, so the kernels can import it from
the package without a cycle.
"""

import os


def pallas_enabled(env: str = "TDDL_FUSED_STATS") -> bool:
    """THE dispatch gate every Pallas kernel in this package shares:
    default ON on TPU, opt-out via ``<env>=0`` (and opt-in via ``=1``
    off-TPU, where the kernel runs in interpret mode — tests only).

    Env-var map: ``TDDL_FUSED_STATS`` gates fused_stats AND
    dequant_matmul (the int8 decode tier shipped riding the stats gate
    and keeps that coupling — flipping it off disables both kernels);
    ``TDDL_PAGED_ATTN`` gates paged_attention.  The policy is
    deliberately identical everywhere: the jnp/XLA path stays the
    always-available reference semantics, and the CPU container tier
    never compiles Mosaic.  Measured dispatch notes live with the
    kernels (e.g. fused_stats: ~20 % step-time win on VGG/ResNet conv
    gradients, parity on transformer gradients)."""
    flag = os.environ.get(env)
    if flag is not None:
        return flag != "0"
    import jax

    return jax.default_backend() == "tpu"


def pallas_interpret() -> bool:
    """Interpret-mode helper shared by every kernel's dispatch: compiled
    Mosaic on the TPU backend, Pallas interpret mode anywhere else (the
    CPU test tier pins kernel-vs-jnp equality through this)."""
    import jax

    return jax.default_backend() != "tpu"


from trustworthy_dl_tpu.ops.flash_attention import flash_attention
from trustworthy_dl_tpu.ops.fused_dequant_matmul import dequant_matmul
from trustworthy_dl_tpu.ops.fused_stats import (
    BLOCK_ROWS,
    LANES,
    fused_moments,
)
# NOTE: the ``paged_attention`` ENTRY-POINT FUNCTION is deliberately not
# re-exported here: ``from ops import paged_attention`` must keep
# resolving to the submodule — generate/scheduler import it as a module
# for the whole kernel surface (attention + trust epilogue + resolver),
# unlike ``flash_attention`` where the function deliberately shadows its
# submodule and callers only ever want the one entry point.
from trustworthy_dl_tpu.ops.paged_attention import (
    adapter_delta,
    fused_verify_tail,
    logit_trust_stats,
    paged_prefill_attention,
    resolve_attn_impl,
    resolve_attn_impls,
    supports_paged_attention,
)

__all__ = [
    "BLOCK_ROWS",
    "LANES",
    "adapter_delta",
    "dequant_matmul",
    "flash_attention",
    "fused_moments",
    "fused_verify_tail",
    "logit_trust_stats",
    "paged_prefill_attention",
    "pallas_enabled",
    "pallas_interpret",
    "resolve_attn_impl",
    "resolve_attn_impls",
    "supports_paged_attention",
]

"""Pallas TPU flash attention: blockwise softmax attention, fwd + bwd.

The reference has no attention code at all (SURVEY §5.7 — models came from
an implied ModelFactory and only the layer list was touched); long-context
support in this framework is first-class, and this kernel is its native
tier (SURVEY §7.1).  ``full_attention`` (models/gpt2.py) materialises the
[T, T] score matrix in HBM; this kernel streams K/V blocks through VMEM
with an online-softmax accumulator, so attention costs O(T·D) memory at
any sequence length, and the two matmuls per block land on the MXU in one
fused pass per tile.

Three kernels:
  * forward — per Q block: stream K/V blocks, keep (m, l, acc) running
    max / normaliser / weighted sum; emits output AND the row logsumexp
    (the residual that makes the backward recomputation exact).
  * dq — per Q block: re-stream K/V, rebuild P = exp(S − lse), accumulate
    dQ = scale · (P ∘ (dO·Vᵀ − Δ)) · K.
  * dkv — per K/V block: stream Q/dO blocks, accumulate
    dV = Pᵀ·dO and dK = scale · (P ∘ (dO·Vᵀ − Δ))ᵀ · Q.

Causal masking skips fully-masked tiles at the grid level (half the work)
and masks the diagonal tile elementwise.  Crucially the skip also kills the
tile's HBM traffic: ``pl.when`` alone only skips compute — Pallas's
pipeline still DMAs every block named by the BlockSpec — so the index maps
CLAMP masked iterations to the last useful block index; Pallas issues no
copy when the block index repeats, making the causal skip save bandwidth
as well as FLOPs (this was the round-2 "advantage shrinks with T" bug: at
long T the kernel is bandwidth-bound and was streaming twice the needed
K/V).  Numerics are f32 throughout the accumulators regardless of input
dtype; outputs cast back.

Registered with the GPT-2 attention registry as ``attn_impl="flash"``.
Shapes that don't tile (T not a multiple of the block) fall back to the
XLA path — same math, so the swap is always safe.  Off-TPU the kernel runs
in Pallas interpret mode; tests pin fwd/bwd equality against
``full_attention`` on the CPU backend.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30          # finite stand-in: exp(NEG_INF - m) flushes to 0
_LANES = 128


def _block_for(t: int) -> int:
    """Largest supported Q block size dividing T (0 = no tiling, fall
    back)."""
    for b in (512, 256, 128, 64):
        if t % b == 0 and t >= b:
            return b
    return 0


def _blocks_for(t: int) -> Tuple[int, int]:
    """(bq, bk) tile sizes, tuned on v5e (BASELINE.md sweep): large tiles
    win — per-tile bookkeeping and online-softmax rescales amortise, and
    the K loop (inner, streaming) benefits most, so bk runs up to 1024.
    (512, 1024) measured 24.6 ms at T=16384 fwd+bwd vs 60.8 ms for the
    round-2 (256, 256) choice and 77.8 ms for XLA full attention."""
    bq = _block_for(t)
    if not bq:
        return 0, 0
    bk = bq
    for cand in (1024, 512):
        if t % cand == 0 and t >= cand and cand > bk:
            bk = cand
            break
    return bq, bk


MAX_HEAD_DIM = 512


def supports_flash(t: int, d: int) -> bool:
    """THE kernel-eligibility predicate — every dispatch site (the public
    flash_attention wrapper, ring attention's chunk path) must use this so
    the fallback condition can never drift from the kernel's real
    constraints."""
    return _block_for(t) != 0 and d <= MAX_HEAD_DIM


def _dot(a: jax.Array, b: jax.Array, trans_a: bool = False,
         trans_b: bool = False) -> jax.Array:
    """f32-accumulating matmul for the MXU."""
    ca = 0 if trans_a else 1
    cb = 1 if trans_b else 0
    return jax.lax.dot_general(
        a, b, (((ca,), (cb,)), ((), ())), preferred_element_type=jnp.float32
    )


def _causal_mask(qi, ki, bq: int, bk: int) -> jax.Array:
    qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return qpos >= kpos


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale: float, causal: bool, bq: int, bk: int, nk: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _compute():
        q = q_ref[0]
        s = _dot(q, k_ref[0], trans_b=True) * scale          # [bq, bk] f32
        if causal:
            s = jnp.where(_causal_mask(qi, ki, bq, bk), s, NEG_INF)
        m_prev = m_ref[:, :1]                                # [bq, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_cur)                               # masked -> 0
        corr = jnp.exp(m_prev - m_cur)
        l_ref[:] = jnp.broadcast_to(
            l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True),
            l_ref.shape,
        )
        acc_ref[:] = acc_ref[:] * corr + _dot(
            p.astype(v_ref.dtype), v_ref[0]
        )
        m_ref[:] = jnp.broadcast_to(m_cur, m_ref.shape)

    if causal:
        # Tiles entirely above the diagonal contribute nothing: skip.
        pl.when(ki * bk <= (qi + 1) * bq - 1)(_compute)
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:, :1] + jnp.log(l)          # [bq, 1] column


@functools.partial(
    jax.jit, static_argnames=("causal", "bq", "bk", "interpret")
)
def _flash_fwd(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
               bq: int, bk: int, interpret: bool
               ) -> Tuple[jax.Array, jax.Array]:
    """[BH, T, D] x3 -> (o [BH, T, D], lse f32[BH, T])."""
    bh, t, d = q.shape
    nq, nk = t // bq, t // bk
    scale = 1.0 / math.sqrt(d)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk
    )
    # Masked-tile DMA clamp (see module docstring): causal Q block i needs
    # K/V blocks j ≤ jmax(i); beyond that the index pins to jmax so the
    # pipeline issues no further copies for this row.
    if causal:
        kv_idx = lambda b, i, j: (
            b, jnp.minimum(j, ((i + 1) * bq - 1) // bk), 0
        )
    else:
        kv_idx = lambda b, i, j: (b, j, 0)
    o, lse_col = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), kv_idx),
            pl.BlockSpec((1, bk, d), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            # lse rides as a [BH, T, 1] column: a (1, bq) row block would
            # violate Mosaic's (8, 128) tiling rule (sublane dim 1), while
            # (1, bq, 1) is legal because the lane dim equals the array's.
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse_col[..., 0]


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, scale: float, causal: bool, bq: int, bk: int,
               nk: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _compute():
        q = q_ref[0]
        s = _dot(q, k_ref[0], trans_b=True) * scale
        if causal:
            s = jnp.where(_causal_mask(qi, ki, bq, bk), s, NEG_INF)
        lse = lse_ref[0]                                      # [bq, 1]
        p = jnp.exp(s - lse)                                  # [bq, bk]
        dp = _dot(do_ref[0], v_ref[0], trans_b=True)          # [bq, bk] f32
        ds = p * (dp - delta_ref[0])
        dq_acc[:] += _dot(ds.astype(k_ref.dtype), k_ref[0]) * scale

    if causal:
        pl.when(ki * bk <= (qi + 1) * bq - 1)(_compute)
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                causal: bool, bq: int, bk: int, nq: int):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute():
        q = q_ref[0]
        s = _dot(q, k_ref[0], trans_b=True) * scale           # [bq, bk]
        if causal:
            s = jnp.where(_causal_mask(qi, ki, bq, bk), s, NEG_INF)
        lse = lse_ref[0]                                      # [bq, 1]
        p = jnp.exp(s - lse)
        do = do_ref[0]
        dv_acc[:] += _dot(p.astype(do.dtype), do, trans_a=True)
        dp = _dot(do, v_ref[0], trans_b=True)
        ds = p * (dp - delta_ref[0])
        dk_acc[:] += _dot(ds.astype(q.dtype), q, trans_a=True) * scale

    if causal:
        pl.when((qi + 1) * bq - 1 >= ki * bk)(_compute)
    else:
        _compute()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "bq", "bk", "interpret")
)
def _flash_bwd(q, k, v, o, lse, do, causal: bool, bq: int, bk: int,
               interpret: bool, dlse=None):
    bh, t, d = q.shape
    nq, nk = t // bq, t // bk
    scale = 1.0 / math.sqrt(d)
    # Δ_i = Σ_d dO_i·O_i — one fused XLA reduction, reused by both kernels.
    # A logsumexp cotangent (ring-attention chunk merging differentiates
    # through the lse-dependent combine weights) enters the shared
    # dS = P ∘ (dP − Δ) term with opposite sign: dS += P ∘ dlse, i.e.
    # Δ_eff = Δ − dlse.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    # Column layout for the same Mosaic tiling reason as the forward's lse.
    lse_col = lse[..., None]
    delta_col = delta[..., None]

    # Same masked-tile DMA clamps as the forward (module docstring).
    if causal:
        kv_idx = lambda b, i, j: (
            b, jnp.minimum(j, ((i + 1) * bq - 1) // bk), 0
        )
        q_idx = lambda b, j, i: (b, jnp.maximum(i, (j * bk) // bq), 0)
    else:
        kv_idx = lambda b, i, j: (b, j, 0)
        q_idx = lambda b, j, i: (b, i, 0)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), kv_idx),
            pl.BlockSpec((1, bk, d), kv_idx),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse_col, delta_col)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), q_idx),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, d), q_idx),
            pl.BlockSpec((1, bq, 1), q_idx),
            pl.BlockSpec((1, bq, 1), q_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse_col, delta_col)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp plumbing + public entry
# ---------------------------------------------------------------------------


def _interpret() -> bool:
    # The shared ops-package interpret helper (one gate for all four
    # kernels); kept as a module-local name because the custom_vjp
    # plumbing below calls it at every trace.
    from trustworthy_dl_tpu.ops import pallas_interpret

    return pallas_interpret()


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal: bool, bq: int, bk: int):
    o, _ = _flash_fwd(q, k, v, causal, bq, bk, _interpret())
    return o


def _flash_vjp_fwd(q, k, v, causal, bq, bk):
    o, lse = _flash_fwd(q, k, v, causal, bq, bk, _interpret())
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, bq, bk, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, causal, bq, bk, _interpret())
    return dq, dk, dv


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_chunk(q, k, v, causal: bool, bq: int, bk: int):
    """[BH, T, D] -> (o, lse f32[BH, T]) with full AD support INCLUDING the
    lse output — the building block for ring attention's per-rotation
    chunk, whose cross-chunk combine weights depend on lse."""
    return _flash_fwd(q, k, v, causal, bq, bk, _interpret())


def _flash_chunk_vjp_fwd(q, k, v, causal, bq, bk):
    o, lse = _flash_fwd(q, k, v, causal, bq, bk, _interpret())
    return (o, lse), (q, k, v, o, lse)


def _flash_chunk_vjp_bwd(causal, bq, bk, res, cot):
    q, k, v, o, lse = res
    do, dlse = cot
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, causal, bq, bk,
                            _interpret(), dlse=dlse)
    return dq, dk, dv


flash_chunk.defvjp(_flash_chunk_vjp_fwd, _flash_chunk_vjp_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True) -> jax.Array:
    """[B, H, T, D] (or [BH, T, D]) blockwise flash attention.

    Drop-in for ``full_attention``: same math (pinned by
    tests/test_flash_attention.py), O(T·D) memory instead of O(T²).
    Non-tiling sequence lengths fall back to the XLA path.
    """
    from trustworthy_dl_tpu.models.gpt2 import full_attention

    squeeze = q.ndim == 3
    if squeeze:
        q, k, v = q[None], k[None], v[None]
    b, h, t, d = q.shape
    if not supports_flash(t, d):
        out = full_attention(q, k, v, causal)
        return out[0] if squeeze else out
    bq, bk = _blocks_for(t)

    merge = lambda a: a.reshape(b * h, t, d)
    out = _flash(merge(q), merge(k), merge(v), causal, bq, bk)
    out = out.reshape(b, h, t, d)
    return out[0] if squeeze else out


__all__ = ["flash_attention", "flash_chunk", "supports_flash"]

"""Pallas TPU kernel: fused weight-dequant matmul for int8 decode.

The weight-only-int8 decode matmul is ``y = (x @ w_q) * scale`` with
``w_q`` int8 ``[K, N]`` and a per-output-channel f32 ``scale [N]``.
Left to XLA, the ``w_q.astype(f32)`` convert can materialise a full
f32 copy of the weight en route to the MXU — which would hand back the
HBM-bandwidth saving that motivates int8 weights in the first place
(b<=MAX_SLOTS decode is weight-streaming-bound).  This kernel makes the
int8 stream explicit: each grid step DMAs one int8 ``[K, TILE_N]``
weight tile HBM→VMEM (half the bytes of bf16, a quarter of f32),
upcasts in-register, runs the MXU contraction with f32 accumulation,
and applies the column scales before the tile leaves VMEM.

Shapes are decode-shaped: ``x [M, K]`` with M = MAX_SLOTS (tiny) rides
along whole; the grid walks N.  Tiling constraints (f32 sublane 8, lane
128, int8 sublane 32) gate dispatch — ``dequant_matmul`` falls back to
the jnp contraction for shapes that do not tile, the same non-tiling
fallback pattern ``flash_attention`` uses.  The kernel runs anywhere via
``interpret=True`` (CPU tests); ``pallas_enabled()`` keeps the compiled
path TPU-only by default.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from trustworthy_dl_tpu.ops import pallas_enabled, pallas_interpret

TILE_N = 128


def _dq_matmul_kernel(x_ref, wq_ref, scale_ref, out_ref):
    """One output tile: [M, K] @ int8 [K, TILE_N] * scale [1, TILE_N]."""
    w = wq_ref[:].astype(jnp.float32)
    acc = jnp.dot(x_ref[:].astype(jnp.float32), w,
                  preferred_element_type=jnp.float32)
    out_ref[:] = acc * scale_ref[0, :][None, :]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _dq_matmul_pallas(x: jax.Array, w_q: jax.Array, scale: jax.Array,
                      interpret: bool = False) -> jax.Array:
    m, k = x.shape
    n = w_q.shape[1]
    return pl.pallas_call(
        _dq_matmul_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        grid=(n // TILE_N,),
        in_specs=[
            pl.BlockSpec((m, k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, TILE_N), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE_N), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, TILE_N), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x.astype(jnp.float32), w_q, scale.reshape(1, -1))


def dequant_matmul_tiles(m: int, k: int, n: int) -> bool:
    """Shape gate for the fused tile: N walks in 128-lane tiles and K
    must satisfy the int8 sublane (32) on the weight tile and the f32
    lane width on x.  M is NOT gated — ``dequant_matmul`` pads the row
    dim to the f32 sublane (8), because decode's M is MAX_SLOTS and slot
    counts are set by HBM budgets, not sublane multiples (the int8
    sizing itself produces odd counts like 15); gating on M would
    silently hand the weight-streaming win back on exactly the shapes
    the tier creates."""
    return n % TILE_N == 0 and k % 128 == 0 and m > 0


def dequant_matmul(x: jax.Array, w_q: jax.Array, scale: jax.Array,
                   interpret: Optional[bool] = None) -> jax.Array:
    """``[M, K] f* @ int8 [K, N] * f32 [N] -> f32 [M, N]`` with f32
    accumulation on every path.

    Dispatch mirrors ``fused_stats``: the Pallas tile runs when
    ``pallas_enabled()`` and the shapes tile (interpret mode off-TPU —
    tests); anything else takes the jnp contraction, whose numerics the
    kernel is pinned against in tests/test_quant.py."""
    m, k = x.shape
    n = w_q.shape[1]
    if interpret is None:
        interpret = pallas_interpret()
    if pallas_enabled() and dequant_matmul_tiles(m, k, n):
        pad = (-m) % 8   # f32 sublane on x/out; M = MAX_SLOTS is tiny
        if pad:
            x = jnp.concatenate(
                [x, jnp.zeros((pad, k), x.dtype)], axis=0
            )
        out = _dq_matmul_pallas(x, w_q, scale, interpret=interpret)
        return out[:m] if pad else out
    acc = jax.lax.dot_general(
        x.astype(jnp.float32), w_q.astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return acc * scale[None, :]


def lowrank_delta(x: jax.Array, a: jax.Array, b: jax.Array,
                  a_scale: Optional[jax.Array] = None,
                  b_scale: Optional[jax.Array] = None) -> jax.Array:
    """Batched gathered low-rank delta ``(x @ A) @ B`` for the paged
    adapter tier (serve/adapters.py), one site at a time::

        x [R, T, D] @ a [R, D, r] -> h [R, T, r] @ b [R, r, D]

    ``a``/``b`` are each row's gathered pool page — R rows may point at
    R different tenants' adapters in one contraction (the segmented
    batched-matmul form of the per-slot page table).  On the int8 tier
    the pages arrive int8 with per-row scales [R]: the upcast happens
    in-register inside the f32-accumulating contraction and the scale
    multiplies the accumulator — the same dequant-in-register discipline
    as :func:`dequant_matmul`, never a materialised f32 pool copy.
    Accumulation is f32 on every path; rank is tiny (r << D), so the
    contraction is bandwidth-trivial next to the base matmuls and needs
    no dedicated tile."""
    h = jnp.einsum("rtd,rdk->rtk", x.astype(jnp.float32),
                   a.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    if a_scale is not None:
        h = h * a_scale[:, None, None]
    out = jnp.einsum("rtk,rkd->rtd", h, b.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    if b_scale is not None:
        out = out * b_scale[:, None, None]
    return out

"""Chunked fused lm-head + cross-entropy.

The standard GPT-2 loss path materialises the full logits tensor
``[B, T, V]`` in f32 (V = 50257): ≈1.6 GB for a 8k-token batch — written
by the head matmul, read by the loss, written again as ``dlogits`` in the
backward pass.  On TPU that HBM round-trip, not the matmul FLOPs, bounds
the loss step, and the tensor's size caps the trainable batch.

This op never builds the logits.  The vocabulary is processed in chunks
inside a ``lax.scan``: each iteration computes one ``[N, C]`` logit block
on the MXU, folds it into a running online logsumexp (the same
streaming-softmax recurrence flash attention uses along the key axis —
here along the vocab axis), and gathers the target column where it lands
in the chunk.  Peak memory is ``O(N · C)`` instead of ``O(N · V)``.

The backward pass recomputes each logit block from the saved activations
and per-row logsumexp — softmax(x)ᵥ = exp(xᵥ − lse) — and immediately
contracts it into ``dx`` and ``dW``; ``dlogits`` exists only one chunk at
a time.  One extra head-matmul of recompute buys the elimination of every
logits-sized HBM round-trip, the standard TPU rematerialisation trade.

No reference equivalent (the reference's criterion is
``nn.CrossEntropyLoss`` over materialised logits,
distributed_trainer.py:435-439); numerics match models/layers.py
``cross_entropy_loss`` to f32 precision — pinned by tests/test_fused_ce.py.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Any, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

# Default vocab-chunk width: multiples of 128 keep the MXU tiling clean;
# 8192 keeps the [N, C] block under ~256 MB f32 for 8k-token batches.
DEFAULT_CHUNK = 8192


def _pad_vocab(w: Array, chunk: int) -> Tuple[Array, int]:
    """Pad [V, D] weights with zero rows to a multiple of ``chunk``."""
    v = w.shape[0]
    pad = (-v) % chunk
    if pad:
        w = jnp.concatenate([w, jnp.zeros((pad, w.shape[1]), w.dtype)], 0)
    return w, v


def fused_lm_loss(x: Array, w: Array, targets: Array,
                  chunk: int = DEFAULT_CHUNK,
                  compute_dtype: Any = jnp.bfloat16) -> Array:
    """Mean cross-entropy of ``softmax(x @ w.T)`` against ``targets``
    without materialising the logits.

    x: [..., D] final (post-ln) activations; w: [V, D] tied embedding;
    targets: [...] int labels.  Returns the scalar mean NLL.
    """
    return _make_fused(int(chunk), jnp.dtype(compute_dtype).name)(
        x, w, targets
    )


@lru_cache(maxsize=None)
def _make_fused(chunk: int, dtype_name: str):
    """custom_vjp requires nondiff config at the front of the arg list;
    closing over it (cached per (chunk, dtype)) keeps the public call
    signature free-form without retracing."""
    compute_dtype = jnp.dtype(dtype_name)

    @jax.custom_vjp
    def fused(x, w, targets):
        loss, _ = _forward(x, w, targets, chunk, compute_dtype)
        return loss

    def fwd(x, w, targets):
        loss, res = _forward(x, w, targets, chunk, compute_dtype)
        return loss, res

    def bwd(carry, g):
        return _bwd(chunk, compute_dtype, carry, g)

    fused.defvjp(fwd, bwd)
    return fused


def _forward(x: Array, w: Array, targets: Array, chunk: int,
             compute_dtype) -> Tuple[Array, Tuple[Array, ...]]:
    d = x.shape[-1]
    xf = x.reshape(-1, d).astype(compute_dtype)
    tgt = targets.reshape(-1)
    n = xf.shape[0]
    wp, v = _pad_vocab(w.astype(compute_dtype), chunk)
    w_chunks = wp.reshape(-1, chunk, d)

    def body(carry, args):
        m, s, tlogit = carry
        wc, base = args
        logits = jnp.einsum("nd,cd->nc", xf, wc,
                            preferred_element_type=jnp.float32)  # MXU, f32 acc
        col = jnp.arange(chunk) + base
        logits = jnp.where(col[None, :] < v, logits, -jnp.inf)
        # online logsumexp: m' = max(m, max_c), s' = s·e^{m−m'} + Σe^{l−m'}
        m_new = jnp.maximum(m, jnp.max(logits, axis=1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=1
        )
        # gather the target column if it falls in this chunk
        local = tgt - base
        in_chunk = (tgt >= base) & (tgt < base + chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk - 1)[:, None], axis=1
        )[:, 0]
        tlogit = jnp.where(in_chunk, picked, tlogit)
        return (m_new, s, tlogit), None

    n_chunks = w_chunks.shape[0]
    bases = jnp.arange(n_chunks) * chunk
    init = (
        jnp.full((n,), -jnp.inf, jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32),
    )
    (m, s, tlogit), _ = jax.lax.scan(body, init, (w_chunks, bases))
    lse = m + jnp.log(s)
    loss = jnp.mean(lse - tlogit)
    # Residuals must be arrays: dx's shape/dtype are reconstructed in the
    # backward pass from ``targets`` (unflattened) and a 0-d dtype token.
    return loss, (xf, w, targets, lse, jnp.zeros((), x.dtype))


def _bwd(chunk, compute_dtype, carry, g):
    xf, w, targets, lse, x_token = carry
    tgt = targets.reshape(-1)
    d = xf.shape[-1]
    n = xf.shape[0]
    x_shape = targets.shape + (d,)
    x_dtype = x_token.dtype
    wp, v = _pad_vocab(w.astype(compute_dtype), chunk)
    w_chunks = wp.reshape(-1, chunk, d)
    scale = g / n  # d(mean)/d(nll_i)

    def body(dx, args):
        wc, base = args
        logits = jnp.einsum("nd,cd->nc", xf, wc,
                            preferred_element_type=jnp.float32)
        col = jnp.arange(chunk) + base
        probs = jnp.exp(logits - lse[:, None])
        probs = jnp.where(col[None, :] < v, probs, 0.0)
        onehot = (tgt[:, None] == col[None, :]).astype(jnp.float32)
        dlogits = ((probs - onehot) * scale).astype(compute_dtype)  # [N, C]
        dx = dx + jnp.einsum("nc,cd->nd", dlogits, wc,
                             preferred_element_type=jnp.float32)
        dwc = jnp.einsum("nc,nd->cd", dlogits, xf,
                         preferred_element_type=jnp.float32)
        return dx, dwc

    n_chunks = w_chunks.shape[0]
    bases = jnp.arange(n_chunks) * chunk
    dx, dw_chunks = jax.lax.scan(
        body, jnp.zeros((n, d), jnp.float32), (w_chunks, bases)
    )
    dw = dw_chunks.reshape(-1, d)[: w.shape[0]].astype(w.dtype)
    dx = dx.reshape(x_shape).astype(x_dtype)
    dtgt = None  # int targets carry no tangent
    return dx, dw, dtgt


def fused_lm_eval(x: Array, w: Array, targets: Array,
                  chunk: int = DEFAULT_CHUNK,
                  compute_dtype: Any = jnp.bfloat16
                  ) -> Tuple[Array, Array]:
    """(mean NLL, accuracy) without materialising the [N, V] logits —
    the evaluation twin of fused_lm_loss (no backward pass, so no
    custom_vjp needed).  Tracks the running (max logit, argmax) across
    vocab chunks for accuracy alongside the online logsumexp for loss."""
    compute_dtype = jnp.dtype(compute_dtype)
    d = x.shape[-1]
    xf = x.reshape(-1, d).astype(compute_dtype)
    tgt = targets.reshape(-1)
    n = xf.shape[0]
    wp, v = _pad_vocab(w.astype(compute_dtype), chunk)
    w_chunks = wp.reshape(-1, chunk, d)

    def body(carry, args):
        m, s, tlogit, best, best_idx = carry
        wc, base = args
        logits = jnp.einsum("nd,cd->nc", xf, wc,
                            preferred_element_type=jnp.float32)
        col = jnp.arange(chunk) + base
        logits = jnp.where(col[None, :] < v, logits, -jnp.inf)
        chunk_max = jnp.max(logits, axis=1)
        chunk_arg = base + jnp.argmax(logits, axis=1)
        better = chunk_max > best
        best = jnp.where(better, chunk_max, best)
        best_idx = jnp.where(better, chunk_arg, best_idx)
        m_new = jnp.maximum(m, chunk_max)
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=1
        )
        local = tgt - base
        in_chunk = (tgt >= base) & (tgt < base + chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk - 1)[:, None], axis=1
        )[:, 0]
        tlogit = jnp.where(in_chunk, picked, tlogit)
        return (m_new, s, tlogit, best, best_idx), None

    n_chunks = w_chunks.shape[0]
    bases = jnp.arange(n_chunks) * chunk
    init = (
        jnp.full((n,), -jnp.inf, jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.full((n,), -jnp.inf, jnp.float32),
        jnp.zeros((n,), jnp.int32),
    )
    (m, s, tlogit, _, best_idx), _ = jax.lax.scan(
        body, init, (w_chunks, bases)
    )
    loss = jnp.mean(m + jnp.log(s) - tlogit)
    accuracy = jnp.mean((best_idx == tgt).astype(jnp.float32))
    return loss, accuracy

"""Pallas TPU serving-kernel tier: ragged paged attention (decode +
query-tiled chunked prefill), the fused speculative-verify tail, the
in-grid adapter gather, and the fused trust epilogue.

Decode attention over the paged KV pool (serve/kv_slots.PagedKV) has been
reading the cache through jnp gathers: ``models/generate._paged_gather``
materialises each row's FULL logical view [R, H, NBPS·BLOCK, Dh] in HBM
every layer of every tick, pays the gather bandwidth for positions past
the row's true length, and dequantises the int8 tier by algebra over that
view.  This kernel makes the stream explicit — the single biggest
tokens/sec lever ROADMAP item 2 names:

* **one program per block-table row** (grid ``(R, H, NBPS)``): the block
  table and per-row lengths ride as scalar-prefetch operands, so the
  KV BlockSpec index map resolves ``logical block j -> physical block
  table[r, j]`` before the DMA is issued — the gather IS the pipeline,
  no [R, H, S, Dh] view is ever materialised;
* **int8 streaming**: int8 KV tiles DMA HBM→VMEM at half the bf16 bytes
  (a quarter of f32), upcast in-register, and the per-(head, position)
  scales PagedKV already pages multiply the scores/probabilities exactly
  where the algebraic jnp path applies them;
* **online softmax** (flash-attention style (m, l, acc) accumulators,
  f32 regardless of input dtype);
* **ragged early exit**: a row with ``start + T`` valid positions streams
  ``ceil((start+T)/BLOCK)`` blocks and not one more — the index map
  CLAMPS masked iterations to the row's last useful block (a repeated
  block index issues no copy, the same bandwidth trick as
  ``flash_attention``'s causal skip) and ``pl.when`` skips their compute.

**Chunked-prefill program** (:func:`paged_prefill_attention`): the
multi-query-row extension.  T chunk rows per slot tile into
``q_tile``-row query tiles (grid ``(R, H, NT, NBPS)``) attending over
the SAME scalar-prefetch block tables with the ragged causal mask in
absolute positions.  The per-(row, tile) last-useful-block bound rides
as a third scalar-prefetch operand, so an early query tile streams only
the KV blocks its causal window can see — the flash-attention causal
skip applied ACROSS query tiles of a paged table, which the one-block-
bound decode program cannot express.  This replaces ``paged_chunk``'s
gathered-view attention (the whole-prompt [R, H, S, Dh] view per chunk
per layer).

**Fused speculative-verify tail** (:func:`fused_verify_tail`): the spec
verify window needs logits at EVERY draft position plus the per-position
trust stats.  The jnp tail (``models/generate._all_logits`` then
``logit_trust_stats``) projects [R·(k+1), V] logits to HBM and re-reads
them for the reductions.  The fused program streams ``wte_head`` in
vocab tiles through ONE grid: each step runs the tile's head matmul,
writes the logits tile (sampling's ``jax.random.categorical`` needs the
full row — gumbel noise cannot be reproduced in-kernel without forking
the sampled stream) and folds the SAME online entropy/top-2 algebra as
the trust epilogue over the tile before it leaves VMEM — one vocab
pass, no separate stats read, margin still bit-exact.

**In-grid adapter gather** (:func:`adapter_delta`): the per-tenant
low-rank delta (serve/adapters.py) was a ``jnp.take`` of each row's
pool page ``a_l[apages]`` OUTSIDE the kernel grid.  Here the per-slot
``adapter_page_row`` joins the scalar-prefetch operands: the A/B delta
tiles stream HBM→VMEM alongside the KV blocks (index map resolves
``row -> pages[row]`` before the DMA), int8 pages upcast in-register
with their per-(page, site) scales applied in exactly the
``fused_dequant_matmul.lowrank_delta`` order — the host-of-grid take is
gone.

**Trust epilogue** (:func:`logit_trust_stats`): the serve-side output
monitor reduces every decode step's logits to softmax entropy + top-1
margin (serve/scheduler._logit_signals).  Left to jnp that is a
log_softmax pass, an exp/sum pass and a hierarchical top-k over the
vocab; the epilogue kernel streams the [B, V] logits ONCE, keeping
online (max, Σe^{x−m}, Σx·e^{x−m}) and an exact top-2 merge — entropy
``logZ − Σxp`` and margin ``top1 − top2`` in a single HBM read, so
serve-side trust monitoring rides the decode step at the cost of reading
logits once (which sampling pays anyway).

Dispatch: behind the shared ops-package gate (``pallas_enabled
("TDDL_PAGED_ATTN")`` — default ON on TPU, opt-in off-TPU where it runs
in interpret mode) with the jnp path as the always-available fallback
and reference semantics.  The serving engine resolves ONE path PER
PROGRAM at construction (:func:`resolve_attn_impl` for the decode
program — "pallas" | "interpret" | "jnp" — and
:func:`resolve_attn_impls` for the whole tier: ineligible satellite
programs downgrade LOUDLY to jnp instead of raising, so a geometry that
can decode but not verify still serves) and threads each through its
compiled programs as STATIC values, so A/B arms and tests retrace
cleanly instead of aliasing each other in the process-global jit cache,
and the compile-once pin is untouched: tables/lengths/adapter pages
stay traced VALUES, block and adapter churn never recompile.

Numerics: the online softmax is mathematically identical to the jnp
path's full softmax but accumulates in a different order, so kernel
logits agree to f32-rounding epsilon rather than bit-for-bit (the same
contract as flash-vs-XLA attention; near-tie greedy flips are possible
in principle).  The margin half of the epilogue IS bit-exact (max/merge
only); entropy agrees to epsilon.  tests/test_paged_attention.py pins
both, plus bit-identical served streams vs ``generate()``.
"""

from __future__ import annotations

import functools
import logging
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from trustworthy_dl_tpu.ops import pallas_enabled, pallas_interpret

logger = logging.getLogger(__name__)

NEG_INF = -1e30          # finite stand-in: exp(NEG_INF - m) flushes to 0
MAX_HEAD_DIM = 512       # same Mosaic comfort bound as flash_attention
#: f32 sublane: the query tile's second-to-minor dim (T) pads up to this.
QROWS = 8
#: Vocab tile of the trust epilogue (lanes; V pads up to a multiple).
TRUST_TILE = 512

#: Engine-facing path names.  "auto" resolves through the shared gate;
#: the resolved value is one of the other three.
ATTN_IMPLS = ("auto", "pallas", "interpret", "jnp")

#: The serving-kernel tier's programs: ragged paged-decode attention,
#: the query-tiled chunked-prefill program, the fused speculative-verify
#: tail, and the in-grid adapter low-rank gather.
PAGED_PROGRAMS = ("decode", "prefill", "verify", "adapter")


def kv_sublane(kv_dtype) -> int:
    """Mosaic sublane width for a compiled KV tile of ``kv_dtype``: the
    second-to-minor dim must be a multiple of 32/itemsize — 8 for f32,
    16 for bf16, 32 for int8 (= quant.int8.INT8_SUBLANE)."""
    import numpy as np

    return max(QROWS, 32 // np.dtype(kv_dtype).itemsize)


def supports_paged_attention(*, head_dim: int, block_size: int,
                             kv_dtype, interpret: bool,
                             program: str = "decode",
                             n_embd: Optional[int] = None,
                             adapter_rank: Optional[int] = None) -> bool:
    """THE kernel-eligibility predicate (the ``supports_flash`` pattern),
    now PER PROGRAM: every dispatch site must consult it so the fallback
    condition can never drift from a kernel's real constraints.

    ``"decode"`` / ``"prefill"`` (the attention programs): compiled
    Mosaic needs the KV tile's sublane (= pool ``block_size``) to be a
    multiple of :func:`kv_sublane` for the POOL's storage dtype (8 f32,
    16 bf16, 32 int8), and ``head_dim <= MAX_HEAD_DIM``.  The prefill
    program's query tiles add no constraint beyond the decode program's
    (its T dim pads to the same :data:`QROWS` sublane).

    ``"verify"`` (the fused logits + trust tail): the head matmul's
    contraction dim is ``n_embd`` — compiled Mosaic wants it a multiple
    of the 128-lane width (true for every real GPT-2 geometry; tiny
    test configs run interpret).

    ``"adapter"`` (the in-grid low-rank gather): the delta contraction's
    minor dim is the adapter rank — compiled eligibility conservatively
    requires ``rank % QROWS == 0`` plus the verify rule on ``n_embd``
    (small-rank Mosaic tiling is unvalidated until a healthy TPU round —
    ROADMAP items 3/4); ranks below that downgrade loudly to the
    gathered jnp path.

    Interpret mode (CPU tests) has no tiling rules — only sanity bounds
    — so the equality pins run at the small geometries the test pools
    use."""
    if program not in PAGED_PROGRAMS:
        raise ValueError(
            f"program must be one of {PAGED_PROGRAMS}, got {program!r}")
    if head_dim < 1 or block_size < 1 or head_dim > MAX_HEAD_DIM:
        return False
    if program == "verify":
        if interpret:
            return True
        return n_embd is not None and n_embd % 128 == 0
    if program == "adapter":
        if adapter_rank is None or adapter_rank < 1:
            return False
        if interpret:
            return True
        return (adapter_rank % QROWS == 0
                and n_embd is not None and n_embd % 128 == 0)
    if interpret:
        return True
    return block_size % kv_sublane(kv_dtype) == 0


def resolve_attn_impl(requested: str, *, head_dim: int, block_size: int,
                      kv_dtype) -> str:
    """Resolve the engine's ``attn_impl`` knob ONCE, at construction —
    never inside a traced program — to the path its compiled programs
    will bake in: ``"pallas"`` (compiled Mosaic, TPU), ``"interpret"``
    (the same kernel through the Pallas interpreter, off-TPU tests) or
    ``"jnp"`` (the gather fallback, the default everywhere the gate is
    off).

    ``"auto"`` consults the shared ``pallas_enabled("TDDL_PAGED_ATTN")``
    gate and downgrades to "jnp" with a loud warning when the geometry
    cannot tile (a silent fallback must at least log; the serve snapshot
    gauge + the sentinel's decode-tick fraction page the rest).  An
    explicit ``"pallas"`` that cannot dispatch COMPILED Mosaic raises —
    the operator asked for the kernel by name, and that includes a
    non-TPU backend (the interpreter is not the kernel; ask for
    ``"interpret"`` explicitly to run it)."""
    if requested not in ATTN_IMPLS:
        raise ValueError(
            f"attn_impl must be one of {ATTN_IMPLS}, got {requested!r}"
        )
    if requested == "jnp":
        return "jnp"
    if requested == "pallas" and pallas_interpret():
        raise ValueError(
            "attn_impl='pallas' needs the TPU backend to dispatch "
            "compiled Mosaic (this process is on "
            "a non-TPU backend); use attn_impl='interpret' to run the "
            "kernel through the Pallas interpreter, or 'auto'/'jnp'"
        )
    if requested == "auto" and not pallas_enabled("TDDL_PAGED_ATTN"):
        return "jnp"
    mode = "interpret" if (requested == "interpret"
                           or pallas_interpret()) else "pallas"
    if supports_paged_attention(head_dim=head_dim, block_size=block_size,
                                kv_dtype=kv_dtype,
                                interpret=(mode == "interpret")):
        return mode
    detail = (
        f"head_dim={head_dim}, block_size={block_size}, "
        f"kv_dtype={kv_dtype}: compiled Mosaic needs block_size % "
        f"{kv_sublane(kv_dtype)} (the dtype's sublane) == 0 "
        f"and head_dim <= {MAX_HEAD_DIM}"
    )
    if requested in ("pallas", "interpret"):
        raise ValueError(
            f"attn_impl={requested!r} cannot dispatch the paged-attention "
            f"kernel ({detail})"
        )
    logger.warning(
        "paged-attention kernel unsupported for this pool geometry (%s); "
        "falling back to the jnp gather path — expect the decode-tick "
        "fraction to page in the perf sentinel", detail,
    )
    return "jnp"


def resolve_attn_impls(requested: str, *, head_dim: int, block_size: int,
                       kv_dtype, n_embd: int,
                       adapter_rank: Optional[int] = None) -> dict:
    """Resolve the WHOLE serving-kernel tier at construction: one impl
    per program in :data:`PAGED_PROGRAMS`.

    The decode program keeps :func:`resolve_attn_impl`'s loud contract
    (explicit asks that cannot dispatch raise).  The satellite programs
    — prefill, verify, adapter — inherit the decode resolution where
    their geometry is eligible and DOWNGRADE LOUDLY to ``"jnp"`` where
    it is not, even under an explicit ask: a pool that can decode but
    whose ``n_embd`` cannot tile the verify matmul must still serve,
    and the per-program gauge + the sentinel fractions page the
    downgrade rather than an exception unwinding the engine.  An
    unconfigured adapter tier (``adapter_rank`` falsy) resolves its
    program to ``"jnp"`` silently — there is nothing to fuse."""
    decode = resolve_attn_impl(requested, head_dim=head_dim,
                               block_size=block_size, kv_dtype=kv_dtype)
    impls = {p: "jnp" for p in PAGED_PROGRAMS}
    impls["decode"] = decode
    if decode == "jnp":
        return impls
    interp = decode == "interpret"
    for program in ("prefill", "verify", "adapter"):
        if program == "adapter" and not adapter_rank:
            continue
        if supports_paged_attention(
                head_dim=head_dim, block_size=block_size,
                kv_dtype=kv_dtype, interpret=interp, program=program,
                n_embd=n_embd, adapter_rank=adapter_rank):
            impls[program] = decode
        else:
            logger.warning(
                "paged %s program cannot dispatch compiled Mosaic for "
                "this geometry (n_embd=%s, adapter_rank=%s); that "
                "program falls back to jnp — expect its sentinel "
                "fraction to page", program, n_embd, adapter_rank,
            )
    return impls


def _dot(a: jax.Array, b: jax.Array, trans_b: bool = False) -> jax.Array:
    """f32-accumulating matmul for the MXU."""
    cb = 1 if trans_b else 0
    return jax.lax.dot_general(
        a, b, (((1,), (cb,)), ((), ())), preferred_element_type=jnp.float32
    )


# ---------------------------------------------------------------------------
# Ragged paged-decode attention kernel
# ---------------------------------------------------------------------------


def _paged_attn_kernel(table_ref, start_ref, jmax_ref, q_ref, k_ref, v_ref,
                       ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref, *,
                       scale: float, bsz: int, tq: int, quantized: bool):
    """One (row, head, logical-block) grid step of the online softmax.

    Scalar-prefetch refs: ``table_ref`` i32[R, NBPS] (physical ids —
    also consumed by the index maps, which is what makes the gather part
    of the DMA pipeline), ``start_ref`` i32[R] (first query's absolute
    position) and ``jmax_ref`` i32[R] (the row's last useful logical
    block — the ragged early-exit bound)."""
    r = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    jmax = jmax_ref[r]

    @pl.when(j <= jmax)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)              # [tq, Dh]
        k = k_ref[0, 0].astype(jnp.float32)              # [bsz, Dh]
        s = _dot(q, k, trans_b=True) * scale             # [tq, bsz] f32
        if quantized:
            # Per-(head, position) K scale: constant along the contracted
            # Dh axis, so it multiplies the int8 score AFTER the dot —
            # the same algebra models/generate._block_with_cache applies
            # to the gathered view.
            s = s * ks_ref[0, 0][None, :]
        # Causal + ragged mask in absolute positions: query start+t sees
        # cache slots [0, start+t]; everything past the row's true length
        # (garbage in the final block, trash-block padding) is masked.
        kpos = j * bsz + jax.lax.broadcasted_iota(jnp.int32, (tq, bsz), 1)
        qpos = start_ref[r] + jax.lax.broadcasted_iota(
            jnp.int32, (tq, bsz), 0)
        s = jnp.where(kpos <= qpos, s, NEG_INF)
        m_prev = m_ref[:, :1]                            # [tq, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_cur)                           # masked -> 0
        corr = jnp.exp(m_prev - m_cur)
        l_ref[:] = jnp.broadcast_to(
            l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True),
            l_ref.shape,
        )
        if quantized:
            # V scale folds into the probabilities before the PV
            # contraction — again the gathered-view algebra, in-register.
            p = p * vs_ref[0, 0][None, :]
        v = v_ref[0, 0].astype(jnp.float32)              # [bsz, Dh]
        acc_ref[:] = acc_ref[:] * corr + _dot(p, v)
        m_ref[:] = jnp.broadcast_to(m_cur, m_ref.shape)

    @pl.when(j == jmax)
    def _finalize():
        # Finalised at the row's LAST USEFUL block, not the grid's last
        # iteration — the remaining j > jmax steps touch neither the
        # accumulators nor the output block, and their DMAs are clamped
        # to repeats by the index maps (no copies issued).
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _paged_attn_call(q: jax.Array, pool_k: jax.Array, pool_v: jax.Array,
                     k_scale: Optional[jax.Array],
                     v_scale: Optional[jax.Array],
                     table: jax.Array, start: jax.Array, jmax: jax.Array,
                     interpret: bool = False) -> jax.Array:
    """q [R, H, TQ, Dh] (TQ a multiple of QROWS) x pool [NB, H, BLOCK, Dh]
    -> out [R, H, TQ, Dh]."""
    r, h, tq, dh = q.shape
    nbps = table.shape[1]
    bsz = pool_k.shape[2]
    quantized = k_scale is not None
    scale = 1.0 / math.sqrt(dh)
    kernel = functools.partial(
        _paged_attn_kernel, scale=scale, bsz=bsz, tq=tq,
        quantized=quantized,
    )

    # Ragged early exit at the DMA level: logical block j of row r maps
    # to physical block table[r, min(j, jmax[r])] — beyond the row's last
    # useful block the index repeats and Pallas issues no further copy.
    def kv_idx(ri, hi, ji, tbl, st, jm):
        return (tbl[ri, jnp.minimum(ji, jm[ri])], hi, 0, 0)

    def scale_idx(ri, hi, ji, tbl, st, jm):
        return (tbl[ri, jnp.minimum(ji, jm[ri])], hi, 0)

    def q_idx(ri, hi, ji, tbl, st, jm):
        return (ri, hi, 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1, tq, dh), q_idx),
        pl.BlockSpec((1, 1, bsz, dh), kv_idx),
        pl.BlockSpec((1, 1, bsz, dh), kv_idx),
    ]
    operands = [q, pool_k, pool_v]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, bsz), scale_idx),
            pl.BlockSpec((1, 1, bsz), scale_idx),
        ]
        operands += [k_scale, v_scale]
    else:
        # Arity filler for the unquantized trace: the kernel never reads
        # ks_ref/vs_ref when ``quantized`` is static-False; feeding the
        # (already-resident) table keeps one kernel body for both tiers.
        in_specs += [
            pl.BlockSpec((1, nbps), lambda ri, hi, ji, tbl, st, jm: (0, 0)),
            pl.BlockSpec((1, nbps), lambda ri, hi, ji, tbl, st, jm: (0, 0)),
        ]
        operands += [table, table]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(r, h, nbps),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, tq, dh), q_idx),
        scratch_shapes=[
            pltpu.VMEM((tq, dh), jnp.float32),
            pltpu.VMEM((tq, 128), jnp.float32),
            pltpu.VMEM((tq, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((r, h, tq, dh), q.dtype),
        interpret=interpret,
    )(table, start, jmax, *operands)


def paged_attention(q: jax.Array, pool_k: jax.Array, pool_v: jax.Array,
                    table: jax.Array, start: jax.Array, *,
                    k_scale: Optional[jax.Array] = None,
                    v_scale: Optional[jax.Array] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Ragged paged-decode attention over ONE layer's block pool.

    ``q`` [R, H, T, Dh] queries at absolute positions ``start[r] + t``
    (``start`` i32[R] or scalar); ``pool_k``/``pool_v`` [NB, H, BLOCK,
    Dh] with optional int8 tier scales [NB, H, BLOCK]; ``table`` i32
    [R, NBPS] physical block ids (traced values — block churn never
    recompiles).  The row's K/V for positions [0, start+T) — INCLUDING
    the freshly written window — must already be in the pool: the
    kernel-path block (models/generate._paged_block) scatters the new
    rows first, then attends, where the jnp path writes into its
    gathered view.  Returns [R, H, T, Dh] in q's dtype with f32
    accumulation throughout.

    Semantics contract (pinned by tests/test_paged_attention.py against
    :func:`paged_attention_reference` and the jnp serve path): causal
    mask ``kpos <= start+t`` in absolute positions, int8 scales applied
    post-dot (K) / pre-contraction (V), positions past a row's length
    never read — neither compute nor DMA."""
    r, h, t, dh = q.shape
    bsz = pool_k.shape[2]
    nbps = table.shape[1]
    if interpret is None:
        interpret = pallas_interpret()
    if jnp.ndim(start) == 0:
        start = jnp.broadcast_to(start, (r,))
    start = start.astype(jnp.int32)
    # Last useful logical block per row (clipped into the table: a padded
    # prefill chunk can extend past the slot's allocation — those query
    # rows are discarded by the caller, and the mask keeps them finite).
    jmax = jnp.clip((start + t - 1) // bsz, 0, nbps - 1).astype(jnp.int32)
    t_pad = -(-t // QROWS) * QROWS
    if t_pad != t:
        # Mosaic sublane: the query tile's T dim pads to 8.  Pad rows
        # compute a (finite, masked) attention nobody reads.
        q = jnp.pad(q, ((0, 0), (0, 0), (0, t_pad - t), (0, 0)))
    out = _paged_attn_call(q, pool_k, pool_v, k_scale, v_scale,
                           table, start, jmax, interpret=interpret)
    return out[:, :, :t]


def paged_attention_reference(q: jax.Array, pool_k: jax.Array,
                              pool_v: jax.Array, table: jax.Array,
                              start: jax.Array, *,
                              k_scale: Optional[jax.Array] = None,
                              v_scale: Optional[jax.Array] = None
                              ) -> jax.Array:
    """The jnp gather semantics the kernel is pinned against — the same
    math models/generate routes through ``_paged_gather`` +
    ``_block_with_cache``, spelled standalone (f32 softmax, full-width
    mask) so the kernel test does not depend on the transformer block."""
    r, h, t, dh = q.shape
    bsz = pool_k.shape[2]
    if jnp.ndim(start) == 0:
        start = jnp.broadcast_to(start, (r,))

    def gather(pool):                       # [R, H, NBPS*BLOCK(, Dh)]
        g = pool[table]
        if g.ndim == 5:
            g = g.transpose(0, 2, 1, 3, 4)
            return g.reshape(r, h, -1, dh)
        g = g.transpose(0, 2, 1, 3)
        return g.reshape(r, h, -1)

    view_k = gather(pool_k).astype(jnp.float32)
    view_v = gather(pool_v).astype(jnp.float32)
    s = jnp.einsum("rhtd,rhkd->rhtk", q.astype(jnp.float32), view_k)
    s = s / math.sqrt(dh)
    if k_scale is not None:
        s = s * gather(k_scale)[:, :, None, :]
    kpos = jnp.arange(view_k.shape[2])[None, None, None, :]
    qpos = (start[:, None] + jnp.arange(t)[None, :])[:, None, :, None]
    s = jnp.where(kpos <= qpos, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if v_scale is not None:
        p = p * gather(v_scale)[:, :, None, :]
    return jnp.einsum("rhtk,rhkd->rhtd", p, view_v).astype(q.dtype)


# ---------------------------------------------------------------------------
# Chunked-prefill program: query-tiled multi-row attention with the
# flash causal skip ACROSS query tiles of the paged table
# ---------------------------------------------------------------------------


def _paged_prefill_kernel(table_ref, start_ref, jmax_ref, q_ref, k_ref,
                          v_ref, ks_ref, vs_ref, o_ref, acc_ref, m_ref,
                          l_ref, *, scale: float, bsz: int, qt: int,
                          quantized: bool):
    """One (row, head, query-tile, logical-block) grid step.

    Identical online-softmax algebra to :func:`_paged_attn_kernel`; the
    difference is the grid's query-tile dim and the PER-TILE ragged
    bound ``jmax_ref`` i32[R, NT]: tile ``ti``'s causal window ends at
    its own last query position, so an early tile of a long chunk
    streams a fraction of the blocks the whole chunk touches — the
    decode program's single per-row bound would stream (and mask) them
    all, for every tile."""
    r = pl.program_id(0)
    ti = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    jmax = jmax_ref[r, ti]

    @pl.when(j <= jmax)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)              # [qt, Dh]
        k = k_ref[0, 0].astype(jnp.float32)              # [bsz, Dh]
        s = _dot(q, k, trans_b=True) * scale             # [qt, bsz] f32
        if quantized:
            s = s * ks_ref[0, 0][None, :]
        # Causal + ragged mask in absolute positions: the tile's queries
        # sit at start + ti·qt + t.
        kpos = j * bsz + jax.lax.broadcasted_iota(jnp.int32, (qt, bsz), 1)
        qpos = start_ref[r] + ti * qt + jax.lax.broadcasted_iota(
            jnp.int32, (qt, bsz), 0)
        s = jnp.where(kpos <= qpos, s, NEG_INF)
        m_prev = m_ref[:, :1]                            # [qt, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_cur)
        corr = jnp.exp(m_prev - m_cur)
        l_ref[:] = jnp.broadcast_to(
            l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True),
            l_ref.shape,
        )
        if quantized:
            p = p * vs_ref[0, 0][None, :]
        v = v_ref[0, 0].astype(jnp.float32)              # [bsz, Dh]
        acc_ref[:] = acc_ref[:] * corr + _dot(p, v)
        m_ref[:] = jnp.broadcast_to(m_cur, m_ref.shape)

    @pl.when(j == jmax)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _paged_prefill_call(q: jax.Array, pool_k: jax.Array,
                        pool_v: jax.Array,
                        k_scale: Optional[jax.Array],
                        v_scale: Optional[jax.Array],
                        table: jax.Array, start: jax.Array,
                        jmax: jax.Array,
                        interpret: bool = False) -> jax.Array:
    """q [R, H, NT·QT, Dh] x pool [NB, H, BLOCK, Dh] -> out like q.
    ``jmax`` i32[R, NT] is the per-(row, query-tile) last useful logical
    block."""
    r, h, t_pad, dh = q.shape
    nt = jmax.shape[1]
    qt = t_pad // nt
    nbps = table.shape[1]
    bsz = pool_k.shape[2]
    quantized = k_scale is not None
    scale = 1.0 / math.sqrt(dh)
    kernel = functools.partial(
        _paged_prefill_kernel, scale=scale, bsz=bsz, qt=qt,
        quantized=quantized,
    )

    # Per-tile ragged early exit at the DMA level: past tile ti's causal
    # window the index repeats and no further copy is issued.
    def kv_idx(ri, hi, ti, ji, tbl, st, jm):
        return (tbl[ri, jnp.minimum(ji, jm[ri, ti])], hi, 0, 0)

    def scale_idx(ri, hi, ti, ji, tbl, st, jm):
        return (tbl[ri, jnp.minimum(ji, jm[ri, ti])], hi, 0)

    def q_idx(ri, hi, ti, ji, tbl, st, jm):
        return (ri, hi, ti, 0)

    in_specs = [
        pl.BlockSpec((1, 1, qt, dh), q_idx),
        pl.BlockSpec((1, 1, bsz, dh), kv_idx),
        pl.BlockSpec((1, 1, bsz, dh), kv_idx),
    ]
    operands = [q, pool_k, pool_v]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, bsz), scale_idx),
            pl.BlockSpec((1, 1, bsz), scale_idx),
        ]
        operands += [k_scale, v_scale]
    else:
        in_specs += [
            pl.BlockSpec((1, nbps),
                         lambda ri, hi, ti, ji, tbl, st, jm: (0, 0)),
            pl.BlockSpec((1, nbps),
                         lambda ri, hi, ti, ji, tbl, st, jm: (0, 0)),
        ]
        operands += [table, table]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(r, h, nt, nbps),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, qt, dh), q_idx),
        scratch_shapes=[
            pltpu.VMEM((qt, dh), jnp.float32),
            pltpu.VMEM((qt, 128), jnp.float32),
            pltpu.VMEM((qt, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((r, h, t_pad, dh), q.dtype),
        interpret=interpret,
    )(table, start, jmax, *operands)


def paged_prefill_attention(q: jax.Array, pool_k: jax.Array,
                            pool_v: jax.Array, table: jax.Array,
                            start: jax.Array, *,
                            k_scale: Optional[jax.Array] = None,
                            v_scale: Optional[jax.Array] = None,
                            interpret: Optional[bool] = None,
                            q_tile: int = QROWS) -> jax.Array:
    """Query-tiled chunked-prefill attention over ONE layer's block pool.

    The multi-query-row twin of :func:`paged_attention` for T ≫ 1: the
    chunk's T query rows split into ``q_tile``-row tiles, each with its
    OWN ragged causal bound (the last logical block its final query can
    see), so KV streaming is proportional to the causal area — the
    flash-attention causal skip over a paged block table.  Same
    semantics contract as :func:`paged_attention` (absolute-position
    mask, int8 scales post-dot / pre-contraction, clamped DMAs past
    each bound); the jnp pin is the same
    :func:`paged_attention_reference`."""
    r, h, t, dh = q.shape
    bsz = pool_k.shape[2]
    nbps = table.shape[1]
    if interpret is None:
        interpret = pallas_interpret()
    if jnp.ndim(start) == 0:
        start = jnp.broadcast_to(start, (r,))
    start = start.astype(jnp.int32)
    t_pad = -(-t // q_tile) * q_tile
    nt = t_pad // q_tile
    # Tile ti's last useful logical block: its final query sits at
    # start + (ti+1)·q_tile − 1 (pad rows in the last tile only widen
    # the bound — their output is sliced away and real rows' masks are
    # position-exact).
    tiles = jnp.arange(nt, dtype=jnp.int32)
    jmax = jnp.clip(
        (start[:, None] + (tiles[None, :] + 1) * q_tile - 1) // bsz,
        0, nbps - 1,
    ).astype(jnp.int32)
    if t_pad != t:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, t_pad - t), (0, 0)))
    out = _paged_prefill_call(q, pool_k, pool_v, k_scale, v_scale,
                              table, start, jmax, interpret=interpret)
    return out[:, :, :t]


# ---------------------------------------------------------------------------
# Trust epilogue: entropy + top-1 margin in one pass over the vocab
# ---------------------------------------------------------------------------


def _trust_init(m_ref, s_ref, w_ref, t1_ref, t2_ref):
    """Reset the five online-reduction accumulators (grid step 0)."""
    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    s_ref[:] = jnp.zeros_like(s_ref)
    w_ref[:] = jnp.zeros_like(w_ref)
    t1_ref[:] = jnp.full_like(t1_ref, NEG_INF)
    t2_ref[:] = jnp.full_like(t2_ref, NEG_INF)


def _trust_update(x, m_ref, s_ref, w_ref, t1_ref, t2_ref):
    """Fold one [B, TV] logit tile into the online reductions: logsumexp
    pieces (m, Σe^{x−m}, Σx·e^{x−m}) for the entropy and an exact top-2
    merge for the margin.  ONE spelling shared by the standalone trust
    epilogue and the fused verify tail, so the fused stats can never
    drift from the pinned epilogue algebra."""
    b, tv = x.shape
    tile_m = jnp.max(x, axis=-1, keepdims=True)          # [B, 1]
    m_prev = m_ref[:, :1]
    m_cur = jnp.maximum(m_prev, tile_m)
    corr = jnp.exp(m_prev - m_cur)
    e = jnp.exp(x - m_cur)
    s_ref[:] = jnp.broadcast_to(
        s_ref[:, :1] * corr + jnp.sum(e, axis=-1, keepdims=True),
        s_ref.shape,
    )
    w_ref[:] = jnp.broadcast_to(
        w_ref[:, :1] * corr + jnp.sum(x * e, axis=-1, keepdims=True),
        w_ref.shape,
    )
    m_ref[:] = jnp.broadcast_to(m_cur, m_ref.shape)
    # Exact top-2 within the tile: mask ONE argmax occurrence (duplicated
    # maxima must surface as top2 == top1), then merge with the running
    # pair — max/min only, so the margin is bit-exact vs lax.top_k.
    amax = jnp.argmax(x, axis=-1)[:, None]               # [B, 1]
    cols = jax.lax.broadcasted_iota(jnp.int32, (b, tv), 1)
    tile_t2 = jnp.max(jnp.where(cols == amax, NEG_INF, x), axis=-1,
                      keepdims=True)
    t1_prev = t1_ref[:, :1]
    t2_prev = t2_ref[:, :1]
    t1_ref[:] = jnp.broadcast_to(jnp.maximum(t1_prev, tile_m),
                                 t1_ref.shape)
    t2_ref[:] = jnp.broadcast_to(
        jnp.maximum(jnp.minimum(t1_prev, tile_m),
                    jnp.maximum(t2_prev, tile_t2)),
        t2_ref.shape,
    )


def _trust_finalize(ent_ref, mar_ref, m_ref, s_ref, w_ref, t1_ref,
                    t2_ref):
    """Write entropy/margin from the accumulators (last grid step)."""
    s = jnp.maximum(s_ref[:, :1], 1e-30)
    logz = m_ref[:, :1] + jnp.log(s)
    # entropy = -Σ p·logp = logZ - Σ p·x with p = e^{x-m}/s.
    ent_ref[:] = logz - w_ref[:, :1] / s                 # [B, 1]
    mar_ref[:] = t1_ref[:, :1] - t2_ref[:, :1]


def _trust_stats_kernel(x_ref, ent_ref, mar_ref, m_ref, s_ref, w_ref,
                        t1_ref, t2_ref, *, nv: int):
    """One [B, TRUST_TILE] logit tile of the standalone epilogue."""
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        _trust_init(m_ref, s_ref, w_ref, t1_ref, t2_ref)

    _trust_update(x_ref[:], m_ref, s_ref, w_ref, t1_ref, t2_ref)

    @pl.when(j == nv - 1)
    def _finalize():
        _trust_finalize(ent_ref, mar_ref, m_ref, s_ref, w_ref, t1_ref,
                        t2_ref)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _trust_stats_call(logits: jax.Array,
                      interpret: bool = False
                      ) -> Tuple[jax.Array, jax.Array]:
    b, v = logits.shape
    nv = v // TRUST_TILE
    ent, mar = pl.pallas_call(
        functools.partial(_trust_stats_kernel, nv=nv),
        grid=(nv,),
        in_specs=[pl.BlockSpec((b, TRUST_TILE), lambda j: (0, j))],
        out_specs=[
            # [B, 1] columns — the same Mosaic lane-dim rule as flash
            # attention's lse output.
            pl.BlockSpec((b, 1), lambda j: (0, 0)),
            pl.BlockSpec((b, 1), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, 1), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((b, 128), jnp.float32)
                        for _ in range(5)],
        interpret=interpret,
    )(logits)
    return ent[:, 0], mar[:, 0]


def logit_trust_stats(logits: jax.Array,
                      interpret: Optional[bool] = None
                      ) -> Tuple[jax.Array, jax.Array]:
    """(softmax entropy [B], top-1 logit margin [B]) of ``logits``
    [B, V] in ONE streaming pass — the output monitor's per-token
    reductions, fused so serve-side trust monitoring costs one extra
    read of nothing (the logits tile is already in VMEM).

    Margin is bit-exact vs the jnp reductions; entropy agrees to f32
    epsilon (online vs two-pass logsumexp)."""
    b, v = logits.shape
    if interpret is None:
        interpret = pallas_interpret()
    logits = logits.astype(jnp.float32)
    pad_v = (-v) % TRUST_TILE
    if pad_v:
        # NEG_INF (finite) padding: e^{pad-m} flushes to exactly 0 and
        # x·0 stays 0 (a true -inf would NaN the Σx·e term), and a pad
        # column can never win either top-2 slot.
        logits = jnp.pad(logits, ((0, 0), (0, pad_v)),
                         constant_values=NEG_INF)
    pad_b = (-b) % QROWS
    if pad_b:
        logits = jnp.pad(logits, ((0, pad_b), (0, 0)))
    ent, mar = _trust_stats_call(logits, interpret=interpret)
    return ent[:b], mar[:b]


def logit_trust_stats_reference(logits: jax.Array
                                ) -> Tuple[jax.Array, jax.Array]:
    """The jnp reference reductions (identical math to
    serve/scheduler._logit_signals' fallback path), for the equality
    pins."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    p = jnp.exp(logp)
    entropy = -jnp.sum(p * logp, axis=-1)
    top2 = jax.lax.top_k(logits, 2)[0]
    return entropy, top2[:, 0] - top2[:, 1]


# ---------------------------------------------------------------------------
# Fused speculative-verify tail: logits projection + trust stats in ONE
# streaming vocab pass
# ---------------------------------------------------------------------------


def _verify_tail_kernel(x_ref, w_ref, logits_ref, ent_ref, mar_ref,
                        m_ref, s_ref, wacc_ref, t1_ref, t2_ref, *,
                        nv: int, v: int, round_dtype):
    """One [TRUST_TILE, D] head tile: matmul the resident activations
    against it, WRITE the logits tile (sampling still needs the full
    row), and fold the tile into the shared trust reductions before it
    leaves VMEM."""
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        _trust_init(m_ref, s_ref, wacc_ref, t1_ref, t2_ref)

    acc = _dot(x_ref[:], w_ref[:], trans_b=True)         # [B, TV] f32
    if round_dtype is not None:
        # The jnp tail's matmul runs in the compute dtype and upcasts
        # AFTER — round the f32 accumulator the same way so the fused
        # logits match the materialised ones.
        acc = acc.astype(round_dtype).astype(jnp.float32)
    logits_ref[:] = acc
    # Vocab-padding columns (zero rows of the padded head) produce logit
    # 0, not NEG_INF — mask them out of the reductions exactly as the
    # standalone epilogue's NEG_INF padding does; the written tile's pad
    # columns are sliced away by the wrapper.
    b, tv = acc.shape
    cols = j * tv + jax.lax.broadcasted_iota(jnp.int32, (b, tv), 1)
    x = jnp.where(cols < v, acc, NEG_INF)
    _trust_update(x, m_ref, s_ref, wacc_ref, t1_ref, t2_ref)

    @pl.when(j == nv - 1)
    def _finalize():
        _trust_finalize(ent_ref, mar_ref, m_ref, s_ref, wacc_ref,
                        t1_ref, t2_ref)


@functools.partial(jax.jit, static_argnames=("v", "interpret", "round_to"))
def _verify_tail_call(normed: jax.Array, head: jax.Array, v: int,
                      interpret: bool = False,
                      round_to: Optional[str] = None
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    b, d = normed.shape
    v_pad = head.shape[0]
    nv = v_pad // TRUST_TILE
    round_dtype = jnp.dtype(round_to) if round_to is not None else None
    logits, ent, mar = pl.pallas_call(
        functools.partial(_verify_tail_kernel, nv=nv, v=v,
                          round_dtype=round_dtype),
        grid=(nv,),
        in_specs=[
            pl.BlockSpec((b, d), lambda j: (0, 0)),
            pl.BlockSpec((TRUST_TILE, d), lambda j: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((b, TRUST_TILE), lambda j: (0, j)),
            pl.BlockSpec((b, 1), lambda j: (0, 0)),
            pl.BlockSpec((b, 1), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, v_pad), jnp.float32),
            jax.ShapeDtypeStruct((b, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, 1), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((b, 128), jnp.float32)
                        for _ in range(5)],
        interpret=interpret,
    )(normed, head)
    return logits, ent[:, 0], mar[:, 0]


def fused_verify_tail(normed: jax.Array, head: jax.Array, *,
                      interpret: Optional[bool] = None
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The speculative-verify tail in ONE streaming vocab pass:
    ``normed`` [B, D] (post-ln_f activations, already in the compute
    dtype) x ``head`` [V, D] (the tied unembedding) -> (logits [B, V]
    f32, entropy [B], margin [B]).

    Replaces the two-pass jnp tail — ``_all_logits`` materialising
    [B, V] to HBM, then :func:`logit_trust_stats` re-reading it — with
    one grid over vocab tiles: each head tile is matmul'd, written once
    (the verify sampler's ``jax.random.categorical`` consumes full
    rows; its gumbel draws cannot be reproduced in-kernel without
    forking the sampled stream, so the logits write stays — the pass
    sampling pays anyway) and reduced while still in VMEM.  The trust
    algebra is literally the epilogue kernel's (`_trust_update`), so
    margin stays bit-exact vs ``lax.top_k`` over the SAME logits and
    entropy agrees to f32 epsilon."""
    b, d = normed.shape
    v = head.shape[0]
    if interpret is None:
        interpret = pallas_interpret()
    # Rounding contract: a bf16 jnp tail rounds the matmul to bf16
    # before the f32 upcast — mirror it so fused == materialised.
    round_to = (None if normed.dtype == jnp.float32
                else jnp.dtype(normed.dtype).name)
    pad_v = (-v) % TRUST_TILE
    if pad_v:
        head = jnp.pad(head, ((0, pad_v), (0, 0)))
    pad_b = (-b) % QROWS
    if pad_b:
        normed = jnp.pad(normed, ((0, pad_b), (0, 0)))
    logits, ent, mar = _verify_tail_call(normed, head, v,
                                         interpret=interpret,
                                         round_to=round_to)
    return logits[:b, :v], ent[:b], mar[:b]


# ---------------------------------------------------------------------------
# In-grid adapter gather: the per-slot low-rank delta with the page
# table as a scalar-prefetch operand
# ---------------------------------------------------------------------------


def _adapter_delta_kernel(pages_ref, sa_ref, sb_ref, x_ref, a_ref, b_ref,
                          o_ref):
    """One row's low-rank delta: the BlockSpec index maps resolved
    ``row -> pages[row]`` before the A/B DMAs were issued, so the pool
    pages stream HBM→VMEM exactly like KV blocks — no gathered [R, D,
    r] copy exists.  Scale order matches ``lowrank_delta`` exactly
    (h·sa between the contractions): scalar folding would change the
    f32 rounding the adapter parity pins rely on."""
    ri = pl.program_id(0)
    x = x_ref[0].astype(jnp.float32)                     # [T, D]
    a = a_ref[0].astype(jnp.float32)                     # [D, r]
    h = _dot(x, a) * sa_ref[ri]                          # [T, r] f32
    b = b_ref[0].astype(jnp.float32)                     # [r, D]
    o_ref[0] = _dot(h, b) * sb_ref[ri]                   # [T, D] f32


@functools.partial(jax.jit, static_argnames=("interpret",))
def _adapter_delta_call(x: jax.Array, a_pool: jax.Array,
                        b_pool: jax.Array, pages: jax.Array,
                        sa: jax.Array, sb: jax.Array,
                        interpret: bool = False) -> jax.Array:
    r, t_pad, d = x.shape
    rank = a_pool.shape[-1]

    def a_idx(ri, pg, sa_, sb_):
        return (pg[ri], 0, 0)

    def x_idx(ri, pg, sa_, sb_):
        return (ri, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(r,),
        in_specs=[
            pl.BlockSpec((1, t_pad, d), x_idx),
            pl.BlockSpec((1, d, rank), a_idx),
            pl.BlockSpec((1, rank, d), a_idx),
        ],
        out_specs=pl.BlockSpec((1, t_pad, d), x_idx),
        scratch_shapes=[],
    )
    return pl.pallas_call(
        _adapter_delta_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((r, t_pad, d), jnp.float32),
        interpret=interpret,
    )(pages, sa, sb, x, a_pool, b_pool)


def adapter_delta(x: jax.Array, a_pool: jax.Array, b_pool: jax.Array,
                  pages: jax.Array, *,
                  a_scale: Optional[jax.Array] = None,
                  b_scale: Optional[jax.Array] = None,
                  interpret: Optional[bool] = None) -> jax.Array:
    """In-grid paged low-rank delta for ONE adapter site:
    ``x`` [R, T, D] x pool pages ``a_pool`` [P+1, D, r] / ``b_pool``
    [P+1, r, D] selected by ``pages`` i32[R] (the per-slot
    ``adapter_page_row`` — a traced value, so adapter churn never
    recompiles) -> f32 [R, T, D].

    The kernel-grid twin of ``fused_dequant_matmul.lowrank_delta`` over
    ``a_pool[pages]`` — same contraction, same f32 accumulation, same
    scale order — minus the take: the page table joins the
    scalar-prefetch operands and each row's A/B tiles stream HBM→VMEM
    alongside its KV blocks.  ``a_scale``/``b_scale`` are the int8
    tier's per-page scales [P+1] for this site (None on the f32 tier —
    the kernel multiplies by exactly 1.0, a bitwise identity)."""
    r, t, d = x.shape
    if interpret is None:
        interpret = pallas_interpret()
    pages = pages.astype(jnp.int32)
    npg = a_pool.shape[0]
    ones = jnp.ones((npg,), jnp.float32)
    sa = ones if a_scale is None else a_scale.astype(jnp.float32)
    sb = ones if b_scale is None else b_scale.astype(jnp.float32)
    # The [R] per-row scale lookup happens outside — R scalars, not the
    # [R, D, r] page take this kernel exists to eliminate — and rides
    # scalar prefetch so the kernel reads its row's scale from SMEM.
    sa_row = sa[pages]
    sb_row = sb[pages]
    t_pad = -(-t // QROWS) * QROWS
    if t_pad != t:
        x = jnp.pad(x, ((0, 0), (0, t_pad - t), (0, 0)))
    out = _adapter_delta_call(x, a_pool, b_pool, pages, sa_row, sb_row,
                              interpret=interpret)
    return out[:, :t]


__all__ = [
    "ATTN_IMPLS",
    "MAX_HEAD_DIM",
    "PAGED_PROGRAMS",
    "adapter_delta",
    "fused_verify_tail",
    "logit_trust_stats",
    "logit_trust_stats_reference",
    "paged_attention",
    "paged_attention_reference",
    "paged_prefill_attention",
    "resolve_attn_impl",
    "resolve_attn_impls",
    "supports_paged_attention",
]

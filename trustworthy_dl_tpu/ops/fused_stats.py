"""Pallas TPU kernel: fused detector moment battery (SURVEY §7.1 native tier).

The in-step detector needs eight reductions of every gradient/feature tensor
(Σx, Σx², Σx³, Σx⁴, min, max, Σ|x|, max|x| — detect/stats.py raw-moment
battery).  XLA fuses same-shaped reductions well but still emits several
passes for the mixed sum/min/max combination on large inputs; this kernel
makes the single pass explicit: each grid step streams one [BLOCK_ROWS, 128]
tile HBM→VMEM and accumulates per-lane partials for all eight statistics in
one VMEM accumulator, so every gradient byte is read exactly once.

The kernel is TPU-shaped (lane width 128, f32 sublane 8) but runs anywhere
via ``interpret=True`` — tests exercise it on the CPU mesh.  The XLA
implementation in detect/stats.py remains the reference semantics; equality
is pinned by tests/test_ops.py.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# The ONE shared Pallas gate (ops/__init__.py) — re-exported here because
# this module introduced it and call sites (detect/stats.py, PARITY.md)
# name it as ``fused_stats.pallas_enabled``.  Measured dispatch policy for
# THIS kernel: on GPT-2-sized transformer gradients XLA's own fusion of
# the eight reductions is at parity with the kernel (round 3), but on
# VGG/ResNet conv gradients XLA emits multiple HBM passes and the
# kernel's explicit single pass is a ~20 % step-time win with detection
# on (round 4: VGG-16 48.3 → 57.8 steps/s).
from trustworthy_dl_tpu.ops import pallas_enabled, pallas_interpret  # noqa: F401

LANES = 128
BLOCK_ROWS = 512          # 512×128 f32 tile = 256 KB VMEM per step
_MIN_FUSED_SIZE = BLOCK_ROWS * LANES  # below this, XLA's fusion wins anyway

# Accumulator row layout.
_ROW_S1, _ROW_S2, _ROW_S3, _ROW_S4 = 0, 1, 2, 3
_ROW_MIN, _ROW_MAX, _ROW_L1, _ROW_LINF = 4, 5, 6, 7


def _moments_kernel(x_ref, acc_ref):
    """One [BLOCK_ROWS, LANES] tile: accumulate per-lane partials."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        acc_ref[_ROW_MIN, :] = jnp.full((LANES,), jnp.inf, jnp.float32)
        acc_ref[_ROW_MAX, :] = jnp.full((LANES,), -jnp.inf, jnp.float32)

    x = x_ref[:]
    x2 = x * x
    ax = jnp.abs(x)
    acc_ref[_ROW_S1, :] += jnp.sum(x, axis=0)
    acc_ref[_ROW_S2, :] += jnp.sum(x2, axis=0)
    acc_ref[_ROW_S3, :] += jnp.sum(x2 * x, axis=0)
    acc_ref[_ROW_S4, :] += jnp.sum(x2 * x2, axis=0)
    acc_ref[_ROW_MIN, :] = jnp.minimum(acc_ref[_ROW_MIN, :], jnp.min(x, axis=0))
    acc_ref[_ROW_MAX, :] = jnp.maximum(acc_ref[_ROW_MAX, :], jnp.max(x, axis=0))
    acc_ref[_ROW_L1, :] += jnp.sum(ax, axis=0)
    acc_ref[_ROW_LINF, :] = jnp.maximum(
        acc_ref[_ROW_LINF, :], jnp.max(ax, axis=0)
    )


@functools.partial(jax.custom_jvp, nondiff_argnums=(1,))
def _fused_tile_moments_impl(tiles: jax.Array,
                             interpret: bool = False) -> jax.Array:
    """[R, 128] f32 (R a multiple of BLOCK_ROWS) -> [8, 128] lane partials.

    custom_jvp with zero tangents: the battery is diagnostics — nothing
    intentionally differentiates it — but it runs on values INSIDE the
    engine's value_and_grad (feature activations depend on params), and
    ``pallas_call`` has no JVP rule (AD through the kernel asserts inside
    pallas' program_id at trace time).  Treating the statistics as
    constant under differentiation is both the fix and the correct
    semantics (fused_moments also stop-gradients its input so the XLA
    tail/fallback paths share that contract)."""
    grid = tiles.shape[0] // BLOCK_ROWS
    return pl.pallas_call(
        _moments_kernel,
        out_shape=jax.ShapeDtypeStruct((8, LANES), jnp.float32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(
                (BLOCK_ROWS, LANES),
                lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec((8, LANES), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(tiles)


@_fused_tile_moments_impl.defjvp
def _fused_tile_moments_jvp(interpret, primals, tangents):
    (tiles,) = primals
    out = _fused_tile_moments_impl(tiles, interpret)
    return out, jnp.zeros_like(out)


_fused_tile_moments = jax.jit(_fused_tile_moments_impl,
                              static_argnames=("interpret",))


def _xla_moments(x: jax.Array) -> Tuple[jax.Array, ...]:
    """Reference XLA path (identical math, detect/stats.py:212-220)."""
    x = x.astype(jnp.float32)
    x2 = x * x
    return (jnp.sum(x), jnp.sum(x2), jnp.sum(x2 * x), jnp.sum(x2 * x2),
            jnp.min(x) if x.size else jnp.asarray(jnp.inf),
            jnp.max(x) if x.size else jnp.asarray(-jnp.inf),
            jnp.sum(jnp.abs(x)), jnp.max(jnp.abs(x)) if x.size else jnp.asarray(0.0))


def fused_moments(x: jax.Array,
                  interpret: Optional[bool] = None) -> Tuple[jax.Array, ...]:
    """(s1, s2, s3, s4, min, max, l1, linf) of a flattened f32 vector in one
    HBM pass.  The aligned prefix streams through the Pallas kernel; the
    ≤BLOCK_ROWS·LANES-1 element tail and small inputs use XLA (negligible and
    keeps shapes static).

    Constant under differentiation on EVERY path (stop_gradient here, plus
    the kernel's zero-tangent custom_jvp): the statistics are diagnostics,
    and per-path gradient behaviour must not flip with input size or the
    dispatch env var."""
    x = jax.lax.stop_gradient(x.reshape(-1))
    if x.dtype != jnp.float32:
        x = x.astype(jnp.float32)
    n = x.shape[0]
    if interpret is None:
        interpret = pallas_interpret()
    chunk = BLOCK_ROWS * LANES
    n_aligned = (n // chunk) * chunk
    if n_aligned == 0:
        return _xla_moments(x)
    tiles = x[:n_aligned].reshape(-1, LANES)
    acc = _fused_tile_moments(tiles, interpret=interpret)
    head = (
        jnp.sum(acc[_ROW_S1]), jnp.sum(acc[_ROW_S2]),
        jnp.sum(acc[_ROW_S3]), jnp.sum(acc[_ROW_S4]),
        jnp.min(acc[_ROW_MIN]), jnp.max(acc[_ROW_MAX]),
        jnp.sum(acc[_ROW_L1]), jnp.max(acc[_ROW_LINF]),
    )
    if n_aligned == n:
        return head
    tail = _xla_moments(x[n_aligned:])
    return (
        head[0] + tail[0], head[1] + tail[1], head[2] + tail[2],
        head[3] + tail[3], jnp.minimum(head[4], tail[4]),
        jnp.maximum(head[5], tail[5]), head[6] + tail[6],
        jnp.maximum(head[7], tail[7]),
    )

"""Host-side trust reporting view over the pure-JAX :mod:`trust.state`.

Single-source-of-truth design: the *only* implementation of the trust math
(weighted 6-component score, EMA/decay blend, status machine — reference
trust_manager.py:92-181) lives in ``trust/state.py``.  This class holds one
:class:`TrustState` pytree as its world-view and forwards every mutation to
the pure functions, adding only what genuinely belongs on the host:

  * wall-clock time as the decay clock for standalone (non-jitted) use,
  * per-node history/attack logs (unbounded python deques),
  * JSON export, statistics aggregation, and operator recommendations,
  * the ``sync_from_device`` / ``to_device_state`` bridge that lets the
    compiled train step own the state between reporting intervals.

API names match the reference surface (trust_manager.py:44-398) so callers
of the original can switch without edits, but there is no second copy of
any formula here.
"""

from __future__ import annotations

import json
import logging
import time
from collections import defaultdict, deque
from dataclasses import dataclass
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from trustworthy_dl_tpu.trust import state as ts
from trustworthy_dl_tpu.utils.io import atomic_write_json
from trustworthy_dl_tpu.trust.state import METRIC_NAMES, NodeStatus, TrustState

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class TrustScore:
    """Read-only snapshot of one node's score row (view over TrustState)."""

    value: float
    last_updated: float
    update_count: int
    decay_rate: float = 0.01
    recovery_rate: float = 0.005


@dataclass(frozen=True)
class NodeMetrics:
    """Read-only snapshot of one node's metrics row (view over TrustState)."""

    output_deviation: float = 0.0
    gradient_consistency: float = 1.0
    communication_latency: float = 0.0
    resource_utilization: float = 0.0
    error_rate: float = 0.0
    uptime: float = 1.0


class TrustManager:
    """Trust bookkeeping facade; math delegated to ``trust/state.py``."""

    def __init__(
        self,
        num_nodes: int,
        trust_threshold: float = 0.7,
        initial_trust: float = 1.0,
        max_history: int = 1000,
        decay_rate: float = 0.01,
        recovery_rate: float = 0.005,
        alpha: float = 0.1,
    ):
        self.num_nodes = num_nodes
        self.initial_trust = initial_trust
        self.max_history = max_history
        self.default_decay_rate = decay_rate
        self.default_recovery_rate = recovery_rate
        self.alpha = alpha

        # The world-view.  Clock unit for standalone use = wall seconds
        # RELATIVE to construction: TrustState stores the clock in f32,
        # whose ulp at absolute epoch magnitudes is 128 s (two updates a
        # minute apart would read dt == 0, or 128 when straddling a grid
        # line).  Relative seconds keep sub-ms resolution for months;
        # export re-bases to absolute via _epoch0.
        self._epoch0 = time.time()
        self._state: TrustState = ts.init_trust_state(
            num_nodes,
            trust_threshold=trust_threshold,
            initial_trust=initial_trust,
            decay_rate=decay_rate,
            recovery_rate=recovery_rate,
            now=0.0,
        )

        # Host-only logs.
        self.trust_history: Dict[int, deque] = defaultdict(
            lambda: deque(maxlen=max_history)
        )
        self.attack_history: Dict[int, List] = defaultdict(list)
        logger.info("TrustManager tracking %d nodes", num_nodes)

    # -- state access -----------------------------------------------------

    @property
    def state(self) -> TrustState:
        return self._state

    @property
    def trust_threshold(self) -> float:
        return float(np.asarray(self._state.threshold))

    @trust_threshold.setter
    def trust_threshold(self, value: float) -> None:
        self._state = self._state._replace(threshold=jnp.asarray(value, jnp.float32))

    def _now(self) -> float:
        """Wall seconds since construction — the f32-safe decay clock."""
        return time.time() - self._epoch0

    def _one_hot(self, node_id: int) -> jnp.ndarray:
        mask = np.zeros(self.num_nodes, dtype=bool)
        mask[node_id] = True
        return jnp.asarray(mask)

    def _snapshot_metrics(self, node_id: int) -> NodeMetrics:
        row = np.asarray(self._state.metrics[node_id])
        return NodeMetrics(**dict(zip(METRIC_NAMES, map(float, row))))

    # -- mutations (all delegate to trust/state.py) -----------------------

    def _grow_to(self, num_nodes: int) -> None:
        """Expand the state arrays for dynamically added node ids (the
        reference auto-initialises unknown ids on first update,
        trust_manager.py:96-97)."""
        old = self._state
        n_old = self.num_nodes
        fresh = ts.init_trust_state(
            num_nodes,
            trust_threshold=self.trust_threshold,
            initial_trust=self.initial_trust,
            decay_rate=self.default_decay_rate,
            recovery_rate=self.default_recovery_rate,
            now=self._now(),
        )
        self._state = fresh._replace(
            scores=fresh.scores.at[:n_old].set(old.scores),
            status=fresh.status.at[:n_old].set(old.status),
            update_count=fresh.update_count.at[:n_old].set(old.update_count),
            last_updated=fresh.last_updated.at[:n_old].set(old.last_updated),
            decay_rate=fresh.decay_rate.at[:n_old].set(old.decay_rate),
            recovery_rate=fresh.recovery_rate.at[:n_old].set(old.recovery_rate),
            metrics=fresh.metrics.at[:n_old].set(old.metrics),
            attack_count=fresh.attack_count.at[:n_old].set(old.attack_count),
        )
        self.num_nodes = num_nodes

    def initialize_node(self, node_id: int) -> None:
        """(Re)set one node to the initial trust/status/metrics; grows the
        state for ids beyond the current node count."""
        if node_id >= self.num_nodes:
            self._grow_to(node_id + 1)
            return
        fresh = ts.init_trust_state(
            1,
            trust_threshold=self.trust_threshold,
            initial_trust=self.initial_trust,
            decay_rate=self.default_decay_rate,
            recovery_rate=self.default_recovery_rate,
            now=self._now(),
        )
        s, f = self._state, fresh
        self._state = s._replace(
            scores=s.scores.at[node_id].set(f.scores[0]),
            status=s.status.at[node_id].set(f.status[0]),
            update_count=s.update_count.at[node_id].set(0),
            last_updated=s.last_updated.at[node_id].set(f.last_updated[0]),
            decay_rate=s.decay_rate.at[node_id].set(f.decay_rate[0]),
            recovery_rate=s.recovery_rate.at[node_id].set(f.recovery_rate[0]),
            metrics=s.metrics.at[node_id].set(f.metrics[0]),
        )

    def update_trust_score(
        self,
        node_id: int,
        output_deviation: float,
        gradient_consistency: float,
        **kwargs: float,
    ) -> None:
        """Standalone per-node update with wall-clock decay.  The formula is
        ``ts.update_trust`` — no math here, only routing one node's metrics
        into the vectorised call via a one-hot mask."""
        if node_id >= self.num_nodes:
            self.initialize_node(node_id)
        st = self._state
        # Columns 2..5 (latency/util/error/uptime): start from the node's
        # previous values and overlay any keyword metrics supplied.
        extra = np.asarray(st.metrics[:, 2:6]).copy()
        for key, value in kwargs.items():
            if key in METRIC_NAMES:
                col = METRIC_NAMES.index(key)
                if col >= 2:
                    extra[node_id, col - 2] = value
        dev = jnp.asarray(st.metrics[:, 0]).at[node_id].set(output_deviation)
        cons = jnp.asarray(st.metrics[:, 1]).at[node_id].set(gradient_consistency)
        self._state = ts.update_trust(
            st,
            dev,
            cons,
            now=self._now(),
            extra_metrics=jnp.asarray(extra),
            update_mask=self._one_hot(node_id),
            alpha=self.alpha,
        )
        self._record_history(node_id)
        logger.debug(
            "trust[%d] <- %.3f", node_id, float(self._state.scores[node_id])
        )

    def mark_compromised(self, node_id: int, attack_type: str = "unknown") -> None:
        """Penalty via ``ts.mark_compromised``; the attack log records the
        trust value *prior* to the overwrite (SURVEY §7.5 fix)."""
        prior = float(self._state.scores[node_id])
        self._state = ts.mark_compromised(self._state, self._one_hot(node_id))
        self.attack_history[node_id].append(
            {
                "timestamp": time.time(),
                "attack_type": attack_type,
                "previous_trust": prior,
            }
        )
        logger.warning("trust: node %d compromised (%s)", node_id, attack_type)

    def initiate_recovery(self, node_id: int) -> None:
        self._state = ts.initiate_recovery(self._state, self._one_hot(node_id))

    def begin_probation(self, node_id: int, trust: float = 0.5) -> None:
        """Probation re-entry for a readmitted identity (elastic):
        ``initiate_recovery`` semantics (RECOVERING + boosted recovery
        rate, trust_manager.py:198-206) plus the readmission trust floor —
        the same 0.5 starting score expand_train_state gives a
        data-parallel readmitted coordinate."""
        self.initiate_recovery(node_id)
        one = self._one_hot(node_id)
        s = self._state
        self._state = s._replace(
            scores=jnp.where(one, jnp.maximum(s.scores, trust), s.scores)
        )

    def reset_node_trust(self, node_id: int) -> None:
        self.initialize_node(node_id)
        logger.info("trust: node %d reset", node_id)

    def adaptive_threshold_adjustment(self) -> None:
        self._state = ts.adaptive_threshold(self._state)
        logger.debug("trust threshold -> %.3f", self.trust_threshold)

    def cleanup(self) -> None:
        logger.info("trust: manager released")

    # -- queries ----------------------------------------------------------

    def get_trust_score(self, node_id: int) -> float:
        if not 0 <= node_id < self.num_nodes:
            return 0.0
        return float(self._state.scores[node_id])

    def get_score_record(self, node_id: int) -> Optional[TrustScore]:
        """One node's score row as a TrustScore snapshot (the reference's
        per-node record type, trust_manager.py:25-32); None out of range.
        ``last_updated`` is re-based to absolute wall-clock."""
        if not 0 <= node_id < self.num_nodes:
            return None
        s = self._state
        return TrustScore(
            value=float(s.scores[node_id]),
            last_updated=float(s.last_updated[node_id]) + self._epoch0,
            update_count=int(s.update_count[node_id]),
            decay_rate=float(s.decay_rate[node_id]),
            recovery_rate=float(s.recovery_rate[node_id]),
        )

    def get_node_status(self, node_id: int) -> NodeStatus:
        if not 0 <= node_id < self.num_nodes:
            return NodeStatus.OFFLINE
        return NodeStatus(int(self._state.status[node_id]))

    def _nodes_with_status(self, status: NodeStatus) -> List[int]:
        return np.flatnonzero(
            np.asarray(self._state.status) == int(status)
        ).tolist()

    def get_trusted_nodes(self) -> List[int]:
        return self._nodes_with_status(NodeStatus.TRUSTED)

    def get_suspicious_nodes(self) -> List[int]:
        return self._nodes_with_status(NodeStatus.SUSPICIOUS)

    def get_compromised_nodes(self) -> List[int]:
        return self._nodes_with_status(NodeStatus.COMPROMISED)

    def can_assign_task(self, node_id: int) -> bool:
        if not 0 <= node_id < self.num_nodes:
            return False
        return bool(ts.can_assign_task(self._state)[node_id])

    def select_best_nodes(self, num_nodes: int) -> List[int]:
        # Clamp like the reference's available[:k] slice — asking for more
        # nodes than exist returns everyone assignable, not an error.
        k = min(num_nodes, self.num_nodes)
        idx = np.asarray(ts.select_best_nodes(self._state, k))
        return [int(i) for i in idx if i >= 0]

    def calculate_system_trust(self) -> float:
        return float(ts.system_trust(self._state))

    def predict_node_reliability(self, node_id: int, horizon: int = 10) -> float:
        """Trend extrapolation via ``ts.predict_reliability`` over the host
        history log (reference window: last 10 samples, min 5)."""
        entries = [e["trust_score"] for e in self.trust_history.get(node_id, ())][-10:]
        window = 10
        hist = np.zeros((1, window), np.float32)
        if entries:
            hist[0, -len(entries):] = entries
        else:
            hist[0, -1] = self.get_trust_score(node_id)
        count = jnp.asarray([max(len(entries), 1)])
        return float(
            ts.predict_reliability(jnp.asarray(hist), count, horizon=horizon)[0]
        )

    # -- aggregates / reporting ------------------------------------------

    def get_trust_statistics(self) -> Dict:
        scores = np.asarray(self._state.scores)
        if scores.size == 0:
            return {}
        return {
            "mean_trust": float(scores.mean()),
            "std_trust": float(scores.std()),
            "min_trust": float(scores.min()),
            "max_trust": float(scores.max()),
            "system_trust": self.calculate_system_trust(),
            "node_status_counts": {
                status.label: len(self._nodes_with_status(status))
                for status in NodeStatus
            },
            "total_attacks": sum(len(a) for a in self.attack_history.values()),
        }

    def get_node_history(self, node_id: int, limit: int = 100) -> List[Dict]:
        history = list(self.trust_history.get(node_id, ()))
        return history[-limit:] if limit else history

    def get_recommendations(self) -> List[str]:
        """Operator hints derived from the current aggregate picture."""
        out: List[str] = []
        stats = self.get_trust_statistics()
        compromised = self.get_compromised_nodes()
        if compromised:
            out.append(
                f"nodes {sorted(compromised)} are compromised: keep them "
                "gated (or evict with elastic_resharding) and initiate "
                "recovery only after the incident is understood"
            )
        if stats.get("mean_trust", 1.0) < 0.6:
            out.append("mean trust below 0.6: audit the flagged nodes before continuing")
        if len(compromised) > self.num_nodes * 0.3:
            out.append(">30% of nodes compromised: treat as coordinated attack, rotate keys/hosts")
        if stats.get("total_attacks", 0) > 10:
            out.append("attack log is long: tighten detector thresholds or enable ML detectors")
        suspicious = self.get_suspicious_nodes()
        if suspicious:
            out.append(f"keep suspicious nodes {suspicious} under per-batch observation")
        return out

    def export_trust_data(self, filepath: str) -> None:
        records = {
            str(i): self.get_score_record(i).__dict__.copy()
            for i in range(self.num_nodes)
        }
        payload = {
            "trust_scores": records,
            "node_status": {
                str(i): self.get_node_status(i).label for i in range(self.num_nodes)
            },
            "trust_history": {str(i): list(h) for i, h in self.trust_history.items()},
            "attack_history": {str(i): a for i, a in self.attack_history.items()},
            "statistics": self.get_trust_statistics(),
        }
        atomic_write_json(filepath, payload)
        logger.info("trust: exported world-view to %s", filepath)

    # -- device bridge ----------------------------------------------------

    def _record_history(self, node_id: int, wall_time: Optional[float] = None) -> None:
        self.trust_history[node_id].append(
            {
                "timestamp": wall_time if wall_time is not None else time.time(),
                "trust_score": self.get_trust_score(node_id),
                "metrics": self._snapshot_metrics(node_id).__dict__.copy(),
            }
        )

    def to_device_state(self, now: float = 0.0) -> TrustState:
        """Current world-view re-clocked for the jitted step (whose decay
        clock is step count, not wall seconds)."""
        return self._state._replace(
            last_updated=jnp.full((self.num_nodes,), now, jnp.float32)
        )

    def sync_from_device(
        self,
        state: TrustState,
        wall_time: Optional[float] = None,
        node_ids: Optional[List[int]] = None,
    ) -> None:
        """Absorb a TrustState computed inside the train step (epoch cadence,
        not per batch).  ``node_ids`` maps device coordinates to host node
        ids — after elastic eviction the device arrays cover only the
        surviving nodes, so absorption is a scatter, not a swap."""
        wall_time = wall_time if wall_time is not None else time.time()
        coords = np.arange(min(self.num_nodes, state.scores.shape[0]))
        if node_ids is None:
            node_ids = coords.tolist()
        pairs = [
            (c, i)
            for c, i in zip(range(state.scores.shape[0]), node_ids)
            if 0 <= i < self.num_nodes
        ]
        if not pairs:
            return
        cs = np.asarray([c for c, _ in pairs])
        ids = np.asarray([i for _, i in pairs])
        idx = jnp.asarray(ids)
        s = self._state
        self._state = s._replace(
            scores=s.scores.at[idx].set(jnp.asarray(np.asarray(state.scores)[cs])),
            status=s.status.at[idx].set(jnp.asarray(np.asarray(state.status)[cs])),
            update_count=s.update_count.at[idx].set(
                jnp.asarray(np.asarray(state.update_count)[cs])
            ),
            metrics=s.metrics.at[idx].set(jnp.asarray(np.asarray(state.metrics)[cs])),
            last_updated=s.last_updated.at[idx].set(wall_time - self._epoch0),
            threshold=jnp.asarray(state.threshold),
        )
        for i in ids:
            self._record_history(int(i), wall_time)

"""Host-side TrustManager — full API parity with the reference
(trust_manager.py:44-398), backed by the pure-JAX TrustState.

This class is the *reporting and control* surface: the per-batch trust math
runs inside the compiled train step on TrustState (trust/state.py); the
manager absorbs device state once per epoch (``sync_from_device``) and keeps
the reference's history/export/recommendation features on the host where they
belong.  It can also be driven standalone (update_trust_score per call) with
wall-clock decay exactly like the reference.
"""

from __future__ import annotations

import json
import logging
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from trustworthy_dl_tpu.trust import state as ts
from trustworthy_dl_tpu.trust.state import NodeStatus, TrustState

logger = logging.getLogger(__name__)


@dataclass
class TrustScore:
    """Trust score with metadata (trust_manager.py:25-32)."""

    value: float
    last_updated: float
    update_count: int
    decay_rate: float = 0.01
    recovery_rate: float = 0.005


@dataclass
class NodeMetrics:
    """Node metrics for trust calculation (trust_manager.py:34-42)."""

    output_deviation: float = 0.0
    gradient_consistency: float = 1.0
    communication_latency: float = 0.0
    resource_utilization: float = 0.0
    error_rate: float = 0.0
    uptime: float = 1.0


class TrustManager:
    """Manages trust scores and node status for distributed training."""

    def __init__(
        self,
        num_nodes: int,
        trust_threshold: float = 0.7,
        initial_trust: float = 1.0,
        max_history: int = 1000,
        decay_rate: float = 0.01,
        recovery_rate: float = 0.005,
        alpha: float = 0.1,
    ):
        self.num_nodes = num_nodes
        self.trust_threshold = trust_threshold
        self.initial_trust = initial_trust
        self.max_history = max_history
        self.default_decay_rate = decay_rate
        self.default_recovery_rate = recovery_rate
        self.alpha = alpha

        self.trust_scores: Dict[int, TrustScore] = {}
        self.node_status: Dict[int, NodeStatus] = {}
        self.node_metrics: Dict[int, NodeMetrics] = {}

        self.trust_history: Dict[int, deque] = defaultdict(
            lambda: deque(maxlen=max_history)
        )
        self.attack_history: Dict[int, List] = defaultdict(list)
        self.performance_history: Dict[int, deque] = defaultdict(
            lambda: deque(maxlen=max_history)
        )

        # Weighted-sum weights (trust_manager.py:67-74); kept as a dict for
        # API parity, the device path uses trust/state.py:TRUST_WEIGHTS.
        self.trust_weights = {
            "output_deviation": 0.3,
            "gradient_consistency": 0.3,
            "communication_latency": 0.1,
            "resource_utilization": 0.1,
            "error_rate": 0.15,
            "uptime": 0.05,
        }

        for node_id in range(num_nodes):
            self.initialize_node(node_id)
        logger.info("TrustManager initialized for %d nodes", num_nodes)

    # ------------------------------------------------------------------
    # Core update path (trust_manager.py:82-206)
    # ------------------------------------------------------------------

    def initialize_node(self, node_id: int) -> None:
        self.trust_scores[node_id] = TrustScore(
            value=self.initial_trust,
            last_updated=time.time(),
            update_count=0,
            decay_rate=self.default_decay_rate,
            recovery_rate=self.default_recovery_rate,
        )
        self.node_status[node_id] = NodeStatus.TRUSTED
        self.node_metrics[node_id] = NodeMetrics()

    def update_trust_score(
        self,
        node_id: int,
        output_deviation: float,
        gradient_consistency: float,
        **kwargs: float,
    ) -> None:
        """Single-node host update, wall-clock decay
        (trust_manager.py:92-140)."""
        if node_id not in self.trust_scores:
            self.initialize_node(node_id)
        metrics = self.node_metrics[node_id]
        metrics.output_deviation = output_deviation
        metrics.gradient_consistency = gradient_consistency
        for key, value in kwargs.items():
            if hasattr(metrics, key):
                setattr(metrics, key, value)

        new_trust = self._calculate_trust_score(node_id, metrics)
        old = self.trust_scores[node_id]
        dt = time.time() - old.last_updated
        decay = float(np.exp(-old.decay_rate * dt))
        final = float(
            np.clip((1 - self.alpha) * old.value * decay + self.alpha * new_trust, 0.0, 1.0)
        )
        self.trust_scores[node_id] = TrustScore(
            value=final,
            last_updated=time.time(),
            update_count=old.update_count + 1,
            decay_rate=old.decay_rate,
            recovery_rate=old.recovery_rate,
        )
        self._update_node_status(node_id, final)
        self.trust_history[node_id].append(
            {
                "timestamp": time.time(),
                "trust_score": final,
                "metrics": metrics.__dict__.copy(),
            }
        )
        logger.debug("Node %d trust updated: %.3f", node_id, final)

    def _calculate_trust_score(self, node_id: int, metrics: NodeMetrics) -> float:
        components = {
            "output_deviation": 1.0 - min(1.0, metrics.output_deviation),
            "gradient_consistency": metrics.gradient_consistency,
            "communication_latency": 1.0
            - min(1.0, metrics.communication_latency / 10.0),
            "resource_utilization": min(1.0, metrics.resource_utilization),
            "error_rate": 1.0 - min(1.0, metrics.error_rate),
            "uptime": metrics.uptime,
        }
        score = sum(self.trust_weights[k] * v for k, v in components.items())
        return float(np.clip(score, 0.0, 1.0))

    def _update_node_status(self, node_id: int, trust_score: float) -> None:
        current = self.node_status[node_id]
        if trust_score < 0.3:
            new = NodeStatus.COMPROMISED
        elif trust_score < self.trust_threshold:
            new = NodeStatus.SUSPICIOUS
        elif current == NodeStatus.COMPROMISED and trust_score > 0.8:
            new = NodeStatus.RECOVERING
        elif current == NodeStatus.RECOVERING and trust_score > 0.9:
            new = NodeStatus.TRUSTED
        elif trust_score >= self.trust_threshold:
            new = NodeStatus.TRUSTED
        else:
            new = current
        if new != current:
            logger.info(
                "Node %d status changed: %s -> %s", node_id, current.label, new.label
            )
            self.node_status[node_id] = new

    def mark_compromised(self, node_id: int, attack_type: str = "unknown") -> None:
        """Severe trust penalty (trust_manager.py:183-196).  Unlike the
        reference, ``previous_trust`` records the value *before* the
        overwrite (SURVEY §7.5 fix)."""
        previous = self.trust_scores[node_id].value
        self.node_status[node_id] = NodeStatus.COMPROMISED
        self.trust_scores[node_id].value = 0.1
        self.attack_history[node_id].append(
            {
                "timestamp": time.time(),
                "attack_type": attack_type,
                "previous_trust": previous,
            }
        )
        logger.warning("Node %d marked as compromised: %s", node_id, attack_type)

    def initiate_recovery(self, node_id: int) -> None:
        if self.node_status[node_id] == NodeStatus.COMPROMISED:
            self.node_status[node_id] = NodeStatus.RECOVERING
            self.trust_scores[node_id].recovery_rate = 0.02
            logger.info("Recovery initiated for node %d", node_id)

    # ------------------------------------------------------------------
    # Queries (trust_manager.py:208-257)
    # ------------------------------------------------------------------

    def get_trust_score(self, node_id: int) -> float:
        if node_id not in self.trust_scores:
            return 0.0
        return self.trust_scores[node_id].value

    def get_node_status(self, node_id: int) -> NodeStatus:
        return self.node_status.get(node_id, NodeStatus.OFFLINE)

    def get_trusted_nodes(self) -> List[int]:
        return [
            i for i in range(self.num_nodes)
            if self.node_status[i] == NodeStatus.TRUSTED
        ]

    def get_suspicious_nodes(self) -> List[int]:
        return [
            i for i in range(self.num_nodes)
            if self.node_status[i] == NodeStatus.SUSPICIOUS
        ]

    def get_compromised_nodes(self) -> List[int]:
        return [
            i for i in range(self.num_nodes)
            if self.node_status[i] == NodeStatus.COMPROMISED
        ]

    def can_assign_task(self, node_id: int) -> bool:
        status = self.node_status.get(node_id, NodeStatus.OFFLINE)
        return status in (NodeStatus.TRUSTED, NodeStatus.RECOVERING)

    def select_best_nodes(self, num_nodes: int) -> List[int]:
        available = [
            (i, self.get_trust_score(i))
            for i in range(self.num_nodes)
            if self.can_assign_task(i)
        ]
        available.sort(key=lambda x: x[1], reverse=True)
        return [i for i, _ in available[:num_nodes]]

    # ------------------------------------------------------------------
    # Aggregates / reporting (trust_manager.py:259-331)
    # ------------------------------------------------------------------

    def calculate_system_trust(self) -> float:
        if not self.trust_scores:
            return 0.0
        values = [s.value for s in self.trust_scores.values()]
        weights = np.array(values)
        if weights.sum() <= 0:
            return 0.0
        return float(np.average(values, weights=weights))

    def get_trust_statistics(self) -> Dict:
        values = [s.value for s in self.trust_scores.values()]
        if not values:
            return {}
        return {
            "mean_trust": float(np.mean(values)),
            "std_trust": float(np.std(values)),
            "min_trust": float(np.min(values)),
            "max_trust": float(np.max(values)),
            "system_trust": self.calculate_system_trust(),
            "node_status_counts": {
                status.label: sum(1 for s in self.node_status.values() if s == status)
                for status in NodeStatus
            },
            "total_attacks": sum(len(a) for a in self.attack_history.values()),
        }

    def get_node_history(self, node_id: int, limit: int = 100) -> List[Dict]:
        if node_id not in self.trust_history:
            return []
        history = list(self.trust_history[node_id])
        return history[-limit:] if limit else history

    def export_trust_data(self, filepath: str) -> None:
        export_data = {
            "trust_scores": {
                str(i): {
                    "value": s.value,
                    "last_updated": s.last_updated,
                    "update_count": s.update_count,
                }
                for i, s in self.trust_scores.items()
            },
            "node_status": {
                str(i): status.label for i, status in self.node_status.items()
            },
            "trust_history": {
                str(i): list(h) for i, h in self.trust_history.items()
            },
            "attack_history": {
                str(i): a for i, a in self.attack_history.items()
            },
            "statistics": self.get_trust_statistics(),
        }
        with open(filepath, "w") as f:
            json.dump(export_data, f, indent=2)
        logger.info("Trust data exported to %s", filepath)

    # ------------------------------------------------------------------
    # Adaptation / prediction (trust_manager.py:333-394)
    # ------------------------------------------------------------------

    def adaptive_threshold_adjustment(self) -> None:
        stats = self.get_trust_statistics()
        mean_trust = stats.get("mean_trust", 0.7)
        if mean_trust < 0.5:
            self.trust_threshold = max(0.3, mean_trust - 0.1)
        elif mean_trust > 0.9:
            self.trust_threshold = min(0.8, mean_trust - 0.1)
        else:
            self.trust_threshold += 0.01 * (0.7 - self.trust_threshold)
        logger.debug("Trust threshold adjusted to %.3f", self.trust_threshold)

    def predict_node_reliability(self, node_id: int, horizon: int = 10) -> float:
        if node_id not in self.trust_history or len(self.trust_history[node_id]) < 5:
            return self.get_trust_score(node_id)
        recent = [e["trust_score"] for e in list(self.trust_history[node_id])[-10:]]
        x = np.arange(len(recent))
        coeffs = np.polyfit(x, recent, 1)
        future = coeffs[0] * (len(recent) + horizon) + coeffs[1]
        return float(np.clip(future, 0.0, 1.0))

    def get_recommendations(self) -> List[str]:
        recommendations = []
        stats = self.get_trust_statistics()
        if stats.get("mean_trust", 1.0) < 0.6:
            recommendations.append(
                "System trust is low - consider investigating compromised nodes"
            )
        compromised = self.get_compromised_nodes()
        if len(compromised) > self.num_nodes * 0.3:
            recommendations.append(
                "High number of compromised nodes - check security measures"
            )
        if stats.get("total_attacks", 0) > 10:
            recommendations.append(
                "Frequent attacks detected - strengthen attack detection"
            )
        suspicious = self.get_suspicious_nodes()
        if suspicious:
            recommendations.append(f"Monitor suspicious nodes: {suspicious}")
        return recommendations

    def reset_node_trust(self, node_id: int) -> None:
        self.initialize_node(node_id)
        logger.info("Trust reset for node %d", node_id)

    def cleanup(self) -> None:
        logger.info("TrustManager cleanup completed")

    # ------------------------------------------------------------------
    # Device-state bridge (TPU-native; no reference equivalent)
    # ------------------------------------------------------------------

    def to_device_state(self, now: float = 0.0) -> TrustState:
        """Materialise the current host view as a TrustState pytree."""
        import jax.numpy as jnp

        n = self.num_nodes
        state = ts.init_trust_state(
            n,
            trust_threshold=self.trust_threshold,
            initial_trust=self.initial_trust,
            decay_rate=self.default_decay_rate,
            recovery_rate=self.default_recovery_rate,
            now=now,
        )
        scores = jnp.array([self.get_trust_score(i) for i in range(n)], jnp.float32)
        status = jnp.array([int(self.get_node_status(i)) for i in range(n)], jnp.int32)
        counts = jnp.array(
            [self.trust_scores[i].update_count for i in range(n)], jnp.int32
        )
        return state._replace(scores=scores, status=status, update_count=counts)

    def sync_from_device(self, state: TrustState,
                         wall_time: Optional[float] = None,
                         node_ids: Optional[List[int]] = None) -> None:
        """Absorb a TrustState computed inside the train step (called once
        per epoch / reporting interval, not per batch).  ``node_ids`` maps
        device coordinates to original host ids — after elastic eviction
        the device arrays cover only the surviving nodes."""
        wall_time = wall_time if wall_time is not None else time.time()
        scores = np.asarray(state.scores)
        status = np.asarray(state.status)
        counts = np.asarray(state.update_count)
        metrics = np.asarray(state.metrics)
        self.trust_threshold = float(np.asarray(state.threshold))
        if node_ids is None:
            node_ids = list(range(min(self.num_nodes, scores.shape[0])))
        for coord, i in enumerate(node_ids):
            if i >= self.num_nodes or coord >= scores.shape[0]:
                continue
            old = self.trust_scores[i]
            self.trust_scores[i] = TrustScore(
                value=float(scores[coord]),
                last_updated=wall_time,
                update_count=int(counts[coord]),
                decay_rate=old.decay_rate,
                recovery_rate=old.recovery_rate,
            )
            self.node_status[i] = NodeStatus(int(status[coord]))
            m = metrics[coord]
            self.node_metrics[i] = NodeMetrics(
                output_deviation=float(m[0]),
                gradient_consistency=float(m[1]),
                communication_latency=float(m[2]),
                resource_utilization=float(m[3]),
                error_rate=float(m[4]),
                uptime=float(m[5]),
            )
            self.trust_history[i].append(
                {
                    "timestamp": wall_time,
                    "trust_score": float(scores[coord]),
                    "metrics": self.node_metrics[i].__dict__.copy(),
                }
            )

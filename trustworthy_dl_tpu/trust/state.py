"""Trust state as a JAX pytree with pure update functions.

Re-designs the reference TrustManager's per-node dict-of-dataclasses
(trust_manager.py:44-181) as fixed-shape arrays so the whole trust update runs
inside the compiled train step — no host round-trip per batch.  The math is
kept exactly (SURVEY §2.2):

  * 6-component weighted score, weights {output_deviation:0.3,
    gradient_consistency:0.3, communication_latency:0.1,
    resource_utilization:0.1, error_rate:0.15, uptime:0.05}
    (trust_manager.py:67-74), components mapped higher-is-better
    (trust_manager.py:142-160, latency normalised /10).
  * EMA blend with temporal decay:
    final = (1-alpha) * old * exp(-decay_rate * dt) + alpha * new, alpha=0.1,
    clipped to [0,1] (trust_manager.py:112-119).
  * 5-state status machine evaluated in the reference's exact branch order
    (trust_manager.py:162-181) — including its quirk that a COMPROMISED node
    with trust in [threshold, 0.8] jumps straight to TRUSTED.
"""

from __future__ import annotations

import enum
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class NodeStatus(enum.IntEnum):
    """Node status (trust_manager.py:18-23).  IntEnum so status lives in an
    i32 array on device; `.label` gives the reference's string values."""

    TRUSTED = 0
    SUSPICIOUS = 1
    COMPROMISED = 2
    RECOVERING = 3
    OFFLINE = 4

    @property
    def label(self) -> str:
        return self.name.lower()


# Metric component order for the [n, 6] metrics array.
METRIC_NAMES = (
    "output_deviation",
    "gradient_consistency",
    "communication_latency",
    "resource_utilization",
    "error_rate",
    "uptime",
)
# Weighted-sum weights (trust_manager.py:67-74).
TRUST_WEIGHTS = jnp.array([0.3, 0.3, 0.1, 0.1, 0.15, 0.05], dtype=jnp.float32)
# Default metric values: NodeMetrics defaults (trust_manager.py:34-42).
METRIC_DEFAULTS = jnp.array([0.0, 1.0, 0.0, 0.0, 0.0, 1.0], dtype=jnp.float32)


class TrustState(NamedTuple):
    """Per-node trust world-view, carried through the jitted step."""

    scores: jax.Array        # f32[n]  current trust in [0,1]
    status: jax.Array        # i32[n]  NodeStatus codes
    update_count: jax.Array  # i32[n]
    last_updated: jax.Array  # f32[n]  clock of last update (step-time units)
    decay_rate: jax.Array    # f32[n]
    recovery_rate: jax.Array # f32[n]
    metrics: jax.Array       # f32[n, 6] last NodeMetrics per node
    threshold: jax.Array     # f32[]   current trust threshold (adaptive)
    attack_count: jax.Array  # i32[n]  attacks recorded per node

    @property
    def num_nodes(self) -> int:
        return self.scores.shape[0]


def init_trust_state(
    num_nodes: int,
    trust_threshold: float = 0.7,
    initial_trust: float = 1.0,
    decay_rate: float = 0.01,
    recovery_rate: float = 0.005,
    now: float = 0.0,
) -> TrustState:
    """Defaults from trust_manager.py:25-32,49-54,82-90."""
    n = num_nodes
    return TrustState(
        scores=jnp.full((n,), initial_trust, jnp.float32),
        status=jnp.zeros((n,), jnp.int32),
        update_count=jnp.zeros((n,), jnp.int32),
        last_updated=jnp.full((n,), now, jnp.float32),
        decay_rate=jnp.full((n,), decay_rate, jnp.float32),
        recovery_rate=jnp.full((n,), recovery_rate, jnp.float32),
        metrics=jnp.tile(METRIC_DEFAULTS[None, :], (n, 1)),
        threshold=jnp.asarray(trust_threshold, jnp.float32),
        attack_count=jnp.zeros((n,), jnp.int32),
    )


def instantaneous_trust(metrics: jax.Array) -> jax.Array:
    """Weighted 6-component score for metrics [..., 6]
    (trust_manager.py:142-160)."""
    components = jnp.stack(
        [
            1.0 - jnp.minimum(1.0, metrics[..., 0]),          # output_deviation
            metrics[..., 1],                                   # gradient_consistency
            1.0 - jnp.minimum(1.0, metrics[..., 2] / 10.0),    # comm_latency
            jnp.minimum(1.0, metrics[..., 3]),                 # resource_util
            1.0 - jnp.minimum(1.0, metrics[..., 4]),           # error_rate
            metrics[..., 5],                                   # uptime
        ],
        axis=-1,
    )
    return jnp.clip(components @ TRUST_WEIGHTS, 0.0, 1.0)


def next_status(status: jax.Array, trust: jax.Array, threshold: jax.Array) -> jax.Array:
    """Vectorised status machine, reference branch order
    (trust_manager.py:162-181)."""
    compromised = status == NodeStatus.COMPROMISED
    recovering = status == NodeStatus.RECOVERING
    return jnp.select(
        [
            trust < 0.3,
            trust < threshold,
            compromised & (trust > 0.8),
            recovering & (trust > 0.9),
            trust >= threshold,
        ],
        [
            jnp.full_like(status, NodeStatus.COMPROMISED),
            jnp.full_like(status, NodeStatus.SUSPICIOUS),
            jnp.full_like(status, NodeStatus.RECOVERING),
            jnp.full_like(status, NodeStatus.TRUSTED),
            jnp.full_like(status, NodeStatus.TRUSTED),
        ],
        default=status,
    )


def update_trust(
    state: TrustState,
    output_deviation: jax.Array,
    gradient_consistency: jax.Array,
    now: jax.Array | float,
    extra_metrics: Optional[jax.Array] = None,
    update_mask: Optional[jax.Array] = None,
    alpha: float = 0.1,
) -> TrustState:
    """One trust update for all nodes at once (trust_manager.py:92-140).

    ``extra_metrics`` optionally supplies columns 2..5 ([n, 4]: latency,
    resource_util, error_rate, uptime) — the reference's **kwargs path
    (trust_manager.py:103-106).  ``update_mask`` ([n] bool) keeps masked-out
    nodes untouched (used when a node produced no signal this step).
    """
    now = jnp.asarray(now, jnp.float32)
    metrics = state.metrics
    metrics = metrics.at[:, 0].set(output_deviation.astype(jnp.float32))
    metrics = metrics.at[:, 1].set(gradient_consistency.astype(jnp.float32))
    if extra_metrics is not None:
        metrics = metrics.at[:, 2:6].set(extra_metrics.astype(jnp.float32))

    new_trust = instantaneous_trust(metrics)
    dt = now - state.last_updated
    decay = jnp.exp(-state.decay_rate * dt)
    final = jnp.clip((1.0 - alpha) * state.scores * decay + alpha * new_trust, 0.0, 1.0)

    if update_mask is None:
        update_mask = jnp.ones_like(final, dtype=bool)
    final = jnp.where(update_mask, final, state.scores)
    metrics = jnp.where(update_mask[:, None], metrics, state.metrics)

    status = jnp.where(
        update_mask, next_status(state.status, final, state.threshold), state.status
    )
    return state._replace(
        scores=final,
        status=status,
        update_count=state.update_count + update_mask.astype(jnp.int32),
        last_updated=jnp.where(update_mask, now, state.last_updated),
        metrics=metrics,
    )


def mark_compromised(state: TrustState, node_mask: jax.Array) -> TrustState:
    """Force trust to 0.1 and status to COMPROMISED for masked nodes
    (trust_manager.py:183-196).  Also counts the attack."""
    node_mask = node_mask.astype(bool)
    return state._replace(
        scores=jnp.where(node_mask, 0.1, state.scores),
        status=jnp.where(
            node_mask, jnp.int32(NodeStatus.COMPROMISED), state.status
        ),
        attack_count=state.attack_count + node_mask.astype(jnp.int32),
    )


def initiate_recovery(state: TrustState, node_mask: jax.Array) -> TrustState:
    """COMPROMISED -> RECOVERING with boosted recovery rate
    (trust_manager.py:198-206)."""
    eligible = node_mask.astype(bool) & (state.status == NodeStatus.COMPROMISED)
    return state._replace(
        status=jnp.where(eligible, jnp.int32(NodeStatus.RECOVERING), state.status),
        recovery_rate=jnp.where(eligible, 0.02, state.recovery_rate),
    )


def probation_recovery(
    state: TrustState,
    clean_streak: jax.Array,
    clean_now: jax.Array,
    probation_steps: int,
) -> Tuple[TrustState, jax.Array]:
    """Engine-driven recovery: after ``probation_steps`` consecutive clean
    steps a COMPROMISED node transitions to RECOVERING with the boosted
    recovery rate (``initiate_recovery`` semantics, trust_manager.py:198-206
    — which the reference exposed but no path ever called).

    Returns (new_state, new_clean_streak).  The readmitted trust is floored
    at 0.5: below 0.3 the status machine would demote the node straight
    back to COMPROMISED on its next update, re-gating it forever."""
    streak = jnp.where(clean_now.astype(bool), clean_streak + 1, 0)
    if probation_steps <= 0:
        return state, streak
    rehab = (streak >= probation_steps) & (
        state.status == NodeStatus.COMPROMISED
    )
    new = initiate_recovery(state, rehab)
    new = new._replace(
        scores=jnp.where(rehab, jnp.maximum(new.scores, 0.5), new.scores)
    )
    return new, jnp.where(rehab, 0, streak)


def can_assign_task(state: TrustState) -> jax.Array:
    """bool[n]: TRUSTED or RECOVERING (trust_manager.py:239-242)."""
    return (state.status == NodeStatus.TRUSTED) | (
        state.status == NodeStatus.RECOVERING
    )


def contribution_weights(state: TrustState, verdict_ok: Optional[jax.Array] = None
                         ) -> jax.Array:
    """f32[n] gradient-contribution gate for the trust-gated psum.

    The reference silently *skips* compromised nodes in the forward pass
    (distributed_trainer.py:154-157) and applies optimizer steps regardless of
    verification (:441-446) — both flagged as bugs in SURVEY §7.5.  Here the
    gate is explicit: a node contributes iff its task-assignable status holds
    and (when supplied) this step's verification verdict passed.
    """
    ok = can_assign_task(state) | (state.status == NodeStatus.SUSPICIOUS)
    if verdict_ok is not None:
        ok = ok & verdict_ok.astype(bool)
    return ok.astype(jnp.float32)


def system_trust(state: TrustState) -> jax.Array:
    """Self-weighted average (trust_manager.py:259-270)."""
    s = state.scores
    denom = jnp.maximum(jnp.sum(s), 1e-12)
    return jnp.sum(s * s) / denom


def select_best_nodes(state: TrustState, k: int) -> jax.Array:
    """Top-k assignable nodes by trust, -1 padding
    (trust_manager.py:244-257)."""
    score = jnp.where(can_assign_task(state), state.scores, -jnp.inf)
    _, idx = jax.lax.top_k(score, k)
    valid = jnp.take(score, idx) > -jnp.inf
    return jnp.where(valid, idx, -1)


def adaptive_threshold(state: TrustState, default: float = 0.7) -> TrustState:
    """Adaptive threshold adjustment (trust_manager.py:333-348)."""
    mean = jnp.mean(state.scores)
    thr = state.threshold
    new_thr = jnp.where(
        mean < 0.5,
        jnp.maximum(0.3, mean - 0.1),
        jnp.where(
            mean > 0.9,
            jnp.minimum(0.8, mean - 0.1),
            thr + 0.01 * (default - thr),
        ),
    )
    return state._replace(threshold=new_thr)


def predict_reliability(history: jax.Array, valid_count: jax.Array, horizon: int = 10
                        ) -> jax.Array:
    """Degree-1 least-squares trend over the last ``window`` trust samples,
    extrapolated ``horizon`` steps (trust_manager.py:350-368).

    ``history`` is [n, window] (most recent last, left-padded), ``valid_count``
    [n] the number of valid entries.  Nodes with <5 samples return their
    latest score, like the reference.
    """
    n, window = history.shape
    x = jnp.arange(window, dtype=jnp.float32)
    mask = x[None, :] >= (window - valid_count[:, None]).astype(jnp.float32)
    cnt = jnp.maximum(jnp.sum(mask, axis=1), 1.0)
    # Re-index x per node so the first valid sample is x=0 (matches polyfit
    # over the dense recent window in the reference).
    x_local = jnp.where(mask, x[None, :] - (window - valid_count[:, None]), 0.0)
    y = jnp.where(mask, history, 0.0)
    xm = jnp.sum(x_local, axis=1) / cnt
    ym = jnp.sum(y, axis=1) / cnt
    cov = jnp.sum(jnp.where(mask, (x_local - xm[:, None]) * (history - ym[:, None]), 0.0), axis=1)
    var = jnp.sum(jnp.where(mask, (x_local - xm[:, None]) ** 2, 0.0), axis=1)
    slope = jnp.where(var > 0, cov / jnp.maximum(var, 1e-12), 0.0)
    intercept = ym - slope * xm
    pred = slope * (valid_count.astype(jnp.float32) + horizon) + intercept
    latest = history[:, -1]
    return jnp.clip(jnp.where(valid_count >= 5, pred, latest), 0.0, 1.0)

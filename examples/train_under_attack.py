#!/usr/bin/env python
"""Train GPT-2 data-parallel while two nodes mount a gradient-poisoning
attack — the framework detects them, collapses their trust, gates them out
of the aggregation, and (optionally) evicts their devices from the mesh.

This is the library-API spelling of what the reference's README quick-start
promised (README.md:40-76); the console scripts `trustworthy-dl-train` and
`trustworthy-dl-experiment` wrap the same machinery.

Run (any JAX backend; for a quick local run on CPU):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_under_attack.py
"""

from trustworthy_dl_tpu import (
    AdversarialAttacker,
    AttackConfig,
    DistributedTrainer,
    TrainingConfig,
    get_dataloader,
)

# Small model so the example runs anywhere; drop model_overrides for the
# real GPT-2 small (124M).
TINY = dict(n_layer=2, n_embd=64, n_head=4, vocab_size=512, n_positions=64,
            seq_len=32)


def main() -> None:
    config = TrainingConfig(
        model_name="gpt2",
        dataset_name="openwebtext",
        batch_size=16,
        num_nodes=8,
        parallelism="data",
        optimizer="adamw",
        learning_rate=1e-3,
        lr_schedule="cosine", warmup_steps=10, lr_decay_steps=200,
        detector_warmup=4,
        elastic_resharding=False,   # True: evict compromised devices
        checkpoint_dir="/tmp/tddl_example_ckpt",
    )
    trainer = DistributedTrainer(config, model_overrides=TINY)
    trainer.initialize()

    attacker = AdversarialAttacker(AttackConfig(
        attack_types=["gradient_poisoning"],
        target_nodes=[1, 3],        # the reference's canonical targets
        intensity=0.5,
        start_step=20,
    ))
    attacker.activate_attacks()
    trainer.set_attack_plan(attacker.plan(config.num_nodes))

    train_dl = get_dataloader("openwebtext", batch_size=16, seq_len=32,
                              vocab_size=512, num_examples=256)
    val_dl = get_dataloader("openwebtext", split="validation", batch_size=16,
                            seq_len=32, vocab_size=512, num_examples=64)

    result = trainer.train(train_dl, val_dl, num_epochs=3)

    print("\n--- epochs ---")
    for rec in result["epochs"]:
        print(rec)
    print("\n--- incidents ---")
    for rec in trainer.attack_history:
        print(f"step {rec['step']}: node {rec['node_id']} "
              f"({rec['attack_type']})")
    print("\n--- trust ---")
    stats = trainer.get_training_stats()
    print({k: round(v, 3) for k, v in stats["trust_scores"].items()})
    print("\n--- recommendations ---")
    for line in trainer.trust_manager.get_recommendations():
        print("*", line)
    print("\nvalidation:", trainer.validate_metrics(val_dl))
    trainer.cleanup()


if __name__ == "__main__":
    main()

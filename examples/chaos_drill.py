#!/usr/bin/env python
"""End-to-end survival drill: train through a seeded fault plan and come
out with the predicted recovery counts and a healthy loss.

The scripted ``FaultPlan`` injects, in one run:

* a lost batch (simulated data-iterator failure)        -> skipped
* a host stall (straggler)                              -> absorbed
* post-commit corruption of the step-10 checkpoint      -> walked past
* NaN-corrupted parameters after step 12                -> retries fail,
  rollback to the last VERIFIED checkpoint (step 5 — step 10 is corrupt)
* a simulated preemption at step 18                     -> save-on-signal
  + auto-resume

The supervisor's report must match ``FaultPlan.predict`` exactly — the
recovery machinery is deterministic, which is what makes it testable
(tests/test_survival.py asserts the same counts).

The drill also routes through the obs flight recorder: every fault,
retry, rollback and preemption is a trace event, the supervisor dumps
the ring buffer next to the checkpoints on each incident, and the final
dump's event counts are asserted against the SAME ``predict`` numbers —
the post-mortem artifact and the recovery report cannot drift apart.

Run:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/chaos_drill.py

Env knobs (the test smoke path shrinks with these): TDDL_DRILL_EPOCHS,
TDDL_DRILL_CKPT_DIR.
"""

import glob
import json
import os
import shutil

from trustworthy_dl_tpu import (
    DistributedTrainer,
    TrainingConfig,
    TrainingSupervisor,
    get_dataloader,
)
from trustworthy_dl_tpu.chaos import FaultEvent, FaultInjector, FaultKind, \
    FaultPlan
from trustworthy_dl_tpu.obs import ObsSession

TINY = dict(n_layer=2, n_embd=32, n_head=4, vocab_size=128, n_positions=32,
            seq_len=16)


def main() -> None:
    epochs = int(os.environ.get("TDDL_DRILL_EPOCHS", "4"))
    ckpt_dir = os.environ.get("TDDL_DRILL_CKPT_DIR",
                              "/tmp/tddl_chaos_drill_ckpt")
    shutil.rmtree(ckpt_dir, ignore_errors=True)
    config = TrainingConfig(
        model_name="gpt2", dataset_name="openwebtext",
        batch_size=8, num_nodes=4, learning_rate=3e-3,
        detector_warmup=4, checkpoint_interval=5,
        checkpoint_dir=ckpt_dir,
        # FaultPlan.predict's retry/rollback arithmetic assumes the
        # synchronous step guard; the async pipeline's lagged guard
        # skips in-place retries (engine/async_host.py).
        async_host_depth=0, num_epochs=epochs,
    )
    trainer = DistributedTrainer(config, model_overrides=dict(TINY))
    dl = get_dataloader("openwebtext", batch_size=8, seq_len=16,
                        vocab_size=128, num_examples=64)

    print("== fault-free baseline ==")
    trainer.initialize()
    baseline = trainer.train(dl, num_epochs=epochs)
    base_loss = baseline["epochs"][-1]["train_loss"]
    print(f"baseline final loss: {base_loss:.4f}")

    print("== survival drill ==")
    shutil.rmtree(ckpt_dir, ignore_errors=True)
    trainer.reset_for_run()  # same compiled step, fresh state
    plan = FaultPlan.scripted([
        FaultEvent(step=3, kind=FaultKind.DATA_LOSS),
        FaultEvent(step=7, kind=FaultKind.STALL, severity=0.01),
        FaultEvent(step=10, kind=FaultKind.CKPT_CORRUPT),
        FaultEvent(step=12, kind=FaultKind.GRAD_NAN),
        FaultEvent(step=18, kind=FaultKind.PREEMPT),
    ])
    obs = ObsSession(os.path.join(ckpt_dir, "obs"))
    supervisor = TrainingSupervisor(
        trainer, max_retries=2, rollback_after=2, max_restarts=2,
        chaos=FaultInjector(plan), obs=obs,
    )
    result = supervisor.run(dl, num_epochs=epochs)
    report = result["supervisor"]
    predicted = plan.predict(max_retries=2, rollback_after=2)

    final_loss = result["epochs"][-1]["train_loss"]
    print(f"drill final loss:    {final_loss:.4f} "
          f"(baseline {base_loss:.4f})")
    print(f"report:    { {k: report[k] for k in predicted} }")
    print(f"predicted: {predicted}")
    print(f"rollback restored from step(s): {report['rollback_steps']} "
          "(step 10 was corrupt, so the walk landed on 5)")
    for key, want in predicted.items():
        got = report[key]
        assert got == want, f"{key}: predicted {want}, got {got}"
    assert report["rollback_steps"] == [5], report["rollback_steps"]
    assert final_loss < base_loss + 0.75, (final_loss, base_loss)

    # Flight-recorder post-mortems: the rollback and the preemption each
    # dumped the ring buffer next to the checkpoints mid-run...
    dumps = sorted(glob.glob(os.path.join(ckpt_dir, "flight_*.json")))
    reasons = set()
    for p in dumps:
        with open(p) as f:
            reasons.add(json.load(f)["reason"])
    assert {"guard_trip", "rollback", "preemption"} <= reasons, reasons
    # ...and the final dump's event sequence must carry the SAME recovery
    # counts the plan predicted — the artifact a post-mortem reads agrees
    # with the report the supervisor returns, by construction.
    final_dump = obs.dump_flight("drill", directory=ckpt_dir)
    with open(final_dump) as f:
        events = json.load(f)["events"]

    def count(etype, **match):
        return sum(
            e["type"] == etype and all(e.get(k) == v
                                       for k, v in match.items())
            for e in events
        )

    observed = {
        "retries": count("supervisor_retry"),
        "rollbacks": count("supervisor_rollback"),
        "restarts": count("supervisor_restart"),
        "preemptions": count("preemption"),
        "dropped_batches": count("chaos_fault", kind="data_loss"),
        "stalls": count("chaos_fault", kind="stall"),
    }
    print(f"flight dump {os.path.basename(final_dump)}: {observed}")
    assert observed == predicted, (observed, predicted)
    obs.finalize()
    print("drill survived with the plan-predicted recovery counts "
          "(supervisor report AND flight-recorder events)")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""The full elastic lifecycle in one run: transient attack → detection →
eviction → attack ends → probation → readmission.

A node mounts a gradient-poisoning attack for a bounded window.  The
in-step detector confirms it, its mesh coordinate is evicted (state
compacted + migrated to the survivors, step re-jitted), and once the
attack window closes the cool-off elapses and the coordinate is
readmitted on probation — fresh detector baselines, RECOVERING trust,
boosted recovery rate.  A false positive costs bounded steps, not 1/n of
the fleet forever.

Run:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/recovery_lifecycle.py
"""

import numpy as np

from trustworthy_dl_tpu import (
    AdversarialAttacker,
    AttackConfig,
    DistributedTrainer,
    TrainingConfig,
    get_dataloader,
)
from trustworthy_dl_tpu.attacks import null_plan

TINY = dict(n_layer=2, n_embd=64, n_head=4, vocab_size=512,
            n_positions=128, seq_len=64)

config = TrainingConfig(
    model_name="gpt2", dataset_name="openwebtext",
    batch_size=16, num_nodes=8, learning_rate=3e-3,
    detector_warmup=4, checkpoint_interval=10_000,
    elastic_resharding=True,      # evict confirmed-compromised coordinates
    readmit_after_steps=10,       # ...and readmit them after a cool-off
    recovery_probation_steps=5,   # in-step probation for gated nodes
    checkpoint_dir="/tmp/recovery_example_ckpt",
)
trainer = DistributedTrainer(config, model_overrides=dict(TINY))
dl = get_dataloader("openwebtext", batch_size=16, seq_len=TINY["seq_len"],
                    vocab_size=TINY["vocab_size"], num_examples=96)
trainer.initialize()

attacker = AdversarialAttacker(AttackConfig(
    attack_types=["gradient_poisoning"], target_nodes=[5],
    intensity=0.5, start_step=8,
))
attacker.activate_attacks()
trainer.set_attack_plan(attacker.plan(8))

print("== attack window ==")
epoch = 0
while trainer.config.num_nodes == 8 and epoch < 4:
    loss = trainer.train_epoch(dl, epoch)
    print(f"epoch {epoch}: loss {loss:.3f}  live nodes "
          f"{trainer.config.num_nodes}  map {trainer.node_map}")
    epoch += 1
assert trainer.config.num_nodes == 7, "expected an eviction"
print(f"node 5 evicted at step {trainer._evicted_at[5]}; "
      f"mesh now {len(list(trainer.mesh.devices.flat))} devices")

print("== attack over: cool-off, then readmission ==")
trainer.set_attack_plan(null_plan(trainer.config.num_nodes))
while trainer.config.num_nodes == 7 and epoch < 9:
    loss = trainer.train_epoch(dl, epoch)
    print(f"epoch {epoch}: loss {loss:.3f}  live nodes "
          f"{trainer.config.num_nodes}  map {trainer.node_map}")
    epoch += 1
assert trainer.config.num_nodes == 8, "expected readmission"

coord = trainer.node_map.index(5)
print(f"node 5 readmitted at coordinate {coord}: trust "
      f"{float(np.asarray(trainer.state.trust.scores)[coord]):.2f}, "
      f"recovery rate "
      f"{float(np.asarray(trainer.state.trust.recovery_rate)[coord]):.3f}")
for rec in trainer.reassignment_history:
    kind = ("eviction" if "evicted_nodes" in rec
            else "readmission" if "readmitted_nodes" in rec else "relabel")
    print(f"  [{kind}] {rec}")

loss = trainer.train_epoch(dl, epoch)
print(f"full fleet training again: epoch {epoch} loss {loss:.3f}")
trainer.cleanup()

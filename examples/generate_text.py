#!/usr/bin/env python
"""Sample from a (toy-trained) GPT-2 with the KV-cache decoder.

Trains a tiny model on the synthetic affine token stream for a few epochs,
then decodes greedily and with nucleus sampling.  With real OpenWebText
under $TDDL_DATA_DIR and the full model size this is the production
inference path (one jitted XLA program per shape).

Run:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/generate_text.py
"""

import jax
import jax.numpy as jnp

from trustworthy_dl_tpu import DistributedTrainer, TrainingConfig, \
    generate, get_dataloader

TINY = dict(n_layer=2, n_embd=64, n_head=4, vocab_size=512, n_positions=128,
            seq_len=32)


def main() -> None:
    config = TrainingConfig(
        model_name="gpt2", dataset_name="openwebtext", batch_size=16,
        num_nodes=4, learning_rate=3e-3,
        checkpoint_dir="/tmp/tddl_gen_ckpt",
    )
    trainer = DistributedTrainer(config, model_overrides=TINY)
    trainer.initialize()
    dl = get_dataloader("openwebtext", batch_size=16, seq_len=32,
                        vocab_size=512, num_examples=256)
    for epoch in range(3):
        loss = trainer.train_epoch(dl, epoch)
        print(f"epoch {epoch}: loss {loss:.3f}")

    params, cfg = trainer.state.params, trainer.model.config
    prompt = jnp.array([[1, 2, 3, 4]], jnp.int32)

    greedy = generate(params, cfg, prompt, max_new_tokens=24)
    print("greedy:   ", greedy[0].tolist())

    sampled = generate(params, cfg, prompt, max_new_tokens=24,
                       temperature=0.8, top_k=40, top_p=0.95,
                       rng=jax.random.PRNGKey(0))
    print("top-k/p:  ", sampled[0].tolist())
    trainer.cleanup()


if __name__ == "__main__":
    main()

"""Elastic resharding: on confirmed compromise the node's mesh coordinate
is actually removed, state migrates to the survivors via device_put, and
training continues — replacing the reference's no-op
perform_task_reassignment (distributed_trainer.py:367-380; plan at SURVEY
§7.4(1))."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from trustworthy_dl_tpu.attacks import AttackConfig, AdversarialAttacker
from trustworthy_dl_tpu.core.config import TrainingConfig
from trustworthy_dl_tpu.data import get_dataloader
from trustworthy_dl_tpu.engine import DistributedTrainer
from trustworthy_dl_tpu.elastic.reassignment import compact_train_state
from trustworthy_dl_tpu.trust.state import NodeStatus

pytestmark = pytest.mark.slow  # heavy jitted-training integration tier

TINY_GPT = dict(n_layer=2, n_embd=32, n_head=4, vocab_size=128,
                n_positions=32, seq_len=16)


def make_trainer(tmp_path, num_nodes=8, **kw):
    config = TrainingConfig(
        model_name="gpt2", dataset_name="openwebtext",
        batch_size=2 * num_nodes, num_nodes=num_nodes, optimizer="adamw",
        learning_rate=3e-3, detector_warmup=4, checkpoint_interval=10_000,
        checkpoint_dir=str(tmp_path / "ckpt"), elastic_resharding=True, **kw,
    )
    return DistributedTrainer(config, model_overrides=dict(TINY_GPT))


def test_compact_train_state_slices_per_node_rows(tmp_path):
    trainer = make_trainer(tmp_path, num_nodes=4)
    state = trainer.initialize()
    state = state._replace(
        trust=state.trust._replace(
            scores=jnp.asarray([0.9, 0.8, 0.1, 0.7], jnp.float32)
        )
    )
    keep = [0, 1, 3]
    compact = compact_train_state(state, keep)
    np.testing.assert_allclose(np.asarray(compact.trust.scores),
                               [0.9, 0.8, 0.7])
    assert compact.out_baseline.ring.shape[0] == 3
    assert compact.verifier.count.shape == (3,)
    assert compact.monitor.grad_norm_avg.shape[0] == 3
    assert compact.prev_suspects.shape == (3,)
    # Shared state untouched.
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(compact.params)):
        assert a.shape == b.shape


@pytest.fixture(scope="module")
def evicted_run(tmp_path_factory):
    """8-node run; node 5 attacked at step 8, confirmed, evicted; training
    continues on 7 nodes."""
    tmp_path = tmp_path_factory.mktemp("elastic")
    trainer = make_trainer(tmp_path)
    dl = get_dataloader("openwebtext", batch_size=16, seq_len=16,
                        vocab_size=128, num_examples=96)
    trainer.initialize()
    attacker = AdversarialAttacker(
        AttackConfig(attack_types=["gradient_poisoning"], target_nodes=[5],
                     intensity=0.5, start_step=8)
    )
    attacker.activate_attacks()
    trainer.set_attack_plan(attacker.plan(8))
    losses = [trainer.train_epoch(dl, epoch) for epoch in range(3)]
    return trainer, losses


def test_eviction_shrinks_mesh_and_continues(evicted_run):
    trainer, losses = evicted_run
    assert trainer.config.num_nodes == 7
    assert trainer.node_map == [0, 1, 2, 3, 4, 6, 7]
    assert len(list(trainer.mesh.devices.flat)) == 7
    assert trainer.state.trust.scores.shape == (7,)
    # Training survived the reshard and kept improving.
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_eviction_recorded_with_measured_migration(evicted_run):
    trainer, _ = evicted_run
    records = [r for r in trainer.reassignment_history
               if "evicted_nodes" in r]
    assert len(records) == 1
    rec = records[0]
    assert rec["evicted_nodes"] == [5]
    assert rec["surviving_nodes"] == [0, 1, 2, 3, 4, 6, 7]
    assert rec["migration_time_s"] > 0
    assert rec["bytes_moved"] > 0
    assert rec["measured_gbps"] > 0
    # The measured rate replaced the 1 GB/s guess for future estimates.
    assert trainer.config.migration_gbps == pytest.approx(
        rec["measured_gbps"], rel=1e-6
    ) or trainer.config.migration_gbps >= 1e-3


def test_evicted_identity_preserved_on_host(evicted_run):
    """Host bookkeeping keys on ORIGINAL ids across the reshard."""
    trainer, _ = evicted_run
    assert trainer.trust_manager.get_node_status(5) == NodeStatus.COMPROMISED
    assert trainer.trust_manager.get_trust_score(5) < 0.3
    # Survivors keep their identities and healthy trust.
    for node in (0, 1, 2, 3, 4, 6, 7):
        assert trainer.trust_manager.get_trust_score(node) > 0.5
    attacked = {r["node_id"] for r in trainer.attack_history}
    assert attacked == {5}


def test_post_eviction_batches_resplit(evicted_run):
    """The 16-sample global batch now splits over 7 nodes (trimmed)."""
    trainer, _ = evicted_run
    batch = {"input": np.zeros((16, 16), np.int32),
             "target": np.zeros((16, 16), np.int32)}
    node_batch = trainer._node_batch(batch)
    assert node_batch["input"].shape == (7, 2, 16)


def test_post_eviction_validation_runs(evicted_run):
    """Validation works on the resharded 7-node fleet: the elastic
    rebuild must install the NODE-vmapped eval step (a plain eval step
    would crash on the node-split [n', B/n', ...] batches
    validate_metrics now always feeds)."""
    from trustworthy_dl_tpu.data import get_dataloader

    trainer, _ = evicted_run
    val = get_dataloader("openwebtext", batch_size=14, seq_len=16,
                         vocab_size=128, num_examples=28)
    metrics = trainer.validate_metrics(val)
    assert np.isfinite(metrics["loss"])
    assert 0.0 <= metrics["accuracy"] <= 1.0


def test_reset_for_run_refuses_after_eviction(evicted_run):
    """The compiled step is shaped for the constructor's 8-node fleet;
    after an eviction (even one that leaves node_map an identity map)
    reset_for_run must refuse rather than silently reset onto the
    shrunken topology."""
    trainer, _ = evicted_run
    with pytest.raises(RuntimeError, match="topology change"):
        trainer.reset_for_run()


def test_second_eviction(tmp_path):
    """Two sequential evictions: 4 -> 3 -> 2 nodes, training still sane."""
    trainer = make_trainer(tmp_path, num_nodes=4)
    dl = get_dataloader("openwebtext", batch_size=8, seq_len=16,
                        vocab_size=128, num_examples=48)
    trainer.initialize()
    attacker = AdversarialAttacker(
        AttackConfig(attack_types=["gradient_poisoning"], target_nodes=[1],
                     intensity=0.5, start_step=6)
    )
    attacker.activate_attacks()
    trainer.set_attack_plan(attacker.plan(4))
    trainer.train_epoch(dl, 0)
    trainer.train_epoch(dl, 1)
    assert trainer.config.num_nodes == 3
    # Second attack targets what is now coordinate 1 (original node 2).
    from trustworthy_dl_tpu.attacks.adversarial import plan_from_config

    plan2 = plan_from_config(
        AttackConfig(attack_types=["gradient_poisoning"], target_nodes=[1],
                     intensity=0.5, start_step=0),
        num_nodes=3, active=True,
    )
    trainer.set_attack_plan(plan2)
    loss = trainer.train_epoch(dl, 2)
    trainer.train_epoch(dl, 3)
    assert trainer.config.num_nodes == 2
    assert trainer.node_map == [0, 3]
    assert np.isfinite(loss)


def test_checkpoint_resume_after_eviction(evicted_run, tmp_path):
    """SURVEY §5.4: a checkpoint written AFTER eviction (7 live nodes) must
    restore into a fresh trainer constructed with the original 8-node
    config — the saved topology is adopted, identities survive, and
    training continues with finite losses."""
    trainer, _ = evicted_run
    trainer.save_checkpoint()

    fresh = DistributedTrainer(
        TrainingConfig(
            model_name="gpt2", dataset_name="openwebtext", batch_size=16,
            num_nodes=8, optimizer="adamw", learning_rate=3e-3,
            detector_warmup=4, checkpoint_interval=10_000,
            checkpoint_dir=trainer.config.checkpoint_dir,
            elastic_resharding=True,
        ),
        model_overrides=dict(TINY_GPT),
    )
    fresh.load_checkpoint()

    assert fresh.config.num_nodes == 7
    assert fresh.node_map == trainer.node_map
    assert fresh.global_step == trainer.global_step
    np.testing.assert_allclose(
        np.asarray(fresh.state.trust.scores),
        np.asarray(trainer.state.trust.scores), rtol=1e-6,
    )
    # The evicted identity's compromised record survives on the host.
    assert fresh.trust_manager.get_node_status(5) == NodeStatus.COMPROMISED

    dl = get_dataloader("openwebtext", batch_size=16, seq_len=16,
                        vocab_size=128, num_examples=32, seed=7)
    avg = fresh.train_epoch(dl, epoch=3)
    assert np.isfinite(avg)

"""tddl-lint (trustworthy_dl_tpu/analysis/): the AST invariant linter.

Three layers, all host-only and fast-tier (``lint`` marker):

* **Fixture drills per rule family** — a positive (seeded violation →
  finding with the right file:line), a negative (idiomatic code →
  clean), and where it matters the regex-ancestor's blind spot the AST
  rule must close (multi-line emits, comprehension-scoped names).
* **Engine mechanics** — inline/file suppressions, baseline round-trip
  (grandfather → clean → stale detection), parse-error containment,
  CLI exit codes and formats.
* **THE tier-1 gate** — the full default rule set over the REAL repo
  with the committed baseline must be clean; this is the test that
  turns every contract above into a merge blocker.
"""

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from trustworthy_dl_tpu.analysis import (LintConfig, LintEngine,
                                         all_rules, load_baseline,
                                         run_lint, write_baseline)
from trustworthy_dl_tpu.analysis.cli import main as lint_main

pytestmark = pytest.mark.lint

REPO = Path(__file__).resolve().parent.parent

#: Synthetic event vocabulary so fixtures don't depend on the real enum.
EVENTS = frozenset({"TRAIN_STEP", "SERVE_RETIRE"})


def _write_tree(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))


def _run(tmp_path, files, config=None, rules=None, baseline=None):
    _write_tree(tmp_path, files)
    engine = LintEngine(
        all_rules(),
        config=config or LintConfig(event_members=EVENTS))
    return engine.run(str(tmp_path), paths=[str(tmp_path)],
                      rule_names=rules, baseline=baseline)


def _rules_of(result):
    return sorted({f.rule for f in result.findings})


# ---------------------------------------------------------------------------
# obs contracts
# ---------------------------------------------------------------------------


def test_obs_emit_rule_catches_raw_strings_typos_and_multiline_calls(
        tmp_path):
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/mod.py": '''\
            def f(bus, EventType):
                bus.emit("train_step", step=1)          # raw string
                bus.emit(EventType.NOPE, step=1)        # typo'd member
                bus.emit(EventType.TRAIN_STEP, step=1)  # fine
                bus.emit(                               # multi-line: the
                    "serve_retire", request_id=1)       # regex blind spot
            ''',
    }, rules=["obs-emit-type"])
    lines = sorted(f.line for f in result.findings)
    assert lines == [2, 3, 6]
    assert all(f.path == "trustworthy_dl_tpu/mod.py"
               for f in result.findings)
    assert "raw" not in result.findings[0].message  # message names the arg
    assert "'train_step'" in result.findings[0].message
    assert "EventType.NOPE" in result.findings[1].message


def test_metric_prefix_rule_literals_fstrings_and_wrapper(tmp_path):
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/mod.py": '''\
            def f(reg, _metric, name):
                reg.counter("bad_total")                 # missing prefix
                reg.gauge(f"bad_{name}_depth")           # f-string head
                reg.histogram("tddl_ok_seconds")         # fine
                reg.counter(f"tddl_{name}_total")        # fine (head ok)
                reg.counter(name)                        # dynamic: skipped
                _metric(reg.counter, "bad_wrapped_total", "help")
                _metric(reg.counter, "tddl_wrapped_total", "help")
            ''',
    }, rules=["metric-prefix"])
    assert sorted(f.line for f in result.findings) == [2, 3, 7]


def test_metric_label_vocab_rule(tmp_path):
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/mod.py": '''\
            def f(reg, dyn):
                reg.counter("tddl_a_total", labels=("tenant",))   # known
                reg.counter("tddl_b_total", labels=("tenent",))   # typo!
                reg.gauge("tddl_c", labels=("status",) + dyn)     # mixed
            ''',
    }, rules=["metric-label-vocab"])
    assert [f.line for f in result.findings] == [3]
    assert "'tenent'" in result.findings[0].message


# ---------------------------------------------------------------------------
# resource locality
# ---------------------------------------------------------------------------


def test_adapter_locality_rule_flags_forked_spellings(tmp_path):
    # The adapter page-table row and pool PartitionSpecs have ONE home
    # (serve/adapters.py): a redefinition elsewhere, or an ad-hoc
    # PartitionSpec inside an adapter-handling function, forks the
    # compile-once pin.  Calling the imported home spelling is fine.
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/serve/scheduler.py": '''\
            from jax.sharding import PartitionSpec as P
            from trustworthy_dl_tpu.serve.adapters import adapter_page_row

            def adapter_page_row(slots, n):          # forked spelling
                return [0] * n

            def _shard_adapter_pool(arrs):           # ad-hoc adapter spec
                return P("data")

            def _shard_kv_pool(arrs):                # non-adapter: fine
                return P("data")

            def admit(task, n):
                return adapter_page_row({}, n)       # calling home: fine
            ''',
    }, rules=["adapter-locality"])
    assert sorted(f.line for f in result.findings) == [4, 8]
    assert "one spelling" in result.findings[0].message


def test_adapter_locality_rule_home_module_and_suppression_clean(tmp_path):
    # The home module itself is exempt; elsewhere an inline suppression
    # with a justification comment silences a deliberate exception.
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/serve/adapters.py": '''\
            from jax.sharding import PartitionSpec

            def adapter_partition_specs():
                return PartitionSpec(), PartitionSpec()
            ''',
        "trustworthy_dl_tpu/serve/engine.py": '''\
            from jax.sharding import PartitionSpec as P

            def _resize_adapter_pool(arrs):
                # tddl-lint: disable=adapter-locality — test fixture
                return P()
            ''',
    }, rules=["adapter-locality"])
    assert result.findings == []


def test_sharding_registry_rule_flags_specs_outside_home(tmp_path):
    # PR 19: PartitionSpec has ONE spelling site — the logical-axis
    # registry (core/sharding.py).  Direct calls, ``as P`` aliases, and
    # attribute spellings elsewhere are all findings; calling the
    # registry's helpers is the sanctioned path.
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/engine/trainer.py": '''\
            import jax.sharding
            from jax.sharding import PartitionSpec as P
            from trustworthy_dl_tpu.core import sharding as shreg

            def place(mesh):
                a = P("data")                            # aliased ctor
                b = jax.sharding.PartitionSpec("model")  # attr spelling
                c = shreg.replicated_spec()              # registry: fine
                return a, b, c
            ''',
    }, rules=["sharding-registry-only"])
    assert sorted(f.line for f in result.findings) == [6, 7]
    assert "logical-axis registry" in result.findings[0].message


def test_sharding_registry_rule_home_whitelist_and_suppression(tmp_path):
    # The registry itself and whitelisted modules (adapter home) are
    # exempt; elsewhere a justified inline suppression still works, and
    # test trees outside the package are out of scope entirely.
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/core/sharding.py": '''\
            from jax.sharding import PartitionSpec

            def replicated_spec():
                return PartitionSpec()
            ''',
        "trustworthy_dl_tpu/serve/adapters.py": '''\
            from jax.sharding import PartitionSpec

            def adapter_partition_specs():
                return PartitionSpec(), PartitionSpec()
            ''',
        "trustworthy_dl_tpu/serve/engine.py": '''\
            from jax.sharding import PartitionSpec as P

            def special_case():
                # tddl-lint: disable=sharding-registry-only — fixture
                return P()
            ''',
        "tests/test_something.py": '''\
            from jax.sharding import PartitionSpec

            def test_spec():
                assert PartitionSpec() is not None
            ''',
    }, rules=["sharding-registry-only"])
    assert result.findings == []


# ---------------------------------------------------------------------------
# determinism
# ---------------------------------------------------------------------------


def test_tick_determinism_rule(tmp_path):
    # The fixture lives AT a real deterministic-module path so the
    # default contract table scopes onto it.
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/serve/control.py": '''\
            import random, time
            import numpy as np

            def decide(seed, ticks):
                t = time.time()                      # wall clock
                r = random.random()                  # global RNG
                x = np.random.rand()                 # global numpy RNG
                bad = np.random.default_rng()        # unseeded
                rng = np.random.default_rng(seed)    # fine
                for k in {1, 2}:                     # set iteration
                    pass
                for k in sorted({1, 2}):             # fine: sorted
                    pass
                return t + r + x
            ''',
        "trustworthy_dl_tpu/other.py": '''\
            import time

            def fine():
                return time.time()   # not a deterministic module
            ''',
    }, rules=["tick-determinism"])
    assert sorted(f.line for f in result.findings) == [5, 6, 7, 8, 10]
    assert all(f.path.endswith("control.py") for f in result.findings)


def test_predict_purity_rule_and_regression_fixture(tmp_path):
    # Regression fixture mirroring the REAL pinned surface: an
    # autoscale_pressure/predict_fleet pair that sneaks in a module
    # -global mutable cache would silently make drill pins depend on
    # call history.
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/serve/mod.py": '''\
            _CACHE = {}
            HISTORY = []
            LIMITS = (1, 2)            # immutable: fine to read

            def autoscale_pressure(cfg, sig):
                if sig in _CACHE:      # read of mutable global
                    return _CACHE[sig]
                return LIMITS[0]

            def predict_fleet(plan, horizon):
                global HISTORY         # impure declaration
                HISTORY.append(horizon)
                return horizon

            def predict_local_ok(cfg, _CACHE):
                return _CACHE          # shadowed by a parameter

            def helper_reads_cache():
                return _CACHE          # not a prediction function
            ''',
    }, rules=["predict-purity"])
    msgs = {(f.line, f.rule) for f in result.findings}
    by_line = sorted(f.line for f in result.findings)
    # _CACHE read twice in autoscale_pressure (lines 6, 7), the global
    # declaration (11) and its HISTORY use (12).
    assert by_line == [6, 7, 11, 12], result.findings
    assert any("global" in f.message for f in result.findings)
    assert any("_CACHE" in f.message for f in result.findings)
    assert msgs  # noqa: keep flake quiet about the helper var


# ---------------------------------------------------------------------------
# import purity
# ---------------------------------------------------------------------------


def test_import_purity_transitive_chain_and_lazy_escape(tmp_path):
    config = LintConfig(
        event_members=EVENTS,
        host_only_modules=("trustworthy_dl_tpu/hostonly.py",))
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/__init__.py": "",
        "trustworthy_dl_tpu/hostonly.py": '''\
            from typing import TYPE_CHECKING

            from trustworthy_dl_tpu import middle

            if TYPE_CHECKING:
                import jax  # annotation-only: never executes

            def lazy():
                import jax  # sanctioned escape hatch
                return jax
            ''',
        "trustworthy_dl_tpu/middle.py": "import jax.numpy as jnp\n",
    }, config=config, rules=["import-purity"])
    assert len(result.findings) == 1
    f = result.findings[0]
    assert f.path == "trustworthy_dl_tpu/hostonly.py"
    assert f.line == 3                      # the first hop's import
    assert "trustworthy_dl_tpu/middle.py -> jax" in f.message

    # Cutting the chain clears it.
    clean = _run(tmp_path, {
        "trustworthy_dl_tpu/middle.py": "import numpy as np\n",
    }, config=config, rules=["import-purity"])
    assert clean.clean


# ---------------------------------------------------------------------------
# jit hazards
# ---------------------------------------------------------------------------


def test_recompile_hazard_rule(tmp_path):
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/serve/scheduler.py": '''\
            import jax
            import jax.numpy as jnp

            def rebuild(fns):
                for fn in fns:
                    fns[fn] = jax.jit(fn)        # re-jit per iteration

            def decode_tick(self, xs):
                step = jax.jit(lambda a: a + 1)  # cache-key churn
                for x in xs:
                    pad = jnp.array([0, 0])      # literal per iteration
                    y = jnp.asarray(x)           # fine: real data
                return pad, y, step

            def _decode_impl(tokens):
                for _ in range(2):
                    z = jnp.array([1.0])         # fine: traced program
                return z

            def cold_setup():
                for _ in range(2):
                    w = jnp.array([1.0])         # fine: not a hot fn
                return w
            ''',
    }, rules=["recompile-hazard"])
    assert sorted(f.line for f in result.findings) == [6, 9, 11]
    assert any("re-traces" in f.message for f in result.findings)
    assert any("lambda" in f.message for f in result.findings)
    assert any("hoist" in f.message for f in result.findings)


def test_host_sync_rule_taint_comprehension_scope_and_suppression(
        tmp_path):
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/serve/scheduler.py": '''\
            import numpy as np
            import jax.numpy as jnp

            def decode_tick(self, progs, tokens):
                packed = progs["decode"](jnp.asarray(tokens))
                host = np.asarray(packed)           # accidental pull
                ent = float(host[1])                # fine: host value
                loss = float(packed[0])             # accidental pull
                drafts = [np.asarray(d) for d in packed]  # sync in comp
                d = drafts[0]
                tok = int(d[0])                     # fine: host (the
                return ent, loss, tok, d            # scheduler d-case)

            def _spec_tick(self, progs, xs):
                out = progs["draft"](xs)
                # tddl-lint: disable=host-sync — the one deliberate pull
                host = np.asarray(out)
                return host

            def cold_path(progs, xs):
                return np.asarray(progs["x"](xs))   # out of scope
            ''',
    }, rules=["host-sync"])
    assert sorted(f.line for f in result.findings) == [6, 8, 9]
    assert all("decode_tick" in f.message for f in result.findings)


# ---------------------------------------------------------------------------
# hygiene
# ---------------------------------------------------------------------------


def test_mutable_default_rule(tmp_path):
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/mod.py": '''\
            import dataclasses
            from dataclasses import field

            def f(xs=[], m={}, ok=None, t=()):        # two findings
                return xs, m, ok, t

            @dataclasses.dataclass
            class Cfg:
                aux: dict = field(default={})          # finding
                tags: list = []                        # finding
                names: list = field(default_factory=list)  # fine
                k: int = 3                             # fine

            class NotADataclass:
                shared = []                            # fine (class attr)
            ''',
    }, rules=["mutable-default"])
    assert len(result.findings) == 4
    assert {f.line for f in result.findings} == {4, 9, 10}


def test_bare_except_rule_scoped_to_recovery_paths(tmp_path):
    files = {
        "trustworthy_dl_tpu/engine/supervisor.py": '''\
            def recover():
                try:
                    pass
                except:                  # swallows SystemExit
                    pass
                try:
                    pass
                except Exception:        # fine
                    pass
            ''',
        "trustworthy_dl_tpu/models/other.py": '''\
            def f():
                try:
                    pass
                except:                  # out of the rule's scope
                    pass
            ''',
    }
    result = _run(tmp_path, files, rules=["bare-except"])
    assert [(f.path, f.line) for f in result.findings] == \
        [("trustworthy_dl_tpu/engine/supervisor.py", 4)]


def test_artifact_metadata_rule(tmp_path):
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/experiments/a.py": '''\
            import json

            def save(path, payload):
                with open(path + ".tmp", "w") as f:
                    json.dump(payload, f)
                import os
                os.replace(path + ".tmp", path)
            ''',
        "trustworthy_dl_tpu/experiments/b.py": '''\
            import json
            from trustworthy_dl_tpu.obs.meta import run_metadata

            def save(path, payload):
                payload["run_metadata"] = run_metadata()
                with open(path + ".tmp", "w") as f:
                    json.dump(payload, f)
                import os
                os.replace(path + ".tmp", path)
            ''',
        "trustworthy_dl_tpu/experiments/c.py": '''\
            from trustworthy_dl_tpu.utils.io import atomic_write_json

            def save(path, payload):
                atomic_write_json(path, payload)   # atomic but unstamped
            ''',
    }, rules=["artifact-metadata"])
    assert sorted(f.path for f in result.findings) == [
        "trustworthy_dl_tpu/experiments/a.py",
        "trustworthy_dl_tpu/experiments/c.py",
    ]


def test_artifact_reason_vocab_rule(tmp_path):
    # The vocabulary applies at dump/assemble surfaces only, in every
    # literal position those surfaces accept: first positional, the
    # ``reason=`` kwarg, and ``dump()``'s second slot (``FlightRecorder
    # .dump(directory, reason)``).  Dynamic reasons and other callables'
    # ``reason=`` namespaces pass through.
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/serve/mod.py": '''\
            import json

            def episode(session, recorder, fleet, payload, why, obs_dir):
                session.dump_flight("slo_breech", step=3)      # typo
                fleet._forensic_incident(reason="preemption ")  # typo
                recorder.dump(obs_dir, "guard_tripp")          # typo
                session.dump_flight("guard_trip", step=3)      # vocab
                session.dump_flight(why, step=3)          # dynamic: ok
                json.dump(payload, open("/dev/null", "w"))  # not ours
                fleet.schedule(reason="retry_budget")     # other ns
                recorder.dump("smoke_drill")  # tddl-lint: disable=artifact-reason-vocab
            ''',
    }, rules=["artifact-reason-vocab"])
    assert _rules_of(result) == ["artifact-reason-vocab"]
    assert sorted(f.line for f in result.findings) == [4, 5, 6]
    assert "slo_breech" in result.findings[0].message


def test_atomic_write_rule(tmp_path):
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/obs/mod.py": '''\
            import json, os
            from pathlib import Path

            def bad(path, payload):
                with open(path, "w") as f:          # truncates in place
                    json.dump(payload, f)

            def bad_pathlib(path, text):
                Path(path).write_text(text)         # same hazard

            def good(path, payload):
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(payload, f)
                os.replace(tmp, path)

            def append_log(path, line):
                with open(path, "a") as f:          # append: fine
                    f.write(line)
            ''',
    }, rules=["atomic-write"])
    assert sorted(f.line for f in result.findings) == [5, 9]


# ---------------------------------------------------------------------------
# engine mechanics: suppressions, baseline, parse errors
# ---------------------------------------------------------------------------


def test_suppressions_line_block_and_file(tmp_path):
    src_variants = {
        # same-line
        "trustworthy_dl_tpu/a.py":
            'def f(reg):\n'
            '    reg.counter("bad_total")  '
            '# tddl-lint: disable=metric-prefix — legacy export\n',
        # justification block above, disable on its first line
        "trustworthy_dl_tpu/b.py":
            'def f(reg):\n'
            '    # tddl-lint: disable=metric-prefix — kept for the\n'
            '    # external dashboard that predates the convention\n'
            '    reg.counter("bad_total")\n',
        # file-level
        "trustworthy_dl_tpu/c.py":
            '# tddl-lint: disable-file=metric-prefix\n'
            'def f(reg):\n'
            '    reg.counter("bad_total")\n'
            '    reg.counter("also_bad_total")\n',
        # a DIFFERENT rule's suppression must not silence this one
        "trustworthy_dl_tpu/d.py":
            'def f(reg):\n'
            '    reg.counter("bad_total")  '
            '# tddl-lint: disable=host-sync\n',
    }
    result = _run(tmp_path, src_variants, rules=["metric-prefix"])
    assert [f.path for f in result.findings] == ["trustworthy_dl_tpu/d.py"]


def test_baseline_round_trip_and_stale_detection(tmp_path):
    files = {
        "trustworthy_dl_tpu/mod.py":
            'def f(reg):\n    reg.counter("bad_total")\n',
    }
    dirty = _run(tmp_path, files, rules=["metric-prefix"])
    assert len(dirty.findings) == 1

    baseline_path = tmp_path / "baseline.json"
    write_baseline(dirty.findings, str(baseline_path),
                   justification="pre-lint metric kept for dashboards")
    entries = load_baseline(str(baseline_path))
    assert entries[0]["justification"].startswith("pre-lint")

    grandfathered = _run(tmp_path, files, rules=["metric-prefix"],
                         baseline=entries)
    assert grandfathered.clean and grandfathered.baselined == 1
    assert grandfathered.stale_baseline == []

    # Fix the source: the entry goes STALE and is surfaced.
    (tmp_path / "trustworthy_dl_tpu/mod.py").write_text(
        'def f(reg):\n    reg.counter("tddl_good_total")\n')
    fixed = _run(tmp_path, files={}, rules=["metric-prefix"],
                 baseline=entries)
    assert fixed.clean and fixed.baselined == 0
    assert len(fixed.stale_baseline) == 1

    # A justification-free entry is refused at load.
    bad = {"version": 1, "findings": [
        {"rule": "metric-prefix", "path": "x.py", "message": "m"}]}
    (tmp_path / "bad.json").write_text(json.dumps(bad))
    with pytest.raises(ValueError, match="justification"):
        load_baseline(str(tmp_path / "bad.json"))


def test_parse_error_is_a_finding_not_a_crash(tmp_path):
    result = _run(tmp_path, {
        "trustworthy_dl_tpu/broken.py": "def f(:\n",
        "trustworthy_dl_tpu/fine.py": "x = 1\n",
    })
    assert [f.rule for f in result.findings] == ["parse-error"]
    assert result.files_scanned == 2


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _seeded_violation_tree():
    """One violation per rule family (the acceptance-criteria drill)."""
    return {
        "trustworthy_dl_tpu/obs/mod.py": '''\
            import json

            def f(bus, reg, path, payload):
                bus.emit("train_step", step=1)
                reg.counter("bad_total", labels=("tenent",))
                with open(path, "w") as f:
                    json.dump(payload, f)
            ''',
        "trustworthy_dl_tpu/experiments/writer.py": '''\
            import json

            def save(path, payload):
                with open(path + ".tmp", "w") as f:
                    json.dump(payload, f)
                import os
                os.replace(path + ".tmp", path)
            ''',
        "trustworthy_dl_tpu/serve/control.py": '''\
            import time

            def decide():
                return time.time()
            ''',
        "trustworthy_dl_tpu/serve/scheduler.py": '''\
            import numpy as np
            import jax
            import jax.numpy as jnp

            def decode_tick(self, progs, xs):
                for x in xs:
                    pad = jnp.array([0])
                step = jax.jit(lambda a: a)
                out = progs["d"](xs)
                return np.asarray(out), pad, step

            def f(xs=[]):
                try:
                    return xs
                except:
                    pass
            ''',
        "trustworthy_dl_tpu/obs/sentinel.py": "import jax\n",
        "trustworthy_dl_tpu/engine/supervisor.py": '''\
            def recover():
                try:
                    pass
                except:
                    pass
            ''',
    }


def test_cli_seeded_violations_exit_nonzero_with_locations(tmp_path,
                                                           capsys):
    _write_tree(tmp_path, _seeded_violation_tree())
    rc = lint_main(["--root", str(tmp_path), str(tmp_path),
                    "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1
    expected = {
        "obs-emit-type": "trustworthy_dl_tpu/obs/mod.py:4",
        "metric-prefix": "trustworthy_dl_tpu/obs/mod.py:5",
        "metric-label-vocab": "trustworthy_dl_tpu/obs/mod.py:5",
        "atomic-write": "trustworthy_dl_tpu/obs/mod.py:6",
        "artifact-metadata": "trustworthy_dl_tpu/experiments/writer.py:5",
        "tick-determinism": "trustworthy_dl_tpu/serve/control.py:4",
        "recompile-hazard": "trustworthy_dl_tpu/serve/scheduler.py:7",
        "host-sync": "trustworthy_dl_tpu/serve/scheduler.py:10",
        "mutable-default": "trustworthy_dl_tpu/serve/scheduler.py:12",
        "bare-except": "trustworthy_dl_tpu/engine/supervisor.py:4",
        "import-purity": "trustworthy_dl_tpu/obs/sentinel.py:1",
    }
    for rule, location in expected.items():
        assert f"{location}: [{rule}]" in out, (rule, out)


def test_cli_formats_filters_and_exit_codes(tmp_path, capsys):
    _write_tree(tmp_path, {
        "trustworthy_dl_tpu/mod.py":
            'def f(reg):\n    reg.counter("bad_total")\n'})

    # Clean when the only violating rule is filtered out.
    rc = lint_main(["--root", str(tmp_path), str(tmp_path),
                    "--no-baseline", "--rules", "obs-emit-type"])
    assert rc == 0
    capsys.readouterr()

    # JSON format carries the structured payload.
    rc = lint_main(["--root", str(tmp_path), str(tmp_path),
                    "--no-baseline", "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1 and payload["clean"] is False
    assert payload["by_rule"] == {"metric-prefix": 1}
    assert payload["findings"][0]["line"] == 2

    # Unknown rule name: usage error.
    rc = lint_main(["--root", str(tmp_path), str(tmp_path),
                    "--rules", "nonsense"])
    assert rc == 2
    assert "unknown rule" in capsys.readouterr().err

    # --list-rules names every shipped rule.
    rc = lint_main(["--list-rules"])
    out = capsys.readouterr().out
    assert rc == 0
    for rule in all_rules():
        assert rule.name in out

    # --write-baseline grandfathers (default scan from --root — it
    # REFUSES --rules/path filters, which would silently drop every
    # other entry), then the default run is clean and rc 0.
    baseline = tmp_path / "base.json"
    rc = lint_main(["--root", str(tmp_path), str(tmp_path),
                    "--write-baseline", "--baseline", str(baseline)])
    assert rc == 2 and not baseline.exists()   # path filter refused
    assert "--write-baseline" in capsys.readouterr().err
    rc = lint_main(["--root", str(tmp_path),
                    "--write-baseline", "--baseline", str(baseline)])
    assert rc == 0 and baseline.exists()
    capsys.readouterr()
    rc = lint_main(["--root", str(tmp_path), str(tmp_path),
                    "--baseline", str(baseline)])
    assert rc == 0


# ---------------------------------------------------------------------------
# the real repo: tier-1 gate + bench hook + self-purity
# ---------------------------------------------------------------------------


def test_repo_is_clean_under_the_committed_baseline():
    """THE gate: full default rule set over the real package, bench.py
    and tests with the committed baseline — zero findings.  A new
    violation fails HERE, at review time, not in a chaos drill."""
    result = run_lint(root=str(REPO))
    assert result.clean, "\n".join(
        f"{f.location}: [{f.rule}] {f.message}" for f in result.findings)
    # Stale entries mean the baseline should shrink — keep it honest.
    assert result.stale_baseline == [], result.stale_baseline
    assert result.files_scanned > 100


def test_committed_baseline_loads_and_is_justified():
    path = REPO / "tddl_lint_baseline.json"
    entries = load_baseline(str(path))   # raises on missing justification
    assert isinstance(entries, list)


def test_lint_cli_process_is_jax_free():
    """The console entry's own contract: a full lint run in a fresh
    process never imports jax (so it works when the backend is the
    broken thing).  sys.modules is the ground truth the import-purity
    rule approximates statically."""
    code = (
        "import sys\n"
        "from trustworthy_dl_tpu.analysis.cli import main\n"
        "rc = main(['-q'])\n"
        "assert rc == 0, rc\n"
        "bad = [m for m in sys.modules if m.split('.')[0] in "
        "('jax', 'jaxlib')]\n"
        "assert not bad, bad\n"
        "print('ok')\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=str(REPO),
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "ok" in proc.stdout


def test_bench_lint_hook_no_op_and_record(monkeypatch):
    import bench

    monkeypatch.delenv("TDDL_BENCH_LINT", raising=False)
    assert bench.bench_lint() is None          # no-op-safe

    monkeypatch.setenv("TDDL_BENCH_LINT", "1")
    record = bench.bench_lint()
    assert record["rc"] == 0, record
    assert record["findings"] == []
    assert record["files_scanned"] > 100

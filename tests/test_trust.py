"""Golden tests for the trust subsystem against the reference math
(SURVEY §2.2; trust_manager.py:92-181)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from trustworthy_dl_tpu.trust import (
    NodeStatus,
    TrustManager,
    adaptive_threshold,
    can_assign_task,
    contribution_weights,
    init_trust_state,
    initiate_recovery,
    instantaneous_trust,
    mark_compromised,
    next_status,
    predict_reliability,
    select_best_nodes,
    system_trust,
    update_trust,
)


def test_instantaneous_trust_golden():
    # components: (1-0.2)*0.3 + 0.9*0.3 + (1-2/10)*0.1 + 0.5*0.1 + (1-0.1)*0.15 + 1.0*0.05
    metrics = jnp.array([[0.2, 0.9, 2.0, 0.5, 0.1, 1.0]])
    expected = 0.8 * 0.3 + 0.9 * 0.3 + 0.8 * 0.1 + 0.5 * 0.1 + 0.9 * 0.15 + 1.0 * 0.05
    got = float(instantaneous_trust(metrics)[0])
    assert got == pytest.approx(expected, abs=1e-6)


def test_instantaneous_trust_clipping():
    # Extreme bad metrics floor at 0; perfect metrics give exactly 1.
    bad = jnp.array([[5.0, 0.0, 100.0, 0.0, 5.0, 0.0]])
    good = jnp.array([[0.0, 1.0, 0.0, 1.0, 0.0, 1.0]])
    assert float(instantaneous_trust(bad)[0]) == pytest.approx(0.0)
    assert float(instantaneous_trust(good)[0]) == pytest.approx(1.0)


def test_ema_decay_blend():
    # final = (1-alpha)*old*exp(-decay*dt) + alpha*new (trust_manager.py:112-119)
    state = init_trust_state(2, now=0.0)
    dev = jnp.array([0.0, 0.0])
    cons = jnp.array([1.0, 1.0])
    new_state = update_trust(state, dev, cons, now=10.0)
    # metrics -> components: 1.0*0.3 + 1.0*0.3 + 1.0*0.1 + 0*0.1 + 1*0.15 + 1*0.05 = 0.9
    expected_inst = 0.9
    expected = 0.9 * 1.0 * math.exp(-0.01 * 10.0) + 0.1 * expected_inst
    np.testing.assert_allclose(np.asarray(new_state.scores),
                               np.full(2, expected), rtol=1e-6)
    assert int(new_state.update_count[0]) == 1
    assert float(new_state.last_updated[0]) == 10.0


def test_update_mask_keeps_nodes_untouched():
    state = init_trust_state(4, now=0.0)
    mask = jnp.array([True, False, True, False])
    new_state = update_trust(
        state,
        jnp.full((4,), 1.0),  # worst deviation
        jnp.zeros((4,)),
        now=1.0,
        update_mask=mask,
    )
    s = np.asarray(new_state.scores)
    assert s[1] == pytest.approx(1.0)
    assert s[3] == pytest.approx(1.0)
    assert s[0] < 1.0 and s[2] < 1.0
    assert int(new_state.update_count[1]) == 0


@pytest.mark.parametrize(
    "current,trust,expected",
    [
        # trust_manager.py:162-181 branch order
        (NodeStatus.TRUSTED, 0.2, NodeStatus.COMPROMISED),
        (NodeStatus.TRUSTED, 0.5, NodeStatus.SUSPICIOUS),
        (NodeStatus.COMPROMISED, 0.85, NodeStatus.RECOVERING),
        (NodeStatus.RECOVERING, 0.95, NodeStatus.TRUSTED),
        (NodeStatus.SUSPICIOUS, 0.75, NodeStatus.TRUSTED),
        # Reference quirk preserved: COMPROMISED with trust in [thr, 0.8]
        # falls through to TRUSTED via the >= threshold branch.
        (NodeStatus.COMPROMISED, 0.75, NodeStatus.TRUSTED),
        (NodeStatus.RECOVERING, 0.85, NodeStatus.TRUSTED),
    ],
)
def test_status_machine(current, trust, expected):
    status = jnp.array([int(current)], jnp.int32)
    out = next_status(status, jnp.array([trust]), jnp.asarray(0.7))
    assert NodeStatus(int(out[0])) == expected


def test_mark_compromised_and_recovery():
    state = init_trust_state(4)
    state = mark_compromised(state, jnp.array([False, True, False, True]))
    assert float(state.scores[1]) == pytest.approx(0.1)
    assert NodeStatus(int(state.status[1])) == NodeStatus.COMPROMISED
    assert int(state.attack_count[1]) == 1
    assert float(state.scores[0]) == pytest.approx(1.0)
    # can_assign excludes compromised
    np.testing.assert_array_equal(
        np.asarray(can_assign_task(state)), [True, False, True, False]
    )
    state = initiate_recovery(state, jnp.array([False, True, False, False]))
    assert NodeStatus(int(state.status[1])) == NodeStatus.RECOVERING
    assert float(state.recovery_rate[1]) == pytest.approx(0.02)
    assert NodeStatus(int(state.status[3])) == NodeStatus.COMPROMISED


def test_contribution_weights_gate():
    state = init_trust_state(4)
    state = mark_compromised(state, jnp.array([False, True, False, False]))
    verdict_ok = jnp.array([True, True, False, True])
    w = np.asarray(contribution_weights(state, verdict_ok))
    np.testing.assert_array_equal(w, [1.0, 0.0, 0.0, 1.0])


def test_system_trust_self_weighted():
    state = init_trust_state(3)
    state = state._replace(scores=jnp.array([1.0, 0.5, 0.1]))
    # weighted avg with weights = values: sum(v^2)/sum(v)
    expected = (1.0 + 0.25 + 0.01) / 1.6
    assert float(system_trust(state)) == pytest.approx(expected, rel=1e-6)


def test_select_best_nodes():
    state = init_trust_state(4)
    state = state._replace(scores=jnp.array([0.9, 0.95, 0.8, 0.99]))
    state = mark_compromised(state, jnp.array([False, False, False, True]))
    idx = np.asarray(select_best_nodes(state, 2))
    np.testing.assert_array_equal(idx, [1, 0])


def test_adaptive_threshold():
    state = init_trust_state(4)
    low = state._replace(scores=jnp.full((4,), 0.4))
    assert float(adaptive_threshold(low).threshold) == pytest.approx(0.3)
    high = state._replace(scores=jnp.full((4,), 0.95))
    assert float(adaptive_threshold(high).threshold) == pytest.approx(0.8, abs=1e-6)
    mid = state._replace(scores=jnp.full((4,), 0.7), threshold=jnp.asarray(0.6))
    assert float(adaptive_threshold(mid).threshold) == pytest.approx(
        0.6 + 0.01 * 0.1, abs=1e-6
    )


def test_predict_reliability_trend():
    # Linearly decaying history: slope extrapolation matches np.polyfit.
    window = 10
    hist = np.zeros((2, window), np.float32)
    series = np.linspace(1.0, 0.55, window)
    hist[0] = series
    hist[1, -3:] = 0.8  # only 3 valid entries -> returns latest
    counts = jnp.array([10, 3])
    pred = np.asarray(predict_reliability(jnp.array(hist), counts, horizon=10))
    coeffs = np.polyfit(np.arange(window), series, 1)
    expected = np.clip(coeffs[0] * (window + 10) + coeffs[1], 0, 1)
    assert pred[0] == pytest.approx(expected, abs=1e-4)
    assert pred[1] == pytest.approx(0.8, abs=1e-6)


def test_update_is_jittable():
    state = init_trust_state(8)

    @jax.jit
    def step(s, dev, cons, now):
        return update_trust(s, dev, cons, now)

    out = step(state, jnp.zeros(8), jnp.ones(8), 1.0)
    assert out.scores.shape == (8,)


# ---------------------------------------------------------------------------
# Host TrustManager parity
# ---------------------------------------------------------------------------


def test_manager_update_and_status():
    tm = TrustManager(num_nodes=4)
    for _ in range(60):
        tm.update_trust_score(1, output_deviation=1.0, gradient_consistency=0.0,
                              error_rate=1.0, uptime=0.0)
    assert tm.get_trust_score(1) < 0.3
    assert tm.get_node_status(1) == NodeStatus.COMPROMISED
    assert 1 in tm.get_compromised_nodes()
    assert not tm.can_assign_task(1)
    assert tm.can_assign_task(0)


def test_manager_mark_compromised_records_prior_trust():
    tm = TrustManager(num_nodes=2)
    tm.mark_compromised(0, "gradient_poisoning")
    record = tm.attack_history[0][-1]
    # SURVEY §7.5: previous_trust must be the value before the overwrite.
    assert record["previous_trust"] == pytest.approx(1.0)
    assert tm.get_trust_score(0) == pytest.approx(0.1)


def test_manager_statistics_and_export(tmp_path):
    tm = TrustManager(num_nodes=3)
    tm.update_trust_score(0, 0.1, 0.9)
    tm.mark_compromised(2)
    stats = tm.get_trust_statistics()
    assert stats["node_status_counts"]["compromised"] == 1
    assert stats["total_attacks"] == 1
    path = tmp_path / "trust.json"
    tm.export_trust_data(str(path))
    import json

    data = json.loads(path.read_text())
    assert data["node_status"]["2"] == "compromised"
    assert "statistics" in data


def test_manager_device_round_trip():
    tm = TrustManager(num_nodes=4)
    state = tm.to_device_state()
    state = mark_compromised(state, jnp.array([False, True, False, False]))
    state = update_trust(state, jnp.zeros(4), jnp.ones(4), now=1.0)
    tm.sync_from_device(state)
    assert tm.get_trust_score(1) < 0.3
    assert tm.get_node_status(1) == NodeStatus.COMPROMISED
    assert len(tm.get_node_history(1)) == 1

"""Config loading (core/config.py): the README schema must actually load
and CLI overrides must win — the behaviour the reference documented but
never implemented (--config parsed then ignored,
experiment_runner.py:605,613-623)."""

import json

import pytest

from trustworthy_dl_tpu.core.config import (
    ExperimentConfig,
    TrainingConfig,
    load_config,
    load_experiment_config,
)

README_SCHEMA_YAML = """
model:
  name: gpt2
  size: medium
training:
  batch_size: 64
  learning_rate: 0.0003
  num_epochs: 7
  lr_schedule: cosine
  warmup_steps: 100
  lr_decay_steps: 1000
distributed:
  num_nodes: 8
  parallelism: model
  num_microbatches: 2
security:
  trust_threshold: 0.6
  attack_detection: true
  gradient_verification: false
dataset: openwebtext
"""


def test_load_readme_schema_yaml(tmp_path):
    path = tmp_path / "cfg.yaml"
    path.write_text(README_SCHEMA_YAML)
    cfg = load_config(str(path))
    assert cfg.model_name == "gpt2-medium"
    assert cfg.batch_size == 64
    assert cfg.learning_rate == pytest.approx(3e-4)
    assert cfg.num_epochs == 7
    assert cfg.lr_schedule == "cosine" and cfg.warmup_steps == 100
    assert cfg.num_nodes == 8 and cfg.parallelism == "model"
    assert cfg.num_microbatches == 2
    assert cfg.trust_threshold == 0.6
    assert cfg.attack_detection_enabled is True
    assert cfg.gradient_verification_enabled is False
    assert cfg.dataset_name == "openwebtext"


def test_flag_overrides_win(tmp_path):
    path = tmp_path / "cfg.yaml"
    path.write_text(README_SCHEMA_YAML)
    cfg = load_config(str(path), num_nodes=2, model_name="resnet32",
                      learning_rate=None)  # None = not provided
    assert cfg.num_nodes == 2
    assert cfg.model_name == "resnet32"
    assert cfg.learning_rate == pytest.approx(3e-4)  # file value survives


def test_flat_keys_and_json_fallback(tmp_path):
    """Flat TrainingConfig field names pass straight through; a JSON file
    loads even without yaml."""
    path = tmp_path / "cfg.json"
    path.write_text(json.dumps({
        "model_name": "vgg16", "batch_size": 12, "grad_accum_steps": 3,
        "shard_opt_state": True, "lm_head_chunk": 4096,
    }))
    cfg = load_config(str(path))
    assert cfg.model_name == "vgg16" and cfg.batch_size == 12
    assert cfg.grad_accum_steps == 3 and cfg.shard_opt_state is True
    assert cfg.lm_head_chunk == 4096


def test_experiment_config_shares_schema(tmp_path):
    path = tmp_path / "cfg.yaml"
    path.write_text(README_SCHEMA_YAML + "experiment_name: my_exp\n"
                                         "attack_intensity: 0.7\n")
    ecfg = load_experiment_config(str(path), num_epochs=3)
    assert isinstance(ecfg, ExperimentConfig)
    assert ecfg.experiment_name == "my_exp"
    assert ecfg.model_name == "gpt2-medium"
    assert ecfg.attack_intensity == 0.7
    assert ecfg.num_epochs == 3  # override wins
    tcfg = ecfg.to_training_config()
    assert isinstance(tcfg, TrainingConfig)
    assert tcfg.parallelism == "model"


def test_bad_parallelism_rejected():
    with pytest.raises(ValueError):
        TrainingConfig(parallelism="fsdp")


def test_non_mapping_file_rejected(tmp_path):
    path = tmp_path / "cfg.yaml"
    path.write_text("- just\n- a\n- list\n")
    with pytest.raises(ValueError):
        load_config(str(path))


def test_remat_plumbs_from_training_config(tmp_path):
    """TrainingConfig.remat/remat_policy reach the model config (they were
    previously only reachable through model_overrides)."""
    from trustworthy_dl_tpu.engine import DistributedTrainer

    config = TrainingConfig(
        model_name="gpt2", batch_size=4, num_nodes=2, remat=True,
        remat_policy="attention", checkpoint_dir=str(tmp_path / "ck"),
    )
    trainer = DistributedTrainer(config, model_overrides=dict(
        n_layer=2, n_embd=32, n_head=4, vocab_size=64, n_positions=32,
        seq_len=16))
    assert trainer.model.config.remat is True
    assert trainer.model.config.remat_policy == "attention"

"""Profiling + debug subsystems (SURVEY §5.1/§5.2 — absent in the
reference)."""

import glob
import os

import numpy as np
import pytest

import jax

from trustworthy_dl_tpu.core.config import TrainingConfig
from trustworthy_dl_tpu.data import get_dataloader
from trustworthy_dl_tpu.engine import DistributedTrainer
from trustworthy_dl_tpu.utils.profiling import enable_nan_debugging, trace

TINY = dict(n_layer=2, n_embd=32, n_head=4, vocab_size=128, n_positions=32,
            seq_len=16)


def test_profile_trace_written(tmp_path):
    profile_dir = str(tmp_path / "traces")
    config = TrainingConfig(
        model_name="gpt2", dataset_name="openwebtext", batch_size=8,
        num_epochs=1, num_nodes=4, optimizer="adamw",
        checkpoint_interval=10_000, checkpoint_dir=str(tmp_path / "ckpt"),
        profile_dir=profile_dir,
    )
    trainer = DistributedTrainer(config, model_overrides=dict(TINY))
    dl = get_dataloader("openwebtext", batch_size=8, seq_len=16,
                        vocab_size=128, num_examples=16)
    result = trainer.train(dl)
    assert np.isfinite(result["epochs"][0]["train_loss"])
    # jax.profiler writes plugins/profile/<ts>/*.xplane.pb (+ trace.json.gz).
    dumps = glob.glob(os.path.join(profile_dir, "**", "*"), recursive=True)
    assert any(p.endswith((".xplane.pb", ".json.gz")) for p in dumps), dumps


def test_trace_noop_without_dir():
    with trace(None):
        pass  # must not create anything or require a profiler session


def test_annotations_are_noop_safe_without_profiler_session():
    """step/phase annotations must enter and exit cleanly with NO active
    profiler session — the trainer annotates every hot-loop step."""
    from trustworthy_dl_tpu.utils.profiling import PHASES, \
        phase_annotation, step_annotation

    with step_annotation(7):
        pass
    for name in PHASES:
        with phase_annotation(name):
            pass
    with pytest.raises(ValueError):
        phase_annotation("not_a_phase")  # typos fail loudly, not silently


def test_annotations_survive_a_broken_profiler_backend(monkeypatch):
    """A backend whose profiler plugin raises (construction OR entry)
    degrades to a no-op instead of killing the step loop."""
    import trustworthy_dl_tpu.utils.profiling as prof

    class BoomOnInit:
        def __init__(self, *a, **k):
            raise RuntimeError("no profiler session")

    class BoomOnEnter:
        def __init__(self, *a, **k):
            pass

        def __enter__(self):
            raise RuntimeError("plugin missing")

        def __exit__(self, *exc):
            return False

    monkeypatch.setattr(prof.jax.profiler, "StepTraceAnnotation",
                        BoomOnInit)
    monkeypatch.setattr(prof.jax.profiler, "TraceAnnotation", BoomOnEnter)
    with prof.step_annotation(1):
        pass
    with prof.phase_annotation("data"):
        pass


def test_nan_debug_mode_traps(monkeypatch):
    enable_nan_debugging(True)
    try:
        with pytest.raises(FloatingPointError):
            jax.jit(lambda x: jax.numpy.log(x - 1.0))(
                jax.numpy.zeros(4)
            ).block_until_ready()
    finally:
        enable_nan_debugging(False)

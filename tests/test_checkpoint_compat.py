"""Checkpoint structure-drift tolerance (engine/checkpoint.py): a template
with fields the checkpoint lacks (new TrainState fields like round 3's
``clean_streak``) or a checkpoint with leaves the template dropped (the
constant schedule's count) restores via merge-by-name instead of failing."""

import numpy as np

import jax.numpy as jnp

from trustworthy_dl_tpu.engine.checkpoint import (
    CheckpointManager,
    _merge_into_template,
)


def test_restore_tolerates_missing_and_extra_fields(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    saved = {
        "a": jnp.arange(4, dtype=jnp.float32),
        "nested": {"b": jnp.ones((2, 2)), "legacy_only": jnp.zeros((3,))},
    }
    mgr.save(saved, step=1)

    template = {
        "a": jnp.zeros(4, jnp.float32),
        "nested": {
            "b": jnp.zeros((2, 2)),
            # New field the checkpoint doesn't have: keeps template value.
            "new_field": jnp.full((5,), 7.0),
        },
    }
    out = mgr.restore(template, step=1)
    np.testing.assert_array_equal(np.asarray(out["a"]), np.arange(4))
    np.testing.assert_array_equal(np.asarray(out["nested"]["b"]),
                                  np.ones((2, 2)))
    np.testing.assert_array_equal(np.asarray(out["nested"]["new_field"]),
                                  np.full((5,), 7.0))
    assert "legacy_only" not in out["nested"]


def test_merge_handles_namedtuples_and_tuples():
    from collections import namedtuple

    Point = namedtuple("Point", ["x", "y", "z"])
    template = Point(x=jnp.zeros(2), y=jnp.zeros(3), z=jnp.full((1,), 9.0))
    raw = {"x": np.arange(2.0), "y": np.arange(3.0)}  # no z
    out = _merge_into_template(template, raw)
    assert isinstance(out, Point)
    np.testing.assert_array_equal(np.asarray(out.x), [0.0, 1.0])
    np.testing.assert_array_equal(np.asarray(out.z), [9.0])

    tpl = (jnp.zeros(2), jnp.ones(1))
    out = _merge_into_template(tpl, {"0": np.arange(2.0)})
    np.testing.assert_array_equal(np.asarray(out[0]), [0.0, 1.0])
    np.testing.assert_array_equal(np.asarray(out[1]), [1.0])


def test_identical_structure_failure_reraises(tmp_path, monkeypatch):
    """ADVICE r3: the merge fallback is for structure drift ONLY.  A restore
    failure on a structure-identical checkpoint (transient I/O error,
    corruption) must re-raise, not silently keep freshly-initialised
    template values."""
    import pytest

    mgr = CheckpointManager(str(tmp_path))
    saved = {"a": jnp.arange(4, dtype=jnp.float32),
             "nested": {"b": jnp.ones((2, 2))}}
    mgr.save(saved, step=1)
    template = {"a": jnp.zeros(4, jnp.float32),
                "nested": {"b": jnp.zeros((2, 2))}}

    def boom(path, abstract=None):
        raise RuntimeError("simulated transient I/O failure")

    monkeypatch.setattr(mgr._ckptr, "restore", boom)
    with pytest.raises(RuntimeError, match="transient"):
        mgr.restore(template, step=1)


def test_structure_path_helpers_agree(tmp_path):
    """_template_paths (live pytree) and _saved_paths (Orbax metadata)
    normalise to the same key space, so the drift check compares like with
    like — including namedtuples (saved as field dicts) and tuples (saved
    as stringified indices)."""
    from collections import namedtuple

    from trustworthy_dl_tpu.engine.checkpoint import (
        _saved_paths,
        _template_paths,
    )

    Pair = namedtuple("Pair", ["u", "v"])
    state = {
        "p": Pair(u=jnp.zeros(2), v=jnp.ones(3)),
        "t": (jnp.zeros(1), jnp.ones(2)),
        "d": {"x": jnp.zeros(4)},
    }
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(state, step=1)
    saved = _saved_paths(mgr._saved_tree(mgr.path_for(1)))
    assert saved == _template_paths(state)
    # A drifted template (extra field) no longer matches.
    drifted = dict(state, extra=jnp.zeros(1))
    assert saved != _template_paths(drifted)

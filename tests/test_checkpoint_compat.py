"""Checkpoint structure-drift tolerance (engine/checkpoint.py): a template
with fields the checkpoint lacks (new TrainState fields like round 3's
``clean_streak``) or a checkpoint with leaves the template dropped (the
constant schedule's count) restores via merge-by-name instead of failing."""

import numpy as np

import jax.numpy as jnp

from trustworthy_dl_tpu.engine.checkpoint import (
    CheckpointManager,
    _merge_into_template,
)


def test_restore_tolerates_missing_and_extra_fields(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    saved = {
        "a": jnp.arange(4, dtype=jnp.float32),
        "nested": {"b": jnp.ones((2, 2)), "legacy_only": jnp.zeros((3,))},
    }
    mgr.save(saved, step=1)

    template = {
        "a": jnp.zeros(4, jnp.float32),
        "nested": {
            "b": jnp.zeros((2, 2)),
            # New field the checkpoint doesn't have: keeps template value.
            "new_field": jnp.full((5,), 7.0),
        },
    }
    out = mgr.restore(template, step=1)
    np.testing.assert_array_equal(np.asarray(out["a"]), np.arange(4))
    np.testing.assert_array_equal(np.asarray(out["nested"]["b"]),
                                  np.ones((2, 2)))
    np.testing.assert_array_equal(np.asarray(out["nested"]["new_field"]),
                                  np.full((5,), 7.0))
    assert "legacy_only" not in out["nested"]


def test_merge_handles_namedtuples_and_tuples():
    from collections import namedtuple

    Point = namedtuple("Point", ["x", "y", "z"])
    template = Point(x=jnp.zeros(2), y=jnp.zeros(3), z=jnp.full((1,), 9.0))
    raw = {"x": np.arange(2.0), "y": np.arange(3.0)}  # no z
    out = _merge_into_template(template, raw)
    assert isinstance(out, Point)
    np.testing.assert_array_equal(np.asarray(out.x), [0.0, 1.0])
    np.testing.assert_array_equal(np.asarray(out.z), [9.0])

    tpl = (jnp.zeros(2), jnp.ones(1))
    out = _merge_into_template(tpl, {"0": np.arange(2.0)})
    np.testing.assert_array_equal(np.asarray(out[0]), [0.0, 1.0])
    np.testing.assert_array_equal(np.asarray(out[1]), [1.0])

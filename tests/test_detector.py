"""Detector math vs hand-built numpy/scipy references
(attack_detector.py:185-363 semantics; SURVEY §2.2)."""

import jax.numpy as jnp
import numpy as np
import pytest
from scipy import stats as sps

from trustworthy_dl_tpu.detect import (
    AttackDetector,
    AttackType,
    GRADIENT_STAT_NAMES,
    GradientVerifier,
    NUM_GRADIENT_STATS,
    STAT_INDEX,
    TENSOR_STAT_NAMES,
    anomaly_verdicts,
    backdoor_divergence,
    baseline_moments,
    byzantine_verdicts,
    gradient_statistics,
    init_baseline_state,
    init_verifier_state,
    push_stats,
    push_then_detect,
    tensor_statistics,
    verify_gradients_array,
)


def test_tensor_statistics_match_numpy_scipy():
    rng = np.random.default_rng(0)
    x = rng.normal(0.5, 2.0, size=1000).astype(np.float32)
    got = np.asarray(tensor_statistics(jnp.asarray(x)))
    expected = [
        np.mean(x), np.std(x), np.min(x), np.max(x), np.median(x),
        sps.skew(x), sps.kurtosis(x),
        np.percentile(x, 25), np.percentile(x, 75),
        np.linalg.norm(x, 1), np.linalg.norm(x, 2), np.linalg.norm(x, np.inf),
    ]
    np.testing.assert_allclose(got, expected, rtol=2e-4)
    assert list(TENSOR_STAT_NAMES) == [
        "mean", "std", "min", "max", "median", "skewness", "kurtosis",
        "percentile_25", "percentile_75", "norm_l1", "norm_l2", "norm_inf",
    ]


def test_gradient_statistics():
    rng = np.random.default_rng(1)
    grads = [rng.normal(size=(8, 4)).astype(np.float32) for _ in range(3)]
    got = np.asarray(gradient_statistics([jnp.asarray(g) for g in grads]))
    assert got.shape == (NUM_GRADIENT_STATS,)
    norms = [np.linalg.norm(g) for g in grads]
    assert got[STAT_INDEX["num_gradients"]] == pytest.approx(3)
    assert got[STAT_INDEX["grad_norms_mean"]] == pytest.approx(np.mean(norms), rel=1e-5)
    assert got[STAT_INDEX["grad_norms_max"]] == pytest.approx(np.max(norms), rel=1e-5)
    # pairwise cosine
    flat = [g.reshape(-1) for g in grads]
    sims = []
    for i in range(3):
        for j in range(i + 1, 3):
            sims.append(
                np.dot(flat[i], flat[j])
                / (np.linalg.norm(flat[i]) * np.linalg.norm(flat[j]))
            )
    assert got[STAT_INDEX["cosine_similarity"]] == pytest.approx(np.mean(sims), rel=1e-4)


def test_ring_buffer_baseline_matches_window():
    n, window, s = 2, 8, NUM_GRADIENT_STATS
    state = init_baseline_state(n, window=window, num_stats=s)
    rng = np.random.default_rng(2)
    samples = rng.normal(size=(12, n, s)).astype(np.float32)
    for t in range(12):
        state = push_stats(state, jnp.asarray(samples[t]))
    mean, std, valid = baseline_moments(state)
    # Window keeps the last 8 samples.
    recent = samples[-window:]
    np.testing.assert_allclose(np.asarray(mean), recent.mean(axis=0), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(std), recent.std(axis=0), rtol=1e-3)
    np.testing.assert_array_equal(np.asarray(valid), [window, window])


def test_anomaly_detection_fires_on_outlier():
    n = 4
    state = init_baseline_state(n, window=100)
    rng = np.random.default_rng(3)
    # Build 20 steps of benign stats history.
    for _ in range(20):
        stats_step = rng.normal(1.0, 0.1, size=(n, NUM_GRADIENT_STATS)).astype(
            np.float32
        )
        state, verdicts = push_then_detect(state, jnp.asarray(stats_step))
    assert not bool(verdicts.is_attack.any())
    # Node 2 suddenly produces wildly shifted stats.
    attacked = rng.normal(1.0, 0.1, size=(n, NUM_GRADIENT_STATS)).astype(np.float32)
    attacked[2] += 10.0
    state, verdicts = push_then_detect(state, jnp.asarray(attacked))
    flags = np.asarray(verdicts.is_attack)
    assert flags[2]
    assert not flags[[0, 1, 3]].any()
    assert float(verdicts.confidence[2]) > 0.8  # score well above threshold


def test_warmup_suppresses_detection():
    n = 2
    state = init_baseline_state(n, window=100)
    rng = np.random.default_rng(4)
    for t in range(9):  # below the 10-entry warm-up (attack_detector.py:91)
        stats_step = rng.normal(size=(n, NUM_GRADIENT_STATS)).astype(np.float32)
        stats_step[1] += 100.0  # blatant outlier
        state, verdicts = push_then_detect(state, jnp.asarray(stats_step))
        assert not bool(verdicts.is_attack.any())


def test_classifier_rules():
    n = 1
    z = np.ones((n, NUM_GRADIENT_STATS), np.float32)
    ev = np.zeros((n, NUM_GRADIENT_STATS), bool)
    from trustworthy_dl_tpu.detect import classify_attack

    # L2 z>5 -> gradient poisoning
    z1, ev1 = z.copy(), ev.copy()
    z1[0, STAT_INDEX["norm_l2"]] = 6.0
    ev1[0, STAT_INDEX["norm_l2"]] = True
    assert AttackType(int(classify_attack(jnp.asarray(z1), jnp.asarray(ev1))[0])) \
        == AttackType.GRADIENT_POISONING
    # std z>4 -> data poisoning
    z2, ev2 = z.copy(), ev.copy()
    z2[0, STAT_INDEX["std"]] = 4.5
    ev2[0, STAT_INDEX["std"]] = True
    assert AttackType(int(classify_attack(jnp.asarray(z2), jnp.asarray(ev2))[0])) \
        == AttackType.DATA_POISONING
    # skew evidence -> adversarial input
    z3, ev3 = z.copy(), ev.copy()
    ev3[0, STAT_INDEX["skewness"]] = True
    assert AttackType(int(classify_attack(jnp.asarray(z3), jnp.asarray(ev3))[0])) \
        == AttackType.ADVERSARIAL_INPUT
    # nothing specific -> byzantine
    assert AttackType(int(classify_attack(jnp.asarray(z), jnp.asarray(ev))[0])) \
        == AttackType.BYZANTINE


def test_byzantine_verdicts():
    rng = np.random.default_rng(5)
    base = rng.normal(size=(64,)).astype(np.float32)
    outputs = np.stack([
        base + rng.normal(scale=0.05, size=64).astype(np.float32) for _ in range(4)
    ])
    outputs[3] = rng.normal(size=(64,)).astype(np.float32)  # uncorrelated node
    flags = np.asarray(byzantine_verdicts(jnp.asarray(outputs)))
    assert flags[3]
    assert not flags[:3].any()
    # <3 nodes: no verdicts (attack_detector.py:146)
    assert not np.asarray(byzantine_verdicts(jnp.asarray(outputs[:2]))).any()


def test_backdoor_divergence():
    logits = np.zeros((4, 10), np.float32)
    same = backdoor_divergence(jnp.asarray(logits), jnp.asarray(logits))
    assert float(same) == pytest.approx(0.0, abs=1e-6)
    shifted = logits.copy()
    shifted[:, 0] = 50.0  # sharply different distribution
    div = backdoor_divergence(jnp.asarray(shifted), jnp.asarray(logits))
    assert float(div) > 2.0


def test_gradient_verifier_state_catches_inflation_and_nan():
    n = 4
    state = init_verifier_state(n)
    rng = np.random.default_rng(6)
    for _ in range(20):
        norms = jnp.asarray(rng.normal(1.0, 0.02, size=n).astype(np.float32))
        state, valid, _ = verify_gradients_array(state, norms, jnp.ones(n, bool))
        assert bool(valid.all())
    # Inflated norm on node 1 (1000x) must fail; NaN on node 2 must fail.
    norms = jnp.asarray(np.array([1.0, 1000.0, 1.0, 1.0], np.float32))
    finite = jnp.asarray(np.array([True, True, False, True]))
    state2, valid, suspect = verify_gradients_array(state, norms, finite)
    np.testing.assert_array_equal(np.asarray(valid), [True, False, False, True])
    # The inflation failure is the *statistical* component (debounceable).
    assert bool(suspect[1]) and not bool(suspect[0])
    # Failed nodes must not have polluted their baselines.
    assert int(state2.count[1]) == int(state.count[1])


def test_host_detector_end_to_end():
    det = AttackDetector()
    rng = np.random.default_rng(7)
    # Benign history then a poisoned gradient set on node 0.
    for step in range(15):
        grads = [rng.normal(0, 0.1, size=(16,)).astype(np.float32) for _ in range(3)]
        assert not det.detect_gradient_poisoning(grads, node_id=0, step=step)
    poisoned = [
        rng.normal(0, 0.1, size=(16,)).astype(np.float32) * 1000 for _ in range(3)
    ]
    assert det.detect_gradient_poisoning(poisoned, node_id=0, step=99)
    stats = det.get_detection_statistics()
    assert stats["total_detections"] == 1


def test_host_detector_none_output_is_attack():
    det = AttackDetector()
    assert det.detect_output_anomaly(None, node_id=0, step=0)  # :74-75


def test_host_verifier_api():
    ver = GradientVerifier()
    rng = np.random.default_rng(8)
    for step in range(15):
        grads = [rng.normal(0, 0.1, size=(8,)).astype(np.float32)]
        assert ver.verify_gradients(grads, node_id=3, step=step)
    bad = [np.full((8,), 1e6, np.float32)]
    assert not ver.verify_gradients(bad, node_id=3, step=99)
    nan = [np.full((8,), np.nan, np.float32)]
    assert not ver.verify_gradients(nan, node_id=3, step=100)


def test_host_detector_export(tmp_path):
    det = AttackDetector()
    rng = np.random.default_rng(9)
    for step in range(12):
        det.detect_output_anomaly(
            rng.normal(size=(32,)).astype(np.float32), node_id=1, step=step
        )
    path = tmp_path / "detect.json"
    det.export_detection_data(str(path))
    import json

    data = json.loads(path.read_text())
    assert "1" in data["baselines"]["output"]
    assert data["history_lengths"]["1"] == 12


def test_ml_detector_tier_fit_and_verdict():
    """The epoch-cadence ML tier (attack_detector.py:381-425, never called
    by the reference's trainer — wired in ours): fits per-node models once
    history reaches 50 samples and separates wild outliers from inliers."""
    det = AttackDetector()
    rng = np.random.default_rng(0)
    names = GRADIENT_STAT_NAMES
    for _ in range(60):
        vec = rng.normal(0.0, 1.0, len(names))
        det.output_history[0].append({"stats": dict(zip(names, vec))})
    det.output_history[1].append({"stats": dict(zip(names, np.zeros(len(names))))})
    det.update_detection_models(fit_clustering=True)
    assert 0 in det.anomaly_detectors and 0 in det.clustering_models
    assert 1 not in det.anomaly_detectors  # below the 50-sample floor

    outlier = dict(zip(names, np.full(len(names), 50.0)))
    inlier = dict(zip(names, np.zeros(len(names))))
    assert det.detect_with_ml_models(outlier, 0) is True
    assert det.detect_with_ml_models(inlier, 0) is False
    assert det.detect_with_ml_models(outlier, 1) is False  # no model yet


def test_host_byzantine_ragged_outputs():
    """Ragged node outputs: the shared-prefix dot is normalised by both
    FULL norms, so unverifiable tail mass counts against its owner.  A
    mildly longer honest output stays clear; an attacker cannot hide a
    payload behind an honest prefix (suffix-append), control everyone's
    comparison support (tiny output), or evade with an empty one."""
    from trustworthy_dl_tpu.detect.detector import AttackDetector

    rng = np.random.default_rng(3)
    base = rng.standard_normal(256).astype(np.float32)
    honest = {
        i: base + 0.01 * rng.standard_normal(256).astype(np.float32)
        for i in range(3)
    }
    det = AttackDetector()

    # Mildly verbose honest node (1/8 extra mass): clear.
    verbose = np.concatenate(
        [base, 0.3 * rng.standard_normal(32).astype(np.float32)]
    )
    assert det.detect_byzantine_behavior({**honest, 3: verbose}, 0) == []

    # Uncorrelated garbage, same length: flagged.
    garbage = rng.standard_normal(256).astype(np.float32)
    assert det.detect_byzantine_behavior({**honest, 3: garbage}, 0) == [3]

    # Suffix-append attack: honest prefix + large adversarial payload —
    # the payload's norm dilutes every similarity, so the node is flagged.
    payload = np.concatenate(
        [base, 10.0 * rng.standard_normal(768).astype(np.float32)]
    )
    assert det.detect_byzantine_behavior({**honest, 3: payload}, 0) == [3]

    # Tiny prefix-echo and empty outputs: flagged, and honest nodes stay
    # clear (the attacker cannot shrink their comparison support).
    assert det.detect_byzantine_behavior({**honest, 3: base[:2].copy()},
                                         0) == [3]
    assert det.detect_byzantine_behavior(
        {**honest, 3: np.zeros(0, np.float32)}, 0) == [3]


def test_combine_microbatch_stats_order_reducers():
    """ADVICE r3: under gradient accumulation the per-microbatch batteries
    combine with per-column reducers — min/max/linf keep extreme-value
    semantics (a single corrupted microbatch's spike survives at full
    strength), sum-moments average."""
    from trustworthy_dl_tpu.detect.stats import (
        NUM_GRADIENT_STATS,
        STAT_INDEX,
        combine_microbatch_stats,
    )

    lo = np.full(NUM_GRADIENT_STATS, 1.0, np.float32)
    hi = np.full(NUM_GRADIENT_STATS, 3.0, np.float32)
    lo[STAT_INDEX["min"]] = -5.0  # one microbatch saw a deep negative
    hi[STAT_INDEX["max"]] = 40.0  # ... and one a huge positive spike
    hi[STAT_INDEX["norm_inf"]] = 40.0
    out = np.asarray(combine_microbatch_stats(jnp.stack(
        [jnp.asarray(lo), jnp.asarray(hi)]
    )))
    assert out[STAT_INDEX["min"]] == -5.0          # min-of-mins
    assert out[STAT_INDEX["max"]] == 40.0          # max-of-maxes, undiluted
    assert out[STAT_INDEX["norm_inf"]] == 40.0
    assert out[STAT_INDEX["mean"]] == pytest.approx(2.0)   # mean elsewhere
    assert out[STAT_INDEX["norm_l2"]] == pytest.approx(2.0)


def test_fleet_surge_update_unit():
    """Fleet norm-surge math (detect/verifier.py:fleet_surge_update):
    one-sided verdict, clean-only absorption, streak bookkeeping, and the
    bounded-latch escape hatch that re-baselines a persistent legitimate
    shift after FLEET_LATCH_LIMIT raw steps."""
    from trustworthy_dl_tpu.detect.verifier import (
        FLEET_LATCH_LIMIT,
        fleet_surge_update,
        init_verifier_state,
    )

    state = init_verifier_state(1)
    streak = jnp.zeros((1,), jnp.int32)
    # Warm the baseline with jittery clean samples around norm 1.0.
    rng = np.random.default_rng(0)
    for _ in range(12):
        sample = jnp.asarray([1.0 + 0.05 * rng.standard_normal()],
                             jnp.float32)
        raw, state, streak = fleet_surge_update(state, sample, streak)
        assert not bool(raw[0])
    warm_count = int(state.count[0])
    assert warm_count == 12  # every clean sample absorbed

    # Upward surge (x20): raw fires, streak counts, baseline FROZEN.
    surge = jnp.asarray([20.0], jnp.float32)
    for expect_streak in (1, 2, 3):
        raw, state, streak = fleet_surge_update(state, surge, streak)
        assert bool(raw[0])
        assert int(streak[0]) == expect_streak
    assert int(state.count[0]) == warm_count  # clean-only absorption

    # One-sided: a DOWNWARD departure of the same magnitude is clean
    # (clean-run norm decay must not alarm) and resets the streak.
    raw, state, streak = fleet_surge_update(
        state, jnp.asarray([0.05], jnp.float32), streak
    )
    assert not bool(raw[0]) and int(streak[0]) == 0
    assert int(state.count[0]) == warm_count + 1  # absorbed

    # Bounded latch: a PERSISTENT shift alarms for FLEET_LATCH_LIMIT
    # steps, then forced absorption re-baselines and the alarm clears.
    absorbed_during_latch = 0
    for _ in range(FLEET_LATCH_LIMIT + 60):
        before = int(state.count[0])
        raw, state, streak = fleet_surge_update(state, surge, streak)
        absorbed_during_latch += int(state.count[0]) - before
        if not bool(raw[0]):
            break
    assert absorbed_during_latch > 0, "latch escape never absorbed"
    assert not bool(raw[0]), "alarm never cleared after re-baselining"

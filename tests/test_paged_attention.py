"""Pallas ragged paged-decode attention + in-kernel trust epilogue
(ops/paged_attention.py, wired through models/generate._paged_block and
the serve scheduler's attn_impl static).

Fast tier, ``pagedattn`` marker.  Interpret-mode kernel equality vs the
jnp gather path (fp32 AND int8 KV pools, ragged lengths, windows
crossing block boundaries, bit-identical pool writes), epilogue
entropy/margin equality vs the engine's existing reductions (margin
bit-exact, entropy f32-epsilon), the resolve/supports dispatch gate,
the compile-once pin under two waves of block churn with the compile
watcher attached (zero storms), bit-identical streams through
``ServingEngine`` (greedy + sampled, spec_k on and off) vs
``generate()``, the ``tddl_serve_attn_kernel{path=}`` gauge +
decode_tick_fraction summary surface, and same-flag-decisions on the
seeded poison drill with the epilogue in the loop."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from trustworthy_dl_tpu.models import generate as gen
from trustworthy_dl_tpu.models import gpt2
from trustworthy_dl_tpu.models.generate import generate
from trustworthy_dl_tpu.obs.registry import MetricsRegistry
from trustworthy_dl_tpu.ops import paged_attention as pattn
from trustworthy_dl_tpu.serve import ServeRequest, ServingEngine
from trustworthy_dl_tpu.serve.scheduler import _logit_signals

pytestmark = pytest.mark.pagedattn

# vocab_size continues the 97/101/103/107/113/127/139 process-global
# jit-cache isolation sequence: the paged program caches are
# process-global (scheduler._PROGRAMS), so a config identical to a
# sibling suite's would let that file pre-warm the programs this file's
# strict compile-once pins measure (and vice versa).  The attn_impl
# static separates kernel-on from kernel-off programs WITHIN this file.
CFG = gpt2.GPT2Config(vocab_size=157, n_positions=64, n_layer=2, n_embd=32,
                      n_head=4, dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return gpt2.init_params(jax.random.PRNGKey(0), CFG)


# --------------------------------------------------------------------------
# Kernel vs reference semantics (standalone, no transformer in the loop)
# --------------------------------------------------------------------------


def test_kernel_matches_reference_fp32_ragged():
    """Interpret-mode kernel equality against the gather-semantics
    reference: ragged per-row lengths, causal windows crossing block
    boundaries, decode (T=1) through chunk-sized windows, scalar and
    vector ``start``."""
    rng = np.random.default_rng(0)
    nb, h, bsz, dh = 9, 3, 8, 16
    r, nbps = 4, 4
    pool_k = jnp.asarray(rng.normal(size=(nb, h, bsz, dh)), jnp.float32)
    pool_v = jnp.asarray(rng.normal(size=(nb, h, bsz, dh)), jnp.float32)
    table = jnp.asarray(rng.integers(0, nb, size=(r, nbps)), jnp.int32)
    # Ragged: row 0 empty history, row 3 nearly full; starts 5 and 13
    # put the causal window mid-block and across a block boundary.
    start = jnp.asarray([0, 5, 13, 30], jnp.int32)
    for t in (1, 3, 8):
        q = jnp.asarray(rng.normal(size=(r, h, t, dh)), jnp.float32)
        got = pattn.paged_attention(q, pool_k, pool_v, table, start,
                                    interpret=True)
        ref = pattn.paged_attention_reference(q, pool_k, pool_v, table,
                                              start)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    # Scalar start (the chunked-prefill spelling, R=1).
    q = jnp.asarray(rng.normal(size=(1, h, 5, dh)), jnp.float32)
    got = pattn.paged_attention(q, pool_k, pool_v, table[:1],
                                jnp.asarray(8, jnp.int32), interpret=True)
    ref = pattn.paged_attention_reference(q, pool_k, pool_v, table[:1],
                                          jnp.asarray(8, jnp.int32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_kernel_matches_reference_int8_scales():
    """int8 KV streaming: the in-register dequant (K scale post-dot, V
    scale folded into the probabilities) equals the reference's
    gathered-view algebra."""
    rng = np.random.default_rng(1)
    nb, h, bsz, dh = 7, 2, 8, 8
    r, nbps = 3, 3
    pool_k = jnp.asarray(rng.integers(-127, 128, size=(nb, h, bsz, dh)),
                         jnp.int8)
    pool_v = jnp.asarray(rng.integers(-127, 128, size=(nb, h, bsz, dh)),
                         jnp.int8)
    ks = jnp.asarray(rng.uniform(0.01, 0.1, size=(nb, h, bsz)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.01, 0.1, size=(nb, h, bsz)), jnp.float32)
    table = jnp.asarray(rng.integers(0, nb, size=(r, nbps)), jnp.int32)
    start = jnp.asarray([0, 7, 17], jnp.int32)
    for t in (1, 4):
        q = jnp.asarray(rng.normal(size=(r, h, t, dh)), jnp.float32)
        got = pattn.paged_attention(q, pool_k, pool_v, table, start,
                                    k_scale=ks, v_scale=vs, interpret=True)
        ref = pattn.paged_attention_reference(q, pool_k, pool_v, table,
                                              start, k_scale=ks,
                                              v_scale=vs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


# --------------------------------------------------------------------------
# Kernel path vs jnp path through the REAL paged transformer stack
# --------------------------------------------------------------------------


@pytest.mark.parametrize("kv_dtype", ["float32", "int8"])
def test_paged_apply_kernel_vs_jnp_logits_and_pools(params, kv_dtype):
    """``_apply_with_cache_paged`` with attn_impl="interpret" vs "jnp"
    over identical pools: decode logits agree to f32 epsilon, verify-
    window (all_logits) logits agree, and the pool writes agree to the
    same epsilon (layer 0's writes are value-identical — same qkv, same
    scatter — and deeper layers inherit the upstream attention epsilon
    through the scan; on the int8 tier that epsilon can flip a rounding
    by at most one int8 step, the same numerics class the parity probe
    tolerates) — fp32 and int8 tiers, ragged lengths, a window crossing
    a block boundary."""
    from trustworthy_dl_tpu.serve.kv_slots import init_paged_pool

    rng = np.random.default_rng(2)
    bsz, num_blocks, r, nbps = 8, 12, 3, 4
    kv = init_paged_pool(CFG, num_blocks, bsz,
                         kv_dtype=jnp.int8 if kv_dtype == "int8"
                         else jnp.float32)
    # Seed the pool with content so history actually matters.
    if kv_dtype == "int8":
        k0 = jnp.asarray(rng.integers(-127, 128, size=kv.k.shape), jnp.int8)
        v0 = jnp.asarray(rng.integers(-127, 128, size=kv.v.shape), jnp.int8)
        ks0 = jnp.asarray(rng.uniform(0.005, 0.05, size=kv.k_scale.shape),
                          jnp.float32)
        pools = (k0, v0, ks0, ks0)
    else:
        k0 = jnp.asarray(rng.normal(size=kv.k.shape) * 0.3, jnp.float32)
        v0 = jnp.asarray(rng.normal(size=kv.v.shape) * 0.3, jnp.float32)
        pools = (k0, v0, None, None)
    # DISJOINT tables — the BlockAllocator's invariant: a row only ever
    # WRITES exclusively-owned blocks (shared prefix blocks are read-only
    # history).  The write-then-attend kernel path and the
    # gather-then-write jnp path agree exactly under that invariant; a
    # row reading another row's same-tick write block would be an
    # allocator bug, not an attention-path choice.  Ragged lengths: 1,
    # 11 (history crosses a block boundary) and 26.
    table = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 12]],
                        jnp.int32)
    lengths = jnp.asarray([1, 11, 26], jnp.int32)
    view = gen._decode_view(params, CFG)
    tokens = jnp.asarray(rng.integers(0, CFG.vocab_size, size=(r, 1)),
                         jnp.int32)
    outs = {}
    for impl in ("jnp", "interpret"):
        outs[impl] = gen._apply_with_cache_paged(
            view, tokens, *pools, table, lengths, CFG, attn_impl=impl)
    np.testing.assert_allclose(np.asarray(outs["jnp"][0]),
                               np.asarray(outs["interpret"][0]),
                               rtol=2e-4, atol=2e-4)
    for i in (1, 2, 3, 4):  # pool k, v, k_scale, v_scale
        if outs["jnp"][i] is None:
            assert outs["interpret"][i] is None
            continue
        a = np.asarray(outs["jnp"][i]).astype(np.float32)
        b = np.asarray(outs["interpret"][i]).astype(np.float32)
        if kv_dtype == "int8" and i in (1, 2):
            assert np.abs(a - b).max() <= 1          # one rounding step
        else:
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    # Verify-window shape (the spec_verify program's read): T=4 starting
    # at the pre-draft lengths, all-position logits.
    tokens_w = jnp.asarray(rng.integers(0, CFG.vocab_size, size=(r, 4)),
                           jnp.int32)
    outs_w = {}
    for impl in ("jnp", "interpret"):
        outs_w[impl] = gen._apply_with_cache_paged(
            view, tokens_w, *pools, table, lengths, CFG,
            all_logits=True, attn_impl=impl)
    np.testing.assert_allclose(np.asarray(outs_w["jnp"][0]),
                               np.asarray(outs_w["interpret"][0]),
                               rtol=2e-4, atol=2e-4)


# --------------------------------------------------------------------------
# Trust epilogue
# --------------------------------------------------------------------------


def test_trust_epilogue_matches_engine_reductions():
    """The fused epilogue equals the engine's existing per-token
    reductions: margin BIT-exact (top-2 merge is max/min only, including
    duplicated maxima), entropy to f32 epsilon — over random,
    collapsed-distribution and near-tie logits at the serve vocab."""
    rng = np.random.default_rng(3)
    cases = [
        jnp.asarray(rng.normal(size=(5, CFG.vocab_size)) * 4, jnp.float32),
        # Collapse (one dominant logit — the backdoor signature).
        jnp.zeros((3, CFG.vocab_size), jnp.float32).at[:, 7].set(30.0),
        # Exact near-tie: duplicated maximum, margin must be exactly 0.
        jnp.zeros((2, CFG.vocab_size), jnp.float32)
        .at[:, 3].set(5.0).at[:, 100].set(5.0),
    ]
    for logits in cases:
        ent_k, mar_k = _logit_signals(logits, "interpret")
        ent_j, mar_j = _logit_signals(logits, "jnp")
        np.testing.assert_array_equal(np.asarray(mar_k), np.asarray(mar_j))
        np.testing.assert_allclose(np.asarray(ent_k), np.asarray(ent_j),
                                   rtol=1e-5, atol=1e-5)
    # And against the module's own reference spelling at an odd vocab.
    logits = jnp.asarray(rng.normal(size=(4, 50257)) * 3, jnp.float32)
    ent_k, mar_k = pattn.logit_trust_stats(logits, interpret=True)
    ent_r, mar_r = pattn.logit_trust_stats_reference(logits)
    np.testing.assert_array_equal(np.asarray(mar_k), np.asarray(mar_r))
    np.testing.assert_allclose(np.asarray(ent_k), np.asarray(ent_r),
                               rtol=1e-5, atol=1e-5)


# --------------------------------------------------------------------------
# Dispatch gate
# --------------------------------------------------------------------------


def test_resolve_and_supports_gate(monkeypatch):
    """The shared-gate dispatch contract: "jnp" passes through; "auto"
    follows TDDL_PAGED_ATTN (default off-TPU = jnp fallback, the CPU
    container tier's green path); opt-in resolves to interpret off-TPU;
    explicit "pallas" on a non-TPU backend RAISES (the interpreter is
    not the kernel); compiled tiling rules (per-dtype sublane: f32 8,
    bf16 16, int8 32) downgrade "auto" loudly and REJECT an explicit
    ask."""
    monkeypatch.delenv("TDDL_PAGED_ATTN", raising=False)
    kw = dict(head_dim=64, block_size=16, kv_dtype=jnp.float32)
    assert pattn.resolve_attn_impl("jnp", **kw) == "jnp"
    # Default off-TPU: gate closed, jnp fallback stays the default.
    assert pattn.resolve_attn_impl("auto", **kw) == "jnp"
    monkeypatch.setenv("TDDL_PAGED_ATTN", "1")
    assert pattn.resolve_attn_impl("auto", **kw) == "interpret"
    monkeypatch.setenv("TDDL_PAGED_ATTN", "0")
    assert pattn.resolve_attn_impl("auto", **kw) == "jnp"
    with pytest.raises(ValueError, match="attn_impl"):
        pattn.resolve_attn_impl("mosaic", **kw)
    # Explicit "pallas" asked for COMPILED Mosaic by name — on this CPU
    # backend that must fail loudly, not silently serve the interpreter.
    with pytest.raises(ValueError, match="TPU backend"):
        pattn.resolve_attn_impl("pallas", **kw)
    # Compiled tiling rules: the sublane follows the POOL dtype
    # (interpret mode has none — the int8 equality pins above run at
    # block_size 8).
    assert pattn.kv_sublane(jnp.float32) == 8
    assert pattn.kv_sublane(jnp.bfloat16) == 16
    assert pattn.kv_sublane(jnp.int8) == 32
    assert pattn.supports_paged_attention(
        head_dim=64, block_size=16, kv_dtype=jnp.float32, interpret=False)
    assert not pattn.supports_paged_attention(
        head_dim=64, block_size=12, kv_dtype=jnp.float32, interpret=False)
    # bf16 pools need the 16-sublane: block_size 8 must NOT pass.
    assert not pattn.supports_paged_attention(
        head_dim=64, block_size=8, kv_dtype=jnp.bfloat16, interpret=False)
    assert pattn.supports_paged_attention(
        head_dim=64, block_size=16, kv_dtype=jnp.bfloat16, interpret=False)
    assert pattn.supports_paged_attention(
        head_dim=64, block_size=32, kv_dtype=jnp.int8, interpret=False)
    assert not pattn.supports_paged_attention(
        head_dim=64, block_size=16, kv_dtype=jnp.int8, interpret=False)
    assert pattn.supports_paged_attention(
        head_dim=64, block_size=8, kv_dtype=jnp.int8, interpret=True)
    with pytest.raises(ValueError, match="cannot dispatch"):
        pattn.resolve_attn_impl("interpret", head_dim=1024, block_size=8,
                                kv_dtype=jnp.float32)


# --------------------------------------------------------------------------
# Served streams: bit-identical vs generate(), compile-once, zero storms
# --------------------------------------------------------------------------


def _requests():
    rng = np.random.default_rng(7)
    reqs = []
    for _ in range(5):
        plen = int(rng.integers(3, 14))
        reqs.append(ServeRequest(
            prompt=rng.integers(0, CFG.vocab_size, plen).tolist(),
            max_new_tokens=int(rng.integers(2, 9))))
    reqs.append(ServeRequest(prompt=[2, 71, 8, 28], max_new_tokens=6,
                             temperature=0.8, rng=jax.random.PRNGKey(42)))
    return reqs


@pytest.mark.parametrize("spec_k", [0, 2])
def test_streams_bit_identical_vs_generate(params, spec_k):
    """THE acceptance pin: with the kernel in the loop (interpret mode —
    the same code path the TPU compiles) the engine serves greedy AND
    seeded-sampled streams bit-identical to ``generate()``, spec_k on
    and off, across chunked prefill, block churn and prefix sharing."""
    engine = ServingEngine(params, CFG, max_slots=3, max_seq=48,
                           queue_limit=32, rng=jax.random.PRNGKey(5),
                           block_size=8, prefill_chunk=16, spec_k=spec_k,
                           attn_impl="interpret")
    assert engine.attn_kernel_path == "interpret"
    for req in _requests():
        engine.submit(req)
    results = engine.run_until_idle()
    assert all(r.status == "completed" for r in results.values())
    for rid, req in enumerate(_requests()):
        ref = generate(params, CFG,
                       jnp.asarray([list(req.prompt)], jnp.int32),
                       req.max_new_tokens, temperature=req.temperature,
                       rng=(req.rng if req.rng is not None
                            else jax.random.fold_in(jax.random.PRNGKey(5),
                                                    rid)))
        ref_tokens = np.asarray(ref)[0, len(req.prompt):].tolist()
        assert results[rid].tokens == ref_tokens, f"request {rid}"


def test_int8_kv_kernel_streams_match_jnp(params):
    """int8 KV pool with the kernel in the loop: streams equal the jnp
    gather path token for token (the in-register dequant is the same
    algebra; the attn_impl static keys separate compiled programs, so
    the two engines genuinely run different code)."""
    kwargs = dict(max_slots=2, max_seq=48, queue_limit=16, block_size=8,
                  kv_dtype="int8", kv_parity_check=False,
                  rng=jax.random.PRNGKey(5))
    outs = {}
    for impl in ("jnp", "interpret"):
        engine = ServingEngine(params, CFG, attn_impl=impl, **kwargs)
        for i in range(3):
            engine.submit(ServeRequest(prompt=[5, 17, 3, 2 + i],
                                       max_new_tokens=5))
        outs[impl] = {r: v.tokens
                      for r, v in engine.run_until_idle().items()}
    assert outs["jnp"] == outs["interpret"]


def test_compile_once_under_block_churn_zero_storms(params):
    """The compile-once pin with the kernel in the loop and the PR 10
    CompileWatcher attached: two waves of ragged requests (retirements
    free and re-map blocks between waves; a shared prefix exercises the
    radix cache) — the fused decode program compiles exactly once and
    the watcher records ZERO storms."""
    from trustworthy_dl_tpu.obs.compilewatch import (
        CompileRegistry,
        CompileWatcher,
    )

    registry = CompileRegistry().install()
    watcher = CompileWatcher(registry)
    try:
        engine = ServingEngine(params, CFG, max_slots=2, max_seq=32,
                               block_size=8, prefill_chunk=8,
                               queue_limit=32, attn_impl="interpret",
                               compilewatch=watcher)
        before = engine.scheduler.decode_cache_size()
        rng = np.random.default_rng(3)
        shared = rng.integers(0, CFG.vocab_size, 9).tolist()
        served = 0
        for _wave in range(2):
            engine.submit(ServeRequest(prompt=shared, max_new_tokens=3))
            for _ in range(3):
                plen = int(rng.integers(3, 12))
                engine.submit(ServeRequest(
                    prompt=rng.integers(0, CFG.vocab_size, plen).tolist(),
                    max_new_tokens=int(rng.integers(2, 6))))
            results = engine.run_until_idle()
            served += len(engine.drain_results())
        assert served == 8
        assert all(r.status == "completed" for r in results.values())
        assert engine.scheduler.decode_cache_size() - before == 1
        assert watcher.storm_total == 0
    finally:
        registry.uninstall()


# --------------------------------------------------------------------------
# Obs surface + the poison drill
# --------------------------------------------------------------------------


def test_attn_gauge_and_summary_surface(params):
    """Every serve snapshot names the active path of EVERY program in
    the serving-kernel tier: the ``tddl_serve_attn_kernel{path=,
    program=}`` gauge sets 1 on exactly the resolved path per program
    (decode / prefill / verify / adapter), and metrics_summary carries
    decode_tick_fraction + prefill_chunk_fraction +
    spec_verify_fraction + the path map (what the perf sentinel
    bands)."""
    for impl, expect in (("interpret", "interpret"), ("jnp", "jnp")):
        registry = MetricsRegistry()
        engine = ServingEngine(params, CFG, max_slots=2, max_seq=32,
                               block_size=8, registry=registry,
                               attn_impl=impl)
        engine.submit(ServeRequest(prompt=[3, 1, 4], max_new_tokens=3))
        engine.run_until_idle()
        paths = engine.attn_kernel_paths
        assert paths["decode"] == expect
        assert paths["prefill"] == expect
        assert paths["verify"] == expect
        # No adapter pool configured: the adapter program has no work,
        # its path stays the structural-absence "jnp".
        assert paths["adapter"] == "jnp"
        gauge = registry.get("tddl_serve_attn_kernel")
        for program in pattn.PAGED_PROGRAMS:
            for path in ("pallas", "interpret", "jnp"):
                want = 1.0 if path == paths[program] else 0.0
                assert gauge.value(path=path, program=program) == want, \
                    (impl, program, path)
        summary = engine.metrics_summary()
        assert summary["attn_kernel_path"] == expect
        assert summary["attn_kernel_paths"] == paths
        assert 0.0 < summary["decode_tick_fraction"] <= 1.0
        assert 0.0 < summary["prefill_chunk_fraction"] <= 1.0
        assert summary["spec_verify_fraction"] == 0.0  # spec_k == 0
    # The stripe pool has no paged kernel: its paths are always jnp.
    stripe = ServingEngine(params, CFG, max_slots=2, max_seq=32,
                           paged=False, registry=MetricsRegistry())
    assert stripe.attn_kernel_path == "jnp"
    assert set(stripe.attn_kernel_paths.values()) == {"jnp"}


def test_config_knob_validation_and_threading(params):
    """ServeConfig.attn_impl fails loudly where the operator typed it
    and threads through from_config to the resolved scheduler path."""
    from trustworthy_dl_tpu.core.config import ServeConfig

    with pytest.raises(ValueError, match="attn_impl"):
        ServeConfig(attn_impl="mosaic")
    engine = ServingEngine.from_config(
        params, CFG, ServeConfig(max_slots=2, max_seq=32, block_size=8,
                                 attn_impl="interpret"))
    assert engine.attn_kernel_path == "interpret"
    off = ServingEngine.from_config(
        params, CFG, ServeConfig(max_slots=2, max_seq=32, block_size=8))
    # Default "auto" resolves to the jnp fallback on the CPU tier (gate
    # closed) — the container default stays green and kernel-free.
    assert off.attn_kernel_path == "jnp"
    # A forced path on the stripe pool (no kernel exists there) fails
    # loudly at the engine, and ServeConfig warns like any paged knob
    # set alongside paged=False.
    with pytest.raises(ValueError, match="paged"):
        ServingEngine(params, CFG, max_slots=2, max_seq=32, paged=False,
                      attn_impl="interpret")
    with pytest.warns(UserWarning, match="attn_impl"):
        ServeConfig(paged=False, attn_impl="jnp")


def test_poison_drill_same_flag_decisions(params):
    """The seeded SERVE_POISON drill with the epilogue in the loop: the
    kernel-path engine flags the SAME request and quarantines the same
    number of slots as the jnp-path engine — monitor decisions ride the
    epilogue's entropy/margin without drift."""
    from trustworthy_dl_tpu.chaos import FaultEvent, FaultInjector, \
        FaultKind, FaultPlan
    from trustworthy_dl_tpu.serve.engine import OutputMonitor

    verdicts = {}
    for impl in ("interpret", "jnp"):
        plan = FaultPlan.scripted([
            FaultEvent(step=4, kind=FaultKind.SERVE_POISON),
        ])
        # z_threshold 50: this vocab's natural margin variation reaches
        # z~6 at warmup 3, while the poison overwrite lands z > 10^4 —
        # the drill isolates the poison path, and the assertion below is
        # the cross-impl one that matters: SAME decisions on both paths.
        engine = ServingEngine(params, CFG, max_slots=2, max_seq=48,
                               block_size=8, attn_impl=impl,
                               monitor=OutputMonitor(warmup=3,
                                                     z_threshold=50.0),
                               chaos=FaultInjector(plan))
        rng = np.random.default_rng(0)
        for _ in range(5):   # ids 0..4; id 4 is the poisoned one
            plen = int(rng.integers(3, 10))
            engine.submit(ServeRequest(
                prompt=rng.integers(0, CFG.vocab_size, plen).tolist(),
                max_new_tokens=int(rng.integers(2, 6))))
        results = engine.run_until_idle()
        verdicts[impl] = {rid: r.flagged for rid, r in results.items()}
        assert results[4].flagged and not results[3].flagged
        assert len(engine.quarantined_slots) == 1
    assert verdicts["interpret"] == verdicts["jnp"]

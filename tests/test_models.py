"""Model zoo: shapes, jit-ability, gradient flow, factory contract
(README.md:85-92 model list; distributed_trainer.py:116-146 partitioning)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from trustworthy_dl_tpu.data import get_dataloader
from trustworthy_dl_tpu.models import ModelFactory, create_model

TINY_GPT = dict(n_layer=2, n_embd=32, n_head=4, vocab_size=128, n_positions=64,
                seq_len=16)


def test_gpt2_forward_and_loss():
    bundle = create_model("gpt2", **TINY_GPT)
    params = bundle.init(jax.random.PRNGKey(0))
    batch = bundle.example_batch(2)
    logits = jax.jit(bundle.apply)(params, batch["input"])
    assert logits.shape == (2, 16, 128)
    loss = jax.jit(bundle.loss)(params, batch)
    assert np.isfinite(float(loss))
    # Random init ≈ uniform over vocab
    assert float(loss) == pytest.approx(np.log(128), rel=0.2)


def test_gpt2_blocks_are_stacked_and_sliceable():
    bundle = create_model("gpt2", **TINY_GPT)
    params = bundle.init(jax.random.PRNGKey(0))
    # `transformer.h` parity: leading axis = layers, sliceable per stage.
    leaves = jax.tree_util.tree_leaves(params["blocks"])
    assert all(l.shape[0] == 2 for l in leaves)
    assert bundle.num_blocks == 2


def test_gpt2_gradients_flow():
    bundle = create_model("gpt2", **TINY_GPT)
    params = bundle.init(jax.random.PRNGKey(0))
    batch = bundle.example_batch(2)
    grads = jax.jit(jax.grad(bundle.loss))(params, batch)
    norms = [float(jnp.linalg.norm(g)) for g in jax.tree_util.tree_leaves(grads)]
    assert all(np.isfinite(n) for n in norms)
    assert sum(norms) > 0


@pytest.mark.parametrize("name,num_blocks", [
    ("resnet32", 15), ("resnet50", 16), ("resnet101", 33),
])
def test_resnet_variants(name, num_blocks):
    bundle = create_model(name, num_classes=10)
    assert bundle.num_blocks == num_blocks
    params = bundle.init(jax.random.PRNGKey(0))
    batch = bundle.example_batch(2)
    logits = jax.jit(bundle.apply)(params, batch["input"])
    assert logits.shape == (2, 10)
    assert np.all(np.isfinite(np.asarray(logits)))


@pytest.mark.parametrize("name,convs", [("vgg11", 8), ("vgg13", 10), ("vgg16", 13)])
def test_vgg_variants(name, convs):
    bundle = create_model(name, num_classes=10)
    assert bundle.num_blocks == convs
    params = bundle.init(jax.random.PRNGKey(0))
    logits = jax.jit(bundle.apply)(params, bundle.example_batch(2)["input"])
    assert logits.shape == (2, 10)


def test_resnet32_param_count_reasonable():
    # CIFAR ResNet-32 is ~0.46M params in the literature; GroupNorm adds a
    # hair. Sanity-check the architecture is the CIFAR variant, not a giant.
    bundle = create_model("resnet32")
    params = bundle.init(jax.random.PRNGKey(0))
    n = bundle.num_params(params)
    assert 3e5 < n < 8e5, n


def test_factory_rejects_unknown():
    with pytest.raises(ValueError):
        ModelFactory().create_model("alexnet")


def test_lm_dataloader_contract():
    dl = get_dataloader("openwebtext", split="train", batch_size=4, seq_len=16,
                        num_examples=32)
    batches = list(dl)
    assert len(batches) == 8
    b = batches[0]
    assert b["input"].shape == (4, 16)
    assert b["target"].shape == (4, 16)
    # target is the shifted stream
    np.testing.assert_array_equal(b["input"][:, 1:], b["target"][:, :-1])


def test_vision_dataloader_contract():
    dl = get_dataloader("cifar10", split="validation", batch_size=8,
                        num_examples=64)
    b = next(iter(dl))
    assert b["input"].shape == (8, 32, 32, 3)
    assert b["target"].shape == (8,)
    assert b["target"].dtype == np.int32


def test_dataloader_deterministic_across_constructions():
    a = next(iter(get_dataloader("cifar10", batch_size=4, num_examples=16, seed=3)))
    b = next(iter(get_dataloader("cifar10", batch_size=4, num_examples=16, seed=3)))
    np.testing.assert_array_equal(a["input"], b["input"])


def test_synthetic_vision_is_learnable():
    # A linear probe should beat chance easily on class-conditional data.
    dl = get_dataloader("cifar10", batch_size=256, num_examples=256)
    b = next(iter(dl))
    x = b["input"].reshape(256, -1)
    y = b["target"]
    # nearest-class-mean classifier
    means = np.stack([x[y == c].mean(axis=0) if (y == c).any() else np.zeros(x.shape[1])
                      for c in range(10)])
    pred = np.argmin(((x[:, None, :] - means[None]) ** 2).sum(-1), axis=1)
    assert (pred == y).mean() > 0.5


def test_remat_policies_numerically_identical():
    """remat off / block remat / attention-policy remat: same loss, same
    gradients (remat changes scheduling, never math)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from trustworthy_dl_tpu.models import gpt2

    base = dict(vocab_size=64, n_positions=16, n_layer=2, n_embd=32,
                n_head=4, dtype=jnp.float32)
    params = gpt2.init_params(jax.random.PRNGKey(0),
                              gpt2.GPT2Config(**base))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    batch = {"input": toks, "target": jnp.roll(toks, -1, -1)}

    results = {}
    for name, kw in (("off", dict(remat=False)),
                     ("block", dict(remat=True)),
                     ("attention", dict(remat=True,
                                        remat_policy="attention"))):
        cfg = gpt2.GPT2Config(**base, **kw)
        loss, grads = jax.jit(
            jax.value_and_grad(gpt2.loss_fn), static_argnums=2
        )(params, batch, cfg)
        results[name] = (float(loss), grads)
    for name in ("block", "attention"):
        assert np.isclose(results[name][0], results["off"][0], rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(results["off"][1]),
                        jax.tree_util.tree_leaves(results[name][1])):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-5, atol=1e-7)

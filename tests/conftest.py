"""Test harness: an 8-device virtual CPU mesh replacing real TPU chips.

This is the framework's "fake backend" (SURVEY §4): tests exercise the real
SPMD train step, shardings and collectives on forced host devices, so the
same code compiles unchanged on a TPU pod.

The container's sitecustomize registers the remote TPU backend at interpreter
startup (before pytest's conftest runs), so setting env vars here is too late
— if the process isn't already on the CPU platform we re-exec pytest once
with the corrected environment.
"""

import os
import sys

_WANT = {
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",  # disables the remote-TPU site hook
    "XLA_FLAGS": (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip(),
}

if os.environ.get("JAX_PLATFORMS") != "cpu" and os.environ.get(
    "TDDL_NO_REEXEC"
) != "1":
    env = dict(os.environ)
    env.update(_WANT)
    env["TDDL_NO_REEXEC"] = "1"  # belt-and-braces against exec loops
    os.execve(
        sys.executable, [sys.executable, "-m", "pytest", *sys.argv[1:]], env
    )

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = _WANT["XLA_FLAGS"]

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

# Persistent compilation cache: integration tests recompile identical SPMD
# programs across runs; on the single-core CI box that dominates wall time.
import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"
))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected >=8 virtual devices, got {len(devices)}"
    return devices[:8]


@pytest.fixture(autouse=True)
def _reset_global_mode_meshes():
    """Trainers in 'sequence'/'expert' (and elastic rebuilds) bind global
    collectives meshes that would otherwise leak across tests — a test
    expecting the unbound state (ring fallback, dense-MLP equivalence)
    fails depending on execution order.  Reset BEFORE each test; bindings
    made within a test stay live for its own duration."""
    from trustworthy_dl_tpu.models.moe import set_expert_mesh
    from trustworthy_dl_tpu.parallel.sequence import set_sequence_mesh

    set_sequence_mesh(None)
    set_expert_mesh(None)
    yield

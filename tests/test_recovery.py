"""Engine-driven recovery / readmission (VERDICT r2 item 3).

The reference exposes ``initiate_recovery`` (trust_manager.py:198-206) and a
COMPROMISED→RECOVERING→TRUSTED ladder (:162-181) but no code path ever calls
it.  Here both halves are wired into the engine:

* in-step probation (`trust/state.py:probation_recovery`): a hard-gated node
  with ``recovery_probation_steps`` consecutive clean steps transitions to
  RECOVERING (boosted 0.02 recovery rate) and its aggregation weight
  returns — a transient attack / false positive costs bounded steps;
* elastic readmission (`elastic/reassignment.py:readmit_and_reshard`): an
  evicted mesh coordinate is restored after ``readmit_after_steps``, with
  fresh detector baselines and probation trust; a still-hostile node is
  re-detected and re-evicted.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from trustworthy_dl_tpu.attacks import AttackConfig, AdversarialAttacker, \
    null_plan
from trustworthy_dl_tpu.core.config import TrainingConfig
from trustworthy_dl_tpu.data import get_dataloader
from trustworthy_dl_tpu.engine import DistributedTrainer
from trustworthy_dl_tpu.trust.state import NodeStatus

pytestmark = pytest.mark.slow  # heavy jitted-training integration tier

TINY_GPT = dict(n_layer=2, n_embd=32, n_head=4, vocab_size=128,
                n_positions=32, seq_len=16)


def make_trainer(tmp_path, num_nodes=4, **kw):
    kw.setdefault("detector_warmup", 4)
    config = TrainingConfig(
        model_name="gpt2", dataset_name="openwebtext",
        batch_size=2 * num_nodes, num_nodes=num_nodes,
        learning_rate=3e-3, checkpoint_interval=10_000,
        checkpoint_dir=str(tmp_path / "ckpt"), **kw,
    )
    return DistributedTrainer(config, model_overrides=dict(TINY_GPT))


def test_probation_recovery_after_transient_attack(tmp_path):
    """Transient attack: node 1 is detected and hard-gated; once the attack
    ends, the probation path readmits it — RECOVERING appears in its status
    trajectory, the aggregation weight returns, and it ends TRUSTED."""
    trainer = make_trainer(tmp_path, num_nodes=4,
                           recovery_probation_steps=2)
    trainer.initialize()
    batch = trainer._node_batch(trainer.model.example_batch(8))

    attacker = AdversarialAttacker(AttackConfig(
        attack_types=["gradient_poisoning"], target_nodes=[1],
        intensity=0.5, start_step=6,
    ))
    attacker.activate_attacks()
    plan = attacker.plan(4)

    state = trainer.state
    gated = False
    for _ in range(14):
        state, metrics = trainer._train_step(state, batch, plan)
        status = np.asarray(metrics.status)
        if status[1] == int(NodeStatus.COMPROMISED):
            gated = True
            assert float(np.asarray(metrics.weights)[1]) == 0.0
    assert gated, "attacked node was never confirmed-compromised"

    # Attack ends; the node's evidence is clean again.
    clean = null_plan(4)
    statuses, weights = [], []
    for _ in range(30):
        state, metrics = trainer._train_step(state, batch, clean)
        statuses.append(int(np.asarray(metrics.status)[1]))
        weights.append(float(np.asarray(metrics.weights)[1]))

    assert int(NodeStatus.RECOVERING) in statuses, \
        f"probation never fired; trajectory {statuses}"
    assert statuses[-1] == int(NodeStatus.TRUSTED)
    assert weights[-1] > 0.0
    # Boosted recovery rate per initiate_recovery semantics.
    assert float(np.asarray(state.trust.recovery_rate)[1]) == \
        pytest.approx(0.02)
    # Readmission is bounded: the weight must return well before the end.
    first_back = next(i for i, w in enumerate(weights) if w > 0)
    assert first_back <= 10
    # Clean nodes were never disturbed.
    for node in (0, 2, 3):
        assert statuses and int(np.asarray(state.trust.status)[node]) == \
            int(NodeStatus.TRUSTED)


def test_probation_does_not_readmit_sustained_attacker(tmp_path):
    """A node under SUSTAINED attack accrues no clean streak: it stays
    gated for the whole run even with a short probation."""
    trainer = make_trainer(tmp_path, num_nodes=4,
                           recovery_probation_steps=2)
    trainer.initialize()
    batch = trainer._node_batch(trainer.model.example_batch(8))
    attacker = AdversarialAttacker(AttackConfig(
        attack_types=["gradient_poisoning"], target_nodes=[1],
        intensity=0.5, start_step=6,
    ))
    attacker.activate_attacks()
    plan = attacker.plan(4)

    state = trainer.state
    confirmed_at = None
    for i in range(30):
        state, metrics = trainer._train_step(state, batch, plan)
        if confirmed_at is None and np.asarray(metrics.attacked)[1]:
            confirmed_at = i
        if confirmed_at is not None and i > confirmed_at:
            assert float(np.asarray(metrics.weights)[1]) == 0.0
            assert int(np.asarray(metrics.status)[1]) == \
                int(NodeStatus.COMPROMISED)
    assert confirmed_at is not None
    assert int(np.asarray(state.clean_streak)[1]) == 0


def test_readmission_restores_evicted_coordinate(tmp_path):
    """Eviction → cool-off → readmission: the mesh grows back to 8
    coordinates, the readmitted identity re-enters on probation, and
    training continues finite."""
    trainer = make_trainer(
        tmp_path, num_nodes=8, elastic_resharding=True,
        readmit_after_steps=8,
    )
    dl = get_dataloader("openwebtext", batch_size=16, seq_len=16,
                        vocab_size=128, num_examples=96)
    trainer.initialize()
    attacker = AdversarialAttacker(AttackConfig(
        attack_types=["gradient_poisoning"], target_nodes=[5],
        intensity=0.5, start_step=8,
    ))
    attacker.activate_attacks()
    trainer.set_attack_plan(attacker.plan(8))

    epoch = 0
    while trainer.config.num_nodes == 8 and epoch < 4:
        loss0 = trainer.train_epoch(dl, epoch)
        epoch += 1
    assert trainer.config.num_nodes == 7, "eviction did not happen"
    assert 5 in trainer._evicted_at

    # Attack over: clear the schedule so the readmitted node behaves.
    trainer.set_attack_plan(null_plan(7))
    while trainer.config.num_nodes == 7 and epoch < 8:
        loss1 = trainer.train_epoch(dl, epoch)
        epoch += 1
    assert np.isfinite(loss0) and np.isfinite(loss1)

    assert trainer.config.num_nodes == 8
    assert trainer.node_map[-1] == 5
    assert trainer.state.trust.scores.shape == (8,)
    assert 5 not in trainer._evicted_at
    readmits = [r for r in trainer.reassignment_history
                if "readmitted_nodes" in r]
    assert len(readmits) == 1 and readmits[0]["readmitted_nodes"] == [5]
    # Probation standing: boosted recovery rate on the readmitted row.
    coord = trainer.node_map.index(5)
    assert float(np.asarray(trainer.state.trust.recovery_rate)[coord]) == \
        pytest.approx(0.02)
    # Host mirror is no longer hard-compromised.
    assert trainer.trust_manager.get_node_status(5) != NodeStatus.COMPROMISED
    # Fresh detector rows: the readmitted coordinate re-warms.
    assert int(np.asarray(trainer.state.out_baseline.count)[coord]) < \
        int(np.asarray(trainer.state.out_baseline.count)[0])

    # Training continues on the full fleet.
    loss2 = trainer.train_epoch(dl, epoch)
    assert np.isfinite(loss2)


def test_readmitted_attacker_is_re_evicted(tmp_path):
    """A readmitted node still in the attack schedule attacks again and is
    evicted a second time — probation does not whitewash hostility."""
    trainer = make_trainer(
        tmp_path, num_nodes=8, elastic_resharding=True,
        readmit_after_steps=6,
    )
    dl = get_dataloader("openwebtext", batch_size=16, seq_len=16,
                        vocab_size=128, num_examples=96)
    trainer.initialize()
    attacker = AdversarialAttacker(AttackConfig(
        attack_types=["gradient_poisoning"], target_nodes=[5],
        intensity=0.5, start_step=8,
    ))
    attacker.activate_attacks()
    trainer.set_attack_plan(attacker.plan(8))

    for epoch in range(6):
        trainer.train_epoch(dl, epoch)
        evictions = [r for r in trainer.reassignment_history
                     if "evicted_nodes" in r]
        if len(evictions) >= 2:
            break

    evictions = [r for r in trainer.reassignment_history
                 if "evicted_nodes" in r and r["evicted_nodes"] == [5]]
    readmits = [r for r in trainer.reassignment_history
                if "readmitted_nodes" in r]
    assert len(evictions) >= 2, (
        f"expected re-eviction; history {trainer.reassignment_history}"
    )
    assert len(readmits) >= 1
    assert trainer.config.num_nodes == 7


def test_loader_resized_after_eviction(tmp_path):
    """VERDICT r2 weak #6: after eviction the live loader's batch size is
    rebuilt to divide nodes × accum — no persistent trimming, no dropped
    samples, no warning."""
    trainer = make_trainer(
        tmp_path, num_nodes=8, elastic_resharding=True,
    )
    dl = get_dataloader("openwebtext", batch_size=16, seq_len=16,
                        vocab_size=128, num_examples=96)
    trainer.initialize()
    attacker = AdversarialAttacker(AttackConfig(
        attack_types=["gradient_poisoning"], target_nodes=[5],
        intensity=0.5, start_step=8,
    ))
    attacker.activate_attacks()
    trainer.set_attack_plan(attacker.plan(8))

    epoch = 0
    while trainer.config.num_nodes == 8 and epoch < 4:
        trainer.train_epoch(dl, epoch)
        epoch += 1
    assert trainer.config.num_nodes == 7
    # The raw loader was re-sized to 7 nodes x 2/node.
    assert dl.batch_size == 14
    assert trainer.config.batch_size == 14
    trainer.train_epoch(dl, epoch)
    assert not trainer._warned_trim


def test_host_detection_stats_reflect_ground_truth(tmp_path):
    """VERDICT r2 weak #5: the host detector's TP/FP rates are fed from
    injection ground truth — a detected real attack counts as a true
    positive, so get_detection_statistics() no longer reports 0.0."""
    trainer = make_trainer(tmp_path, num_nodes=4)
    dl = get_dataloader("openwebtext", batch_size=8, seq_len=16,
                        vocab_size=128, num_examples=48)
    trainer.initialize()
    attacker = AdversarialAttacker(AttackConfig(
        attack_types=["gradient_poisoning"], target_nodes=[1],
        intensity=0.5, start_step=8,
    ))
    attacker.activate_attacks()
    trainer.set_attack_plan(attacker.plan(4))
    for epoch in range(3):
        trainer.train_epoch(dl, epoch)

    assert {r["node_id"] for r in trainer.attack_history} == {1}
    stats = trainer.attack_detector.get_detection_statistics()
    assert stats["total_detections"] >= 1
    assert stats["true_positive_rate"] == 1.0
    assert stats["false_positive_rate"] == 0.0
    assert sum(stats["attack_type_distribution"].values()) == \
        stats["total_detections"]


def test_attacker_plan_for_live_topology():
    """plan_for lays the target mask in COORDINATE space via node_map —
    an attack on original identity 7 lands wherever 7 currently sits
    after evictions (fast unit for the runner's post-eviction path)."""
    attacker = AdversarialAttacker(AttackConfig(
        attack_types=["gradient_poisoning"], target_nodes=[7],
        intensity=0.5, start_step=0,
    ))
    attacker.activate_attacks()
    node_map = [0, 1, 3, 4, 5, 6, 7]  # identity 2 was evicted
    plan = attacker.plan_for(node_map)
    mask = np.asarray(plan.target_mask)
    assert mask.shape == (7,)
    assert mask[6] and mask.sum() == 1  # identity 7 sits at coordinate 6
    # plan() (identity == coordinate) would have dropped the target:
    assert not np.asarray(attacker.plan(7).target_mask)[6]


def test_readmission_cooloff_survives_resume(tmp_path):
    """ADVICE r3: a pending readmission cool-off must survive a
    save/restore round-trip — the sidecar persists _evicted_at and the
    evicted coordinate's device, and a resumed trainer readmits on
    schedule instead of making the eviction silently permanent."""
    trainer = make_trainer(
        tmp_path, num_nodes=8, elastic_resharding=True,
        readmit_after_steps=8,
    )
    dl = get_dataloader("openwebtext", batch_size=16, seq_len=16,
                        vocab_size=128, num_examples=96)
    trainer.initialize()
    attacker = AdversarialAttacker(AttackConfig(
        attack_types=["gradient_poisoning"], target_nodes=[5],
        intensity=0.5, start_step=8,
    ))
    attacker.activate_attacks()
    trainer.set_attack_plan(attacker.plan(8))

    epoch = 0
    while trainer.config.num_nodes == 8 and epoch < 4:
        trainer.train_epoch(dl, epoch)
        epoch += 1
    assert trainer.config.num_nodes == 7
    assert 5 in trainer._evicted_at
    evicted_step = trainer._evicted_at[5]
    trainer.save_checkpoint()
    saved_step = trainer.global_step

    # Fresh process: a new trainer resumes from the checkpoint.  The
    # constructor config says 8 nodes; the sidecar adopts the 7-node
    # post-eviction topology AND the pending cool-off.
    resumed = make_trainer(
        tmp_path, num_nodes=8, elastic_resharding=True,
        readmit_after_steps=8,
    )
    resumed.load_checkpoint(saved_step)
    assert resumed.config.num_nodes == 7
    assert resumed._evicted_at == {5: evicted_step}
    assert 5 in resumed._evicted_devices

    # Attack is over in the resumed run: readmission fires on schedule.
    resumed.set_attack_plan(null_plan(7))
    epoch = 0
    while resumed.config.num_nodes == 7 and epoch < 4:
        loss = resumed.train_epoch(dl, epoch)
        epoch += 1
    assert resumed.config.num_nodes == 8
    assert resumed.node_map[-1] == 5
    assert np.isfinite(loss)
